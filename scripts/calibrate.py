#!/usr/bin/env python
"""Lattice calibration CLI (ISSUE 16) — measure this deployment's
actual edge bandwidths and persist them as a stamped profile.

Runs the probe suite (``heat_tpu.observability.calibration``): an
on-device copy for ``hbm``, the depth-2 ``device_put`` stream for
``pcie``, a slab read for ``disk``, and tiny per-tier-group all_gather
programs for ``ici``/``dcn`` — each bench.py style (repeat, keep the
floor, flag wide dispersion ``measurement_suspect``). With
``--workload`` it first runs one traced staged pass so the span
ingestion path has real windows to fold in — the same fold a
long-lived deployment gets for free just by running traced.

Prints the constants-vs-measured table and writes the versioned
envelope (sha256 ``profile_id``) to ``--out``. Activate with::

    export HEAT_TPU_LATTICE_PROFILE=/path/to/profile.json

Unset, nothing changes: every price stays the constant and every
plan_id/program stays byte-identical. Exit 0 iff a profile with at
least one measured edge was produced (and saved, when ``--out``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _span_workload() -> None:
    """One traced staged pass over a host-resident operand: populates
    the span buffer with ``stage_in`` windows (tier=pcie, bytes, real
    wall) for the ingestion fold."""
    import numpy as np

    import heat_tpu as ht
    from heat_tpu.observability import tracing
    from heat_tpu.redistribution import staging

    os.environ.setdefault("HEAT_TPU_OOC_SLAB_MB", "8")  # force several windows
    tracing.enable()
    host = staging.HostArray(np.zeros((512, 4096), dtype=np.float32))  # 8 MiB
    ht.linalg.hsvd_rank(host, 8)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", metavar="PATH",
                    help="write the profile envelope JSON here")
    ap.add_argument("--edges", metavar="E[,E...]",
                    help="probe only these edges (default: all five)")
    ap.add_argument("--bytes", type=int, default=None, metavar="N",
                    help="probe payload size (default 32 MiB)")
    ap.add_argument("--repeats", type=int, default=None, metavar="K",
                    help="probe repeats per edge (default 3, floor kept)")
    ap.add_argument("--workload", action="store_true",
                    help="run one traced staged pass first so span "
                         "ingestion has real windows to fold in")
    ap.add_argument("--no-spans", action="store_true",
                    help="probes only; skip span-buffer ingestion")
    ap.add_argument("--platform", help="override the platform stamp")
    ap.add_argument("--topology", help="override the topology stamp")
    ap.add_argument("--json", action="store_true",
                    help="print the envelope JSON instead of the table")
    args = ap.parse_args()

    from heat_tpu.observability import calibration

    if args.workload:
        _span_workload()

    kw = {}
    if args.bytes is not None:
        kw["nbytes"] = args.bytes
    if args.repeats is not None:
        kw["repeats"] = args.repeats
    try:
        profile = calibration.calibrate(
            path=args.out,
            edges=[e.strip() for e in args.edges.split(",")] if args.edges else None,
            include_spans=not args.no_spans,
            platform=args.platform,
            topology=args.topology,
            **kw,
        )
    except RuntimeError as e:
        print(f"calibrate: {e}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps(profile, indent=1, sort_keys=True))
    else:
        print(calibration.describe_profile(profile))
    if args.out:
        print(f"# profile {profile['profile_id']} -> {args.out}", file=sys.stderr)
        print(f"# activate: export HEAT_TPU_LATTICE_PROFILE={args.out}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
