#!/usr/bin/env bash
# CI contract — the analog of the reference's test matrix
# (/root/reference/.github/workflows/ci.yaml:54-56: `mpirun -n 3/4 pytest`,
# deliberately one even AND one odd world to catch divisibility bugs).
#
# One command reproduces the full evidence:
#  1. the whole suite on a virtual 8-device CPU mesh (tests/conftest.py
#     forces JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8),
#     which includes the REAL 2x2- and 4x1-process Gloo worlds
#     (tests/test_multiprocess.py) covering ingest, saves, sort,
#     percentile, ring attention, KMeans, compaction ops, DP + DASO;
#  2. the ODD-mesh leg (VERDICT r4 #6): the suite again at 5 devices —
#     where chunk geometry, DASO node factorization, and every
#     p-divisibility assumption degenerate differently — with the slow
#     marks and the (process-spawning, mesh-size-independent)
#     multiprocess worlds excluded;
#  3. the telemetry-enabled smoke leg: the instrumentation hooks
#     (program-cache counters, shard/reshard events, ht.jit tracing)
#     must add NO failures when live — the zero-cost-when-disabled
#     default is covered by every other leg running with them off;
#  4. the multi-chip dryrun: the full training step jit-compiled and
#     executed on an 8-device mesh (real dp/sp shardings);
#  5. the bench regression gate, whenever bench artifacts exist:
#     threshold regressions are report-only (BENCH_COMPARE.json + one
#     verdict line; a bench-carrying change gates itself via --strict),
#     but DETERMINISTIC analytic fields (model speedups, pass counts)
#     HARD-gate via --unchanged-fields (ISSUE 12): they can only move
#     when a PR intentionally changes pricing, and such a PR must
#     regenerate its bench artifacts in the same change — the same
#     update-the-pin rule the golden plan_ids follow;
#  6. the shardlint legs: source lint over heat_tpu/ (undeclared host
#     syncs, bare jax.jit, unsanitized public ops) and the IR check of
#     the __graft_entry__ training step on the 8-device CPU mesh
#     (ht.analysis.check: implicit reshards, replicated
#     materializations, missed donations). Warnings report only;
#     error-severity findings fail the leg.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m pytest tests/ -q "$@"

XLA_FLAGS="--xla_force_host_platform_device_count=5" \
  python -m pytest tests/ -q -m "not slow" --ignore tests/test_multiprocess.py "$@"

HEAT_TPU_TELEMETRY=1 python -m pytest tests/test_smoke.py tests/test_observability.py -q "$@"

XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun_multichip(8): OK')"

# sort-kernel legs (ISSUE 4): the kernel family FORCED on CPU — the
# Pallas radix block kernel runs in interpret mode, the XLA radix and
# blocked-columnsort engines natively — against the lax.sort oracle
# (leg 8); and the HEAT_TPU_SORT_KERNEL=0 escape hatch over the public
# sort surface, proving the hatch is oracle-identical (leg 9)
HEAT_TPU_SORT_KERNEL=1 python -m pytest tests/test_kernels_sort.py -q "$@"

HEAT_TPU_SORT_KERNEL=0 python -m pytest tests/test_manipulations.py tests/test_kernels_sort.py -q -k "sort" "$@"

# relayout-kernel legs (ISSUE 5), mirroring the sort legs: the
# lane-packing pack/unpack FORCED onto the Pallas tiled-copy kernel
# (interpret mode on CPU) under the whole redistribution surface
# (leg 10); and the HEAT_TPU_RELAYOUT_KERNEL=0 escape hatch, proving
# the XLA formulation is bit-identical over the packed programs
# (leg 11)
HEAT_TPU_RELAYOUT_KERNEL=1 python -m pytest tests/test_kernels_relayout.py tests/test_redistribution.py -q "$@"

HEAT_TPU_RELAYOUT_KERNEL=0 python -m pytest tests/test_kernels_relayout.py -q "$@"

# overlap legs (ISSUE 6), mirroring the kernel legs: forced software
# pipelining + collective-matmul ring forms over the redistribution and
# linalg suites (Pallas-interpret compatible — the packed-pivot programs
# run their relayout kernels in interpret mode on CPU) (leg 12); and the
# HEAT_TPU_REDIST_OVERLAP=0 escape hatch, proving the sequential oracle
# is bit-identical over the same surface (leg 13). ISSUE 19 extends
# both legs over the dense-factorization suite: the ring schedules
# (polar / eigh / cholesky / lu / solve) must be bit-identical under
# pipelined and sequential issue order — the suite's pinned seq/pipe
# parity tests run under BOTH gate values.
HEAT_TPU_REDIST_OVERLAP=1 python -m pytest tests/test_overlap.py tests/test_redistribution.py tests/test_linalg.py tests/test_kernels_relayout.py tests/test_factorizations.py -q "$@"

HEAT_TPU_REDIST_OVERLAP=0 python -m pytest tests/test_overlap.py tests/test_redistribution.py tests/test_factorizations.py -q "$@"

# wire-quant legs (ISSUE 7), mirroring the overlap legs: the int8 wire
# codec FORCED on CPU over the redistribution + optim suites — the
# admissibility policy keeps every bit-exact contract exact while the
# big-spec programs compile (and the mid-size ones execute) with
# encoded payloads (leg 14); and the HEAT_TPU_WIRE_QUANT=0 escape
# hatch, proving the full-width plans/programs are byte-identical to
# the PR 6 forms (leg 15). (The codec is pure XLA — no Pallas path to
# interpret-gate. RingKernelAttention is excluded the way the PR-2
# notes document: those tests carry a container capability gate —
# head_dim multiples of 128 — that fails STANDALONE on this image with
# or without any quant gate; leg 1 covers them in the full suite.)
HEAT_TPU_WIRE_QUANT=1 python -m pytest tests/test_quant.py tests/test_redistribution.py tests/test_nn_optim.py -q -k "not RingKernelAttention" "$@"

HEAT_TPU_WIRE_QUANT=0 python -m pytest tests/test_quant.py tests/test_redistribution.py tests/test_overlap.py -q "$@"

# two-tier topology legs (ISSUE 8): the simulated 2x4 factorization of
# the 8-device mesh forced over the redistribution/overlap/quant suites
# — tiered plans execute end to end, census == tiered plan, the flat
# golden pins hold via their explicit topology="flat" anchors (leg 16);
# the two-tier dryrun pins hierarchical-vs-flat bit-identity, TSQR
# slice-major census, and the hierarchical DP wire (leg 17); and the
# auto-on-CPU no-op parity leg proves HEAT_TPU_TOPOLOGY=auto on a
# single-slice world dumps plans byte-identical to the unset default
# (leg 18)
HEAT_TPU_TOPOLOGY=2x4 python -m pytest tests/test_topology.py tests/test_redistribution.py tests/test_overlap.py tests/test_quant.py -q "$@"

XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu HEAT_TPU_TOPOLOGY=2x4 \
  python -c "import __graft_entry__ as g; g.dryrun_two_tier(8); print('dryrun_two_tier(8): OK')"

topo_a="$(mktemp)"; topo_b="$(mktemp)"
python scripts/redist_plans.py > "$topo_a"
HEAT_TPU_TOPOLOGY=auto python scripts/redist_plans.py > "$topo_b"
diff "$topo_a" "$topo_b"
echo "HEAT_TPU_TOPOLOGY=auto on CPU: flat plans byte-identical"
rm -f "$topo_a" "$topo_b"

# serving legs (ISSUE 9): (19) warmup export into a fresh store, then a
# FRESH process against the same store must serve every declared
# program from disk (--expect-hits: the cross-process cache-hit proof —
# an AOT-served cold start compiles 0 programs); (20) the dispatcher
# concurrency + AOT suite FORCED on (HEAT_TPU_SERVING_AOT=1 with a
# scratch store, so the ambient default-enabled hooks are exercised by
# every test, not just the ServingCase-anchored ones); (21) the
# HEAT_TPU_SERVING_AOT=0 escape hatch over the serving + jit suites —
# hooks never install and the wrapper runs its exact pre-serving paths
srv_store="$(mktemp -d)"
HEAT_TPU_SERVING_AOT=1 HEAT_TPU_SERVING_CACHE="$srv_store" python scripts/warmup.py > /dev/null
HEAT_TPU_SERVING_AOT=1 HEAT_TPU_SERVING_CACHE="$srv_store" python scripts/warmup.py --expect-hits
echo "serving warmup reload: cross-process AOT hits OK"

srv_scratch="$(mktemp -d)"
HEAT_TPU_SERVING_AOT=1 HEAT_TPU_SERVING_CACHE="$srv_scratch" \
  python -m pytest tests/test_serving.py -q "$@"
rm -rf "$srv_store" "$srv_scratch"

HEAT_TPU_SERVING_AOT=0 python -m pytest tests/test_serving.py tests/test_jit.py tests/test_jit_sweep.py -q "$@"

# out-of-core staging legs (ISSUE 11), mirroring the kernel legs:
# (22) HEAT_TPU_OOC=1 FORCES the staged window pipeline — every
# rank-budget hsvd sketch on the supported (single-device-orientation)
# path runs host->slab->compute windows — over the linalg + cluster +
# redistribution suites, which must stay green AND bit-identical to
# the in-HBM forms (tests/test_staging.py pins the sweep); (23) the
# HEAT_TPU_OOC=0 escape hatch: staging never engages, HostArray twins
# materialize, exact pre-staging program forms
HEAT_TPU_OOC=1 python -m pytest tests/test_staging.py tests/test_linalg.py tests/test_estimators.py tests/test_redistribution.py -q "$@"

HEAT_TPU_OOC=0 python -m pytest tests/test_staging.py tests/test_linalg.py -q "$@"

# resilience legs (ISSUE 13): (24) the chaos drill at the even AND odd
# meshes — a seeded slice kill mid-fit at the simulated 2x4 topology:
# detection is a typed WorldChangedError (never a hang), the live
# dispatcher's queued requests shed as
# ServingOverloaded(reason="resize") while its in-flight batch
# COMPLETES, the world re-resolves onto the survivors with the epoch
# bump + cache sweep, and the checkpoint-resumed fit is BIT-IDENTICAL
# to an uninterrupted same-seed run (a chaos-truncated newest envelope
# falls back to its committed predecessor); (25) the resilience +
# serving suites with the runtime FORCED on; (26) the
# HEAT_TPU_RESILIENCE=0 escape hatch: golden plan dumps byte-identical
# with the runtime off, and the suite's escape-hatch pins pass
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
  HEAT_TPU_RESILIENCE=1 python scripts/chaos_drill.py
XLA_FLAGS="--xla_force_host_platform_device_count=5" JAX_PLATFORMS=cpu \
  HEAT_TPU_RESILIENCE=1 python scripts/chaos_drill.py

HEAT_TPU_RESILIENCE=1 python -m pytest tests/test_resilience.py tests/test_serving.py -q "$@"

res_a="$(mktemp)"; res_b="$(mktemp)"
python scripts/redist_plans.py > "$res_a"
HEAT_TPU_RESILIENCE=0 python scripts/redist_plans.py > "$res_b"
diff "$res_a" "$res_b"
HEAT_TPU_RESILIENCE=0 python -m pytest tests/test_resilience.py -q "$@"
echo "HEAT_TPU_RESILIENCE=0: golden dumps byte-identical + escape-hatch pins clean"
rm -f "$res_a" "$res_b"

# tracing legs (ISSUE 15): (27) span collection FORCED on
# (HEAT_TPU_TRACE=1) over the four instrumented layers — redistribution
# lap probes, staging window spans, the dispatcher lifecycle, and the
# resilience slab/drain spans — every suite must stay green with the
# recorder live (the census==plan pins in tests/test_tracing.py run
# anchored, the rest prove the probes never perturb behavior); (28) the
# HEAT_TPU_TRACE=0 escape hatch: the gate is registered
# affects_programs=False, so the golden plan dumps must be
# byte-identical with tracing hard-off vs forced on — the diff IS the
# proof that observation never changes what runs; (29) the
# metrics_dump/export_trace smoke: one workload process emits
# parseable Prometheus text, a telemetry JSON snapshot, and a
# Chrome-trace JSON doc that round-trips
HEAT_TPU_TRACE=1 python -m pytest tests/test_tracing.py tests/test_redistribution.py tests/test_staging.py tests/test_serving.py tests/test_resilience.py -q "$@"

trace_a="$(mktemp)"; trace_b="$(mktemp)"
HEAT_TPU_TRACE=0 python scripts/redist_plans.py > "$trace_a"
HEAT_TPU_TRACE=1 python scripts/redist_plans.py > "$trace_b"
diff "$trace_a" "$trace_b"
HEAT_TPU_TRACE=0 python -m pytest tests/test_tracing.py -q "$@"
echo "HEAT_TPU_TRACE=0: golden dumps byte-identical to =1 + zero-overhead pins clean"
rm -f "$trace_a" "$trace_b"

trace_json="$(mktemp)"
HEAT_TPU_TRACE=1 python scripts/metrics_dump.py --trace "$trace_json" | python -c "
import sys
lines = sys.stdin.read().splitlines()
assert any(l.startswith('# TYPE heat_tpu_') for l in lines), 'no TYPE comments'
vals = [l for l in lines if l and not l.startswith('#')]
assert vals, 'no samples rendered'
for l in vals:
    float(l.rpartition(' ')[2])
print(f'prometheus text: {len(vals)} samples OK')
"
HEAT_TPU_TRACE=1 python scripts/metrics_dump.py --json > /dev/null
python - "$trace_json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
evs = doc["traceEvents"]
assert evs and any(e["ph"] == "X" for e in evs), "no complete span events"
print(f"chrome trace: {len(evs)} events OK")
EOF
rm -f "$trace_json"

# the single CI lint entry (ISSUE 14; ISSUE 17 adds pass 6): passes
# 2 + 4 + 5 + 6 — srclint (SL2xx source hygiene), effectcheck (SL40x
# gate/cache-key staleness, raw gate reads, lock discipline, pipeline
# protocol, swallowed worker exceptions), commcheck (SL504 unfenced
# dispatch entries) and numcheck (SL602 planar precision policy:
# deleting the PR 5 precision="highest" default is an error here) — in
# ONE process, gated at error severity, with one SARIF document
# carrying one run per pass for CI annotations. Exit codes are pinned
# format-invariant (tests/test_analysis.py::TestLintCLI): 0 on the
# clean tree, 1 on any error-severity finding, text or sarif alike.
python scripts/lint.py heat_tpu/ --pass all
python scripts/lint.py heat_tpu/ --pass all --format sarif > /dev/null
echo "lint --pass all: SL2xx/SL4xx/SL5xx/SL6xx clean + SARIF emitted"

# seeded-bug proof (ISSUE 12 + 14 + 17 acceptance): each mutation
# removes ONE invariant — a gate from a program-cache key (SL402), a
# lock acquisition from a guarded dispatcher path (SL404), a pair from
# a ring_all_gather permutation (SL502), the full-axis reduction off a
# collective-launching cond predicate (SL501), the epoch-fence call
# off the executor / the serving endpoint (SL504), the planar
# precision="highest" default (SL602), the gram builders' f32
# accumulator (SL601), the f32 error-feedback carry (SL603), a golden
# plan's tolerance annotation / encode tags / wire markers (the
# tolerance invariant, step named) — and the lint must trip on the
# mutated source with the invariant named.
python -m pytest tests/test_effectcheck.py tests/test_commcheck.py tests/test_numcheck.py -q -k "mutation" "$@"

# pass-5 IR + progress legs (ISSUE 14): the SL5xx golden bad fixtures
# trip at their declared severities with clean twins, the shipped
# collective contracts pin commcheck-clean, every golden plan replays
# to completion under the progress invariant, and a hand-mutated dump
# fails scripts/verify_plans.py NAMING "progress" (the sweep test).
python -m pytest tests/test_commcheck.py -q "$@"

# pass-6 IR + tolerance legs (ISSUE 17): the SL6xx golden bad fixtures
# trip at their declared severities with clean twins, the shipped
# numeric contracts (TSQR, hSVD-L0, ring cmatmul, the quantized
# all-reduce, the kcluster endpoint, the training step) pin
# numcheck-clean, and every golden plan composes to exactly its
# quant.tol annotation under the tolerance invariant.
python -m pytest tests/test_numcheck.py -q "$@"

XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
  python scripts/lint.py --ir-entry 8

# golden-plan determinism + well-formedness: redistribution plans key
# the executor's program cache, so two fresh processes must serialize
# the golden matrix byte-identically (leg 7) — at the flat default AND
# at the forced 2x4/2x8 two-tier topologies (ISSUE 8: tier annotations
# fold into plan_ids, so the tiered dumps must be just as
# deterministic). ISSUE 10 adds the verify_plan sweep over every dumped
# plan (flat/2x4/2x8, quant on+off — redist_plans dumps both): byte
# identity catches nondeterminism, the verifier catches a plan that is
# deterministically MALFORMED (broken composition/conservation/codec
# pairing/tier labels/overlap structure/plan-id) and fails the leg with
# the violated invariant named. ISSUE 11 adds the staged golden plans
# (host-staging window schedules) to every dump: the staging invariant
# (stage pairing, window conservation, depth-2 slab occupancy, lattice
# time model) is proven on each. ISSUE 14 adds the progress invariant
# to the same sweep: a symbolic per-device replay proving every
# participant runs each plan to completion — congruent subgroup
# structure, rings closing in exactly p-1 hops, hierarchical ici/dcn
# lap pairs sharing one chunk, depth-2 lap tags issued in consume
# order — so a dump that would HANG a mesh fails here, not on TPU
plans_a="$(mktemp)"; plans_b="$(mktemp)"
python scripts/redist_plans.py > "$plans_a"
python scripts/redist_plans.py > "$plans_b"
diff "$plans_a" "$plans_b"
python scripts/verify_plans.py "$plans_a"
echo "redist golden plans: deterministic + well-formed ($(wc -l < "$plans_a") plans)"
for topo in 2x4 2x8; do
  python scripts/redist_plans.py --topology "$topo" > "$plans_a"
  python scripts/redist_plans.py --topology "$topo" > "$plans_b"
  diff "$plans_a" "$plans_b"
  python scripts/verify_plans.py --topology "$topo" "$plans_a"
  echo "redist golden plans @$topo: deterministic + well-formed ($(wc -l < "$plans_a") plans)"
done
rm -f "$plans_a" "$plans_b"

# tolerance-budget sweep (ISSUE 17): the standalone check_tolerance
# entry re-proves the pass-6 dynamic invariant over every dumped golden
# plan (flat + both tiered topologies) — each plan's end-to-end error
# bound, recomposed from its recorded per-step tolerances, equals the
# schedule-level quant.tol annotation — and a hand-malformed tol
# annotation fails NAMING the tolerance invariant (verify_plans.py
# gates the same defect; this leg pins the findings-collecting face).
tol_dump="$(mktemp)"
python scripts/redist_plans.py > "$tol_dump"
python scripts/redist_plans.py --topology 2x4 >> "$tol_dump"
python scripts/redist_plans.py --topology 2x8 >> "$tol_dump"
python - "$tol_dump" <<'EOF'
import json, sys
from heat_tpu.analysis.planverify import check_tolerance
n = nq = 0
mutable = None
for line in open(sys.argv[1]):
    name, _, payload = line.strip().partition("\t")
    if not payload:
        continue
    findings = check_tolerance(payload)
    assert not findings, f"{name}: {[str(f) for f in findings]}"
    n += 1
    d = json.loads(payload)
    if d.get("quant"):
        nq += 1
        mutable = mutable or d
assert n and nq, f"swept {n} plans but {nq} quantized"
mutable["quant"]["tol"] = float(mutable["quant"]["tol"]) * 2
bad = check_tolerance(mutable)
assert bad and all(f.rule == "SL605" for f in bad), [str(f) for f in bad]
assert "tol" in str(bad[0]), str(bad[0])
print(f"check_tolerance: {n} plan(s) ({nq} quantized) compose to their "
      "declared budgets; malformed tol names SL605")
EOF
rm -f "$tol_dump"

# calibration legs (ISSUE 16): (30) the escape-hatch parity diff —
# gate unset, gate EMPTY, and a measured profile sitting on disk but
# NOT activated must dump byte-identical plans (the constants era);
# (31) the measured-profile dump: scripts/calibrate.py probes this
# container's real edges on the 8-device CPU mesh, the activated
# profile stamps every plan (calibration annotation + re-keyed
# plan_ids — recalibration is a VISIBLE invalidation), two fresh
# processes agree byte-for-byte, and the verifier sweep accepts the
# stamped dumps from a process WITHOUT the gate (the prices verify_plan
# recomputes from are recorded in the plan, not read from the
# environment); (32) the loop-closure gate: one traced staged run, a
# profile built from that run's own effective bandwidths, and the
# re-judged mean |model_error| must SHRINK vs the constants column —
# the whole point of calibrating
cal_dir="$(mktemp -d)"
python scripts/redist_plans.py > "$cal_dir/unset.txt"
HEAT_TPU_LATTICE_PROFILE= python scripts/redist_plans.py > "$cal_dir/empty.txt"
diff "$cal_dir/unset.txt" "$cal_dir/empty.txt"
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
  python scripts/calibrate.py --out "$cal_dir/profile.json" --bytes $((1<<22)) --repeats 2
python scripts/redist_plans.py > "$cal_dir/inactive.txt"
diff "$cal_dir/unset.txt" "$cal_dir/inactive.txt"
echo "HEAT_TPU_LATTICE_PROFILE unset/empty/inactive: dumps byte-identical"

HEAT_TPU_LATTICE_PROFILE="$cal_dir/profile.json" python scripts/redist_plans.py > "$cal_dir/cal_a.txt"
HEAT_TPU_LATTICE_PROFILE="$cal_dir/profile.json" python scripts/redist_plans.py > "$cal_dir/cal_b.txt"
diff "$cal_dir/cal_a.txt" "$cal_dir/cal_b.txt"
if cmp -s "$cal_dir/unset.txt" "$cal_dir/cal_a.txt"; then
  echo "activated profile did not re-key the golden plans" >&2; exit 1
fi
python scripts/verify_plans.py "$cal_dir/cal_a.txt"
echo "measured-profile dumps: deterministic + re-keyed + well-formed (gate-free verify)"
rm -rf "$cal_dir"

HEAT_TPU_TRACE=1 HEAT_TPU_OOC_SLAB_MB=8 python - <<'EOF'
import numpy as np
import heat_tpu as ht
from heat_tpu.observability import calibration, tracing
from heat_tpu.redistribution import staging

tracing.enable()
host = staging.HostArray(
    np.random.default_rng(0).standard_normal((4096, 4096)).astype(np.float32))
u, _ = ht.linalg.hsvd_rank(host, 8)
u.larray.block_until_ready()
rows = tracing.spans()
pids = [p for p in ((r.get("attrs") or {}).get("plan_id") for r in rows) if p]
assert pids, "no staged plan traced"
# this run's EFFECTIVE per-edge bandwidth (sum bytes / sum seconds)
agg = {}
for r in rows:
    a = r.get("attrs") or {}
    t, nb, d = a.get("tier"), a.get("bytes"), r.get("dur_s")
    if a.get("traced") or t is None or not nb or not d:
        continue
    agg.setdefault(t, [0, 0.0])
    agg[t][0] += nb
    agg[t][1] += d
edges = {t: {"bps": b / s, "method": "spans-effective"}
         for t, (b, s) in agg.items() if s > 0}
assert edges, "no tiered spans measured"
prof = calibration.build_profile(edges, platform="cpu")
rep = calibration.calibration_report(pids[-1], span_rows=rows, profile=prof)
assert rep["n_legs"] > 0, rep
assert rep["improved"], rep
print(f"calibration loop closure: mean |model_error| "
      f"{rep['mean_abs_error_constants']} -> {rep['mean_abs_error_calibrated']} "
      f"over {rep['n_legs']} leg(s), profile {prof['profile_id']}")
EOF

# spmm-kernel legs (ISSUE 18), mirroring the sort/relayout legs: the
# brick SpMM/SDDMM family FORCED onto the Pallas scalar-prefetch
# kernels (interpret mode on CPU) over the sparse + graph suites —
# every workload from raw brick matmuls through the PageRank fixpoint
# and spectral embedding runs kernel-backed against the same oracles;
# and the HEAT_TPU_SPMM_KERNEL=0 escape hatch over the same surface,
# proving the gather-free XLA formulation is bit-identical. (The
# 5-device odd-mesh leg above already replays the sparse suite: it
# runs all of tests/, which includes test_spmm.py/test_graph.py/
# test_sparse.py since this ISSUE.)
HEAT_TPU_SPMM_KERNEL=1 python -m pytest tests/test_spmm.py tests/test_sparse.py tests/test_graph.py -q "$@"

HEAT_TPU_SPMM_KERNEL=0 python -m pytest tests/test_spmm.py tests/test_sparse.py tests/test_graph.py -q "$@"

if [ -f BENCH_DETAIL.json ] && ls BENCH_r*.json >/dev/null 2>&1; then
  # the regex holds every DETERMINISTIC analytic field
  # (model_speedup, tier_model_speedup, stage_model_gbps, ...) to exact
  # equality: a pure refactor — the ISSUE 12 gate-registry move — must
  # prove it shifted zero bench numbers, not just stayed in threshold
  python scripts/bench_compare.py --unchanged-fields 'model|passes_over_A|_algorithmic'
fi
