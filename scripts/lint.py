#!/usr/bin/env python
"""shardlint CLI — the CI face of ``heat_tpu.analysis``.

Two modes, combinable (both run when both are requested; exit status is
the OR of their gates):

Source lint (pass 2)::

    python scripts/lint.py heat_tpu/            # lint the tree
    python scripts/lint.py --json heat_tpu/     # machine-readable

  Walks every ``.py`` file and enforces the repo invariants (SL2xx:
  undeclared ``jax.device_get``, bare ``jax.jit``, unsanitized public
  ops). Exit 1 iff an error-severity finding gates; warnings report
  only.

IR lint (pass 1) over the driver training step::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        python scripts/lint.py --ir-entry 8

  Builds the ``__graft_entry__`` data-parallel training step on an
  N-device mesh and runs ``ht.analysis.check`` on it — the compiled
  train step must launch only the collectives the algorithm needs.
  Exit 1 iff an error-severity finding gates.

Rule catalog: ``heat_tpu.analysis.findings.RULES`` / docs/PERF.md
§ Static analysis. Whitelist workflow: heat_tpu/analysis/boundaries.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _print_report(report, label: str, as_json: bool) -> None:
    if as_json:
        print(json.dumps({"label": label, **report.as_dict()}))
        return
    for f in report.findings:
        where = f"{f.path}:{f.line}: " if f.path else ""
        print(f"{f.severity.upper():7s} {f.rule} {where}{f.message}")
    n_err, n_warn = len(report.errors), len(report.warnings)
    files = report.context.get("files", "")
    scope = f"{files} file(s), " if isinstance(files, int) else (f"{files}: " if files else "")
    print(
        f"[{label}] {scope}"
        f"{n_err} error(s), {n_warn} warning(s) "
        f"-> {'GATE' if n_err else 'ok'}"
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="*", help="files/dirs to source-lint (pass 2)")
    ap.add_argument(
        "--ir-entry",
        type=int,
        metavar="N",
        default=None,
        help="run ht.analysis.check over the __graft_entry__ training step "
        "on an N-device mesh (pass 1)",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line per pass")
    args = ap.parse_args()
    if not args.paths and args.ir_entry is None:
        args.paths = [os.path.join(ROOT, "heat_tpu")]

    gate = False
    if args.paths:
        from heat_tpu.analysis import srclint

        report = srclint.lint_paths(args.paths, root=ROOT)
        _print_report(report, "srclint", args.json)
        gate |= not report.ok

    if args.ir_entry is not None:
        import __graft_entry__ as graft

        import heat_tpu as ht

        fn, example_args = graft.training_step_program(args.ir_entry)
        report = ht.analysis.check(fn, *example_args)
        report.context["files"] = "training_step"
        _print_report(report, f"ircheck@{args.ir_entry}dev", args.json)
        gate |= not report.ok

    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
