#!/usr/bin/env python
"""shardlint CLI — the CI face of ``heat_tpu.analysis``.

Two modes, combinable (both run when both are requested; exit status is
the OR of their gates):

Source lint (pass 2)::

    python scripts/lint.py heat_tpu/            # lint the tree
    python scripts/lint.py --json heat_tpu/     # machine-readable

  Walks every ``.py`` file and enforces the repo invariants (SL2xx:
  undeclared ``jax.device_get``, bare ``jax.jit``, unsanitized public
  ops). Exit 1 iff an error-severity finding gates; warnings report
  only.

Effect lint (pass 4) over the same tree::

    python scripts/lint.py heat_tpu/ --pass effectcheck

  The ``gatecheck``/``racecheck`` rules (SL4xx): gate/cache-key
  staleness against the ``heat_tpu.core.gates`` registry, raw
  ``HEAT_TPU_*`` env reads bypassing it, lock-discipline races in the
  threaded modules, and the depth-2 issue/consume pipeline protocol.

Comm lint (pass 5) over the same tree::

    python scripts/lint.py heat_tpu/ --pass commcheck

  The ``commcheck`` source rule (SL504): executor/dispatcher entry
  points that issue collectives without the ``WorldChangedError``
  epoch fence reachable on entry. (The IR rules SL501–SL503 ride
  ``ht.analysis.check``/``ht.analysis.commcheck``; the plan-side
  ``progress`` invariant rides ``scripts/verify_plans.py``.)

Precision lint (pass 6) over the same tree::

    python scripts/lint.py heat_tpu/ --pass numcheck

  The ``numcheck`` source arm (SL602): every op
  ``numcheck.PLANAR_PRECISION_POLICY`` marks ``"highest"`` must default
  its MXU precision to HIGHEST in ``core/complex_planar.py`` — deleting
  the PR 5 ``precision="highest"`` default is an error here, the
  mechanized form of the 13% on-chip defect. (The IR rules SL601–SL603
  ride ``ht.analysis.check``/``ht.analysis.numcheck``, SL604 rides the
  standalone entry, and the plan-side ``tolerance`` invariant rides
  ``scripts/verify_plans.py``.)

  ``--pass all`` (the default when paths are given) is the single CI
  lint entry (ISSUE 14): passes 2, 4, 5 and 6 run in ONE process with
  one SARIF document per run.

IR lint (pass 1) over the driver training step::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        python scripts/lint.py --ir-entry 8

  Builds the ``__graft_entry__`` data-parallel training step on an
  N-device mesh and runs ``ht.analysis.check`` on it — the compiled
  train step must launch only the collectives the algorithm needs.
  Exit 1 iff an error-severity finding gates.

Output formats (``--format text|json|sarif``; ``--json`` is shorthand):
``text`` (default, one finding per line + a gate summary), ``json``
(one JSON object per pass), ``sarif`` (ONE SARIF 2.1.0 document on
stdout with one run per pass, rule ids = SLxxx — what CI annotation
uploads consume; findings land on their ``file:line`` anchors). Exit
codes are identical across formats: the gate is the findings, not the
rendering.

Rule catalog: ``heat_tpu.analysis.findings.RULES`` / docs/PERF.md
§ Static analysis. Whitelist workflow: heat_tpu/analysis/boundaries.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_SARIF_LEVEL = {"error": "error", "warning": "warning", "info": "note"}


def _print_report(report, label: str, fmt: str) -> None:
    if fmt == "json":
        print(json.dumps({"label": label, **report.as_dict()}))
        return
    if fmt == "sarif":
        return  # rendered once, at the end, over all passes
    for f in report.findings:
        where = f"{f.path}:{f.line}: " if f.path else ""
        print(f"{f.severity.upper():7s} {f.rule} {where}{f.message}")
    n_err, n_warn = len(report.errors), len(report.warnings)
    files = report.context.get("files", "")
    scope = f"{files} file(s), " if isinstance(files, int) else (f"{files}: " if files else "")
    print(
        f"[{label}] {scope}"
        f"{n_err} error(s), {n_warn} warning(s) "
        f"-> {'GATE' if n_err else 'ok'}"
    )


def _sarif_run(report, label: str) -> dict:
    """One SARIF run per analyzer pass: the tool is shardlint/<pass>,
    its rules are the SLxxx catalog entries the pass fired."""
    from heat_tpu.analysis.findings import RULES

    fired = sorted({f.rule for f in report.findings})
    results = []
    for f in report.findings:
        res = {
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
        }
        if f.path:
            region = {"startLine": int(f.line)} if f.line else {}
            res["locations"] = [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path.replace(os.sep, "/")},
                        **({"region": region} if region else {}),
                    }
                }
            ]
        results.append(res)
    return {
        "tool": {
            "driver": {
                "name": f"shardlint/{label}",
                "informationUri": "docs/PERF.md",
                "rules": [
                    {
                        "id": rule,
                        "shortDescription": {"text": RULES.get(rule, rule)},
                    }
                    for rule in fired
                ],
            }
        },
        "results": results,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="*", help="files/dirs to source-lint (pass 2)")
    ap.add_argument(
        "--ir-entry",
        type=int,
        metavar="N",
        default=None,
        help="run ht.analysis.check over the __graft_entry__ training step "
        "on an N-device mesh (pass 1)",
    )
    ap.add_argument(
        "--pass",
        dest="which",
        choices=("srclint", "effectcheck", "commcheck", "numcheck", "all"),
        default="all",
        help="which source passes to run over the given paths: pass 2 "
        "(srclint, SL2xx), pass 4 (effectcheck, SL4xx: gate/cache-key "
        "staleness, raw gate reads, lock discipline, pipeline protocol), "
        "pass 5 (commcheck, SL504: unfenced dispatch entries), pass 6 "
        "(numcheck, SL602: the planar precision policy), or all four in "
        "ONE process — the single CI lint entry (default; one SARIF "
        "document with one run per pass)",
    )
    ap.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default=None,
        help="output format (default text; sarif = one SARIF 2.1.0 doc, "
        "one run per pass, for CI file annotations)",
    )
    ap.add_argument(
        "--json", action="store_true", help="shorthand for --format json"
    )
    args = ap.parse_args()
    fmt = args.format or ("json" if args.json else "text")
    if not args.paths and args.ir_entry is None:
        args.paths = [os.path.join(ROOT, "heat_tpu")]

    gate = False
    reports = []
    if args.paths and args.which in ("srclint", "all"):
        from heat_tpu.analysis import srclint

        report = srclint.lint_paths(args.paths, root=ROOT)
        _print_report(report, "srclint", fmt)
        reports.append(("srclint", report))
        gate |= not report.ok

    if args.paths and args.which in ("effectcheck", "all"):
        from heat_tpu.analysis import effectcheck

        report = effectcheck.lint_paths(args.paths, root=ROOT)
        _print_report(report, "effectcheck", fmt)
        reports.append(("effectcheck", report))
        gate |= not report.ok

    if args.paths and args.which in ("commcheck", "all"):
        from heat_tpu.analysis.commcheck import lint_paths as _commcheck_paths

        report = _commcheck_paths(args.paths, root=ROOT)
        _print_report(report, "commcheck", fmt)
        reports.append(("commcheck", report))
        gate |= not report.ok

    if args.paths and args.which in ("numcheck", "all"):
        from heat_tpu.analysis.numcheck import lint_paths as _numcheck_paths

        report = _numcheck_paths(args.paths, root=ROOT)
        _print_report(report, "numcheck", fmt)
        reports.append(("numcheck", report))
        gate |= not report.ok

    if args.ir_entry is not None:
        import __graft_entry__ as graft

        import heat_tpu as ht

        fn, example_args = graft.training_step_program(args.ir_entry)
        report = ht.analysis.check(fn, *example_args)
        report.context["files"] = "training_step"
        _print_report(report, f"ircheck@{args.ir_entry}dev", fmt)
        reports.append((f"ircheck@{args.ir_entry}dev", report))
        gate |= not report.ok

    if fmt == "sarif":
        doc = {
            "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json",
            "version": "2.1.0",
            "runs": [_sarif_run(report, label) for label, report in reports],
        }
        print(json.dumps(doc, indent=2))

    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
