#!/usr/bin/env python
"""Serving warmup CLI — pre-compile and export the declared program set.

A fleet rollout runs this ONCE per (jax version, heat_tpu version,
platform, device count, env-gate combination) and ships the resulting
cache directory with the image; every serving replica then cold-starts
load-not-compile (``heat_tpu.serving.aot_cache``). The declared set is
``heat_tpu.serving.WARMUP_PROGRAMS`` — estimator predict programs at
their bucket shapes plus the representative ``ht.jit`` pipeline.

Usage::

    python scripts/warmup.py --cache-dir /var/cache/heat_tpu
    python scripts/warmup.py --list
    python scripts/warmup.py --cache-dir DIR --programs kcluster_predict
    python scripts/warmup.py --cache-dir DIR --expect-hits   # reload smoke

``--expect-hits`` exits nonzero unless EVERY declared program came back
from the store (the cross-process cache-hit proof the CI serving leg
pins: a fresh process compiles 0 programs).

Exit code 0 on success; one JSON summary line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--cache-dir", default=None,
                    help="store root (default: HEAT_TPU_SERVING_CACHE or ~/.cache/heat_tpu/aot)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of the declared set (default: all)")
    ap.add_argument("--list", action="store_true", help="list the declared set and exit")
    ap.add_argument("--expect-hits", action="store_true",
                    help="exit 1 unless every program loaded from the store (reload smoke)")
    args = ap.parse_args()

    # gate resolution must happen before the heat_tpu import
    os.environ.setdefault("HEAT_TPU_SERVING_AOT", "1")
    if args.cache_dir:
        os.environ["HEAT_TPU_SERVING_CACHE"] = args.cache_dir

    import heat_tpu as ht

    if args.list:
        print(json.dumps({"programs": sorted(ht.serving.WARMUP_PROGRAMS)}))
        return 0

    if not ht.serving.enabled():
        print(json.dumps({"error": "serving AOT cache disabled (HEAT_TPU_SERVING_AOT=0?)"}))
        return 1

    names = args.programs.split(",") if args.programs else None
    results = ht.serving.warmup(names)
    store = ht.serving.active_store()
    statuses = [s for v in results.values() for s in v["variants"].values()]
    summary = {
        "cache_dir": store.root,
        "programs": results,
        "stats": store.stats,
        "entries": len(store.entries()),
        "all_hits": bool(statuses) and all(s == "hit" for s in statuses),
    }
    print(json.dumps(summary))
    if args.expect_hits and not summary["all_hits"]:
        print("[warmup] --expect-hits: at least one program was not served "
              "from the store", file=sys.stderr)
        return 1
    if not statuses or any(s in ("off", "bypass") for s in statuses):
        print("[warmup] warning: some programs bypassed the store", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
