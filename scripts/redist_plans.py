#!/usr/bin/env python
"""Dump the canonical serialization of every golden redistribution plan.

The ci.sh determinism leg runs this twice and diffs the output: plans
key the executor's program cache (``plan_id`` = sha1 of the canonical
serialization), so they must be byte-identical run-to-run — any
nondeterminism in the planner (dict ordering, float formatting,
environment leakage) shows up here as a diff before it can show up as a
phantom cache miss or a flapping golden test. The ISSUE-6 ``overlap``
annotation (pipe tags per step, per-group critical-path model,
``model_speedup``) is part of the canonical serialization, so the
determinism leg covers the annotated plans and their plan_ids — and the
annotation is gate-independent (``HEAT_TPU_REDIST_OVERLAP`` switches
the executor's issue order, never the plan), so an ambient gate cannot
make two runs diverge either.

ISSUE 7: every golden spec is dumped TWICE — the full-width plan
(``quant="0"``) and the forced-int8 plan (``quant="int8"``, suffixed
``.quant``) — both pinned explicitly, so the quant-annotated plan_ids
are covered by the determinism diff and an ambient ``HEAT_TPU_WIRE_QUANT``
cannot make two CI runs diverge.

Pure Python: no mesh, no jax device work — safe on any container.
"""

import sys

from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from heat_tpu.redistribution import planner

    # the default budget and codec, pinned explicitly so an ambient
    # HEAT_TPU_REDIST_BUDGET_MB / HEAT_TPU_WIRE_QUANT cannot make two
    # CI runs diverge
    budget = planner.DEFAULT_BUDGET_MB << 20
    for name, spec in planner.golden_specs():
        sched = planner.plan(spec, budget, quant="0")
        print(f"{name}\t{sched.canonical_json()}")
    for name, spec in planner.golden_specs():
        sched = planner.plan(spec, budget, quant="int8")
        print(f"{name}.quant\t{sched.canonical_json()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
