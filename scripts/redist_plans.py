#!/usr/bin/env python
"""Dump the canonical serialization of every golden redistribution plan.

The ci.sh determinism leg runs this twice and diffs the output: plans
key the executor's program cache (``plan_id`` = sha1 of the canonical
serialization), so they must be byte-identical run-to-run — any
nondeterminism in the planner (dict ordering, float formatting,
environment leakage) shows up here as a diff before it can show up as a
phantom cache miss or a flapping golden test. The ISSUE-6 ``overlap``
annotation (pipe tags per step, per-group critical-path model,
``model_speedup``) is part of the canonical serialization, so the
determinism leg covers the annotated plans and their plan_ids — and the
annotation is gate-independent (``HEAT_TPU_REDIST_OVERLAP`` switches
the executor's issue order, never the plan), so an ambient gate cannot
make two runs diverge either.

ISSUE 7: every golden spec is dumped TWICE — the full-width plan
(``quant="0"``) and the forced-int8 plan (``quant="int8"``, suffixed
``.quant``) — both pinned explicitly, so the quant-annotated plan_ids
are covered by the determinism diff and an ambient ``HEAT_TPU_WIRE_QUANT``
cannot make two CI runs diverge.

ISSUE 8: ``--topology SxC`` dumps the golden matrix planned at a forced
two-tier topology (suffix ``@SxC``). The ci.sh determinism leg runs the
dump twice at the DEFAULT (flat — pinned explicitly, so an ambient
``HEAT_TPU_TOPOLOGY`` cannot make runs diverge) and twice at ``2x8``,
diffing both pairs: tiered plan_ids differ from flat ones only via the
tier/topology annotations, and both must be byte-identical run-to-run.

Pure Python: no mesh, no jax device work — safe on any container.
"""

import argparse
import sys

from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument(
        "--topology",
        default=None,
        help="force a two-tier topology (e.g. 2x8) for every golden plan; "
        "default: flat (pinned — NOT the ambient HEAT_TPU_TOPOLOGY)",
    )
    args = ap.parse_args()

    from heat_tpu.redistribution import planner

    # the default budget / codec / topology, pinned explicitly so an
    # ambient HEAT_TPU_REDIST_BUDGET_MB / HEAT_TPU_WIRE_QUANT /
    # HEAT_TPU_TOPOLOGY cannot make two CI runs diverge
    budget = planner.DEFAULT_BUDGET_MB << 20
    topology = args.topology if args.topology else "flat"
    suffix = f"@{args.topology}" if args.topology else ""
    for name, spec in planner.golden_specs():
        sched = planner.plan(spec, budget, quant="0", topology=topology)
        print(f"{name}{suffix}\t{sched.canonical_json()}")
    for name, spec in planner.golden_specs():
        sched = planner.plan(spec, budget, quant="int8", topology=topology)
        print(f"{name}.quant{suffix}\t{sched.canonical_json()}")

    # ISSUE 11: the out-of-core staged golden plans ride the same
    # determinism + verify_plan sweep. Slab/working-set bytes are pinned
    # inside golden_staged_plans (NOT the ambient HEAT_TPU_OOC* env),
    # and host-staging plans are topology-free (mesh_size 1, no
    # collectives), so the tiered dump rows are identical to the flat
    # ones by construction — dumped in every topology run so each diff
    # pair covers them.
    from heat_tpu.redistribution import staging

    for name, sched in staging.golden_staged_plans():
        print(f"{name}{suffix}\t{sched.canonical_json()}")

    # ISSUE 19: the dense-factorization ring schedules ride the same
    # determinism + verify_plan sweep. Shapes/budget are pinned inside
    # golden_factorization_plans (NOT the ambient env), and the plans
    # are pure ppermute rings over a flat split-0 mesh — topology-free
    # like the staged plans — so the tiered dump rows are identical to
    # the flat ones by construction; dumped in every topology run so
    # each diff pair covers them.
    from heat_tpu.core.linalg.factorizations import golden_factorization_plans

    for name, sched in golden_factorization_plans():
        print(f"{name}{suffix}\t{sched.canonical_json()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
