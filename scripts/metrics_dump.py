#!/usr/bin/env python
"""Metrics/trace dump CLI (ISSUE 15) — the exposition surface for a
process that has no HTTP endpoint of its own.

Three output forms over one small workload (or an importing caller's
already-live registry when ``--no-workload``):

- default: Prometheus text format (``ht.observability.prometheus_text``)
  — registry counters as ``_total``, timers as summaries with
  p50/p95/p99 quantile labels, event-ring + flight-recorder health
  (``heat_tpu_flight_dropped_total``), per-leg attribution
  ``model_error`` gauges (ISSUE 16 — the built-in workload performs
  one fenced attribution join so they render), and per-dispatcher
  gauges when the serving layer is live;
- ``--json``: the raw ``telemetry.snapshot()`` (counters, timers, event
  ring metadata) as one JSON document;
- ``--trace PATH``: additionally export the span buffer as Chrome
  trace-event JSON (``ht.observability.export_trace``), loadable in
  Perfetto/chrome://tracing.

The built-in workload runs one planned redistribution with telemetry +
tracing enabled, so the smoke leg exercises the whole pipeline: spans
recorded -> counters rendered -> trace exported. Exit 0 iff every
requested output was produced and parses.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _workload() -> None:
    """One planned redistribution + a tiny reduction: enough to light up
    op/program counters, redistribution spans, the event ring — and,
    ISSUE 16, one fenced attribution join so the per-leg
    ``model_error`` gauges render in the exposition below."""
    import time

    import heat_tpu as ht
    from heat_tpu.observability import tracing

    x = ht.arange(4096, split=0).astype(ht.float32)
    plan = ht.redistribution.explain(x.reshape((64, 64)), 1)
    t0 = time.perf_counter()
    y = x.reshape((64, 64)).resplit(1)
    ht.sum(y).numpy()
    tracing.add_span(
        "metrics.execute", t0, time.perf_counter(),
        plan_id=plan.plan_id, step="execute", fenced=True,
    )
    ht.observability.attribution(plan)  # populates last_reports()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="emit telemetry.snapshot() JSON instead of Prometheus text")
    ap.add_argument("--trace", metavar="PATH",
                    help="also export the span buffer as Chrome-trace JSON to PATH")
    ap.add_argument("--no-workload", action="store_true",
                    help="dump whatever is already collected; run nothing")
    args = ap.parse_args()

    from heat_tpu.observability import telemetry, tracing
    import heat_tpu.observability as obs

    if not args.no_workload:
        telemetry.enable()  # tracing follows at HEAT_TPU_TRACE=auto
        _workload()

    if args.json:
        print(json.dumps(telemetry.snapshot(), indent=1, sort_keys=True, default=str))
    else:
        sys.stdout.write(obs.prometheus_text())

    if args.trace:
        n = obs.export_trace(args.trace)
        with open(args.trace) as f:
            doc = json.load(f)  # must round-trip as valid JSON
        if doc.get("traceEvents") is None or len(doc["traceEvents"]) != n:
            raise SystemExit(
                f"trace export mismatch: {args.trace} holds "
                f"{len(doc.get('traceEvents') or [])} events, expected {n}"
            )
        print(f"# trace: {n} events -> {args.trace} "
              f"({len(tracing.spans())} spans, dropped={tracing.dropped()})",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
