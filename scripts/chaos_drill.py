#!/usr/bin/env python
"""Chaos CI drill (ISSUE 13): kill a simulated slice mid-``fit`` and
prove recovery end to end.

One seeded run, five asserted facts:

1. **Detection** — the declared slice loss fires as a typed
   ``WorldChangedError`` mid-stream (never a hang), and the world
   re-resolves onto the survivors (8 -> 4 devices at the 2x4 topology;
   5 -> 3 on the odd mesh).
2. **Serving failover** — the live dispatcher's queued requests resolve
   as ``ServingOverloaded(reason="resize")`` (the fail-over contract;
   the in-flight batch COMPLETES), submits during the drain are
   rejected with the same reason, and the endpoint re-warms against the
   new world and serves again.
3. **Cache rekey** — the epoch bumps and the plan/program/jit caches
   are swept.
4. **Bit-reproducible resume** — the checkpoint-resumed ``fit`` (which
   also survives a chaos-truncated newest envelope by falling back to
   the committed predecessor) produces centers bit-identical to an
   uninterrupted same-seed run on the ORIGINAL world.
5. **Flight-recorder post-mortem** (ISSUE 15) — the always-on flight
   ring recorded the injected kill at its declared step, the
   ``WorldChangedError`` carries that tail (``e.flight_tail``), and the
   chaos truncation landed in the ring too — the post-mortem is inside
   the exception, not in scrollback.

Run under both CI meshes::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        HEAT_TPU_RESILIENCE=1 python scripts/chaos_drill.py
    XLA_FLAGS=--xla_force_host_platform_device_count=5 JAX_PLATFORMS=cpu \\
        HEAT_TPU_RESILIENCE=1 python scripts/chaos_drill.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("HEAT_TPU_OOC_SLAB_MB", "1")  # multi-window stream

import numpy as np  # noqa: E402

import jax  # noqa: E402

import heat_tpu as ht  # noqa: E402
from heat_tpu.redistribution import staging  # noqa: E402
from heat_tpu.resilience import chaos, checkpoint as ck, elastic  # noqa: E402
from heat_tpu.serving.admission import ServingOverloaded  # noqa: E402
from heat_tpu.serving.dispatcher import Dispatcher, Endpoint  # noqa: E402

KILL_STEP = 2
SEED = 11


def main() -> int:
    n_dev = len(jax.devices())
    topology = "2x4" if n_dev == 8 else None  # odd meshes: flat, kill half
    rng = np.random.default_rng(0)
    pts = rng.standard_normal((40960, 16)).astype(np.float32)
    host = staging.HostArray(pts)
    wins = staging.window_extents(host.shape, 4, 0, staging.slab_bytes())
    assert len(wins) >= 4, f"drill needs a multi-window stream, got {len(wins)}"

    # --- uninterrupted reference on the full world ------------------- #
    km_ref = ht.cluster.KMeans(n_clusters=4, init="random", random_state=SEED)
    km_ref.fit(host)
    ref_bits = np.asarray(km_ref.cluster_centers_.numpy()).view(np.uint32)

    # --- the chaos run ------------------------------------------------ #
    report = {"devices": n_dev, "windows": len(wins), "topology": topology or "flat"}
    with tempfile.TemporaryDirectory(prefix="ht-chaos-") as d:
        cfg = ck.CheckpointConfig(directory=d, tag="drill", every=1)
        monkey = (
            chaos.ChaosMonkey(seed=3)
            .kill_slice(step=KILL_STEP)
            .truncate_checkpoint(step=KILL_STEP + 1)
        )
        watcher = monkey.watcher(topology=topology)

        # a live serving dispatcher with a parked worker so requests are
        # provably QUEUED when the drain fires (the place hook blocks
        # the worker inside the batch it already collected)
        gate = threading.Event()
        entered = threading.Event()

        def blocking_place(batch):
            entered.set()
            gate.wait(30)
            import jax.numpy as jnp

            return jnp.asarray(batch)

        ep = Endpoint(
            {8: jax.jit(lambda b: b * 2.0)}, (16,), np.float32, place=blocking_place
        )
        disp = Dispatcher(ep, max_queue=32, poll_s=0.005).start()
        inflight = disp.submit(np.ones((2, 16), np.float32))
        assert entered.wait(10), "worker never started the in-flight batch"
        # enqueued only once the worker is provably INSIDE the blocked
        # batch — these can only be served by a later batch or shed
        queued = [disp.submit(np.ones((1, 16), np.float32)) for _ in range(6)]

        km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=SEED)
        epoch_before = elastic.world_epoch()
        try:
            km.fit(host, ckpt=cfg, _watcher=watcher, _chaos=monkey)
            raise AssertionError("declared slice kill never fired")
        except elastic.WorldChangedError as e:
            report["detected"] = str(e)
            # ISSUE 15: the error is its own post-mortem — the flight
            # tail it carries must contain the injected kill at its
            # declared step
            tail = getattr(e, "flight_tail", None)
            assert tail, "WorldChangedError carries no flight-recorder tail"
            kills = [r for r in tail
                     if r["kind"] == "chaos.slice-lost" and r["value"] == KILL_STEP]
            assert kills, (
                f"flight tail is missing the injected kill at step {KILL_STEP}: "
                f"{[(r['kind'], r['value']) for r in tail]}"
            )
            report["flight_tail_kill"] = kills[-1]

        # serving side: fence + shed typed, reject during drain. The
        # drain is ARMED while the worker is still inside the blocked
        # in-flight batch (so the 6 queued requests are provably still
        # queued), then the batch is released: the worker fences it —
        # its future RESOLVES — and sheds the backlog typed.
        drained = []
        drain_t = threading.Thread(
            target=lambda: drained.append(disp.drain(reason="resize", timeout=30))
        )
        drain_t.start()
        gate.set()  # release the in-flight batch so the fence can pass
        drain_t.join(35)
        assert drained and drained[0], "drain timed out"
        np.testing.assert_allclose(np.asarray(inflight.result(1)), 2.0)
        shed = 0
        for f in queued:
            try:
                f.result(1)
            except ServingOverloaded as exc:
                assert exc.reason == "resize", exc.reason
                shed += 1
        assert shed >= 1, "no queued request was shed typed"
        try:
            disp.submit(np.ones((1, 16), np.float32))
            raise AssertionError("submit during drain must be rejected")
        except ServingOverloaded as exc:
            assert exc.reason == "resize", exc.reason
        report["shed_typed"] = shed

        # rekey: re-resolve onto the survivors, bump + sweep
        new_comm = elastic.resolve_world(watcher.devices())
        counts = elastic.invalidate_caches("resize")
        assert elastic.world_epoch() == epoch_before + 1
        report["survivors"] = new_comm.size
        report["evicted"] = counts
        assert new_comm.size < n_dev

        # re-warm the endpoint against the new world and serve again
        ep2 = Endpoint({8: jax.jit(lambda b: b * 2.0)}, (16,), np.float32)
        disp.resume(endpoint=ep2)
        np.testing.assert_allclose(
            np.asarray(disp.call(np.ones((2, 16), np.float32), timeout=30)), 2.0
        )
        disp.stop()

        # resume: the truncated newest envelope must fall back, and the
        # resumed run must reproduce the uninterrupted bits exactly
        steps_before = ck.list_steps(d, "drill")
        km.fit(host, ckpt=cfg)
        got_bits = np.asarray(km.cluster_centers_.numpy()).view(np.uint32)
        assert np.array_equal(ref_bits, got_bits), (
            "resumed centers differ from the uninterrupted run"
        )
        report["resumed_from_steps"] = steps_before
        report["chaos_log"] = monkey.log
        report["bit_identical"] = True
        truncated = [e for e in monkey.log if e["kind"] == "truncate-ckpt"]
        assert truncated, "the declared checkpoint truncation never fired"
        # the truncation must be in the flight ring too (fire-time
        # breadcrumb next to the kill, for post-mortems with no error)
        from heat_tpu.observability import tracing as _tracing

        flight = _tracing.flight_tail(_tracing.flight_capacity())
        assert any(r["kind"] == "chaos.truncate" for r in flight), (
            "flight ring is missing the chaos truncation record"
        )
        report["flight_records"] = sorted({r["kind"] for r in flight})

    print(json.dumps({"chaos_drill": "ok", **report}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
