#!/usr/bin/env python
"""Sweep ``ht.analysis.verify_plan`` over dumped golden plans.

The ci.sh determinism leg already proves the golden plan dumps
(``scripts/redist_plans.py``: flat / 2x4 / 2x8, quant on+off, staged)
are byte-identical run-to-run; this script proves each dumped plan is
WELL-FORMED — composition, byte conservation, codec pairing, tier
labels, overlap structure, plan-id integrity, and (ISSUE 14) the
``progress`` invariant: a symbolic per-device replay proving every
participant runs the schedule to completion — congruent group
structure, rings closing in exactly p-1 hops, hierarchical ici/dcn
lap pairs sharing one chunk, depth-2 lap tags issued in exactly the
order the double buffer consumes them — and (ISSUE 17) the
``tolerance`` invariant: the end-to-end error bound recomputed from
the recorded per-step tolerances (each quantize step contributes the
codec's pinned ``tolerance(mode)`` to the disjoint payload leg it
encodes; staging/relayout/overlap steps are exact-bit; hierarchical
plans charge only dcn-tier legs) must equal the schedule-level
``quant.tol`` annotation. A malformed plan fails the leg with the
violated invariant named (tests/test_commcheck.py proves a
hand-mutated lap order fails here naming ``progress``;
tests/test_numcheck.py proves ≥6 seeded tolerance mutations fail
naming ``tolerance`` with the step)::

    python scripts/redist_plans.py > plans.txt
    python scripts/verify_plans.py plans.txt
    python scripts/redist_plans.py --topology 2x8 > plans28.txt
    python scripts/verify_plans.py --topology 2x8 plans28.txt

Input lines are ``name\\tcanonical_json`` (the dump format). With no
file arguments the dump is read from stdin. Pure Python — no mesh, no
jax device work — like the dump itself.
"""

from __future__ import annotations

import argparse
import sys

from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("files", nargs="*", help="plan dump files (default: stdin)")
    ap.add_argument(
        "--topology",
        default=None,
        help="expected topology of the dump ('flat' or 'SxC' — the value "
        "the dump was produced with); default: self-consistency only",
    )
    args = ap.parse_args()

    from heat_tpu.analysis.planverify import PlanVerificationError, verify_plan

    streams = [open(f) for f in args.files] if args.files else [sys.stdin]
    n = 0
    failed = False
    for stream in streams:
        for line in stream:
            line = line.strip()
            if not line:
                continue
            name, _, payload = line.partition("\t")
            if not payload:
                print(f"verify_plans: malformed dump line {name[:60]!r}", file=sys.stderr)
                failed = True
                continue
            try:
                res = verify_plan(payload, topology=args.topology)
            except PlanVerificationError as e:
                print(f"FAIL  {name}: {e}")
                failed = True
                continue
            n += 1
            print(f"ok    {name}  ({res['strategy']}, plan {res['plan_id']})")
    for stream in streams:
        if stream is not sys.stdin:
            stream.close()
    if failed:
        return 1
    if not n:
        print("verify_plans: no plans verified (empty input)", file=sys.stderr)
        return 1
    print(f"verify_plans: {n} plan(s) well-formed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
