#!/usr/bin/env python
"""Bench regression gate.

VERDICT r5 caught an attention-MFU regression (0.68 -> 0.58 run-over-run)
that nothing in the repo flagged: bench.py checks each run against
PHYSICAL bounds, but nothing compared a run against the PREVIOUS run.
This script closes that gap: it diffs the current bench record
(``BENCH_DETAIL.json``) against a baseline (default: the highest-numbered
``BENCH_r*.json`` driver artifact in the repo root), flags every shared
metric that moved more than ``--threshold`` (default 10%) in the BAD
direction without a ``measurement_suspect`` marker on either side, and
emits ONE machine-readable verdict line plus ``BENCH_COMPARE.json`` —
so a perf regression is caught at PR time instead of by the round judge.
Rows only one side knows about never gate or crash the diff: a
benchmark new in the current record reports as ``new_row``, one the
baseline had but the current run dropped as ``missing_row``.

Exit code is 0 unless ``--strict`` is given and an unflagged regression
was found (CI runs report-only; a bench-carrying PR should run
``--strict``).

Usage::

    python scripts/bench_compare.py                 # auto-pick files
    python scripts/bench_compare.py --strict        # gate (nonzero exit)
    python scripts/bench_compare.py --baseline BENCH_r04.json --threshold 0.15
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# metric -> direction. Rows/fields not listed are informational and
# never gate (method strings, passes_over_A, ordering_ok, ...).
HIGHER_IS_BETTER = {
    "value",
    "vs_baseline",
    "vs_torch_svd_lowrank",
    "mfu",
    "tflops",
    "gbps",
    # `hbm_frac` gates the ROADMAP reshape acceptance fields too
    # (ISSUE 5): `reshape_split1_1gb.hbm_frac` and the lane-friendly
    # companion `reshape_lane_1gb.hbm_frac` ride in the compact
    # key_rows, so driver artifacts carry them round over round (the
    # string-valued `path`/`strategy` fields are informational)
    "hbm_frac",
    "hbm_frac_algorithmic",
    "iter_per_s",
    "projected_iter_per_s_1Bx64_v5e64",
    "melem_per_s",
    "speedup_vs_torch_cpu",
    "speedup_vs_torch_svd_lowrank",
    # sort-row acceptance fields (ISSUE 4): public fused sort vs raw
    # values-only jnp.sort, and achieved fraction of the dispatched
    # path's pass-count HBM model (heat_tpu.kernels.sort.sort_plan)
    "vs_jnp_sort",
    "sort_frac",
    # overlap acceptance fields (ISSUE 6) on the redistribution rows:
    # `critical_path_model` is the planner's modeled max-vs-sum speedup
    # of the pipelined stage groups, `vs_sequential` the measured
    # same-run ratio against the HEAT_TPU_REDIST_OVERLAP=0 twin — both
    # ride in the compact key_rows so driver artifacts gate them
    "critical_path_model",
    "vs_sequential",
    # wire-quantization acceptance field (ISSUE 7): the analytic
    # v5e-64 quantized-gradient DP model's step-time speedup
    # (dp_step_quant row; tests pin >= 1.5x on ICI-bound layers)
    "dp_model_speedup",
    # two-tier acceptance field (ISSUE 8): hierarchical-vs-flat modeled
    # speedup of the `*_2x8_dcn` rows (tests pin >= 2x; dp_step_quant_2x8
    # reuses dp_model_speedup)
    "tier_model_speedup",
    # serving acceptance fields (ISSUE 9): sustained micro-batched QPS
    # (serving_qps row) and the fresh-process AOT-load-vs-compile ratio
    # (serving_coldstart row, target >= 10x on TPU rounds)
    "qps",
    "coldstart_speedup",
    # out-of-core staging acceptance fields (ISSUE 11) on the
    # `*_hostram`/`kmeans_stream_2gb` rows: achieved fraction of the
    # depth-2 staging bound (tests pin >= 0.5; ~1.0 on real PCIe DMA),
    # the analytic lattice throughput of the 20 GB scenario, and the
    # measured streamed GB/s (`gbps` above covers the measured rows)
    "stage_bw_frac",
    "stage_model_gbps",
    "rows_per_s",
    # resilience acceptance fields (ISSUE 13) on the ckpt_write_2gb
    # row: durable slab-streamed commit throughput and its fraction of
    # the lattice's host->disk durable-commit bound (floor 0.5 pinned)
    "write_gbps",
    "bound_frac",
    # dense-factorization acceptance field (ISSUE 19): the solver's
    # flop rate over the SAME-RUN reference GEMM's rate (polar_2gb's
    # floor is 0.5 — the bare GEMM is the ceiling by construction; the
    # polar_2gb/eig_2gb `mfu` fields gate via `mfu` above, and the
    # analytic 200 GB v5e-64 `model_*` fields hard-gate via ci.sh's
    # --unchanged-fields sweep like every other analytic model output)
    "frac_of_matmul",
    # sparse-engine acceptance fields (ISSUE 18): spmm_1gb's achieved
    # fraction of the lattice's nnz-weighted wire-mass floor (>= 0.5
    # pinned on CPU) and its same-run dense-matmul-twin ratio; the
    # pagerank_2m scenario's edge throughput (`gbps` above covers the
    # nnz-bandwidth figure itself)
    "nnz_bw_frac",
    "vs_dense_matmul",
    "edges_per_s",
}

# rows that changed name across rounds: a baseline row under the old
# name gates against the current row under the new one (PR 4 folded the
# legacy `reshape` detail row — which still carried the pre-planner
# 0.084 hbm_frac in old artifacts — into the planner-attributed
# `reshape_split1_1gb` row; both always measured the same workload)
ROW_RENAMES = {"reshape": "reshape_split1_1gb"}
LOWER_IS_BETTER = {
    "seconds",
    "seconds_unrounded",
    "eager_wallclock_s",
    "overhead_vs_raw_jnp",
    "overhead_vs_fused_jnp",
    # the kernel-ring wrapper cost relative to bare splash: growth is a
    # real regression (bench.py flags <0.9 samples as weather)
    "vs_splash_row",
    # ISSUE 7: encoded/raw wire bytes of the executing plan on the
    # gated redistribution rows (and the dp_step_quant model row) —
    # a ratio drifting back toward 1.0 means the codec disengaged
    "wire_ratio",
    # ISSUE 8: per-device bytes the tiered plans route over the
    # expensive tier — growth means movement regressed onto DCN
    "dcn_bytes",
    # ISSUE 9: per-request p95 latency of the serving_qps row
    "p95_s",
    # ISSUE 10: memcheck's static per-device peak-HBM estimate of the
    # gated redistribution programs (ht.analysis.memcheck) — growth
    # means a planner/executor change inflated the live set, caught
    # pre-TPU (the xla_* cross-check fields are informational: the
    # compiler's buffer assignment moves with XLA versions)
    "static_peak_bytes",
    # ISSUE 13: the recovery_resume row's detect→drain→rekey→restore
    # wall-clock (and the resumed replay) — growth means the failover
    # control plane slowed down
    "recovery_s",
    "resume_s",
    # ISSUE 16: mean |model_error| over an attribution-carrying row's
    # priced legs — growth means the cost model's fidelity regressed;
    # the calibrated column's mean must land at or below the constants
    # figure (the ci.sh calibration leg's shrinkage gate)
    "mean_abs_model_error",
    "mean_abs_calibrated_error",
    # ISSUE 19: cholesky_2gb's measured seconds over its matmul-count
    # time model (n³/3 flops at the same-run reference GEMM rate) —
    # the acceptance bound is <= 2.0; growth means the ring-lookahead
    # pipeline regressed against the matmuls it is made of
    "vs_matmul_count",
    # ISSUE 18: pagerank_2m's iterations-to-tol — deterministic for the
    # seeded graph, so growth means an engine numerics change slowed
    # the fixpoint, not weather
    "iterations",
}


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _rows_of(record: dict) -> dict:
    """Normalize any of the three record shapes to {row: {field: num}}.

    - BENCH_DETAIL.json: {"detail": {row: {...}}, "value": ...}
    - driver BENCH_r0N.json: {"parsed": <compact line>} with
      parsed.key_rows
    - a compact line itself: {"key_rows": {...}, "value": ...}
    """
    if "parsed" in record and isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    rows = {}
    if isinstance(record.get("detail"), dict):
        rows.update({k: dict(v) for k, v in record["detail"].items()})
    elif isinstance(record.get("key_rows"), dict):
        rows.update({k: dict(v) for k, v in record["key_rows"].items()})
    if isinstance(record.get("value"), (int, float)):
        rows["_headline"] = {"value": record["value"]}
    return rows


def _latest_round_artifact() -> str | None:
    best, best_n = None, -1
    for path in glob.glob(os.path.join(ROOT, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m and int(m.group(1)) > best_n:
            best, best_n = path, int(m.group(1))
    return best


def compare(current: dict, baseline: dict, threshold: float) -> dict:
    cur_rows, base_rows = _rows_of(current), _rows_of(baseline)
    # rename handling: re-key baseline rows whose name the bench retired,
    # unless the baseline already carries the new name too
    for old, new in ROW_RENAMES.items():
        if old in base_rows and new not in base_rows:
            base_rows[new] = base_rows.pop(old)
        if old in cur_rows and new not in cur_rows:
            cur_rows[new] = cur_rows.pop(old)
    regressions, improvements, compared = [], [], 0
    # rows only one side knows about never gate: a brand-new benchmark
    # (in BENCH_DETAIL.json but not yet in any BENCH_r*.json artifact)
    # is reported as new_row — it has no baseline to regress against —
    # and a row the baseline had but the current run dropped is
    # missing_row (usually a renamed bench; worth eyes, not a gate)
    new_rows = sorted(set(cur_rows) - set(base_rows))
    missing_rows = sorted(set(base_rows) - set(cur_rows))
    for row, base_fields in sorted(base_rows.items()):
        cur_fields = cur_rows.get(row)
        if cur_fields is None:
            continue
        suspect = bool(
            cur_fields.get("measurement_suspect") or base_fields.get("measurement_suspect")
        )
        for field, base_val in sorted(base_fields.items()):
            if field in HIGHER_IS_BETTER:
                sign = 1.0
            elif field in LOWER_IS_BETTER:
                sign = -1.0
            else:
                continue
            cur_val = cur_fields.get(field)
            if not isinstance(cur_val, (int, float)) or not isinstance(base_val, (int, float)):
                continue
            if base_val == 0:
                continue
            compared += 1
            # relative move in the GOOD direction (negative = got worse)
            rel = sign * (cur_val - base_val) / abs(base_val)
            entry = {
                "row": row,
                "field": field,
                "baseline": base_val,
                "current": cur_val,
                "rel_change": round(rel, 4),
            }
            if rel < -threshold:
                if suspect:
                    entry["waived"] = "measurement_suspect"
                regressions.append(entry)
            elif rel > threshold:
                improvements.append(entry)
    gating = [r for r in regressions if "waived" not in r]
    return {
        "verdict": "regressed" if gating else "ok",
        "threshold": threshold,
        "compared": compared,
        # suspect-flagged moves are excluded from the gate but COUNTED:
        # a waived regression is data for eyes (re-run the bench), not
        # silence — the r5 attention-MFU slip must stay visible
        "waived": len(regressions) - len(gating),
        "regressions": regressions,
        "improvements": improvements,
        "new_rows": new_rows,
        "missing_rows": missing_rows,
    }


def unchanged_check(current: dict, baseline: dict, pattern: str) -> dict:
    """Exact-equality guard over DETERMINISTIC fields (ISSUE 12): fields
    matching ``pattern`` are analytic-model outputs (``*model*`` speedups,
    planned byte counts, wire ratios) that a pure refactor — e.g. the
    gate-registry move — must reproduce bit-for-bit; any drift means the
    refactor changed a plan or a price, not just plumbing. Rows only one
    side has are skipped (the threshold compare reports those)."""
    rx = re.compile(pattern)
    cur_rows, base_rows = _rows_of(current), _rows_of(baseline)
    mismatches, held = [], 0
    for row, base_fields in sorted(base_rows.items()):
        cur_fields = cur_rows.get(row)
        if cur_fields is None:
            continue
        for field, base_val in sorted(base_fields.items()):
            if not rx.search(field) or not isinstance(base_val, (int, float)):
                continue
            cur_val = cur_fields.get(field)
            if not isinstance(cur_val, (int, float)):
                continue
            if cur_val == base_val:
                held += 1
            else:
                mismatches.append(
                    {"row": row, "field": field, "baseline": base_val, "current": cur_val}
                )
    return {
        "verdict": "moved" if mismatches else "unchanged",
        "pattern": pattern,
        "held": held,
        "mismatches": mismatches,
    }


def run(current_path=None, baseline_path=None, threshold=0.10, out_path=None,
        unchanged_fields=None) -> dict:
    """Library entry (bench.py calls this after writing BENCH_DETAIL.json).
    ``unchanged_fields`` (a regex) additionally runs the exact-equality
    guard and persists its verdict in the written BENCH_COMPARE.json."""
    current_path = current_path or os.path.join(ROOT, "BENCH_DETAIL.json")
    baseline_path = baseline_path or _latest_round_artifact()
    if baseline_path is None or not os.path.exists(current_path):
        return {
            "verdict": "skipped",
            "reason": "missing bench artifacts",
            "current": current_path,
            "baseline": baseline_path,
        }
    current, baseline = _load(current_path), _load(baseline_path)
    result = compare(current, baseline, threshold)
    result["current_file"] = os.path.relpath(current_path, ROOT)
    result["baseline_file"] = os.path.relpath(baseline_path, ROOT)
    if unchanged_fields:
        result["unchanged_fields"] = unchanged_check(
            current, baseline, unchanged_fields
        )
    if out_path is None:
        out_path = os.path.join(ROOT, "BENCH_COMPARE.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--current", default=None, help="bench record (default BENCH_DETAIL.json)")
    ap.add_argument(
        "--baseline", default=None, help="baseline record (default: latest BENCH_r*.json)"
    )
    ap.add_argument("--threshold", type=float, default=0.10, help="relative move that gates")
    ap.add_argument(
        "--strict", action="store_true", help="exit 1 on an unflagged regression"
    )
    ap.add_argument(
        "--unchanged-fields",
        default=None,
        metavar="REGEX",
        help="additionally require fields matching REGEX to be EXACTLY "
        "equal between current and baseline (deterministic model fields; "
        "exit 1 on any drift) — the pure-refactor guard",
    )
    args = ap.parse_args()
    result = run(
        args.current, args.baseline, args.threshold,
        unchanged_fields=args.unchanged_fields,
    )
    unchanged = result.get("unchanged_fields")
    # one compact machine-readable line on stdout (details in BENCH_COMPARE.json)
    compact = {
        "verdict": result["verdict"],
        "threshold": result.get("threshold"),
        "compared": result.get("compared"),
        "regressed": [
            f"{r['row']}.{r['field']}" for r in result.get("regressions", []) if "waived" not in r
        ],
        "waived": [
            f"{r['row']}.{r['field']}" for r in result.get("regressions", []) if "waived" in r
        ],
        "improved": [f"{r['row']}.{r['field']}" for r in result.get("improvements", [])],
        "new_row": result.get("new_rows", []),
        "missing_row": result.get("missing_rows", []),
        "baseline_file": result.get("baseline_file") or result.get("baseline"),
    }
    if unchanged is not None:
        compact["unchanged_fields"] = {
            "verdict": unchanged["verdict"],
            "held": unchanged["held"],
            "moved": [f"{m['row']}.{m['field']}" for m in unchanged["mismatches"]],
        }
    print(json.dumps(compact))
    if unchanged is not None and unchanged["verdict"] == "moved":
        return 1
    return 1 if (args.strict and result["verdict"] == "regressed") else 0


if __name__ == "__main__":
    sys.exit(main())
