"""Pallas TPU kernel: fused distance + argmin + accumulate for KMeans.

The native-kernel layer SURVEY §7 plans ("custom Pallas kernels for hot
spots — fused distance+argmin for KMeans"). The reference's Lloyd update
(kmeans.py:74-100) materializes the (n × k) distance matrix and a one-hot
assignment matrix; the fused jnp step (`kmeans._lloyd_step`) still writes
both through HBM. This kernel streams row tiles of X through VMEM once per
iteration and never materializes either:

    per (TM × d) tile:  d² = ‖x‖² + ‖c‖² − 2 x·cᵀ   (MXU)
                        labels = argmin d²            (VPU)
                        acc   += onehotᵀ · [x | 1 | min d²]  (MXU)

The single (k × d+2) accumulator carries cluster sums, counts and
per-cluster inertia; HBM traffic is exactly one read of X per iteration —
the bandwidth lower bound.

MEASURED OUTCOME (TPU v5e, n=1M d=64 k=8): the XLA-fused jnp Lloyd step
runs at 1.14 ms/iter ≈ 225 GB/s — already at the HBM bandwidth bound —
while this kernel reaches 6.8 ms (k=8 lanes waste 15/16 of the VPU; the
(k × d+2) matmul underfills the MXU). Exactly the guide's rule: don't
hand-schedule what the compiler already fuses. The kernel is therefore
OPT-IN (``use_pallas=True``), kept as the validated native-kernel path
(numerics match the jnp step to 2e-6) and as the scaffold for shapes
where XLA's fusion does fall short (very large k, fused multi-metric).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

try:  # pragma: no cover - present in all TPU-capable jax builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["fused_assign_program", "pallas_available"]


def pallas_available() -> bool:
    """True when the backend can execute the compiled kernel (gate for the
    opt-in path; auto-selection stays on the XLA-fused formulation, which
    measures at the bandwidth bound — see module docstring)."""
    return (
        pltpu is not None
        and jax.default_backend() == "tpu"
        and jax.device_count() == 1
        and not jax.config.jax_enable_x64  # Mosaic rejects x64-mode traces
    )


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _make_kernel(tm: int, n: int, k: int):
    def kernel(x_ref, c_ref, acc_ref):
        # every scalar is pinned to a ≤32-bit dtype: x64 mode would
        # otherwise leak int64/float64 into the kernel, which Mosaic rejects
        f1 = jnp.float32(1.0)
        f0 = jnp.float32(0.0)
        i = pl.program_id(0)
        x = x_ref[:].astype(jnp.float32)          # (TM, d)
        c = c_ref[:].astype(jnp.float32)          # (k, d)
        x2 = jnp.sum(x * x, axis=1, keepdims=True)
        c2 = jnp.sum(c * c, axis=1, keepdims=True).T
        d2 = x2 + c2 - jnp.float32(2.0) * jnp.dot(x, c.T, preferred_element_type=jnp.float32)
        d2 = jnp.maximum(d2, f0)                  # (TM, k)
        dmin = jnp.min(d2, axis=1, keepdims=True)
        # first-argmin via min-reduction over indices (Mosaic's argmin
        # primitive rejects the int64 index dtype x64 mode implies)
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (tm, k), 1)
        labels = jnp.min(
            jnp.where(d2 == dmin, col_ids, jnp.int32(k)), axis=1, keepdims=True
        )
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
        valid = (i.astype(jnp.int32) * jnp.int32(tm) + row_ids) < jnp.int32(n)
        onehot = col_ids == labels
        onehot = jnp.where(valid & onehot, f1, f0)
        ones = jnp.where(valid, f1, f0)
        # [x | 1 | min d²]: one MXU matmul yields sums, counts AND
        # per-cluster inertia in a single (k, d+2) accumulator
        xe = jnp.concatenate([x, ones, jnp.where(valid, dmin, f0)], axis=1)
        part = jnp.dot(onehot.T, xe, preferred_element_type=jnp.float32)

        @pl.when(i == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += part

    return kernel


@functools.lru_cache(maxsize=64)
def fused_assign_program(n: int, d: int, k: int, jdtype: str, interpret: bool = False):
    """Compiled fused-assignment pass: (x (n,d), centers (k,d)) →
    (sums (k,d) f32, counts (k,) f32, inertia () f32)."""
    tm = max(8, min(1024, _round_up(min(n, 1024), 8)))
    npad = _round_up(n, tm)
    kernel = _make_kernel(tm, n, k)
    call = pl.pallas_call(
        kernel,
        grid=(npad // tm,),
        in_specs=[
            pl.BlockSpec((tm, d), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((k, d + 2), lambda i: (0, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((k, d + 2), jnp.float32),
        interpret=interpret,
    )

    def run(x, centers):
        # x64 is off on TPU by platform policy, so Mosaic's grid/index
        # machinery traces with 32-bit scalars; the forced-x64
        # configuration is gated out in pallas_available
        if npad != n:
            x = jnp.pad(x, ((0, npad - n), (0, 0)))
        acc = call(x.astype(jnp.dtype(jdtype)), centers.astype(jnp.dtype(jdtype)))
        return acc[:, :d], acc[:, d], jnp.sum(acc[:, d + 1])

    return jax.jit(run)
