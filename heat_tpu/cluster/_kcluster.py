"""Shared k-clustering machinery.

API parity with /root/reference/heat/cluster/_kcluster.py (``_KCluster``:
init strategies ``random``/``probability_based`` (k-means++) with
per-centroid Bcast from the owning rank at _kcluster.py:100-187; assignment
= cdist + argmin at :196-209). Here initialization samples/percolates on
the sharded global array (no rank-owned rows — the controller indexes the
global array and XLA fetches the row), and each Lloyd-style iteration is a
single jit: distances on the MXU via the quadratic expansion, masked
per-cluster reductions lowering to one all-reduce over the mesh.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional, Union

from ..core import random as ht_random, types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core.communication import place as _place

__all__ = ["_KCluster"]


def _seed_key(k: int) -> jax.Array:
    """Derive the seeding PRNG key from the global heat stream and advance
    it by the k draws the ++-seeding consumes. The single source of truth
    for BOTH the composite ``_kmeanspp`` path and the fused fit — they
    must derive identically or seeded results diverge between paths."""
    state = ht_random.get_state()
    key = jax.random.fold_in(jax.random.PRNGKey(int(state[1])), int(state[2]))
    ht_random.set_state((state[0], state[1], state[2] + k, 0, 0.0))
    return key


def make_fit_loop(step, jdtype: str, tol: float, max_iter: int, returns_inertia: bool):
    """Whole-fit while_loop with on-device convergence (a host check per
    iteration costs a ~90 ms tunnel round trip). ``step(arr, centers)``
    returns (new_centers, shift[, inertia]). Shared by the k-cluster
    family; callers lru-cache the jitted result per configuration."""

    def run(arr, centers0):
        big = jnp.asarray(jnp.inf, dtype=jnp.dtype(jdtype))
        zero = jnp.asarray(0.0, dtype=jnp.dtype(jdtype))

        def cond(state):
            return (state[0] < max_iter) & (state[2] > tol)

        if returns_inertia:
            def body(state):
                it, centers, _, _ = state
                new_centers, shift, inertia = step(arr, centers)
                return (it + 1, new_centers, shift, inertia)

            it, centers, _, inertia = jax.lax.while_loop(
                cond, body, (0, centers0, big, zero)
            )
            return centers, it, inertia

        def body(state):
            it, centers, _ = state
            new_centers, shift = step(arr, centers)
            return (it + 1, new_centers, shift)

        it, centers, _ = jax.lax.while_loop(cond, body, (0, centers0, big))
        return centers, it

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _fused_fit_program(step, k: int, shape, jdtype: str, tol: float, max_iter: int,
                       returns_inertia: bool, metric: str, seeded: bool):
    """The ENTIRE fit — ++-seeding (when ``seeded``), the convergence
    while_loop, and the final label assignment — as ONE jitted program:
    a single dispatch per fit. The eager composite paid 3-4 dispatches
    (seeding, loop, assignment, functional value), which dominated fit
    time for cb-scale inputs on the remote TPU. ``init_arg`` is a PRNG
    key when ``seeded`` else the (k, d) initial centers."""
    loop = make_fit_loop(step, jdtype, tol, max_iter, returns_inertia)
    seed_prog = _kmeanspp_program(k, shape, jdtype) if seeded else None

    @jax.jit
    def run(arr, init_arg):
        centers0 = seed_prog(arr, init_arg) if seeded else init_arg.astype(arr.dtype)
        res = loop(arr, centers0)
        centers, n_iter = res[0], res[1]
        d = _KCluster._pairwise(arr, centers, metric)
        labels = jnp.argmin(d, axis=1).astype(types.index_jax_type())
        if metric == "manhattan":
            fun = jnp.sum(jnp.min(d, axis=1))
        else:
            fun = jnp.sum(jnp.min(d, axis=1) ** 2)
        inertia = res[2] if returns_inertia else fun
        return centers, n_iter, labels, inertia

    return run


@functools.lru_cache(maxsize=64)
def _predict_program(metric: str, eval_fv: bool):
    """The fused label-assignment program ``(arr, centers) -> labels[,
    functional value]`` — ONE dispatch for the whole predict path
    (distances on the MXU, argmin, optional functional value), where
    the eager composite paid one per op. Shared by eager ``predict``
    and the serving endpoints (ISSUE 9), so a served request is
    bit-identical to an eager one by construction; shapes retrace under
    the same cached program."""

    def run(arr, centers):
        d = _KCluster._pairwise(arr, centers, metric)
        labels = jnp.argmin(d, axis=1).astype(types.index_jax_type())
        if not eval_fv:
            return labels
        if metric == "manhattan":
            fun = jnp.sum(jnp.min(d, axis=1))
        else:
            fun = jnp.sum(jnp.min(d, axis=1) ** 2)
        return labels, fun

    return jax.jit(run)


def serving_spec(metric: str, centers: jax.Array, comm=None) -> dict:
    """The serving-endpoint description of a k-cluster predict program
    (consumed by ``ht.serving.estimator_endpoint`` and the warmup CLI's
    declared set — both must derive identical AOT cache keys, which is
    why the key lives here, next to the program)."""
    k, d = int(centers.shape[0]), int(centers.shape[1])
    return {
        "build": lambda: _predict_program(metric, False),
        "args": (centers,),
        "key": ("kcluster-predict", metric, k, d, str(np.dtype(centers.dtype))),
        "feature_shape": (d,),
        "dtype": np.dtype(centers.dtype),
        "comm": comm,
        "name": "kcluster-predict",
    }


@functools.lru_cache(maxsize=64)
def _kmeanspp_program(k: int, shape, jdtype: str):
    """Compiled greedy k-means++ seeding: (arr, key) -> (k, d) centers.
    A ``fori_loop`` over the k steps keeps the traced program size
    constant in k (an unrolled loop would compile k copies of the
    (L, n, d) candidate-distance computation)."""
    n = shape[0]
    n_candidates = 2 + int(np.log(max(k, 2)))

    def run(arr, key):
        keys = jax.random.split(key, k)
        first = jax.random.randint(keys[0], (), 0, n)
        centers0 = jnp.zeros((k, arr.shape[1]), dtype=arr.dtype).at[0].set(arr[first])
        d2_0 = jnp.sum((arr - centers0[0]) ** 2, axis=1)

        def body(i, state):
            centers, d2 = state
            probs = d2 / jnp.maximum(jnp.sum(d2), 1e-30)
            cand = jax.random.choice(keys[i], n, shape=(n_candidates,), p=probs)
            cand_pts = jnp.take(arr, cand, axis=0)  # (L, d)
            cand_d2 = jnp.sum((arr[None, :, :] - cand_pts[:, None, :]) ** 2, axis=2)  # (L, n)
            potentials = jnp.sum(jnp.minimum(d2[None, :], cand_d2), axis=1)  # (L,)
            best = jnp.argmin(potentials)
            centers = centers.at[i].set(cand_pts[best])
            d2 = jnp.minimum(d2, cand_d2[best])
            return (centers, d2)

        centers, _ = jax.lax.fori_loop(1, k, body, (centers0, d2_0))
        return centers

    return jax.jit(run)


class _KCluster(BaseEstimator, ClusteringMixin):
    """Base class for k-statistics clustering (reference: _kcluster.py)."""

    def __init__(
        self,
        metric: Callable,
        n_clusters: int,
        init: Union[str, DNDarray],
        max_iter: int,
        tol: float,
        random_state: Optional[int],
    ):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._metric = metric
        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

        # ISSUE 13 satellite — seed/stream state is EXPLICIT MODEL
        # state. The old contract ("the ctor reseeds the GLOBAL stream,
        # every init advances it") meant two same-seed models created
        # then fitted in sequence drew DIFFERENT inits, and a
        # checkpoint could not capture "where this model's stream is".
        # New contract: ``random_state`` establishes a model-PRIVATE
        # (seed, counter=0) stream; only this model's inits advance it,
        # the global stream is never touched. Two same-seed models —
        # fresh or restored from the same checkpoint — therefore draw
        # identical inits. A model WITHOUT a random_state keeps the
        # legacy global-stream draws (``_rng_state is None``).
        # Single-model seeded results are unchanged: the first init
        # still draws from (seed, counter=0) exactly as before.
        self._rng_state = (
            None if random_state is None
            else ("Threefry", int(random_state), 0, 0, 0.0)
        )

    def _with_stream(self, fn):
        """Run ``fn()`` against the model's private RNG stream when one
        exists (``random_state`` given, or restored from a checkpoint),
        else against the global stream (legacy). The private stream is
        swapped into the global slot for the draw and the ADVANCED
        state captured back — so ``_seed_key``/``randperm`` derivations
        stay byte-identical to the pre-satellite code at equal
        (seed, counter), and the outer global stream is untouched."""
        if self._rng_state is None:
            return fn()
        outer = ht_random.get_state()
        ht_random.set_state(self._rng_state)
        try:
            return fn()
        finally:
            self._rng_state = ht_random.get_state()
            ht_random.set_state(outer)

    @property
    def rng_state(self):
        """The model's explicit RNG stream state — ``("Threefry", seed,
        counter, 0, 0.0)`` for seeded models (checkpoint material), or
        ``None`` for models on the legacy global stream."""
        return self._rng_state

    @rng_state.setter
    def rng_state(self, state) -> None:
        self._rng_state = None if state is None else tuple(state)

    @property
    def cluster_centers_(self) -> DNDarray:
        """Coordinates of the cluster centers."""
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        """Label of each sample point."""
        return self._labels

    @property
    def inertia_(self) -> float:
        """Sum of squared distances of samples to their closest center.
        Stored as a lazy device scalar by fit; the first access pays the
        host read (~90 ms over the remote tunnel) and caches the float."""
        if self._inertia is None:
            return None
        if not isinstance(self._inertia, float):
            self._inertia = float(self._inertia)
        return self._inertia

    @property
    def n_iter_(self) -> int:
        """Number of iterations run (lazy device scalar; see inertia_)."""
        if self._n_iter is None:
            return None
        if not isinstance(self._n_iter, int):
            self._n_iter = int(self._n_iter)
        return self._n_iter

    # ------------------------------------------------------------------ #
    # initialization (reference: _kcluster.py:87-187)                    #
    # ------------------------------------------------------------------ #
    def _initialize_cluster_centers(self, x: DNDarray) -> None:
        k = self.n_clusters
        n, d = x.shape
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)

        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, d):
                raise ValueError(
                    f"passed centroids need to be of shape ({k}, {d}), got {self.init.shape}"
                )
            centers = self.init.larray.astype(arr.dtype)
        elif isinstance(self.init, str) and self.init == "random":
            # k observations drawn at random from the data (reference:
            # per-centroid rank-owned row + Bcast; here a global gather)
            idx = self._with_stream(
                lambda: ht_random.randperm(n, comm=x.comm).larray[:k]
            )
            centers = jnp.take(arr, idx, axis=0)
        elif isinstance(self.init, str) and self.init in ("probability_based", "kmeans++", "k-means++"):
            centers = self._kmeanspp(arr, k)
        else:
            raise ValueError(f"initialization needs to be 'random', 'probability_based' or a DNDarray, got {self.init}")

        # centers are replicated (small k×d)
        self._cluster_centers = DNDarray(
            _place(centers, x.comm.sharding(2, None)),
            (k, d),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )

    def _kmeanspp(self, arr: jax.Array, k: int) -> jax.Array:
        """Greedy k-means++ seeding on the sharded global array (reference:
        _kcluster.py:123-187 draws one candidate per step with per-centroid
        owner-rank broadcasts; here the sklearn-style greedy variant draws
        2+log(k) candidates per step and keeps the one minimizing the
        potential — markedly more robust seeding at negligible cost).
        The whole seeding is ONE jitted program (the eager unrolled loop
        cost ~20 dispatches, each a millisecond-class round trip over the
        remote execution tunnel)."""
        prog = _kmeanspp_program(k, tuple(arr.shape), np.dtype(arr.dtype).name)
        return prog(arr, self._with_stream(lambda: _seed_key(k)))

    # ------------------------------------------------------------------ #
    # assignment (reference: _kcluster.py:196-209)                       #
    # ------------------------------------------------------------------ #
    _assignment_metric = "euclidean"

    def _assign_to_cluster(self, x: DNDarray, eval_functional_value: bool = False) -> DNDarray:
        """Label of the closest center for every sample, using the
        subclass's assignment metric (reference passes cdist or manhattan
        into _KCluster; kmedians/kmedoids use L1)."""
        sanitize_in(x)
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        c = self._cluster_centers.larray
        prog = _predict_program(self._assignment_metric, eval_functional_value)
        if eval_functional_value:
            # L1/L2 functional value (lazy device scalar, read by inertia_)
            labels, self._inertia = prog(arr, c)
        else:
            labels = prog(arr, c)
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            labels = x.comm.shard(labels, split)
        # same index-output dtype convention as _fit_fused / sort / topk
        return DNDarray(
            labels, gshape, types.canonical_heat_type(labels.dtype), split,
            x.device, x.comm,
        )

    @staticmethod
    def _pairwise(arr: jax.Array, c: jax.Array, metric: str = "euclidean") -> jax.Array:
        """Pairwise sample×center distances: Euclidean via the MXU-friendly
        quadratic expansion, or Manhattan for the L1 family."""
        if metric == "manhattan":
            return jnp.sum(jnp.abs(arr[:, None, :] - c[None, :, :]), axis=-1)
        x2 = jnp.sum(arr * arr, axis=1, keepdims=True)
        c2 = jnp.sum(c * c, axis=1, keepdims=True).T
        return jnp.sqrt(jnp.maximum(x2 + c2 - 2.0 * (arr @ c.T), 0.0))

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray):
        raise NotImplementedError()

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    # ------------------------------------------------------------------ #
    # shared fused fit driver                                            #
    # ------------------------------------------------------------------ #
    def _fit_fused(self, x: DNDarray, step_factory, returns_inertia: bool):
        """Run the whole fit as one compiled program (see
        ``_fused_fit_program``). ``step_factory(k, shape, jdtype)`` returns
        the per-iteration update (Lloyd / median / medoid)."""
        sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-dimensional, got {x.ndim}")
        k = self.n_clusters
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)

        seeded = isinstance(self.init, str) and self.init in (
            "probability_based", "kmeans++", "k-means++",
        )
        if seeded:
            # the SHARED derivation keeps seeded results identical between
            # the fused fit and the composite _kmeanspp path
            init_arg = self._with_stream(lambda: _seed_key(k))
        else:
            self._initialize_cluster_centers(x)
            init_arg = self._cluster_centers.larray

        step = step_factory(k, tuple(arr.shape), np.dtype(arr.dtype).name)
        prog = _fused_fit_program(
            step, k, tuple(arr.shape), np.dtype(arr.dtype).name,
            float(self.tol), int(self.max_iter), returns_inertia,
            self._assignment_metric, seeded,
        )
        centers, n_iter_dev, labels, inertia_dev = prog(arr, init_arg)

        self._n_iter = n_iter_dev  # lazy device scalars; properties read them
        self._inertia = inertia_dev
        self._cluster_centers = DNDarray(
            _place(centers, x.comm.sharding(2, None)),
            (k, x.shape[1]),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            labels = x.comm.shard(labels, split)
        # index-output dtype convention (ADVICE r4): like sort/topk/unique
        # indices, labels declare the PHYSICAL buffer's canonical type —
        # int64 in x64 mode, int32 under the TPU degrade policy — so
        # index-valued outputs expose one consistent logical dtype
        self._labels = DNDarray(
            labels, gshape, types.canonical_heat_type(labels.dtype), split,
            x.device, x.comm,
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels of the closest cluster center for new data (reference:
        _kcluster.py predict). One fused program dispatch (see
        ``_predict_program``)."""
        sanitize_in(x)
        if self._cluster_centers is None:
            raise RuntimeError("fit needs to be called before predict")
        return self._assign_to_cluster(x)

    def serving_program(self) -> dict:
        """The endpoint description ``ht.serving.estimator_endpoint``
        consumes: the fitted predict program, its replicated model state
        (the centers), and the persistent AOT cache key parts."""
        if self._cluster_centers is None:
            raise RuntimeError("fit needs to be called before serving")
        return serving_spec(
            self._assignment_metric,
            self._cluster_centers.larray,
            comm=self._cluster_centers.comm,
        )
