"""K-Medoids clustering.

API parity with /root/reference/heat/cluster/kmedoids.py: Lloyd-style
iterations where each new center snaps to the closest actual data point
of the cluster (reference kmedoids.py:116 performs the snap with extra
comm). Here the snap is an argmin over the sharded distance column —
one reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from typing import Optional, Union

from ..core.dndarray import DNDarray
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


@functools.lru_cache(maxsize=64)
def _medoid_step(k: int, shape, jdtype: str):
    @jax.jit
    def step(arr, centers):
        # L1 assignment; medoid snap also by L1 (reference kmedoids.py:48)
        d1 = jnp.sum(jnp.abs(arr[:, None, :] - centers[None, :, :]), axis=-1)
        labels = jnp.argmin(d1, axis=1)

        # median per cluster, then snap to nearest member point in L1
        def one_cluster(i):
            mask = labels == i
            cnt = jnp.sum(mask)
            masked = jnp.where(mask[:, None], arr, jnp.nan)
            med_i = jnp.where(cnt > 0, jnp.nanmedian(masked, axis=0), centers[i])
            dist_to_med = jnp.sum(jnp.abs(arr - med_i), axis=1)
            dist_masked = jnp.where(mask, dist_to_med, jnp.inf)
            idx = jnp.argmin(dist_masked)
            return jnp.where(cnt > 0, arr[idx], centers[i])

        new_centers = jax.vmap(one_cluster)(jnp.arange(k))
        shift = jnp.sum((new_centers - centers) ** 2)
        return new_centers, shift

    return step


class KMedoids(_KCluster):
    """K-Medoids: centers are actual data points; Manhattan metric
    throughout (reference: kmedoids.py:48)."""

    _assignment_metric = "manhattan"

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedoids++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: None,
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        """Seeding + convergence loop + assignment as ONE compiled program
        (see ``_kcluster._fused_fit_program``)."""
        return self._fit_fused(x, _medoid_step, returns_inertia=False)
