"""K-Means clustering.

API parity with /root/reference/heat/cluster/kmeans.py (``KMeans``; Lloyd
update via masked mean at kmeans.py:74-100, issuing k Allreduces per
iteration — reference call stack SURVEY §3.4). Here one Lloyd iteration is
ONE jit-compiled program: the distance matrix rides the MXU (quadratic
expansion), the per-cluster sums are a single one-hot matmul whose
reduction over the sharded sample axis lowers to ONE all-reduce of a
(k × d+1) buffer — independent of k — and convergence is a scalar.

ISSUE 11 adds the STREAMING form: ``partial_fit`` (sklearn
MiniBatchKMeans-style running-mean updates, one fused program per
batch) and, through it, fits over HOST-RESIDENT operands — a
``ht.redistribution.staging.HostArray`` larger than HBM streams
(8,128)-aligned windows through the depth-2 double-buffered staging
slab, each window one ``partial_fit`` batch.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Union

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ._kcluster import _KCluster
from ..core.communication import place as _place

__all__ = ["KMeans"]


@functools.lru_cache(maxsize=64)
def _lloyd_step(k: int, shape, jdtype: str, use_pallas: Optional[bool] = None):
    """One Lloyd iteration as a pure jitted function: (x, centers) →
    (new_centers, shift², inertia).

    The default is the XLA-fused jnp formulation: measured on TPU v5e it
    runs at the HBM bandwidth bound (1.14 ms/iter at n=1M, d=64, k=8 ≈
    225 GB/s), which no hand-scheduled kernel can beat. ``use_pallas=True``
    opts into the fused Pallas assignment kernel
    (``_pallas.fused_assign_program``) — numerically equivalent (≤2e-6),
    kept for shapes where XLA's fusion falls short; see ``_pallas``.
    """
    from . import _pallas

    if use_pallas is None:
        use_pallas = False

    if use_pallas:
        assign = _pallas.fused_assign_program(int(shape[0]), int(shape[1]), k, jdtype)

        @jax.jit
        def step(arr, centers):
            sums, counts, inertia = assign(arr, centers)
            sums = sums.astype(arr.dtype)
            counts = counts.astype(arr.dtype)
            new_centers = jnp.where(
                counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers
            )
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, shift, inertia.astype(arr.dtype)

        return step

    @jax.jit
    def step(arr, centers):
        x2 = jnp.sum(arr * arr, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1, keepdims=True).T
        d2 = jnp.maximum(x2 + c2 - 2.0 * (arr @ centers.T), 0.0)
        labels = jnp.argmin(d2, axis=1)
        onehot = jax.nn.one_hot(labels, k, dtype=arr.dtype)  # (n, k)
        sums = onehot.T @ arr  # (k, d) — one all-reduce over the mesh
        counts = jnp.sum(onehot, axis=0)  # (k,)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers
        )
        shift = jnp.sum((new_centers - centers) ** 2)
        inertia = jnp.sum(jnp.min(d2, axis=1))
        return new_centers, shift, inertia

    return step


@functools.lru_cache(maxsize=64)
def _partial_fit_step(k: int, shape, jdtype: str):
    """One STREAMING minibatch update as a pure jitted function:
    ``(arr, centers, counts) -> (new_centers, new_counts, inertia)``.

    The standard running-mean update (sklearn MiniBatchKMeans with
    per-center counts): every center is the mean of ALL samples ever
    assigned to it, so one epoch over a stream of disjoint batches
    touches each sample once — the pass-structured form the out-of-core
    staging executor feeds window by window. Same program shape as the
    Lloyd step: distances on the MXU, the per-cluster sums ONE one-hot
    matmul (a single all-reduce on a sharded batch), inertia a scalar.
    """

    @jax.jit
    def step(arr, centers, counts):
        x2 = jnp.sum(arr * arr, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1, keepdims=True).T
        d2 = jnp.maximum(x2 + c2 - 2.0 * (arr @ centers.T), 0.0)
        labels = jnp.argmin(d2, axis=1)
        onehot = jax.nn.one_hot(labels, k, dtype=arr.dtype)  # (n, k)
        sums = onehot.T @ arr  # (k, d) — one all-reduce over the mesh
        # counts accumulate in f32 REGARDLESS of the data dtype: a bf16
        # running count saturates at 256 and the stream silently
        # overweights late batches (f32 additions are exact to 16M)
        bcounts = jnp.sum(onehot.astype(jnp.float32), axis=0)  # (k,)
        new_counts = counts + bcounts
        # running mean: n_c·c + Σ_batch, renormalized by the new count —
        # the mix runs in f32 (exact no-op for f32 data)
        new_centers = jnp.where(
            new_counts[:, None] > 0,
            (centers.astype(jnp.float32) * counts[:, None] + sums.astype(jnp.float32))
            / jnp.maximum(new_counts[:, None], 1),
            centers.astype(jnp.float32),
        ).astype(arr.dtype)
        inertia = jnp.sum(jnp.min(d2, axis=1))
        return new_centers, new_counts, inertia

    return step


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference: kmeans.py:17).

    Parameters follow the reference: n_clusters, init
    ('random' | 'probability_based'/'kmeans++' | DNDarray), max_iter, tol,
    random_state.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: None,
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )
        # streaming state (partial_fit): samples-per-center running
        # counts — None until the first batch initializes the centers
        self._partial_counts = None

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Masked-mean centroid update (reference: kmeans.py:74-100) —
        exposed for API parity; ``fit`` uses the fused jitted step."""
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        labels = matching_centroids.larray
        onehot = jax.nn.one_hot(labels, self.n_clusters, dtype=arr.dtype)
        sums = onehot.T @ arr
        counts = jnp.sum(onehot, axis=0)
        centers = self._cluster_centers.larray
        new_centers = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers)
        return DNDarray(
            _place(new_centers, x.comm.sharding(2, None)),
            tuple(int(s) for s in new_centers.shape),
            types.canonical_heat_type(new_centers.dtype),
            None,
            x.device,
            x.comm,
        )

    def fit(self, x, ckpt=None, _watcher=None, _chaos=None) -> "KMeans":
        """Run Lloyd iterations to convergence (reference: kmeans.py:102).
        Seeding, the convergence while_loop and the final assignment run
        as ONE compiled program — a single dispatch per fit (see
        ``_kcluster._fused_fit_program``).

        ``x`` may be a ``ht.redistribution.staging.HostArray`` (ISSUE
        11): the fit then STREAMS the host-resident operand — one epoch
        of :meth:`partial_fit` windows through the staging slab (the
        documented streaming-k-means algorithm; ``labels_`` stays unset
        — call :meth:`predict` batch-wise). With ``HEAT_TPU_OOC=0`` a
        fitting host operand materializes whole and runs the exact
        in-HBM Lloyd fit instead.

        ``ckpt`` (ISSUE 13, streaming path only): a
        ``ht.resilience.CheckpointConfig`` — the window stream commits
        a checkpoint every ``ckpt.every`` windows (centers, counts, the
        explicit RNG stream state, the window cursor and the slab the
        windows derive from) and, when a committed checkpoint for
        ``ckpt.tag`` already exists, RESUMES from it: the remaining
        windows replay with the recorded slab, so the resumed fit is
        bit-identical to an uninterrupted one — on the original world
        or a re-resolved (shrunk/grown) one. ``_watcher``/``_chaos``
        are the elastic runtime's hooks (``ht.resilience.elastic_fit``
        drives them); with ``HEAT_TPU_RESILIENCE=0`` a ``ckpt`` is
        ignored EVERYWHERE — including the unstreamable-input errors
        below, which only fire when the runtime is live — and the exact
        pre-resilience paths run."""
        from ..redistribution import staging as _staging

        if ckpt is not None:
            from ..resilience import checkpoint as _ckpt_mod

            if not _ckpt_mod.resilience_enabled(explicit=True):
                ckpt = None  # the documented escape hatch: ckpt is inert
        if isinstance(x, _staging.HostArray):
            if not _staging.ooc_engaged(x.nbytes, host_resident=True):
                if ckpt is not None:
                    raise ValueError(
                        "KMeans.fit(ckpt=): checkpointed resume rides the "
                        "streaming window path, which HEAT_TPU_OOC=0 "
                        "disables — unset the gate or drop ckpt="
                    )
                return self._fit_fused(
                    _staging.materialize(x, what="KMeans.fit"),
                    _lloyd_step,
                    returns_inertia=True,
                )
            return self._partial_fit_stream(
                x, ckpt=ckpt, watcher=_watcher, chaos=_chaos, fresh=True
            )
        if ckpt is not None:
            raise ValueError(
                "KMeans.fit(ckpt=): the fused in-HBM Lloyd fit runs as ONE "
                "device program with no host cut points to checkpoint at — "
                "stream a staging.HostArray (or drive partial_fit batches) "
                "to checkpoint mid-fit"
            )
        return self._fit_fused(x, _lloyd_step, returns_inertia=True)

    # ------------------------------------------------------------------ #
    # streaming / out-of-core (ISSUE 11)                                 #
    # ------------------------------------------------------------------ #
    def partial_fit(self, x) -> "KMeans":
        """Incremental fit on ONE batch (sklearn MiniBatchKMeans-style;
        no reference analog): the first call initializes the centers
        from the batch with the configured ``init``, every call folds
        the batch into the per-center running means — one fused program
        dispatch per batch (``_partial_fit_step``). A
        ``staging.HostArray`` batch streams its windows through the
        staging executor, each window one update (with
        ``HEAT_TPU_OOC=0`` it materializes whole — one update — when it
        fits). ``inertia_`` reports the LAST batch's functional value."""
        from ..redistribution import staging as _staging

        if isinstance(x, _staging.HostArray):
            if not _staging.ooc_engaged(x.nbytes, host_resident=True):
                return self._partial_fit_batch(
                    _staging.materialize(x, what="KMeans.partial_fit")
                )
            return self._partial_fit_stream(x)
        return self._partial_fit_batch(x)

    def _partial_fit_batch(self, x: DNDarray) -> "KMeans":
        sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-dimensional, got {x.ndim}")
        k = self.n_clusters
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        if self._cluster_centers is None:
            self._initialize_cluster_centers(x)
        if self._partial_counts is None:
            # fresh stream — also the partial_fit-after-fit() case, which
            # continues refining the FITTED centers from count zero
            # (sklearn MiniBatchKMeans.partial_fit semantics)
            self._partial_counts = jnp.zeros((k,), dtype=jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)
        step = _partial_fit_step(k, tuple(arr.shape), np.dtype(arr.dtype).name)
        centers, self._partial_counts, self._inertia = step(
            arr, centers, self._partial_counts
        )
        self._cluster_centers = DNDarray(
            _place(centers, x.comm.sharding(2, None)),
            (k, x.shape[1]),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )
        return self

    def _partial_fit_stream(self, host, ckpt=None, watcher=None, chaos=None,
                            fresh: bool = False) -> "KMeans":
        """One epoch of ``partial_fit`` windows over a host-resident
        operand: the window schedule is planned as a ``host-staging``
        Schedule (axis-0 windows), PROVEN to fit ``capacity("hbm")``,
        and executed depth-2 double-buffered — window k+1's
        ``device_put`` rides under window k's fused update.

        The elastic hooks (ISSUE 13, all optional and ALL inert under
        ``HEAT_TPU_RESILIENCE=0`` — the gate governs every hook, not
        just checkpointing, so the escape hatch runs the exact
        pre-resilience stream): ``ckpt`` commits/resumes the window
        cursor + model state; ``watcher`` is polled after each window
        (a world change raises the typed ``WorldChangedError``);
        ``chaos`` injects the declared faults. Poisoned state is
        caught by the finite-state validation AT COMMIT CADENCE — a
        host sync per window would pay the ~90 ms tunnel round trip
        the codebase optimizes away; validating immediately before
        each save preserves the invariant that matters (poisoned state
        is never COMMITTED: restore lands behind the poisoned window
        and replays it clean)."""
        from ..core import factories
        from ..redistribution import staging as _staging
        from ..resilience import checkpoint as _ckpt_mod, elastic as _elastic

        enabled_rt = _ckpt_mod.resilience_enabled(
            explicit=ckpt is not None or watcher is not None or chaos is not None
        )
        engaged = enabled_rt and ckpt is not None
        guarded = enabled_rt and (
            engaged or watcher is not None or chaos is not None
        )
        start = 0
        slab_override = None
        if fresh:
            # fit() is a FRESH fit: drop any previous streaming state
            # (partial_fit is the API that continues a stream)
            self._cluster_centers = None
            self._partial_counts = None
        if engaged:
            found = _ckpt_mod.restore_latest(ckpt.directory, tag=ckpt.tag)
            if found is not None:
                _step, state, _meta = found
                saved_shape = state.get("host_shape")
                if saved_shape is not None and (
                    tuple(saved_shape) != tuple(host.shape)
                    or str(state.get("host_dtype")) != str(host.dtype)
                ):
                    raise ValueError(
                        f"checkpoint tag {ckpt.tag!r} was written for a "
                        f"{tuple(saved_shape)}/{state.get('host_dtype')} "
                        f"operand but this fit streams {host.shape}/"
                        f"{host.dtype} — resuming would adopt another "
                        "dataset's cursor; use a fresh tag"
                    )
                self._load_stream_state(state)
                start = int(state["window_index"])
                slab_override = int(state["slab_bytes"])
        sched = _staging.plan_staged_passes(
            host.shape,
            host.dtype,
            [{"tag": "partial-fit", "axis": 0}],
            out_bytes=self.n_clusters * host.shape[1] * 8 + (1 << 20),
            slab=slab_override,
        )
        _staging.prove_fits(sched)
        slab = int(sched.staging["slab_bytes"])
        wins = _staging.window_extents(host.shape, host.dtype.itemsize, 0, slab)
        n_win = len(wins)
        if start >= n_win:
            return self  # the committed checkpoint already covers the epoch
        put = None
        if guarded and chaos is not None:
            chaos.bind_offset(start)
            put = chaos.poison_put()

        def _validate(k):
            if not _elastic._finite_state(self):
                raise _elastic.CollectivePoisoned(
                    f"window {k}: non-finite centers after the update — "
                    "poisoned exchange; restore from the last committed "
                    "checkpoint and replay"
                )

        def consume(j, slab_arr, win):
            k = start + j
            self._partial_fit_batch(factories.array(slab_arr, split=None))
            if not guarded:
                return
            if engaged and ((k + 1) % ckpt.every == 0 or k == n_win - 1):
                _validate(k)  # never COMMIT poisoned state
                path = _ckpt_mod.save(
                    self._stream_checkpoint_state(k + 1, slab, host),
                    tag=ckpt.tag, step=k + 1, directory=ckpt.directory,
                )
                _ckpt_mod.prune(ckpt.directory, ckpt.tag, ckpt.keep)
                if chaos is not None:
                    chaos.after_checkpoint(path, k + 1)
            elif chaos is not None:
                # chaos without checkpoints (drills/tests): detect at
                # every window — there is no commit cadence to ride
                _validate(k)
            if watcher is not None:
                evt = watcher.poll(k)
                if evt is not None:
                    raise _elastic.WorldChangedError(
                        evt.kind,
                        old_size=evt.detail.get("old_size"),
                        new_size=len(evt.devices),
                        epoch=_elastic.world_epoch(),
                    )

        rng0 = self._rng_state
        try:
            _staging.stream_windows(host, 0, wins[start:], consume, device_put=put,
                                    plan_id=sched.plan_id)
        except BaseException:
            if guarded:
                # a failed guarded stream rewinds the model's private
                # stream to where THIS attempt started: a retry with no
                # committed checkpoint then re-inits IDENTICALLY (when a
                # checkpoint exists, restore overwrites the stream
                # anyway) — the bit-reproducible-resume contract holds
                # even for failures before the first commit
                self._rng_state = rng0
            raise
        return self

    # -- checkpoint material (ISSUE 13) -------------------------------- #
    def _stream_checkpoint_state(self, window_index: int, slab_bytes: int,
                                 host) -> dict:
        """What a mid-stream checkpoint must capture to resume
        bit-reproducibly: centers, running counts, the EXPLICIT RNG
        stream state, the window cursor + slab the window geometry
        derives from (a resumed stream must replay the SAME windows —
        the running-mean update is batch-boundary dependent), and the
        OPERAND IDENTITY (shape/dtype) so a same-tag resume against a
        different dataset fails typed instead of adopting a foreign
        cursor."""
        state = {
            "centers": self._cluster_centers,
            "rng_state": self._rng_state,
            "window_index": int(window_index),
            "slab_bytes": int(slab_bytes),
            "n_clusters": int(self.n_clusters),
            "host_shape": [int(s) for s in host.shape],
            "host_dtype": str(host.dtype),
        }
        if self._partial_counts is not None:
            state["counts"] = self._partial_counts
        return state

    def _load_stream_state(self, state: dict) -> None:
        """Adopt a restored checkpoint's model state — the arrays
        arrive already re-sharded onto the CURRENT world."""
        if int(state.get("n_clusters", self.n_clusters)) != self.n_clusters:
            raise ValueError(
                f"checkpoint carries n_clusters={state.get('n_clusters')} "
                f"but this model has {self.n_clusters}"
            )
        self._cluster_centers = state["centers"]
        self._partial_counts = state.get("counts")
        rng = state.get("rng_state")
        self._rng_state = tuple(rng) if rng is not None else None
