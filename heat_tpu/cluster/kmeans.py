"""K-Means clustering.

API parity with /root/reference/heat/cluster/kmeans.py (``KMeans``; Lloyd
update via masked mean at kmeans.py:74-100, issuing k Allreduces per
iteration — reference call stack SURVEY §3.4). Here one Lloyd iteration is
ONE jit-compiled program: the distance matrix rides the MXU (quadratic
expansion), the per-cluster sums are a single one-hot matmul whose
reduction over the sharded sample axis lowers to ONE all-reduce of a
(k × d+1) buffer — independent of k — and convergence is a scalar.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from typing import Optional, Union

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster
from ..core.communication import place as _place

__all__ = ["KMeans"]


@functools.lru_cache(maxsize=64)
def _lloyd_step(k: int, shape, jdtype: str, use_pallas: Optional[bool] = None):
    """One Lloyd iteration as a pure jitted function: (x, centers) →
    (new_centers, shift², inertia).

    The default is the XLA-fused jnp formulation: measured on TPU v5e it
    runs at the HBM bandwidth bound (1.14 ms/iter at n=1M, d=64, k=8 ≈
    225 GB/s), which no hand-scheduled kernel can beat. ``use_pallas=True``
    opts into the fused Pallas assignment kernel
    (``_pallas.fused_assign_program``) — numerically equivalent (≤2e-6),
    kept for shapes where XLA's fusion falls short; see ``_pallas``.
    """
    from . import _pallas

    if use_pallas is None:
        use_pallas = False

    if use_pallas:
        assign = _pallas.fused_assign_program(int(shape[0]), int(shape[1]), k, jdtype)

        @jax.jit
        def step(arr, centers):
            sums, counts, inertia = assign(arr, centers)
            sums = sums.astype(arr.dtype)
            counts = counts.astype(arr.dtype)
            new_centers = jnp.where(
                counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers
            )
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, shift, inertia.astype(arr.dtype)

        return step

    @jax.jit
    def step(arr, centers):
        x2 = jnp.sum(arr * arr, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1, keepdims=True).T
        d2 = jnp.maximum(x2 + c2 - 2.0 * (arr @ centers.T), 0.0)
        labels = jnp.argmin(d2, axis=1)
        onehot = jax.nn.one_hot(labels, k, dtype=arr.dtype)  # (n, k)
        sums = onehot.T @ arr  # (k, d) — one all-reduce over the mesh
        counts = jnp.sum(onehot, axis=0)  # (k,)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers
        )
        shift = jnp.sum((new_centers - centers) ** 2)
        inertia = jnp.sum(jnp.min(d2, axis=1))
        return new_centers, shift, inertia

    return step


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference: kmeans.py:17).

    Parameters follow the reference: n_clusters, init
    ('random' | 'probability_based'/'kmeans++' | DNDarray), max_iter, tol,
    random_state.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: None,
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Masked-mean centroid update (reference: kmeans.py:74-100) —
        exposed for API parity; ``fit`` uses the fused jitted step."""
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        labels = matching_centroids.larray
        onehot = jax.nn.one_hot(labels, self.n_clusters, dtype=arr.dtype)
        sums = onehot.T @ arr
        counts = jnp.sum(onehot, axis=0)
        centers = self._cluster_centers.larray
        new_centers = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers)
        return DNDarray(
            _place(new_centers, x.comm.sharding(2, None)),
            tuple(int(s) for s in new_centers.shape),
            types.canonical_heat_type(new_centers.dtype),
            None,
            x.device,
            x.comm,
        )

    def fit(self, x: DNDarray) -> "KMeans":
        """Run Lloyd iterations to convergence (reference: kmeans.py:102).
        Seeding, the convergence while_loop and the final assignment run
        as ONE compiled program — a single dispatch per fit (see
        ``_kcluster._fused_fit_program``)."""
        return self._fit_fused(x, _lloyd_step, returns_inertia=True)
