"""K-Medians clustering.

API parity with /root/reference/heat/cluster/kmedians.py: Lloyd-style
iterations where the centroid update is the per-cluster coordinate-wise
median (reference computes distributed medians with extra comm per
cluster). Here the masked median over the sharded sample axis is one jnp
reduction per iteration.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional, Union

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ._kcluster import _KCluster

__all__ = ["KMedians"]


@functools.lru_cache(maxsize=64)
def _median_step(k: int, shape, jdtype: str):
    @jax.jit
    def step(arr, centers):
        # L1 assignment matches the coordinate-wise-median update
        d1 = jnp.sum(jnp.abs(arr[:, None, :] - centers[None, :, :]), axis=-1)
        labels = jnp.argmin(d1, axis=1)
        # masked per-cluster coordinate-wise median via NaN-masking
        def one_cluster(i):
            mask = labels == i
            masked = jnp.where(mask[:, None], arr, jnp.nan)
            med = jnp.nanmedian(masked, axis=0)
            return jnp.where(jnp.any(mask), med, centers[i])

        new_centers = jax.vmap(one_cluster)(jnp.arange(k))
        shift = jnp.sum((new_centers - centers) ** 2)
        return new_centers, shift

    return step


@functools.lru_cache(maxsize=64)
def _fit_loop(k: int, shape, jdtype: str, tol: float, max_iter: int):
    """Whole fit as one jitted while_loop — see ``_kcluster.make_fit_loop``."""
    from ._kcluster import make_fit_loop

    step = _median_step(k, shape, jdtype)
    return make_fit_loop(step, jdtype, tol, max_iter, returns_inertia=False)


class KMedians(_KCluster):
    """K-Medians: cluster centers are coordinate-wise medians; assignment
    and functional value use the Manhattan metric (reference:
    kmedians.py:49 passes ht.spatial.distance.manhattan)."""

    _assignment_metric = "manhattan"

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedians++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: None,
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-dimensional, got {x.ndim}")
        self._initialize_cluster_centers(x)
        arr = x.larray
        if types.heat_type_is_exact(x.dtype):
            arr = arr.astype(jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)
        loop = _fit_loop(
            self.n_clusters, tuple(arr.shape), np.dtype(arr.dtype).name,
            float(self.tol), int(self.max_iter),
        )
        centers, n_iter_dev = loop(arr, centers)
        self._n_iter = n_iter_dev  # lazy device scalar; n_iter_ reads it
        self._cluster_centers = DNDarray(
            jax.device_put(centers, x.comm.sharding(2, None)),
            (self.n_clusters, x.shape[1]),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )
        self._labels = self._assign_to_cluster(x, eval_functional_value=True)
        return self
