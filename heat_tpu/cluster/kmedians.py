"""K-Medians clustering.

API parity with /root/reference/heat/cluster/kmedians.py: Lloyd-style
iterations where the centroid update is the per-cluster coordinate-wise
median (reference computes distributed medians with extra comm per
cluster). Here the masked median over the sharded sample axis is one jnp
reduction per iteration.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from typing import Optional, Union

from ..core.dndarray import DNDarray
from ._kcluster import _KCluster

__all__ = ["KMedians"]


@functools.lru_cache(maxsize=64)
def _median_step(k: int, shape, jdtype: str):
    @jax.jit
    def step(arr, centers):
        # L1 assignment matches the coordinate-wise-median update
        d1 = jnp.sum(jnp.abs(arr[:, None, :] - centers[None, :, :]), axis=-1)
        labels = jnp.argmin(d1, axis=1)
        # masked per-cluster coordinate-wise median via NaN-masking
        def one_cluster(i):
            mask = labels == i
            masked = jnp.where(mask[:, None], arr, jnp.nan)
            med = jnp.nanmedian(masked, axis=0)
            return jnp.where(jnp.any(mask), med, centers[i])

        new_centers = jax.vmap(one_cluster)(jnp.arange(k))
        shift = jnp.sum((new_centers - centers) ** 2)
        return new_centers, shift

    return step


class KMedians(_KCluster):
    """K-Medians: cluster centers are coordinate-wise medians; assignment
    and functional value use the Manhattan metric (reference:
    kmedians.py:49 passes ht.spatial.distance.manhattan)."""

    _assignment_metric = "manhattan"

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedians++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: None,
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        """Seeding + convergence loop + assignment as ONE compiled program
        (see ``_kcluster._fused_fit_program``)."""
        return self._fit_fused(x, _median_step, returns_inertia=False)
