"""Distributed clustering (reference: /root/reference/heat/cluster/)."""

from .kmeans import *
from .kmedians import *
from .kmedoids import *
from .spectral import *
