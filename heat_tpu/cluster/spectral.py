"""Spectral clustering.

API parity with /root/reference/heat/cluster/spectral.py (``Spectral``:
RBF/euclidean similarity → ``graph.Laplacian`` → Lanczos m-step
eigen-approximation → eig of the small tridiagonal T → KMeans on the
spectral embedding). Same pipeline here; the Lanczos iterations run on the
sharded Laplacian, the tiny T eigenproblem runs replicated.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Optional

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..graph import Laplacian
from ..spatial import distance
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(BaseEstimator, ClusteringMixin):
    """Spectral clustering on the graph Laplacian eigenspace (reference:
    spectral.py:16)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        if metric == "rbf":
            sig = np.sqrt(1.0 / (2.0 * gamma))
            sim = lambda x: distance.rbf(x, sigma=sig, quadratic_expansion=True)
        elif metric == "euclidean":
            sim = lambda x: distance.cdist(x, quadratic_expansion=True)
        else:
            raise NotImplementedError("Other kernels currently not supported")

        if laplacian == "eNeighbour":
            self._laplacian = Laplacian(
                sim,
                definition="norm_sym",
                mode="eNeighbour",
                threshold_key=boundary,
                threshold_value=threshold,
            )
        elif laplacian == "fully_connected":
            self._laplacian = Laplacian(sim, definition="norm_sym", mode="fully_connected")
        else:
            raise NotImplementedError("Other approaches currently not supported")

        if assign_labels == "kmeans":
            kmeans_params = params.get("params", {"n_clusters": n_clusters, "init": "kmeans++"})
            if n_clusters is not None:
                kmeans_params["n_clusters"] = n_clusters
            self._cluster = KMeans(**kmeans_params)
        else:
            raise NotImplementedError(
                "Other Label Assignment Algorithms are currently not available"
            )

        self._labels = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Eigenvectors of the Laplacian via Lanczos (reference:
        spectral.py:~120)."""
        from ..core import linalg

        L = self._laplacian.construct(x)
        m = min(self.n_lanczos, x.shape[0])
        V, T = linalg.lanczos(L, m)
        # eig of the small tridiagonal on host/device (reference uses
        # torch.linalg.eig on every rank)
        t = np.asarray(T.numpy(), dtype=np.float64)
        eval_, evec = np.linalg.eigh(t)
        order = np.argsort(eval_)
        eval_, evec = eval_[order], evec[:, order]
        # approximate eigenvectors of L
        emb = V.larray @ jnp.asarray(evec.astype(np.asarray(V.larray).dtype))
        embedding = DNDarray(
            V.comm.shard(emb, 0 if x.split is not None else None) if x.split is not None else emb,
            tuple(int(s) for s in emb.shape),
            V.dtype,
            0 if x.split is not None else None,
            x.device,
            x.comm,
        )
        return eval_, embedding

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference: spectral.py:~160)."""
        sanitize_in(x)
        if x.split is not None and x.split != 0:
            raise NotImplementedError("Not implemented for other splitting-axes")
        eval_, embedding = self._spectral_embedding(x)

        if self.n_clusters is None:
            # eigengap heuristic (reference: spectral.py selects by gap)
            diff = np.diff(eval_)
            self.n_clusters = int(np.argmax(diff)) + 1
            self._cluster.n_clusters = self.n_clusters

        components = embedding[:, : self.n_clusters]
        self._cluster.fit(components)
        self._labels = self._cluster.labels_
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels for the fitted data (embedding is transductive —
        reference spectral.py predict re-embeds the training graph)."""
        sanitize_in(x)
        if self._labels is None:
            raise RuntimeError("fit needs to be called before predict")
        return self._labels
