"""Tracing / profiling instrumentation.

The reference instruments its continuous benchmarks with the external
``perun`` energy/runtime monitor (``@monitor()`` decorators,
reference benchmarks/cb/linalg.py:4-23); the library itself ships no
profiler. The TPU-native equivalents here:

- ``@monitor()`` — the same decorator shape: wall-time (and, on TPU,
  device-synchronized time) per call, accumulated in a module-level
  registry; ``report()`` renders/returns it. Drop-in for porting the
  reference's ``benchmarks/cb`` scripts.
- ``trace(path)`` — context manager around ``jax.profiler`` emitting a
  Perfetto/XPlane trace of everything inside (compile, HBM transfers,
  collectives on ICI) for offline analysis in TensorBoard/Perfetto.

Energy (the perun-parity deviation, explicit per VERDICT r4 #8): perun
reads RAPL/NVML counters on the reference's CPU/GPU hosts. This
platform exposes NO per-process energy counter — TPU power telemetry
lives in the cloud monitoring plane (``tpu.googleapis.com`` duty-cycle /
watts metrics), not in any in-container API, and the jax profiler
reports time/bytes/FLOPs but not joules. ``@monitor`` therefore records
runtime only; for energy estimates, multiply device-seconds by the
chip's published TDP envelope (v5e: ~170-250 W/chip depending on
workload class) or read the fleet metrics externally. docs/PERF.md
carries the same note next to the benchmark table.
"""

from __future__ import annotations

import contextlib
import functools
import json
import time

from typing import Any, Callable, Dict, Optional

import jax

__all__ = ["monitor", "report", "reset", "trace"]

_REGISTRY: Dict[str, Dict[str, float]] = {}


def _blockable(out):
    """Unwrap DNDarray leaves to their jax arrays: jax.block_until_ready
    treats a DNDarray as an opaque pytree leaf and returns immediately,
    which would make device work look free."""
    from ..core.dndarray import DNDarray

    if isinstance(out, DNDarray):
        return out._phys
    if isinstance(out, dict):
        return {k: _blockable(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return [_blockable(v) for v in out]
    return out


def monitor(name: Optional[str] = None, sync: bool = True):
    """Decorator recording per-call wall time under ``name`` (defaults to
    the function name) — the shape of perun's ``@monitor()`` used by the
    reference's continuous benchmarks.

    ``sync=True`` blocks on jax array outputs before stopping the clock,
    so asynchronous dispatch doesn't make device work look free.
    """

    def deco(fn: Callable) -> Callable:
        key = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if sync:
                try:
                    jax.block_until_ready(_blockable(out))
                except (TypeError, ValueError):
                    # non-blockable output structure; device-execution
                    # errors must propagate, not be recorded as timings
                    pass
            dt = time.perf_counter() - t0
            ent = _REGISTRY.setdefault(key, {"calls": 0, "total_s": 0.0, "best_s": float("inf")})
            ent["calls"] += 1
            ent["total_s"] += dt
            ent["best_s"] = min(ent["best_s"], dt)
            return out

        return wrapper

    return deco


def report(as_json: bool = False) -> Any:
    """Accumulated monitor table: {name: {calls, total_s, best_s, mean_s}}."""
    table = {
        k: {**v, "mean_s": v["total_s"] / v["calls"] if v["calls"] else 0.0}
        for k, v in _REGISTRY.items()
    }
    if as_json:
        return json.dumps(table)
    return table


def reset() -> None:
    """Clear the monitor registry."""
    _REGISTRY.clear()


@contextlib.contextmanager
def trace(path: str):
    """Capture a jax.profiler trace (Perfetto/XPlane) of the enclosed
    block to ``path`` — view in TensorBoard or ui.perfetto.dev. The
    TPU-side story the reference delegates to perun's energy counters."""
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
