"""Tracing / profiling instrumentation — compat shim over
``heat_tpu.observability``.

The reference instruments its continuous benchmarks with the external
``perun`` energy/runtime monitor (``@monitor()`` decorators,
reference benchmarks/cb/linalg.py:4-23); the library itself ships no
profiler. This module keeps the perun-shaped surface (``monitor`` /
``report`` / ``reset`` / ``trace``) for ported ``benchmarks/cb``
scripts, but since the observability subsystem landed it is a THIN
SHIM: timings go into a dedicated
:class:`heat_tpu.observability.telemetry.Registry` (always on — the
decorator is explicit opt-in, independent of the global
``HEAT_TPU_TELEMETRY`` switch), and ``report()`` renders that
registry's statistics — call counts, totals, best, mean AND p50/p95,
which the old standalone implementation could not provide. The backing
registry is sharded per recording thread (ISSUE 9: the serving
dispatcher's worker and its client threads record concurrently), so
``@monitor``-ed functions called from many threads never serialize on
one lock and the reported totals stay exact. For first-party metrics
(collective counts, reshard bytes, cache hits) use ``ht.telemetry`` /
``ht.observability`` directly.

Energy (the perun-parity deviation, explicit per VERDICT r4 #8): perun
reads RAPL/NVML counters on the reference's CPU/GPU hosts. This
platform exposes NO per-process energy counter — TPU power telemetry
lives in the cloud monitoring plane (``tpu.googleapis.com`` duty-cycle /
watts metrics), not in any in-container API, and the jax profiler
reports time/bytes/FLOPs but not joules. ``@monitor`` therefore records
runtime only; for energy estimates, multiply device-seconds by the
chip's published TDP envelope (v5e: ~170-250 W/chip depending on
workload class) or read the fleet metrics externally. docs/PERF.md
carries the same note next to the benchmark table.
"""

from __future__ import annotations

import contextlib
import functools
import json
import time

from typing import Any, Callable, Optional

import jax

from ..observability import telemetry as _telemetry

__all__ = ["monitor", "report", "reset", "trace"]

# dedicated always-on registry: decorating a function IS the opt-in, so
# @monitor timings must not depend on the global telemetry switch
_REGISTRY = _telemetry.Registry()


def _blockable(out):
    """Unwrap DNDarray leaves to their jax arrays: jax.block_until_ready
    treats a DNDarray as an opaque pytree leaf and returns immediately,
    which would make device work look free."""
    from ..core.dndarray import DNDarray

    if isinstance(out, DNDarray):
        return out._phys
    if isinstance(out, dict):
        return {k: _blockable(v) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return [_blockable(v) for v in out]
    return out


def monitor(name: Optional[str] = None, sync: bool = True):
    """Decorator recording per-call wall time under ``name`` (defaults to
    the function name) — the shape of perun's ``@monitor()`` used by the
    reference's continuous benchmarks.

    ``sync=True`` blocks on jax array outputs before stopping the clock,
    so asynchronous dispatch doesn't make device work look free. When the
    global telemetry switch is on, each call is mirrored as a
    ``monitor.<name>`` timer in the process-wide registry too, so
    ``@monitor``-ed workloads land in the same export as the first-party
    metrics.
    """

    def deco(fn: Callable) -> Callable:
        key = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if sync:
                try:
                    jax.block_until_ready(_blockable(out))
                except (TypeError, ValueError):
                    # non-blockable output structure; device-execution
                    # errors must propagate, not be recorded as timings
                    pass
            dt = time.perf_counter() - t0
            _REGISTRY.observe(key, dt)
            _telemetry.observe(f"monitor.{key}", dt)  # no-op unless enabled
            return out

        return wrapper

    return deco


def report(as_json: bool = False) -> Any:
    """Accumulated monitor table:
    ``{name: {calls, total_s, best_s, mean_s, max_s, p50_s, p95_s}}``
    (the old report carried totals only; call counts and percentiles
    come from the registry's sample reservoir)."""
    table = _REGISTRY.timer_table()
    if as_json:
        return json.dumps(table)
    return table


def reset() -> None:
    """Clear the monitor registry."""
    _REGISTRY.clear()


@contextlib.contextmanager
def trace(path: str):
    """Capture a jax.profiler trace (Perfetto/XPlane) of the enclosed
    block to ``path`` — view in TensorBoard or ui.perfetto.dev. The
    TPU-side story the reference delegates to perun's energy counters."""
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
