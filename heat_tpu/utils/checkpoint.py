"""Checkpoint / resume for model and optimizer state.

The reference ships NO model checkpointing (SURVEY §5: persistence is
``ht.save``/``ht.load`` to HDF5/netCDF, and the only optimizer-state
capture is ``DetectMetricPlateau.get_state/set_state``,
reference optim/utils.py:72/89). A TPU framework needs a real story:
training state is a pytree of sharded arrays, and a checkpoint must be
written per-host in parallel without gathering onto one controller.

This wraps orbax — the TPU-ecosystem checkpointer — with DNDarray
awareness: DNDarrays are decomposed into their physical arrays plus
(gshape, split, dtype) metadata; orbax persists the arrays (sharded
arrays are written shard-parallel on multi-host meshes) and restore
rebinds DNDarrays on the current world communicator.

Works on arbitrary pytrees: ``{"model": params, "opt": opt_state}``,
lists, nested dicts, plain jax arrays, numpy, scalars, DNDarrays.
"""

from __future__ import annotations

import os

import numpy as np

import jax

from typing import Any, Optional

from ..core import types
from ..core.communication import sanitize_comm
from ..core.devices import sanitize_device
from ..core.dndarray import DNDarray

__all__ = ["save_checkpoint", "load_checkpoint"]

_DND_KEY = "__heat_dndarray__"


def _encode(obj):
    """Recursively decompose DNDarrays into orbax-storable leaves."""
    if isinstance(obj, dict) and (_DND_KEY in obj or "__tuple__" in obj):
        raise ValueError(
            f"dict keys {_DND_KEY!r} and '__tuple__' are reserved by the "
            "checkpoint encoding"
        )
    if isinstance(obj, DNDarray):
        return {
            _DND_KEY: True,
            "data": obj._phys,
            "gshape": list(obj.gshape),
            "split": -1 if obj.split is None else int(obj.split),
            "dtype": np.dtype(obj.dtype.jax_type()).name,
        }
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        enc = [_encode(v) for v in obj]
        return enc if isinstance(obj, list) else {"__tuple__": enc}
    return obj


def _decode(obj, comm, device):
    if isinstance(obj, dict):
        if obj.get(_DND_KEY):
            split = None if int(obj["split"]) < 0 else int(obj["split"])
            gshape = tuple(int(s) for s in obj["gshape"])
            data = obj["data"]
            # ALWAYS rebind to the current communicator: orbax restores
            # with the sharding (and pad extent) recorded at save time,
            # which may belong to a different mesh/topology — strip the
            # old pad against the recorded logical shape, then reshard
            from ..core import _padding

            logical = _padding.unpad(jax.numpy.asarray(data), gshape, split)
            phys = comm.shard(logical, split)
            return DNDarray(
                phys,
                gshape,
                types.canonical_heat_type(np.dtype(obj["dtype"])),
                split,
                device,
                comm,
            )
        if "__tuple__" in obj and len(obj) == 1:
            return tuple(_decode(v, comm, device) for v in obj["__tuple__"])
        return {k: _decode(v, comm, device) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v, comm, device) for v in obj]
    return obj


def save_checkpoint(path: str, tree: Any, overwrite: bool = True) -> None:
    """Persist a pytree of DNDarrays / jax arrays / numpy / scalars.

    On multi-host meshes orbax writes each host's shards in parallel —
    the global array is never materialized on one controller (the
    scale-safety requirement SURVEY §7 sets for all I/O paths).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, _encode(tree), force=overwrite)


def load_checkpoint(path: str, comm=None, device=None) -> Any:
    """Restore a pytree saved by ``save_checkpoint``; DNDarrays rebind to
    ``comm`` (default: the global world communicator), resharded to their
    recorded split."""
    import orbax.checkpoint as ocp

    comm = sanitize_comm(comm)
    device = sanitize_device(device)
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path)
    return _decode(restored, comm, device)
