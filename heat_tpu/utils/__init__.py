"""Utilities (reference: /root/reference/heat/utils/)."""

from . import data
