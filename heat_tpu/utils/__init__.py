"""Utilities (reference: /root/reference/heat/utils/)."""

from . import data
from . import vision_transforms
