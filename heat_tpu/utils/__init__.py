"""Utilities (reference: /root/reference/heat/utils/). ``checkpoint`` is a
TPU-native addition: sharding-aware training-state persistence (the
reference has no model checkpointing — SURVEY §5)."""

from . import checkpoint
from . import monitor
from . import data
from . import vision_transforms
from .checkpoint import load_checkpoint, save_checkpoint
