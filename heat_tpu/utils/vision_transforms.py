"""Vision transforms.

The reference delegates wholesale to ``torchvision.transforms``
(/root/reference/heat/utils/vision_transforms.py:10). torchvision is not in
this stack, so the transforms the reference's MNIST example actually uses
(ToTensor, Normalize, Compose — examples/nn/mnist.py) are provided as
small numpy/jax-compatible callables; anything else raises with a clear
pointer.
"""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "Normalize", "ToTensor"]


class Compose:
    """Chain transforms (torchvision.transforms.Compose semantics)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """uint8 HWC/HW image(s) → float32 in [0, 1] (torchvision semantics;
    channel reordering is a no-op for MNIST's single channel)."""

    def __call__(self, x):
        x = np.asarray(x)
        if x.dtype == np.uint8:
            x = x.astype(np.float32) / 255.0
        return x.astype(np.float32)


class Normalize:
    """(x - mean) / std per channel (torchvision.transforms.Normalize)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)

    def __call__(self, x):
        return (np.asarray(x, dtype=np.float32) - self.mean) / self.std


def __getattr__(name):
    raise AttributeError(
        f"vision transform '{name}' is not implemented (the reference delegates to "
        f"torchvision, which is not available in this stack); available: {__all__}"
    )
