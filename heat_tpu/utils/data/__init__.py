"""Data utilities (reference: /root/reference/heat/utils/data/)."""

from . import matrixgallery
from . import spherical
from . import datatools
from . import partial_dataset
from . import mnist
from .spherical import create_spherical_dataset
from .datatools import DataLoader, Dataset, dataset_shuffle, dataset_ishuffle
from .partial_dataset import PartialH5Dataset
from .mnist import MNISTDataset
