"""Data utilities (reference: /root/reference/heat/utils/data/)."""

from . import matrixgallery
from . import spherical
from .spherical import create_spherical_dataset
