"""MNIST dataset over sharded arrays.

Parity with /root/reference/heat/utils/data/mnist.py (``MNISTDataset`` at
mnist.py:16, a split-aware torchvision MNIST). torchvision is not part of
this stack (and the build environment has no network egress), so this
reader parses the standard IDX files directly from a local directory —
the same files torchvision's MNIST stores under ``<root>/MNIST/raw``.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from typing import Optional

from ...core import factories, types
from ...core.dndarray import DNDarray
from .datatools import Dataset

__all__ = ["MNISTDataset"]

_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (optionally .gz): big-endian magic, dims, data."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(root: str, name: str) -> str:
    for cand in (
        os.path.join(root, name),
        os.path.join(root, name + ".gz"),
        os.path.join(root, "MNIST", "raw", name),
        os.path.join(root, "MNIST", "raw", name + ".gz"),
    ):
        if os.path.exists(cand):
            return cand
    raise FileNotFoundError(
        f"MNIST file {name}(.gz) not found under {root} (expected the standard "
        f"IDX layout, e.g. <root>/MNIST/raw/{name}); download is not possible "
        f"in an egress-free environment"
    )


class MNISTDataset(Dataset):
    """MNIST over the mesh (reference mnist.py:16).

    Parameters
    ----------
    root : str
        Directory containing the IDX files.
    train : bool
        Training split vs test split (reference: ``train``).
    transform : callable, optional
        Applied to the image array (host-side, once) — e.g.
        ``heat_tpu.utils.vision_transforms.Normalize``.
    ishuffle : bool
        Async inter-epoch shuffling (reference mnist.py:122).
    split : 0 or None
        Sample-axis distribution (the reference always splits dim 0).
    """

    def __init__(
        self,
        root: str,
        train: bool = True,
        transform=None,
        target_transform=None,
        ishuffle: bool = False,
        test_set: Optional[bool] = None,
        split: Optional[int] = 0,
    ):
        if split not in (None, 0):
            raise ValueError(f"MNISTDataset supports split 0 or None, got {split}")
        img_name, lbl_name = _FILES[bool(train)]
        images = _read_idx(_find(root, img_name)).astype(np.float32) / 255.0
        labels = _read_idx(_find(root, lbl_name)).astype(np.int32)
        if transform is not None:
            images = np.asarray(transform(images))
        if target_transform is not None:
            labels = np.asarray(target_transform(labels))
        data = factories.array(images, split=split)
        targets = factories.array(labels, split=split)
        super().__init__(
            data,
            targets=targets,
            ishuffle=ishuffle,
            test_set=(not train) if test_set is None else bool(test_set),
        )
        self.train = bool(train)
