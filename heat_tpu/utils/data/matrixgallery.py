"""Test/benchmark matrix generators.

API parity with /root/reference/heat/utils/data/matrixgallery.py
(``hermitian``, ``parter``, ``random_orthogonal``,
``random_known_singularvalues``, ``random_known_rank``) — fixtures for the
linalg tests and the hSVD benchmarks.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Callable, Optional, Tuple, Union

from ...core import factories, random as ht_random, types
from ...core.dndarray import DNDarray
from ...core.linalg import matmul, qr, transpose

__all__ = [
    "hermitian",
    "parter",
    "random_orthogonal",
    "random_known_singularvalues",
    "random_known_rank",
]


def hermitian(n: int, dtype=types.complex64, split=None, device=None, comm=None) -> DNDarray:
    """Random hermitian (or symmetric, for real dtype) n×n matrix
    (reference: matrixgallery.py hermitian)."""
    dtype = types.canonical_heat_type(dtype)
    if types.heat_type_is_complexfloating(dtype):
        real = ht_random.randn(n, n, split=split, device=device, comm=comm)
        imag = ht_random.randn(n, n, split=split, device=device, comm=comm)
        arr = real.larray + 1j * imag.larray
        a = DNDarray(
            real.comm.shard(arr.astype(dtype.jax_type()), real.split),
            (n, n),
            dtype,
            real.split,
            real.device,
            real.comm,
        )
        out_arr = (a.larray + jnp.conj(a.larray).T) / 2
    else:
        a = ht_random.randn(n, n, split=split, device=device, comm=comm, dtype=dtype)
        out_arr = (a.larray + a.larray.T) / 2
    return DNDarray(
        a.comm.shard(out_arr, a.split) if a.split is not None else out_arr,
        (n, n),
        dtype,
        a.split,
        a.device,
        a.comm,
    )


def parter(n: int, split=None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """Parter matrix: Cauchy matrix with singular values near π
    (reference: matrixgallery.py parter)."""
    ii = factories.arange(n, dtype=types.float32, split=None, device=device, comm=comm)
    arr = 1.0 / (ii.larray[:, None] - ii.larray[None, :] + 0.5)
    dtype = types.canonical_heat_type(dtype)
    comm_ = ii.comm
    from ...core.stride_tricks import sanitize_axis

    split = sanitize_axis((n, n), split)
    out = arr.astype(dtype.jax_type())
    if split is not None:
        out = comm_.shard(out, split)
    return DNDarray(out, (n, n), dtype, split, ii.device, comm_)


def random_orthogonal(m: int, n: int, split=None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """Random m×n matrix with orthonormal columns (requires m >= n;
    reference: matrixgallery.py random_orthogonal)."""
    if m < n:
        raise ValueError(f"m >= n required, got {m} < {n}")
    a = ht_random.randn(m, n, dtype=types.canonical_heat_type(dtype), split=split, device=device, comm=comm)
    q, _ = qr(a)
    return q


def random_known_singularvalues(
    m: int, n: int, singular_values: DNDarray, split=None, device=None, comm=None, dtype=types.float32
) -> Tuple[DNDarray, Tuple[DNDarray, DNDarray]]:
    """Random matrix with prescribed singular values (reference:
    matrixgallery.py random_known_singularvalues). Returns
    (A, (U, V))."""
    if isinstance(singular_values, DNDarray):
        k = singular_values.shape[0]
        s = singular_values.larray
    else:
        s = jnp.asarray(np.asarray(singular_values))
        k = int(s.shape[0])
    if k > min(m, n):
        raise ValueError(f"number of singular values {k} exceeds min(m, n)={min(m, n)}")
    U = random_orthogonal(m, k, split=split, device=device, comm=comm, dtype=dtype)
    V = random_orthogonal(n, k, split=split, device=device, comm=comm, dtype=dtype)
    us = U.larray * s
    A_arr = us @ V.larray.T
    comm_ = U.comm
    from ...core.stride_tricks import sanitize_axis

    split = sanitize_axis((m, n), split)
    if split is not None:
        A_arr = comm_.shard(A_arr, split)
    A = DNDarray(A_arr, (m, n), types.canonical_heat_type(dtype), split, U.device, comm_)
    s_arr = factories.array(np.asarray(s), comm=comm_)
    return A, (U, s_arr, V)


def random_known_rank(
    m: int,
    n: int,
    r: int,
    quantile_function: Callable = lambda x: -np.log(x),
    split=None,
    device=None,
    comm=None,
    dtype=types.float32,
) -> Tuple[DNDarray, Tuple[DNDarray, DNDarray]]:
    """Random matrix of known rank r with singular values drawn through
    ``quantile_function`` (reference: matrixgallery.py random_known_rank)."""
    if r > min(m, n):
        raise ValueError(f"rank {r} exceeds min(m, n)={min(m, n)}")
    # draw through the framework RNG so ht.random.seed governs the fixture
    u = np.sort(np.asarray(ht_random.rand(r).numpy()))[::-1]
    s = np.asarray([quantile_function(x) for x in u], dtype=np.float32)
    return random_known_singularvalues(m, n, factories.array(s), split=split, device=device, comm=comm, dtype=dtype)
