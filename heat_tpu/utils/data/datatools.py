"""Dataset / DataLoader over sharded arrays.

Parity with /root/reference/heat/utils/data/datatools.py: ``Dataset``
(datatools.py:143) wraps the local shard of a DNDarray; ``DataLoader``
(:16) wraps a torch DataLoader over it; ``dataset_shuffle``/
``dataset_ishuffle`` (:246/:301) ring-send HALF of each rank's samples to
the next rank and then locally permute — a partial cross-rank shuffle
bounded by what two-sided MPI makes cheap.

TPU-native redesign: data stays a global sharded ``jax.Array``; a batch is
a slice along axis 0 (still sharded — every device reads only its rows);
the inter-epoch shuffle is ONE jitted global gather ``x[perm]`` whose
all-to-all XLA emits over ICI. That is a FULL uniform shuffle — strictly
stronger mixing than the reference's half-ring — at the cost the ring was
approximating. ``ishuffle`` keeps the reference's overlap intent: XLA
dispatch is asynchronous, so the shuffle for the next epoch is launched
eagerly and only consumed at first batch access.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Iterator, List, Optional, Union

from ...core import random as ht_random
from ...core import types
from ...core.communication import sanitize_comm
from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle"]


@functools.lru_cache(maxsize=64)
def _cached_permute(comm, ndim: int, split):
    """Jitted global permutation along axis 0, sharding preserved — the
    collective replacement for the reference's Isend/Irecv half-ring +
    local randperm (datatools.py:246-343). ``x`` is committed, so
    ``jit_sharded``'s one-device fast path applies; jit retraces per
    operand dtype/shape on its own."""

    def permute(x, perm):
        return jnp.take(x, perm, axis=0)

    return comm.jit_sharded(permute, ndim, split)


def _global_shuffle(array: DNDarray, perm: jax.Array) -> DNDarray:
    """Apply a global sample permutation to a split-0 (or replicated)
    DNDarray. The physical pad rows are permuted along — perm is over the
    PHYSICAL extent with pad rows fixed in place, keeping the zero-pad
    invariant."""
    phys = array._phys
    permute = _cached_permute(array.comm, phys.ndim, array.split)
    out = permute(phys, perm)
    return DNDarray(out, array.shape, array.dtype, array.split, array.device, array.comm)


class Dataset:
    """Dataset over one or more sharded arrays (reference datatools.py:143).

    Parameters
    ----------
    array : DNDarray
        Samples, split along axis 0 (or replicated).
    targets : DNDarray, optional
        Labels with the same leading extent.
    ishuffle : bool
        Launch next-epoch shuffles asynchronously (reference :237).
    test_set : bool
        Never shuffle (reference: test sets are static).

    The reference exposes the torch-local shard via ``__getitem__``; here
    indexing returns DNDarray slices of the global array.
    """

    def __init__(
        self,
        array: DNDarray,
        targets: Optional[DNDarray] = None,
        ishuffle: bool = False,
        test_set: bool = False,
    ):
        if not isinstance(array, DNDarray):
            raise TypeError(f"array must be a DNDarray, got {type(array)}")
        if array.split not in (None, 0):
            raise ValueError("Dataset requires the sample axis (0) as split")
        if targets is not None and targets.shape[0] != array.shape[0]:
            raise ValueError(
                f"targets leading extent {targets.shape[0]} != samples {array.shape[0]}"
            )
        self.htdata = array
        self.httargets = targets
        self.comm = array.comm
        self.ishuffle = bool(ishuffle)
        self.test_set = bool(test_set)

    def __len__(self) -> int:
        return self.htdata.shape[0]

    def __getitem__(self, index) -> Union[DNDarray, tuple]:
        if self.httargets is None:
            return self.htdata[index]
        return self.htdata[index], self.httargets[index]

    def Shuffle(self) -> None:
        """Full global sample shuffle (reference datatools.py:229)."""
        dataset_shuffle(self, self._default_attrs())

    def Ishuffle(self) -> None:
        """Asynchronously dispatched shuffle (reference :237) — XLA's
        async dispatch provides the overlap the reference hand-builds."""
        dataset_ishuffle(self, self._default_attrs())

    def _default_attrs(self) -> List[List[str]]:
        attrs = [["htdata", None]]
        if self.httargets is not None:
            attrs.append(["httargets", None])
        return attrs


def dataset_shuffle(dataset, attrs: List[list]) -> None:
    """Shuffle the named DNDarray attributes of ``dataset`` with ONE shared
    global permutation (reference datatools.py:246: half-ring exchange +
    local randperm; here a jitted sharded gather — a full uniform
    shuffle). Attributes may differ in split (and hence pad extent); the
    shared LOGICAL permutation is extended per array so pad rows stay
    parked at each array's own tail."""
    first = getattr(dataset, attrs[0][0])
    n_logical = first.shape[0]
    perm_logical = ht_random.randperm(n_logical).larray
    for att in attrs:
        arr = getattr(dataset, att[0])
        if arr.shape[0] != n_logical:
            raise ValueError(
                f"attribute {att[0]} has leading extent {arr.shape[0]}, expected "
                f"{n_logical} (all shuffled attrs must share the sample axis)"
            )
        n_phys = arr._phys.shape[0]
        perm = perm_logical
        if n_phys > n_logical:
            perm = jnp.concatenate([perm, jnp.arange(n_logical, n_phys)])
        setattr(dataset, att[0], _global_shuffle(arr, perm))


def dataset_ishuffle(dataset, attrs: List[list]) -> None:
    """Non-blocking shuffle (reference datatools.py:301): the gather is
    dispatched now, consumed whenever the data is next touched — XLA's
    async runtime replaces the Isend/Irecv + wait-handle machinery."""
    dataset_shuffle(dataset, attrs)


class DataLoader:
    """Iterate a Dataset (or DNDarray) in sharded global batches
    (reference datatools.py:16 wraps torch's DataLoader over the local
    shard; batch_size there is PER RANK — here it is the GLOBAL batch,
    i.e. reference_batch_size × comm.size).

    Each yielded batch is a DNDarray slice, split over the mesh; feeding it
    to ``DataParallelOptimizer.step`` keeps the whole pipeline on device.
    """

    def __init__(
        self,
        dataset: Union[Dataset, DNDarray],
        batch_size: int = 1,
        drop_last: bool = True,
        shuffle: bool = False,
        ishuffle: Optional[bool] = None,
    ):
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset)
        if not isinstance(dataset, Dataset) and not hasattr(dataset, "__iter__"):
            raise TypeError(f"dataset must be a Dataset or DNDarray, got {type(dataset)}")
        if batch_size < 1:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self.shuffle = bool(shuffle)
        if self.shuffle and not isinstance(dataset, Dataset):
            raise ValueError(
                "shuffle=True requires a Dataset; streaming datasets own their "
                "shuffling (e.g. PartialH5Dataset.Shuffle)"
            )
        if ishuffle is not None and isinstance(dataset, Dataset):
            dataset.ishuffle = bool(ishuffle)
        self._first_epoch = True

    def __len__(self) -> int:
        if not isinstance(self.dataset, Dataset):
            # streaming datasets batch themselves; defer to their count
            return len(self.dataset)
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator:
        ds = self.dataset
        if isinstance(ds, Dataset):
            if self.shuffle and not ds.test_set:
                ds.Shuffle()
            n = len(ds)
            nbatch = len(self)
            for b in range(nbatch):
                start = b * self.batch_size
                stop = min(start + self.batch_size, n)
                yield ds[start:stop]
        else:  # custom iterable dataset (e.g. PartialH5Dataset)
            yield from ds

from ...core.communication import register_mesh_cache

# entries bake mesh geometry: cleared when init_distributed rebuilds the world
register_mesh_cache(_cached_permute)
