"""Synthetic spherical cluster data.

API parity with /root/reference/heat/utils/data/spherical.py
(``create_spherical_dataset``): four 3-D gaussian clusters at ±offset used
by the clustering benchmarks and tests.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core import factories, manipulations, random as ht_random, types
from ...core.dndarray import DNDarray

__all__ = ["create_spherical_dataset"]


def create_spherical_dataset(
    num_samples_cluster: int,
    radius: float = 1.0,
    offset: float = 4.0,
    dtype=types.float32,
    random_state: int = 1,
) -> DNDarray:
    """Four spherical clusters of ``num_samples_cluster`` 3-D points each,
    uniformly distributed inside spheres of the given ``radius`` centered
    at (±offset, ±2·offset) on the diagonal (reference: spherical.py —
    same centers and bounded spread)."""
    ht_random.seed(random_state)
    n = int(num_samples_cluster)
    parts = []
    for sign in (-2.0, -1.0, 1.0, 2.0):
        center = float(sign) * offset
        # uniform inside the sphere: gaussian direction × U^(1/3) radius
        direction = ht_random.randn(n, 3, dtype=types.canonical_heat_type(dtype))
        u = ht_random.rand(n, 1, dtype=types.canonical_heat_type(dtype))
        d_arr = direction.larray
        norms = (d_arr / jnp.maximum(jnp.linalg.norm(d_arr, axis=1, keepdims=True), 1e-30))
        pts = norms * (u.larray ** (1.0 / 3.0)) * radius + center
        blob = DNDarray(
            direction.comm.shard(pts, direction.split),
            (n, 3),
            direction.dtype,
            direction.split,
            direction.device,
            direction.comm,
        )
        parts.append(blob)
    data = manipulations.concatenate(parts, axis=0)
    return data.resplit(0)
