"""Streaming dataset for HDF5 files larger than device memory.

Parity with /root/reference/heat/utils/data/partial_dataset.py
(``PartialH5Dataset`` at partial_dataset.py:32): load ``initial_load``
samples up front, then background-thread prefetch of the next file chunk
while the accelerator consumes the current one (queue_thread :20,
loader iterator :224-330).

TPU-native shape: the prefetch thread reads host hyperslabs with h5py; the
consuming iterator device_puts each global batch onto the mesh (split=0)
and yields DNDarrays. Host read ↔ device compute overlap comes from the
thread + XLA's async dispatch rather than the reference's hand-rolled
convert/insert queues.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

import jax

from typing import Iterator, List, Optional, Union

from ...core import types
from ...core.communication import sanitize_comm
from ...core.devices import sanitize_device
from ...core.dndarray import DNDarray

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter"]


class PartialH5Dataset:
    """Stream a large HDF5 dataset in chunks (reference
    partial_dataset.py:32).

    Parameters
    ----------
    file : str
        HDF5 file path.
    dataset_names : str or list of str
        Dataset keys to stream jointly (reference: ``dataset_names``).
    batch_size : int
        Global batch size of the yielded DNDarrays.
    initial_load : int
        Samples resident at a time (the reference's ``initial_load``).
    use_gpu_prefetch : bool
        Kept for API parity; device placement is always asynchronous.
    shuffle_within_chunk : bool
        Permute samples inside each resident chunk (the reference shuffles
        converted batches; a streaming pass cannot do a full global
        shuffle without a second copy on disk).
    """

    def __init__(
        self,
        file: str,
        dataset_names: Union[str, List[str]] = "data",
        batch_size: int = 64,
        initial_load: int = 4096,
        use_gpu_prefetch: bool = True,
        shuffle_within_chunk: bool = False,
        dtype=types.float32,
        device=None,
        comm=None,
    ):
        import h5py

        self.file = file
        self.dataset_names = [dataset_names] if isinstance(dataset_names, str) else list(dataset_names)
        self.batch_size = int(batch_size)
        self.initial_load = int(initial_load)
        self.shuffle_within_chunk = bool(shuffle_within_chunk)
        self.dtype = types.canonical_heat_type(dtype)
        self.device = sanitize_device(device)
        self.comm = sanitize_comm(comm)
        with h5py.File(file, "r") as f:
            lengths = {name: f[name].shape[0] for name in self.dataset_names}
            if len(set(lengths.values())) != 1:
                raise ValueError(f"datasets disagree on sample count: {lengths}")
            self.total_size = next(iter(lengths.values()))
            self.shapes = {name: tuple(f[name].shape[1:]) for name in self.dataset_names}

    def __len__(self) -> int:
        return self.total_size // self.batch_size

    def _read_chunk(self, start: int, stop: int) -> dict:
        import h5py

        with h5py.File(self.file, "r") as f:
            return {name: np.asarray(f[name][start:stop]) for name in self.dataset_names}

    def _wrap(self, host: np.ndarray) -> DNDarray:
        arr = jax.numpy.asarray(host.astype(np.dtype(self.dtype.jax_type())
                                            if self.dtype is not types.bfloat16 else np.float32))
        if self.dtype is types.bfloat16:
            arr = arr.astype(jax.numpy.bfloat16)
        phys = self.comm.shard(arr, 0)
        return DNDarray(
            phys, tuple(int(s) for s in arr.shape), self.dtype, 0, self.device, self.comm
        )

    def __iter__(self) -> Iterator:
        return PartialH5DataLoaderIter(self)

    def Shuffle(self) -> None:
        """Within-chunk shuffling toggle (reference partial_dataset.py:157
        notes full shuffling is unsupported for partial datasets too)."""
        self.shuffle_within_chunk = True

    def Ishuffle(self) -> None:
        raise NotImplementedError(
            "PartialH5Dataset does not support global ishuffle (reference "
            "partial_dataset.py:166 raises likewise)"
        )


class PartialH5DataLoaderIter:
    """Iterator with a background prefetch thread (reference
    partial_dataset.py:224): chunk N+1 is read from disk while chunk N's
    batches stream to the devices."""

    def __init__(self, loader: PartialH5Dataset):
        self._loader = loader
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        self._current: Optional[dict] = None
        self._pos = 0
        self._exhausted = False

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer is gone — an
        abandoned iterator must not leak a thread parked in Queue.put."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        ld = self._loader
        try:
            for start in range(0, ld.total_size, ld.initial_load):
                if self._stop.is_set():
                    return
                stop = min(start + ld.initial_load, ld.total_size)
                if not self._put(("chunk", ld._read_chunk(start, stop))):
                    return
        except Exception as exc:  # surface reader errors at the consumer
            self._put(("error", exc))
        finally:
            self._put(("done", None))

    def close(self) -> None:
        """Stop the prefetch thread and release queued chunks."""
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        ld = self._loader
        while True:
            if self._current is not None:
                n = next(iter(self._current.values())).shape[0]
                if self._pos + ld.batch_size <= n:
                    start, stop = self._pos, self._pos + ld.batch_size
                    self._pos = stop
                    out = [ld._wrap(arr[start:stop]) for arr in self._current.values()]
                    return out[0] if len(out) == 1 else tuple(out)
                self._current = None  # tail smaller than a batch: drop (reference drops too)
            if self._exhausted:
                self.close()
                raise StopIteration
            kind, payload = self._queue.get()
            if kind == "error":
                raise payload
            if kind == "done":
                self._exhausted = True
                continue
            if ld.shuffle_within_chunk:
                n = next(iter(payload.values())).shape[0]
                prm = np.random.default_rng().permutation(n)
                payload = {k: v[prm] for k, v in payload.items()}
            self._current = payload
            self._pos = 0
