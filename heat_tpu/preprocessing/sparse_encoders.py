"""Transforms that EMIT sparse outputs: one-hot encoding and TF-IDF.

The framework's first transforms whose natural output is sparse — a
one-hot row has exactly one stored value per feature, a TF-IDF row
keeps the document's term pattern — so both return ``DCSR_matrix``
(``sparse_output=True``, the default) instead of densifying N x C.

Both register as serving ``transform`` endpoints
(``ht.serving.transform_endpoint`` consumes their
``serving_program()``, the same contract the k-cluster predict
endpoints use) and both stream host-resident inputs through the PR 11
staging windows with ``stage_out`` WRITEBACK
(:meth:`~OneHotEncoder.stream_transform`): the transformed window
returns to a host buffer while the next window's ``stage_in`` rides
the wire, which is the first workload to exercise the staged plans'
``stage_out`` steps with real traffic.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, TransformMixin
from ..core.dndarray import DNDarray
from ..redistribution import staging as _staging
from ..sparse.dcsr_matrix import DCSR_matrix
from ..sparse import factories as _sfactories

__all__ = ["OneHotEncoder", "TfidfTransformer"]


def _host_2d(x, dtype=None) -> np.ndarray:
    """Any accepted input to a host 2-D ndarray (samples on axis 0)."""
    if isinstance(x, DNDarray):
        arr = np.asarray(x.numpy())
    elif isinstance(x, DCSR_matrix):
        raise TypeError("expected a dense operand, got a sparse matrix")
    else:
        arr = np.asarray(x)
    if arr.ndim == 1:
        arr = arr[:, None]
    if arr.ndim != 2:
        raise ValueError(f"expected 1-D or 2-D input, got {arr.ndim}-D")
    return arr if dtype is None else arr.astype(dtype, copy=False)


class OneHotEncoder(BaseEstimator, TransformMixin):
    """Encode integer categorical features as one-hot rows, emitted
    sparse.

    ``fit`` learns the per-column category tables (host-side
    ``np.unique``); ``transform`` emits an (N, sum-of-categories)
    ``DCSR_matrix`` with exactly one stored 1.0 per (sample, feature) —
    nnz = N * F regardless of the encoded width. Unknown categories at
    transform time encode as all-zero rows for that feature block
    (sklearn's ``handle_unknown='ignore'``).
    """

    def __init__(self, sparse_output: bool = True):
        self.sparse_output = bool(sparse_output)
        self.categories_ = None   # list of sorted 1-D int arrays, per column
        self._offsets = None      # starting column of each feature block

    @property
    def n_features_out_(self) -> int:
        if self.categories_ is None:
            raise RuntimeError("fit needs to be called first")
        return int(sum(len(c) for c in self.categories_))

    def fit(self, x, y=None) -> "OneHotEncoder":
        arr = _host_2d(x)
        if not np.issubdtype(arr.dtype, np.integer):
            raise TypeError(
                f"OneHotEncoder encodes integer codes, got {arr.dtype}"
            )
        self.categories_ = [np.unique(arr[:, f]) for f in range(arr.shape[1])]
        sizes = np.array([len(c) for c in self.categories_], np.int64)
        self._offsets = np.concatenate([[0], np.cumsum(sizes)])
        return self

    def _encode_columns(self, arr: np.ndarray) -> np.ndarray:
        """Global output column per (sample, feature); -1 for unknown."""
        cols = np.empty(arr.shape, np.int64)
        for f, cats in enumerate(self.categories_):
            idx = np.searchsorted(cats, arr[:, f])
            idx_c = np.clip(idx, 0, len(cats) - 1)
            known = cats[idx_c] == arr[:, f]
            cols[:, f] = np.where(known, self._offsets[f] + idx_c, -1)
        return cols

    def transform(self, x) -> Union[DCSR_matrix, DNDarray]:
        if self.categories_ is None:
            raise RuntimeError("fit needs to be called before transform")
        arr = _host_2d(x)
        if arr.shape[1] != len(self.categories_):
            raise ValueError(
                f"fit saw {len(self.categories_)} features, transform got {arr.shape[1]}"
            )
        import scipy.sparse as sp

        N, F = arr.shape
        C = self.n_features_out_
        cols = self._encode_columns(arr)
        keep = cols.ravel() >= 0
        rows = np.repeat(np.arange(N, dtype=np.int64), F)[keep]
        csr = sp.csr_matrix(
            (np.ones(keep.sum(), np.float32), (rows, cols.ravel()[keep])),
            shape=(N, C),
        )
        split = x.split if isinstance(x, DNDarray) else None
        out = _sfactories.sparse_csr_matrix(
            csr, dtype=types.float32, split=0 if split is not None else None
        )
        if self.sparse_output:
            return out
        from ..sparse.manipulations import to_dense

        return to_dense(out)

    def serving_program(self) -> dict:
        """The ``transform`` endpoint description
        (``ht.serving.transform_endpoint``): a jitted dense one-hot of
        an int32 feature batch, category tables riding as replicated
        args. Dense is the wire format — a serving batch is b x C with
        b small, and endpoint results are arrays."""
        if self.categories_ is None:
            raise RuntimeError("fit needs to be called before serving")
        F = len(self.categories_)
        C = self.n_features_out_
        Cmax = max(len(c) for c in self.categories_)
        cats = np.full((F, Cmax), np.iinfo(np.int32).min, np.int32)
        for f, c in enumerate(self.categories_):
            cats[f, : len(c)] = c
        sizes = np.array([len(c) for c in self.categories_], np.int32)
        offsets = self._offsets[:-1].astype(np.int32)

        def build():
            @jax.jit  # shardlint: ignore[SL202] -- serving program body; the endpoint cache owns wrapping/donation (aot_cache precedent)
            def run(batch, cats, sizes, offsets):
                hit = batch[:, :, None] == cats[None, :, :]        # (b,F,Cmax)
                valid = jnp.arange(Cmax, dtype=jnp.int32)[None, :] < sizes[:, None]
                hit = (hit & valid[None, :, :]).astype(jnp.float32)
                col = offsets[:, None] + jnp.arange(Cmax, dtype=jnp.int32)[None, :]
                col = jnp.where(valid, col, C)  # pad lanes -> sentinel column
                b = batch.shape[0]
                out = jnp.zeros((b, C + 1), jnp.float32)
                out = out.at[
                    jnp.arange(b)[:, None],
                    jnp.broadcast_to(col.reshape(-1), (b, F * Cmax)),
                ].add(hit.reshape(b, -1))
                return out[:, :C]

            return run

        return {
            "build": build,
            "args": (jnp.asarray(cats), jnp.asarray(sizes), jnp.asarray(offsets)),
            "key": ("onehot-transform", F, C, Cmax),
            "feature_shape": (F,),
            "dtype": np.dtype(np.int32),
            "comm": None,
            "name": "onehot-transform",
        }

    def stream_transform(
        self, host: Union[_staging.HostArray, np.ndarray],
        slab: Optional[int] = None,
    ) -> np.ndarray:
        """Transform a host-resident code matrix window by window,
        writing each dense one-hot window BACK to a host buffer — the
        staged plan's ``stage_out`` steps carrying real traffic. The
        output is dense (N, C) on the HOST tier (never resident on
        device at once); sparse callers use :meth:`transform`."""
        if self.categories_ is None:
            raise RuntimeError("fit needs to be called before stream_transform")
        if not isinstance(host, _staging.HostArray):
            host = _staging.HostArray(np.ascontiguousarray(host, np.int32))
        N, F = host.shape
        if F != len(self.categories_):
            raise ValueError(
                f"fit saw {len(self.categories_)} features, stream got {F}"
            )
        C = self.n_features_out_
        sched = _staging.plan_staged_passes(
            host.shape, host.dtype,
            [{"tag": "onehot", "axis": 0, "writeback": True}],
            out_bytes=C * 4 * 4096 + (1 << 20), slab=slab,
        )
        _staging.prove_fits(sched)
        slab_b = int(sched.staging["slab_bytes"])
        wins = _staging.window_extents(host.shape, host.dtype.itemsize, 0, slab_b)
        out = np.zeros((N, C), np.float32)

        def consume(k, slab_arr, win):
            arr = np.asarray(jax.device_get(slab_arr))
            cols = self._encode_columns(arr)
            block = np.zeros((arr.shape[0], C), np.float32)
            r = np.repeat(np.arange(arr.shape[0]), arr.shape[1])
            c = cols.ravel()
            keep = c >= 0
            np.add.at(block, (r[keep], c[keep]), 1.0)
            out[win[0]:win[1]] = block  # stage_out: result hbm->host

        _staging.stream_windows(host, 0, wins, consume, plan_id=sched.plan_id)
        return out


class TfidfTransformer(BaseEstimator, TransformMixin):
    """Scale a term-count matrix to smoothed TF-IDF, emitted sparse.

    ``idf = log((1 + N) / (1 + df)) + 1`` (sklearn's ``smooth_idf``),
    rows l2-normalized. ``fit`` accepts a dense count matrix or a
    ``DCSR_matrix``; ``transform`` preserves the input's sparsity
    pattern exactly — the work is a per-stored-element scale plus a
    per-row norm, never a densify."""

    def __init__(self, sparse_output: bool = True, norm: Optional[str] = "l2"):
        if norm not in (None, "l2"):
            raise ValueError(f"norm must be 'l2' or None, got {norm!r}")
        self.sparse_output = bool(sparse_output)
        self.norm = norm
        self.idf_ = None

    def _counts_csr(self, x):
        import scipy.sparse as sp

        if isinstance(x, DCSR_matrix):
            indptr = np.asarray(jax.device_get(x.indptr))
            indices = np.asarray(jax.device_get(x.indices))
            data = np.asarray(jax.device_get(x.data))
            return sp.csr_matrix((data, indices, indptr), shape=x.shape)
        return sp.csr_matrix(_host_2d(x, np.float32))

    def fit(self, x, y=None) -> "TfidfTransformer":
        csr = self._counts_csr(x)
        N = csr.shape[0]
        df = np.bincount(csr.indices, minlength=csr.shape[1]).astype(np.float64)
        self.idf_ = (np.log((1.0 + N) / (1.0 + df)) + 1.0).astype(np.float32)
        return self

    def transform(self, x) -> Union[DCSR_matrix, DNDarray]:
        if self.idf_ is None:
            raise RuntimeError("fit needs to be called before transform")
        csr = self._counts_csr(x).astype(np.float32)
        if csr.shape[1] != self.idf_.shape[0]:
            raise ValueError(
                f"fit saw {self.idf_.shape[0]} terms, transform got {csr.shape[1]}"
            )
        out = csr.copy()
        out.data = out.data * self.idf_[out.indices]
        if self.norm == "l2":
            norms = np.sqrt(np.asarray(out.multiply(out).sum(axis=1))).ravel()
            scale = np.where(norms > 0, 1.0 / np.maximum(norms, 1e-30), 0.0)
            out.data = out.data * np.repeat(
                scale.astype(np.float32), np.diff(out.indptr)
            )
        split = x.split if isinstance(x, (DNDarray, DCSR_matrix)) else None
        res = _sfactories.sparse_csr_matrix(
            out, dtype=types.float32, split=0 if split == 0 else None
        )
        if self.sparse_output:
            return res
        from ..sparse.manipulations import to_dense

        return to_dense(res)

    def serving_program(self) -> dict:
        """``transform`` endpoint description: dense count batch in,
        dense tf-idf out, idf vector riding replicated."""
        if self.idf_ is None:
            raise RuntimeError("fit needs to be called before serving")
        V = int(self.idf_.shape[0])
        l2 = self.norm == "l2"

        def build():
            @jax.jit  # shardlint: ignore[SL202] -- serving program body; the endpoint cache owns wrapping/donation (aot_cache precedent)
            def run(batch, idf):
                y = batch * idf[None, :]
                if l2:
                    nrm = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
                    y = y / jnp.where(nrm > 0, nrm, 1.0)
                return y

            return run

        return {
            "build": build,
            "args": (jnp.asarray(self.idf_),),
            "key": ("tfidf-transform", V, "l2" if l2 else "none"),
            "feature_shape": (V,),
            "dtype": np.dtype(np.float32),
            "comm": None,
            "name": "tfidf-transform",
        }

    def stream_transform(
        self, host: Union[_staging.HostArray, np.ndarray],
        slab: Optional[int] = None,
    ) -> np.ndarray:
        """Streamed TF-IDF of a host-resident count matrix with
        ``stage_out`` writeback, same contract as
        :meth:`OneHotEncoder.stream_transform`."""
        if self.idf_ is None:
            raise RuntimeError("fit needs to be called before stream_transform")
        if not isinstance(host, _staging.HostArray):
            host = _staging.HostArray(np.ascontiguousarray(host, np.float32))
        N, V = host.shape
        if V != self.idf_.shape[0]:
            raise ValueError(f"fit saw {self.idf_.shape[0]} terms, stream got {V}")
        sched = _staging.plan_staged_passes(
            host.shape, host.dtype,
            [{"tag": "tfidf", "axis": 0, "writeback": True}],
            out_bytes=V * 4 + (1 << 20), slab=slab,
        )
        _staging.prove_fits(sched)
        slab_b = int(sched.staging["slab_bytes"])
        wins = _staging.window_extents(host.shape, host.dtype.itemsize, 0, slab_b)
        out = np.zeros((N, V), np.float32)
        idf = jnp.asarray(self.idf_)
        l2 = self.norm == "l2"

        @jax.jit
        def _win(arr):
            y = arr.astype(jnp.float32) * idf[None, :]
            if l2:
                nrm = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
                y = y / jnp.where(nrm > 0, nrm, 1.0)
            return y

        def consume(k, slab_arr, win):
            out[win[0]:win[1]] = np.asarray(jax.device_get(_win(slab_arr)))

        _staging.stream_windows(host, 0, wins, consume, plan_id=sched.plan_id)
        return out
