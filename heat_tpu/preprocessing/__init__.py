"""Data preprocessing (reference: /root/reference/heat/preprocessing/)."""

from .preprocessing import *
