"""Data preprocessing (reference: /root/reference/heat/preprocessing/).

``preprocessing`` holds the reference-parity scalers; ``sparse_encoders``
EXCEEDS the reference with one-hot and TF-IDF transforms that emit
``DCSR_matrix`` outputs and register as serving ``transform`` endpoints."""

from .preprocessing import *
from .sparse_encoders import OneHotEncoder, TfidfTransformer
