"""Feature scaling transformers.

API parity with /root/reference/heat/preprocessing/preprocessing.py
(``StandardScaler`` :49, ``MinMaxScaler`` :158, ``Normalizer`` :284,
``MaxAbsScaler`` :358, ``RobustScaler`` :444). All statistics are sharded
reductions over the sample axis (mean/var/min/max/percentile — one
all-reduce each in the reference's terms).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Optional, Tuple

from ..core import statistics, types
from ..core.base import BaseEstimator, TransformMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["StandardScaler", "MinMaxScaler", "Normalizer", "MaxAbsScaler", "RobustScaler"]


def _float_of(x: DNDarray):
    return x.dtype if types.heat_type_is_inexact(x.dtype) else types.float32


class StandardScaler(BaseEstimator, TransformMixin):
    """Standardize features to zero mean and unit variance (reference:
    preprocessing.py:49)."""

    def __init__(self, copy: bool = True, with_mean: bool = True, with_std: bool = True):
        self.copy = copy
        self.with_mean = with_mean
        self.with_std = with_std
        self.mean_ = None
        self.var_ = None

    def fit(self, x: DNDarray, sample_weight=None) -> "StandardScaler":
        sanitize_in(x)
        self.mean_ = statistics.mean(x, axis=0) if self.with_mean or self.with_std else None
        if self.with_std:
            self.var_ = statistics.var(x, axis=0)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        sanitize_in(x)
        arr = x.larray.astype(_float_of(x).jax_type())
        if self.with_mean and self.mean_ is not None:
            arr = arr - self.mean_.larray
        if self.with_std and self.var_ is not None:
            scale = jnp.sqrt(self.var_.larray)
            arr = arr / jnp.where(scale > 0, scale, 1.0)
        return _like(x, arr)

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        sanitize_in(y)
        arr = y.larray
        if self.with_std and self.var_ is not None:
            scale = jnp.sqrt(self.var_.larray)
            arr = arr * jnp.where(scale > 0, scale, 1.0)
        if self.with_mean and self.mean_ is not None:
            arr = arr + self.mean_.larray
        return _like(y, arr)


def _like(x: DNDarray, arr) -> DNDarray:
    gshape = tuple(int(s) for s in arr.shape)
    split = x.split
    if split is not None:
        arr = x.comm.shard(arr, split)
    return DNDarray(
        arr, gshape, types.canonical_heat_type(arr.dtype), split, x.device, x.comm
    )


class MinMaxScaler(BaseEstimator, TransformMixin):
    """Scale features to a given range (reference: preprocessing.py:158)."""

    def __init__(self, feature_range: Tuple[float, float] = (0.0, 1.0), copy: bool = True, clip: bool = False):
        if feature_range[0] >= feature_range[1]:
            raise ValueError(f"minimum of feature_range must be smaller than maximum, got {feature_range}")
        self.feature_range = feature_range
        self.copy = copy
        self.clip = clip
        self.data_min_ = None
        self.data_max_ = None
        self.data_range_ = None
        self.min_ = None
        self.scale_ = None

    def fit(self, x: DNDarray) -> "MinMaxScaler":
        sanitize_in(x)
        self.data_min_ = statistics.min(x, axis=0)
        self.data_max_ = statistics.max(x, axis=0)
        rng = self.data_max_.larray - self.data_min_.larray
        rng = jnp.where(rng > 0, rng, 1.0)
        lo, hi = self.feature_range
        scale = (hi - lo) / rng
        self.scale_ = scale
        self.min_ = lo - self.data_min_.larray * scale
        self.data_range_ = rng
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        sanitize_in(x)
        arr = x.larray.astype(jnp.result_type(self.scale_.dtype))
        arr = arr * self.scale_ + self.min_
        if self.clip:
            arr = jnp.clip(arr, self.feature_range[0], self.feature_range[1])
        return _like(x, arr)

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        sanitize_in(y)
        arr = (y.larray - self.min_) / self.scale_
        return _like(y, arr)


class Normalizer(BaseEstimator, TransformMixin):
    """Normalize samples to unit norm (reference: preprocessing.py:284)."""

    def __init__(self, norm: str = "l2", copy: bool = True):
        if norm not in ("l1", "l2", "max"):
            raise NotImplementedError(f"unsupported norm {norm}")
        self.norm = norm
        self.copy = copy

    def fit(self, x: DNDarray) -> "Normalizer":
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        sanitize_in(x)
        arr = x.larray.astype(_float_of(x).jax_type())
        if self.norm == "l2":
            norms = jnp.sqrt(jnp.sum(arr * arr, axis=1, keepdims=True))
        elif self.norm == "l1":
            norms = jnp.sum(jnp.abs(arr), axis=1, keepdims=True)
        else:
            norms = jnp.max(jnp.abs(arr), axis=1, keepdims=True)
        arr = arr / jnp.where(norms > 0, norms, 1.0)
        return _like(x, arr)


class MaxAbsScaler(BaseEstimator, TransformMixin):
    """Scale by the per-feature maximum absolute value (reference:
    preprocessing.py:358)."""

    def __init__(self, copy: bool = True):
        self.copy = copy
        self.max_abs_ = None
        self.scale_ = None

    def fit(self, x: DNDarray) -> "MaxAbsScaler":
        sanitize_in(x)
        arr = x.larray
        max_abs = jnp.max(jnp.abs(arr), axis=0)
        self.max_abs_ = max_abs
        self.scale_ = jnp.where(max_abs > 0, max_abs, 1.0)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        sanitize_in(x)
        arr = x.larray.astype(_float_of(x).jax_type()) / self.scale_
        return _like(x, arr)

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        sanitize_in(y)
        return _like(y, y.larray * self.scale_)


class RobustScaler(BaseEstimator, TransformMixin):
    """Scale by median and IQR (reference: preprocessing.py:444 — uses the
    distributed percentile)."""

    def __init__(
        self,
        quantile_range: Tuple[float, float] = (25.0, 75.0),
        copy: bool = True,
        with_centering: bool = True,
        with_scaling: bool = True,
        unit_variance: bool = False,
    ):
        q_min, q_max = quantile_range
        if not 0 <= q_min <= q_max <= 100:
            raise ValueError(f"invalid quantile range {quantile_range}")
        if unit_variance:
            raise NotImplementedError("unit_variance rescaling is not yet supported (reference parity)")
        self.quantile_range = quantile_range
        self.copy = copy
        self.with_centering = with_centering
        self.with_scaling = with_scaling
        self.unit_variance = unit_variance
        self.center_ = None
        self.iqr_ = None

    def fit(self, x: DNDarray) -> "RobustScaler":
        sanitize_in(x)
        if self.with_centering:
            self.center_ = statistics.median(x, axis=0)
        if self.with_scaling:
            q_min, q_max = self.quantile_range
            lo = statistics.percentile(x, q_min, axis=0)
            hi = statistics.percentile(x, q_max, axis=0)
            iqr = hi.larray - lo.larray
            self.iqr_ = jnp.where(iqr > 0, iqr, 1.0)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        sanitize_in(x)
        arr = x.larray.astype(_float_of(x).jax_type())
        if self.with_centering and self.center_ is not None:
            arr = arr - self.center_.larray
        if self.with_scaling and self.iqr_ is not None:
            arr = arr / self.iqr_
        return _like(x, arr)

    def inverse_transform(self, y: DNDarray) -> DNDarray:
        sanitize_in(y)
        arr = y.larray
        if self.with_scaling and self.iqr_ is not None:
            arr = arr * self.iqr_
        if self.with_centering and self.center_ is not None:
            arr = arr + self.center_.larray
        return _like(y, arr)
