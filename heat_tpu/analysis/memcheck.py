"""Pass 3: memory lint — ``ht.analysis.memcheck(fn, *args)``.

shardlint's first two passes check WHAT a program launches (collectives,
host syncs) and what the tree looks like; this pass checks whether the
program FITS. It is a whole-program abstract interpreter over the jaxpr
(the same trace-to-one-program machinery as ``check`` and
``collective_counts``): every value gets a dataflow fact — per-device
local shard bytes, replication, dtype — propagated GSPMD-style
(arXiv:2105.04663: sharding is a per-value dataflow fact), a linear-scan
liveness analysis assigns each value a live range over a flattened
event timeline, and the maximum of live local bytes over program points
is the **static peak-HBM estimate per device**. Compile-only: nothing
executes, so the pass is cheap enough for tests, CI and serving
admission control.

The estimate is deliberately a *model*, cross-checked against the
compiler's own buffer assignment (``Compiled.memory_analysis()``, read
via ``core.jit.executable_memory_stats``) where the backend reports it
— tier-1 pins the model within 2x of XLA on the gated redistribution
programs. The rules:

========  ========  ====================================================
rule      severity  fires when
========  ========  ====================================================
SL301     error     the static peak estimate exceeds the per-device HBM
                    budget (``HEAT_TPU_HBM_BYTES``; default 16 GiB, the
                    v5e HBM) — the program cannot fit at dispatch, so
                    reject it at compile time (serving admission raises
                    the typed ``ServingOverloaded(reason="hbm-estimate")``
                    from the same number)
SL302     error     donation was DECLARED (``donate_argnums`` /
                    ``ht.jit`` bookkeeping) but the compiled
                    executable's ``input_output_aliases`` never reuse
                    the donated buffer — the donation was silently
                    dropped and both copies stay live in HBM. The
                    executable-level upgrade of SL105 ("should donate"),
                    sharing one donation resolver
                    (``analysis._donation``) with it
SL303     warning   a replicated value at least ``min_bytes`` large
                    stays live across >= 2 collective steps — a
                    per-device materialization whose residency the
                    redistribution planner's transient peak accounting
                    never sees
========  ========  ====================================================

The interpreter walks nested jaxprs (pjit / custom_* / shard_map
bodies). Inside ``shard_map`` the body avals ARE the per-device local
shapes, so bytes are taken at face value and ``in_names``/``out_names``
decide replication; outside, a value's local bytes are its global aval
bytes divided by its propagated sharding factor. ``scan``/``while``/
``cond`` bodies are scanned for collective events but treated as opaque
for liveness (their internals execute under their own transient
footprint; the carried values are accounted at the call site).
"""

from __future__ import annotations

import bisect
import os
import warnings

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .findings import AnalysisReport, Finding

__all__ = ["DEFAULT_HBM_BYTES", "HBM_ENV", "hbm_budget_bytes", "memcheck"]

from ..core import tiers as _tiers

#: per-device HBM of the deployment target (v5e: 16 GiB) — the SL301
#: budget when ``HEAT_TPU_HBM_BYTES`` is unset. Since ISSUE 11 the
#: number is the ``hbm`` tier's capacity in the one memory-tier cost
#: lattice (``core.tiers``); aliased here for the established imports.
DEFAULT_HBM_BYTES = _tiers.DEFAULT_HBM_BYTES
HBM_ENV = _tiers.HBM_ENV

#: jaxpr primitives that launch a collective — the "steps" rule SL303
#: counts a replicated live range across.
_COLLECTIVE_PRIMS = frozenset(
    {
        "all_gather", "all_gather_invariant", "all_to_all", "pmax", "pmin",
        "ppermute", "psum", "psum2", "psum_scatter", "reduce_scatter",
    }
)

#: collectives whose RESULT is identical on every device of the group.
_REPLICATING_PRIMS = frozenset(
    {"all_gather", "all_gather_invariant", "pmax", "pmin", "psum", "psum2"}
)

_CALL_PARAM_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "fwd_jaxpr_thunk")


def hbm_budget_bytes() -> int:
    """Per-device HBM budget for rule SL301 (``HEAT_TPU_HBM_BYTES``,
    default 16 GiB — the v5e chip): ``tiers.capacity("hbm")``, the hbm
    tier's capacity in the memory-tier lattice. One number shared with
    serving admission and the out-of-core staging slab ceiling."""
    return _tiers.capacity("hbm")


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG keys): 4 bytes per 32-bit key word
        item = 4
    return n * item


def _closed_of(val):
    """The (raw) jaxprs a param value holds, if any."""
    out = []
    vals = val if isinstance(val, (list, tuple)) else (val,)
    for v in vals:
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(v, "consts"):  # ClosedJaxpr
            out.append(inner)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            out.append(v)
    return out


def _spec_is_replicated(names) -> bool:
    """A shard_map in_names/out_names entry with no mesh axes means the
    body sees (or produces) the full value on every device."""
    return not names


class _Fact:
    """Per-value dataflow fact: local (per-device) bytes + replication."""

    __slots__ = ("local_bytes", "replicated")

    def __init__(self, local_bytes: int, replicated: bool):
        self.local_bytes = int(local_bytes)
        self.replicated = bool(replicated)


class _Interp:
    """One whole-program abstract interpretation: flat event timeline,
    per-value facts, born/last-use liveness."""

    def __init__(self, mesh_size: int):
        self.mesh_size = max(1, int(mesh_size))
        self.n_events = 0
        self.collective_events: List[int] = []
        self.facts: Dict[int, _Fact] = {}
        self.born: Dict[int, int] = {}
        self.last_use: Dict[int, int] = {}
        self.pinned: List[int] = []  # var ids live to program end
        # sub-jaxpr invars ALIAS the caller's buffers (a call passes a
        # reference, not a copy): canon maps a body var onto the outer
        # var's liveness record so nesting never double-counts a value
        self.canon: Dict[int, int] = {}

    # ------------------------------------------------------------------ #
    def _event(self, collective: bool = False) -> int:
        ev = self.n_events
        self.n_events += 1
        if collective:
            self.collective_events.append(ev)
        return ev

    def _vid(self, var) -> int:
        vid = id(var)
        while vid in self.canon:
            vid = self.canon[vid]
        return vid

    def _define(self, var, fact: _Fact, ev: int) -> None:
        vid = id(var)
        self.facts[vid] = fact
        self.born[vid] = ev
        self.last_use[vid] = ev

    def _bind(self, sub_var, outer_var, fallback: _Fact, ev: int) -> None:
        """Bind a body invar to the caller's buffer: alias when the
        outer var carries a fact, define fresh otherwise (literals)."""
        outer_vid = self._vid(outer_var) if outer_var is not None else None
        if outer_vid is not None and outer_vid in self.facts:
            self.canon[id(sub_var)] = outer_vid
            if ev > self.last_use[outer_vid]:
                self.last_use[outer_vid] = ev
        else:
            self._define(sub_var, fallback, ev)

    def _use(self, var, ev: int) -> None:
        vid = self._vid(var)
        if vid in self.facts and ev > self.last_use[vid]:
            self.last_use[vid] = ev

    def _fact_of(self, var) -> Optional[_Fact]:
        return self.facts.get(self._vid(var))

    # ------------------------------------------------------------------ #
    def run(
        self,
        jaxpr,
        in_facts: List[_Fact],
        local_avals: bool,
        bind_to: Optional[list] = None,
    ) -> List[_Fact]:
        """Interpret one (sub-)jaxpr; returns the outvar facts.
        ``local_avals``: inside a shard_map body, avals are already
        per-device local shapes (factor 1). ``bind_to``: the caller's
        invars this body's invars alias (same buffers, one liveness)."""
        ev0 = self._event()
        for k, (var, fact) in enumerate(zip(jaxpr.invars, in_facts)):
            outer = bind_to[k] if bind_to is not None and k < len(bind_to) else None
            self._bind(var, outer, fact, ev0)
        for eqn in jaxpr.eqns:
            self._eqn(eqn, local_avals)
        out = []
        ev_end = self._event()
        for var in jaxpr.outvars:
            fact = self._fact_of(var)
            if fact is None:  # Literal / constvar output
                fact = _Fact(_aval_bytes(getattr(var, "aval", None)), False)
            else:
                self._use(var, ev_end)
            out.append(fact)
        return out

    # ------------------------------------------------------------------ #
    def _eqn(self, eqn, local_avals: bool) -> None:
        name = eqn.primitive.name
        in_facts = [self._fact_of(v) for v in eqn.invars]
        array_facts = [f for f in in_facts if f is not None]

        if name == "shard_map":
            self._shard_map(eqn)
        elif name in ("pjit", "closed_call", "core_call", "remat",
                      "checkpoint", "custom_jvp_call", "custom_vjp_call",
                      "custom_vjp_call_jaxpr"):
            self._call(eqn, local_avals)
        elif name in ("scan", "while", "cond"):
            # opaque for liveness; their bodies' collectives still count
            # as timeline steps so SL303 stays sound
            n_coll = 0
            for val in eqn.params.values():
                for sub in _closed_of(val):
                    n_coll += self._count_collectives(sub)
            for _ in range(n_coll):
                self._event(collective=True)
            self._default(eqn, local_avals, array_facts)
        else:
            self._default(eqn, local_avals, array_facts)

    def _default(self, eqn, local_avals: bool, array_facts) -> None:
        name = eqn.primitive.name
        ev = self._event(collective=name in _COLLECTIVE_PRIMS)
        for v in eqn.invars:
            self._use(v, ev)
        if name in _REPLICATING_PRIMS:
            replicated = self.mesh_size > 1
        elif name == "sharding_constraint":
            s = eqn.params.get("sharding")
            replicated = bool(getattr(s, "is_fully_replicated", False)) and self.mesh_size > 1
        elif name in ("all_to_all", "ppermute", "psum_scatter", "reduce_scatter"):
            replicated = False
        elif array_facts:
            replicated = all(f.replicated for f in array_facts)
        else:
            # literal-only producers (iota, scalar broadcasts): identical
            # by construction, not a materialized exchange product — never
            # SL303 candidates
            replicated = False
        for var in eqn.outvars:
            gb = _aval_bytes(getattr(var, "aval", None))
            if local_avals or replicated:
                local = gb
            else:
                local = gb // self.mesh_size
            self._define(var, _Fact(local, replicated), ev)

    def _call(self, eqn, local_avals: bool) -> None:
        sub = None
        for key in _CALL_PARAM_KEYS:
            if key in eqn.params:
                subs = _closed_of(eqn.params[key])
                if subs:
                    sub = subs[0]
                    break
        if sub is None:
            for val in eqn.params.values():
                subs = _closed_of(val)
                if subs:
                    sub = subs[0]
                    break
        in_facts = []
        for v, sv in zip(eqn.invars, getattr(sub, "invars", ())):
            f = self._fact_of(v)
            if f is None:
                gb = _aval_bytes(getattr(sv, "aval", None))
                f = _Fact(gb if local_avals else gb // self.mesh_size, False)
            in_facts.append(f)
        if sub is None or len(sub.invars) != len(eqn.invars):
            self._default(eqn, local_avals, [f for f in in_facts if f])
            return
        out_facts = self.run(sub, in_facts, local_avals, bind_to=list(eqn.invars))
        ev = self._event()
        for var, fact in zip(eqn.outvars, out_facts):
            self._define(var, fact, ev)

    def _shard_map(self, eqn) -> None:
        body = None
        for val in eqn.params.values():
            subs = _closed_of(val)
            if subs:
                body = subs[0]
                break
        in_names = eqn.params.get("in_names") or ()
        out_names = eqn.params.get("out_names") or ()
        if body is None or len(body.invars) != len(eqn.invars):
            self._default(eqn, False, [f for f in (self._fact_of(v) for v in eqn.invars) if f])
            return
        in_facts = []
        for k, sv in enumerate(body.invars):
            names = in_names[k] if k < len(in_names) else {}
            in_facts.append(
                _Fact(
                    _aval_bytes(getattr(sv, "aval", None)),  # body avals are LOCAL
                    _spec_is_replicated(names) and self.mesh_size > 1,
                )
            )
        out_facts = self.run(body, in_facts, local_avals=True, bind_to=list(eqn.invars))
        ev = self._event()
        for k, var in enumerate(eqn.outvars):
            names = out_names[k] if k < len(out_names) else {}
            local = (
                out_facts[k].local_bytes
                if k < len(out_facts)
                else _aval_bytes(getattr(var, "aval", None))
            )
            # a FRESH fact: for a passthrough output the body fact is the
            # canon-aliased CALLER fact — out_names describes this eqn's
            # result, and mutating the shared object would retroactively
            # rewrite the input value's replication flag
            self._define(
                var,
                _Fact(local, _spec_is_replicated(names) and self.mesh_size > 1),
                ev,
            )

    def _count_collectives(self, jaxpr) -> int:
        n = 0
        todo, seen = [jaxpr], set()
        while todo:
            jx = todo.pop()
            if id(jx) in seen:
                continue
            seen.add(id(jx))
            for eqn in jx.eqns:
                if eqn.primitive.name in _COLLECTIVE_PRIMS:
                    n += 1
                for val in eqn.params.values():
                    todo.extend(_closed_of(val))
        return n

    # ------------------------------------------------------------------ #
    def peak_bytes(self, baseline: int = 0) -> int:
        """Liveness peak: max over events of the summed live local bytes
        (plus ``baseline`` resident constant bytes)."""
        if not self.n_events:
            return baseline
        delta = [0] * (self.n_events + 1)
        pinned = set(self.pinned)
        for vid, fact in self.facts.items():
            if not fact.local_bytes:
                continue
            end = self.n_events - 1 if vid in pinned else self.last_use[vid]
            delta[self.born[vid]] += fact.local_bytes
            delta[end + 1] -= fact.local_bytes
        peak, live = 0, 0
        for d in delta:
            live += d
            peak = max(peak, live)
        return peak + baseline

    def replicated_live_ranges(self, min_bytes: int) -> List[Tuple[int, int, int]]:
        """(local_bytes, n_collectives_spanned, born_event) of every
        replicated value >= ``min_bytes`` whose live range spans >= 2
        collective steps — the SL303 candidates."""
        pinned = set(self.pinned)
        out = []
        for vid, fact in self.facts.items():
            if not fact.replicated or fact.local_bytes < min_bytes:
                continue
            b = self.born[vid]
            e = self.n_events - 1 if vid in pinned else self.last_use[vid]
            # collectives strictly after the value exists, up to its last use
            lo = bisect.bisect_right(self.collective_events, b)
            hi = bisect.bisect_right(self.collective_events, e)
            n = hi - lo
            if n >= 2:
                out.append((fact.local_bytes, n, b))
        out.sort(key=lambda t: (-t[0], t[2]))
        return out


def _input_facts(fn, args, kwargs, traced_in, mesh_size: int) -> List[_Fact]:
    """Facts for the flat traced inputs: DNDarray leaves carry their
    split (split ``None`` on a real mesh = replicated), jax arrays their
    placement sharding."""
    import jax

    from ..core.dndarray import DNDarray
    from ..core.jit import _is_leaf

    from ..sparse.dbcsr_matrix import DBCSR_matrix
    from ..sparse.dcsr_matrix import DCSR_matrix

    leaves, _ = jax.tree.flatten((args, kwargs), is_leaf=_is_leaf)
    facts = []
    for leaf in leaves:
        if isinstance(leaf, (DCSR_matrix, DBCSR_matrix)):
            # sparse operands price by their ACTUAL nnz-padded component
            # bytes (data + indices + metadata), never the dense shape —
            # a 1%-occupancy matrix would otherwise fail admission 100x
            # too early
            gb = int(leaf.component_nbytes)
            if leaf.split is None or leaf.comm.size <= 1:
                facts.append(_Fact(gb, leaf.comm.size > 1))
            else:
                facts.append(_Fact(gb // max(leaf.comm.size, 1), False))
        elif isinstance(leaf, DNDarray):
            phys = leaf._phys
            gb = int(np.prod(phys.shape, dtype=np.int64)) * np.dtype(phys.dtype).itemsize
            if leaf.split is None or leaf.comm.size <= 1:
                facts.append(_Fact(gb, leaf.comm.size > 1))
            else:
                facts.append(_Fact(gb // max(leaf.comm.size, 1), False))
        elif isinstance(leaf, jax.Array):
            gb = int(np.prod(leaf.shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize
            try:
                sharding = leaf.sharding
                n_dev = len(sharding.device_set)
                replicated = bool(sharding.is_fully_replicated) and n_dev > 1
            except Exception:
                n_dev, replicated = 1, False
            if replicated or n_dev <= 1:
                facts.append(_Fact(gb, replicated or mesh_size > 1 and n_dev > 1))
            else:
                facts.append(_Fact(gb // n_dev, False))
    return facts[: len(traced_in)] if len(facts) > len(traced_in) else facts


def memcheck(
    fn,
    *args,
    hbm_bytes: Optional[int] = None,
    min_bytes: int = 1 << 20,
    donate_argnums: Optional[Tuple[int, ...]] = None,
    mesh=None,
    **kwargs,
) -> AnalysisReport:
    """Statically bound the per-device memory of ``fn(*args, **kwargs)``.

    ``fn`` may be a public heat_tpu function over DNDarrays, an
    ``ht.jit``-wrapped function, or an already-jitted jax callable (same
    contract as :func:`ht.analysis.check`). Compile-only — the program
    is traced and compiled exactly like a real dispatch (donation
    included), never executed.

    Parameters
    ----------
    hbm_bytes : per-device HBM budget for rule SL301; default the
        ``HEAT_TPU_HBM_BYTES`` env (v5e 16 GiB when unset).
    min_bytes : replicated values below this size never fire SL303.
    donate_argnums : positional args donated at dispatch time; defaults
        to the checked ``ht.jit`` wrapper's own bookkeeping (the shared
        resolver in ``analysis._donation`` — the same one SL105 uses).
    mesh : optional mesh, recorded in the report context.

    Returns an :class:`AnalysisReport` whose ``context`` carries
    ``static_peak_bytes`` (the liveness peak estimate per device),
    ``hbm_budget_bytes``, and — where the backend reports them — the
    compiler's own ``xla_*`` buffer-assignment numbers for cross-check.
    """
    import jax

    from ..core.jit import (
        executable_input_output_aliases,
        executable_memory_stats,
    )
    from ..observability.hlo import _build_traceable
    from ._donation import declared_donate_argnums, donated_leaf_positions
    # the ONE definition of "the program concretizes on the host" — shared
    # with pass 1 so both passes classify the same program identically
    from .ircheck import _trace_errors

    budget = hbm_budget_bytes() if hbm_bytes is None else max(1, int(hbm_bytes))
    findings: List[Finding] = []
    context: Dict[str, Any] = {
        "pass": "memcheck",
        "hbm_budget_bytes": int(budget),
        "min_bytes": int(min_bytes),
    }
    if mesh is not None:
        context["mesh_devices"] = int(np.asarray(mesh.devices).size)

    kind, target, traced_in = _build_traceable(fn, args, kwargs)
    donate_user = declared_donate_argnums(fn, donate_argnums)
    donate_positions: Tuple[int, ...] = ()
    try:
        with warnings.catch_warnings():
            # a dropped donation raises OUR finding (SL302), not jax's
            # "donated buffers were not usable" warning noise
            warnings.simplefilter("ignore")
            if kind == "lower":
                try:
                    closed = jax.make_jaxpr(target)(*args, **kwargs)
                except TypeError:
                    closed = target.trace(*args, **kwargs).jaxpr
                if donate_user:
                    # an EXPLICIT donate_argnums on an already-jitted fn:
                    # apply it through an outer jit (jax maps user argnums
                    # onto the flat parameters) so the compiled form — and
                    # therefore the SL302 alias check, the pinning, and
                    # the xla cross-check — is the donated program, not a
                    # silently undonated twin
                    donate_positions = donated_leaf_positions(
                        fn, args, kwargs, donate_argnums
                    )
                    try:
                        compiled = jax.jit(  # shardlint: ignore[SL202] -- compile-only analyzer lowering
                            target, donate_argnums=donate_user
                        ).lower(*args, **kwargs).compile()
                    except TypeError:
                        # static-arg jitted fns cannot be re-wrapped: fall
                        # back to the fn's own lowering, donation unchecked
                        donate_positions = ()
                        compiled = target.lower(*args, **kwargs).compile()
                else:
                    compiled = target.lower(*args, **kwargs).compile()
            else:
                if donate_user:
                    donate_positions = donated_leaf_positions(
                        fn, args, kwargs, donate_argnums
                    )
                closed = jax.make_jaxpr(target)(*traced_in)
                # compile-only lowering of the CHECKED program, donation
                # applied the way ht.jit would apply it at dispatch
                compiled = jax.jit(  # shardlint: ignore[SL202] -- compile-only analyzer lowering
                    target, donate_argnums=donate_positions
                ).lower(*traced_in).compile()
    except _trace_errors() as e:
        findings.append(
            Finding(
                "SL106",
                "error",
                "trace aborted: the program reads device VALUES on the host "
                f"(concretization) — {type(e).__name__}: {str(e).splitlines()[0]}",
            )
        )
        return AnalysisReport(findings, context)

    # mesh size: the DNDarray arguments' communicator, else the compiled
    # module's own partition count
    mesh_size = 1
    from ..core.dndarray import DNDarray

    leaves, _ = jax.tree.flatten((args, kwargs), is_leaf=lambda x: isinstance(x, DNDarray))
    for leaf in leaves:
        if isinstance(leaf, DNDarray):
            mesh_size = max(mesh_size, leaf.comm.size)
    if mesh_size == 1:
        import re as _re

        m = _re.search(r"num_partitions=(\d+)", compiled.as_text())
        if m:
            mesh_size = int(m.group(1))
    context["mesh_size"] = int(mesh_size)

    # ---- abstract interpretation + liveness ---------------------------
    interp = _Interp(mesh_size)
    if kind == "lower":
        in_facts = [
            _Fact(_aval_bytes(a) // mesh_size if mesh_size > 1 else _aval_bytes(a), False)
            for a in closed.in_avals
        ]
    else:
        in_facts = _input_facts(fn, args, kwargs, traced_in, mesh_size)
        if len(in_facts) != len(closed.jaxpr.invars):
            in_facts = [
                _Fact(_aval_bytes(getattr(v, "aval", None)) // max(mesh_size, 1), False)
                for v in closed.jaxpr.invars
            ]
    const_baseline = 0
    for c in getattr(closed, "consts", ()):
        shape = getattr(c, "shape", ())
        dtype = getattr(c, "dtype", None)
        if dtype is not None:
            const_baseline += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    interp.run(closed.jaxpr, in_facts, local_avals=False)
    # arguments the caller did NOT donate stay resident for the whole
    # program (XLA's buffer assignment charges them end to end), and so
    # do the program outputs
    donated_set = set(donate_positions)
    for pos, var in enumerate(closed.jaxpr.invars):
        if pos not in donated_set:
            interp.pinned.append(id(var))
    for var in closed.jaxpr.outvars:
        if id(var) in interp.facts:
            interp.pinned.append(id(var))

    static_peak = interp.peak_bytes(baseline=const_baseline)
    context["static_peak_bytes"] = int(static_peak)
    context["n_events"] = interp.n_events
    context["n_collective_events"] = len(interp.collective_events)

    xla = executable_memory_stats(compiled)
    if xla is not None:
        context["xla_argument_bytes"] = xla["argument_bytes"]
        context["xla_output_bytes"] = xla["output_bytes"]
        context["xla_temp_bytes"] = xla["temp_bytes"]
        context["xla_alias_bytes"] = xla["alias_bytes"]
        context["xla_peak_bytes"] = xla["peak_bytes"]

    # ---- SL301: over the HBM budget ------------------------------------
    if static_peak > budget:
        xla_note = (
            f"; the compiler's own assignment says {xla['peak_bytes']} B"
            if xla is not None
            else ""
        )
        findings.append(
            Finding(
                "SL301",
                "error",
                f"static peak-HBM estimate {static_peak} B exceeds the "
                f"per-device budget {budget} B ({HBM_ENV}; v5e default "
                f"{DEFAULT_HBM_BYTES} B){xla_note} — the program cannot "
                "fit at dispatch; shrink the live set (donate inputs, "
                "stage through the redistribution planner) or raise the "
                "budget",
                nbytes=int(static_peak),
            )
        )

    # ---- SL302: donation declared but dropped by the executable --------
    if donate_user and donate_positions:
        aliased = {a["param_number"] for a in executable_input_output_aliases(compiled)}
        context["donated_params"] = list(donate_positions)
        context["aliased_params"] = sorted(aliased)
        for pos in donate_positions:
            if pos in aliased:
                continue
            aval = closed.in_avals[pos] if pos < len(closed.in_avals) else None
            nb = _aval_bytes(aval)
            shape = tuple(getattr(aval, "shape", ()))
            findings.append(
                Finding(
                    "SL302",
                    "error",
                    f"donation silently dropped: argument buffer {shape} "
                    f"(~{nb} B, parameter {pos}) was declared donated but "
                    "the compiled executable's input_output_aliases never "
                    "reuse it — both copies stay live in HBM while the "
                    "caller believes one was reclaimed (no output matches "
                    "its shape/dtype, or XLA could not alias it)",
                    nbytes=nb,
                )
            )

    # ---- SL303: replicated value live across >= 2 collective steps ----
    for local_bytes, n_coll, _born in interp.replicated_live_ranges(min_bytes)[:8]:
        findings.append(
            Finding(
                "SL303",
                "warning",
                f"replicated value (~{local_bytes} B per device) stays "
                f"live across {n_coll} collective steps — a per-device "
                "materialization the redistribution planner's transient "
                "peak accounting never sees; consume it before the "
                "collective chain, or keep it sharded and gather late",
                nbytes=int(local_bytes),
            )
        )

    findings.sort(key=lambda f: ({"error": 0, "warning": 1, "info": 2}[f.severity], f.rule))
    return AnalysisReport(findings, context)
