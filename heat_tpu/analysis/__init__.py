"""Static sharding / collective / host-sync analysis (``shardlint``).

The north-star contract — compiled programs launch exactly the
collectives the algorithm needs, every intermediate stays distributed,
nothing round-trips through the host — is a *static* property of the
traced program and the source tree. This package checks it before any
TPU minute is spent, in six passes:

- **Pass 1, IR lint** — :func:`ht.analysis.check(fn, *args) <check>`
  walks the jaxpr and compiled StableHLO of any heat_tpu program
  (reusing the ``ht.observability`` HLO walker) and reports implicit
  reshards, replicated materializations, gather-fed reductions, dtype
  widening, missed donations and host syncs as structured findings
  with rule ids, severities and byte estimates.
- **Pass 2, source lint** — :mod:`~heat_tpu.analysis.srclint` (CLI:
  ``python scripts/lint.py heat_tpu/``) enforces repo invariants over
  the tree itself: no undeclared ``jax.device_get``, no bare
  ``jax.jit`` outside private program builders, public ops routed
  through ``core/sanitation.py``.
- **Pass 3, memory lint** — :func:`ht.analysis.memcheck(fn, *args)
  <memcheck>` abstract-interprets the jaxpr with a liveness analysis
  (per-value local shard bytes, replication, live range) into a static
  peak-HBM estimate per device, cross-checked against the compiler's
  own ``memory_analysis()``: programs that cannot fit (SL301), declared
  donations the executable silently dropped (SL302), and replicated
  values held live across collective chains (SL303) are findings, not
  OOMs. Its sibling :func:`ht.analysis.verify_plan(plan) <verify_plan>`
  symbolically executes Schedule-IR redistribution plans and proves
  composition, byte conservation, codec pairing, tier labels, overlap
  lap structure and plan-id integrity — swept over every golden-matrix
  plan in tier-1 and the ci.sh determinism leg.

- **Pass 4, effect lint** — :mod:`~heat_tpu.analysis.effectcheck`
  (``gatecheck`` + ``racecheck``; CLI: ``python scripts/lint.py
  heat_tpu/ --pass effectcheck``) proves the properties BETWEEN
  programs: SL401 use-after-donate (jaxpr dataflow on the shared
  ``_donation.py`` resolver, also folded into :func:`check`), SL402
  gate/cache-key staleness over the ``heat_tpu.core.gates`` registry
  (the rule that mechanizes "the gate is a component of every program
  cache key"), SL403 raw ``HEAT_TPU_*`` env reads bypassing the
  registry, SL404 lock-discipline race lint over the threaded
  dispatcher/telemetry classes, and SL405 the depth-2 issue/consume
  pipeline protocol (static loop shape + the plan-annotation sweep
  :func:`check_plan_protocol`).

- **Pass 5, commcheck** — :mod:`~heat_tpu.analysis.commcheck` (CLI:
  ``python scripts/lint.py heat_tpu/ --pass commcheck``; ``--pass
  all`` runs passes 2+4+5 in one process) proves SPMD collective
  CONGRUENCE — the MPI-heritage failure mode that hangs a TPU mesh
  instead of erroring: SL501 divergent-collective (a collective under
  a ``cond``/``while`` predicate not provably replicated — a
  replication lattice over the jaxpr decides), SL502
  incomplete-permute (``source_target_pairs`` not a permutation of the
  axis group, ``replica_groups`` not a partition of the mesh — the
  shared ``_groups.py`` parser, one verdict with SL107), SL503
  collective-order divergence (cycle in the per-axis-group channel
  graph / unordered independent subgroup collectives), SL504
  unfenced dispatch entry (an executor/dispatcher path issuing
  collectives without the PR 13 epoch fence). The dynamic half —
  :func:`check_progress` and ``verify_plan``'s ``progress`` invariant
  — symbolically replays every Schedule-IR plan per device: rings
  close in exactly p-1 hops, hierarchical ici/dcn lap pairs partition
  the mesh, depth-2 lap tags never consume an unissued lap. The
  IR rules fold into :func:`check`; the MPMD stage-graph work
  (ROADMAP) consumes this verifier per pipeline stage.

- **Pass 6, numcheck** — :mod:`~heat_tpu.analysis.numcheck` (CLI:
  ``python scripts/lint.py heat_tpu/ --pass numcheck``; ``--pass all``
  runs passes 2+4+5+6 in one process) mechanizes the WRONG-NUMBER
  class the CPU-mesh suite structurally cannot see (on CPU every
  matmul runs f32): SL601 low-precision accumulation (bf16/f16
  ``dot_general``/``reduce_sum``/scan carries over reduction extents
  past the ``HEAT_TPU_NUMCHECK_ACC_DIM`` threshold without an f32
  ``preferred_element_type``), SL602 cancellation-prone
  subtraction-of-shared-operand-products at default MXU precision (the
  planar-complex 13% on-chip defect, mechanized — the source arm holds
  ``core/complex_planar.py`` to :data:`numcheck.PLANAR_PRECISION_POLICY`),
  SL603 low-precision casts feeding loop-carried accumulators (EF
  carries, running means — the KMeans bf16-counts bug as a rule), and
  SL604 f64 requests under the x64-disabled platform policy (standalone
  :func:`numcheck` only — a trace-time silent degrade no jaxpr shows).
  The dtype vocabulary is shared with SL104 through ``_dtypes.py``. The
  dynamic half — :func:`check_tolerance` and ``verify_plan``'s
  ``tolerance`` invariant (SL605) — recomputes every golden plan's
  end-to-end error bound from its recorded per-step tolerances and
  proves it equals the schedule-level ``quant.tol`` annotation; the
  Newton–Schulz and MPMD tolerance budgets (ROADMAP) read this
  contract.

Legitimate host boundaries are declared, by name and category, in
:mod:`~heat_tpu.analysis.boundaries` — the whitelist is code, reviewed
like code, and tier-1 pins its exact ``core/`` population. Rule
catalog and workflow: docs/PERF.md § Static analysis.
"""

from . import boundaries
from . import effectcheck
from . import findings
from . import ircheck
from . import planverify
from . import srclint

from .boundaries import HOST_BOUNDARIES, is_declared_sync
from .commcheck import commcheck
from .effectcheck import check_donation, check_plan_protocol
from .findings import RULES, AnalysisReport, Finding
from .ircheck import check
from .memcheck import hbm_budget_bytes, memcheck
from .numcheck import numcheck
from .planverify import (
    PlanVerificationError,
    check_progress,
    check_tolerance,
    verify_plan,
)
from .srclint import lint_paths, lint_source

__all__ = [
    "AnalysisReport",
    "Finding",
    "HOST_BOUNDARIES",
    "PlanVerificationError",
    "RULES",
    "check",
    "check_donation",
    "check_plan_protocol",
    "check_progress",
    "check_tolerance",
    "commcheck",
    "hbm_budget_bytes",
    "is_declared_sync",
    "lint_paths",
    "lint_source",
    "memcheck",
    "numcheck",
    "verify_plan",
]
