"""Static sharding / collective / host-sync analysis (``shardlint``).

The north-star contract — compiled programs launch exactly the
collectives the algorithm needs, every intermediate stays distributed,
nothing round-trips through the host — is a *static* property of the
traced program and the source tree. This package checks it before any
TPU minute is spent, in two passes:

- **Pass 1, IR lint** — :func:`ht.analysis.check(fn, *args) <check>`
  walks the jaxpr and compiled StableHLO of any heat_tpu program
  (reusing the ``ht.observability`` HLO walker) and reports implicit
  reshards, replicated materializations, gather-fed reductions, dtype
  widening, missed donations and host syncs as structured findings
  with rule ids, severities and byte estimates.
- **Pass 2, source lint** — :mod:`~heat_tpu.analysis.srclint` (CLI:
  ``python scripts/lint.py heat_tpu/``) enforces repo invariants over
  the tree itself: no undeclared ``jax.device_get``, no bare
  ``jax.jit`` outside private program builders, public ops routed
  through ``core/sanitation.py``.

Legitimate host boundaries are declared, by name and category, in
:mod:`~heat_tpu.analysis.boundaries` — the whitelist is code, reviewed
like code, and tier-1 pins its exact ``core/`` population. Rule
catalog and workflow: docs/PERF.md § Static analysis.
"""

from . import boundaries
from . import findings
from . import ircheck
from . import srclint

from .boundaries import HOST_BOUNDARIES, is_declared_sync
from .findings import RULES, AnalysisReport, Finding
from .ircheck import check
from .srclint import lint_paths, lint_source

__all__ = [
    "AnalysisReport",
    "Finding",
    "HOST_BOUNDARIES",
    "RULES",
    "check",
    "is_declared_sync",
    "lint_paths",
    "lint_source",
]
