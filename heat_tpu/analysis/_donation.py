"""Donation resolution shared by the analyzer passes.

Rule SL105 (ircheck: "output aliases an argument but the buffer is not
donated") and rule SL302 (memcheck: "donation declared but the compiled
executable dropped it") are two halves of one question — *which buffers
did the caller donate, and did the pipeline actually reuse them?* Both
passes used to answer the first half with their own bookkeeping walk;
this module is the single resolver they now share, so the two rules can
never disagree about what was donated.

The resolution contract mirrors ``ht.jit`` exactly (core/jit.py): user
``donate_argnums`` are USER-VISIBLE positional indices; each donated
argument contributes the flattened traced leaves it spans (statics carry
no buffer and drop out), and DNDarray leaves donate their padded
physical arrays.
"""

from __future__ import annotations

from typing import Optional, Set, Tuple

import numpy as np

__all__ = [
    "declared_donate_argnums",
    "donated_avals",
    "donated_leaf_positions",
]


def declared_donate_argnums(fn, donate_argnums=None) -> Tuple[int, ...]:
    """The user-visible positional argnums ``fn`` donates: the explicit
    override when given, else the ``ht.jit`` wrapper's own bookkeeping
    (``_ht_jit_donate_argnums``), else nothing."""
    if donate_argnums is None:
        donate_argnums = getattr(fn, "_ht_jit_donate_argnums", ())
    if isinstance(donate_argnums, int):
        donate_argnums = (donate_argnums,)
    return tuple(int(u) for u in donate_argnums)


def donated_avals(fn, args, donate_argnums=None) -> Set[Tuple[tuple, str]]:
    """(shape, dtype-str) of every leaf of every donated positional arg —
    the aval-level view rule SL105 keys on. DNDarray leaves contribute
    their PADDED physical arrays (what the compiled program sees)."""
    import jax

    from ..core.jit import _is_leaf

    donated: Set[Tuple[tuple, str]] = set()
    for u in declared_donate_argnums(fn, donate_argnums):
        if 0 <= u < len(args):
            for leaf in jax.tree.leaves(args[u], is_leaf=_is_leaf):
                phys = getattr(leaf, "_phys", leaf)  # DNDarray -> padded physical
                shape = getattr(phys, "shape", None)
                dtype = getattr(phys, "dtype", None)
                if shape is not None and dtype is not None:
                    donated.add((tuple(shape), str(np.dtype(dtype))))
    return donated


def donated_leaf_positions(fn, args, kwargs=None, donate_argnums=None) -> Tuple[int, ...]:
    """Flat TRACED-leaf positions the donated args span — the same
    user-arg -> traced-position mapping ``ht.jit`` builds at dispatch,
    and therefore the XLA parameter numbers rule SL302 checks against
    the compiled module's ``input_output_alias`` map. Static leaves
    (non-array hashables) carry no buffer and are skipped."""
    import jax

    from ..core.dndarray import DNDarray
    from ..core.jit import _is_leaf

    donate_user = declared_donate_argnums(fn, donate_argnums)
    if not donate_user:
        return ()
    kwargs = kwargs or {}
    leaves, _ = jax.tree.flatten((args, kwargs), is_leaf=_is_leaf)
    # the traced-leaf predicate of observability.hlo._build_traceable —
    # the SAME trace both analyzer passes compile, so these positions
    # ARE the compiled module's parameter numbers
    is_traced = [isinstance(leaf, (DNDarray, jax.Array)) for leaf in leaves]
    spans, off = [], 0
    for a in args:
        n = len(jax.tree.flatten(a, is_leaf=_is_leaf)[0])
        spans.append(range(off, off + n))
        off += n
    traced_pos, t = {}, 0
    for i, traced in enumerate(is_traced):
        if traced:
            traced_pos[i] = t
            t += 1
    return tuple(
        traced_pos[i]
        for u in donate_user
        if 0 <= u < len(spans)
        for i in spans[u]
        if i in traced_pos
    )
