"""Pass 2: source lint — AST enforcement of repo invariants over
``heat_tpu/`` itself.

Three rules (catalog in :mod:`~heat_tpu.analysis.findings`):

- **SL201 host-sync** — ``jax.device_get`` (the one primitive every
  host read in this codebase funnels through: ``.numpy()``, ``float()``,
  io writes all reach it) is an error outside a boundary declared in
  :mod:`~heat_tpu.analysis.boundaries`. New syncs must be declared —
  the declaration is the review artifact.
- **SL202 bare-jit** — ``jax.jit`` is an error outside a *private
  program builder*. The sanctioned idiom is: public surfaces route
  through ``ht.jit`` (donation mapping, telemetry hooks, DNDarray
  metadata) or ``comm.jit_sharded`` (output-sharding pins); raw
  ``jax.jit`` lives only in ``_``-prefixed builder functions/modules
  that those surfaces call.
- **SL203 unsanitized-public-op** — a public function in a declared op
  module must route its inputs through ``core/sanitation.py`` (call a
  ``sanitize_*`` helper), delegate to the ``_operations`` wrappers
  (which sanitize), or delegate to another routed op. Warning severity:
  it reports drift, the error rules gate.

Inline escape hatch (fixtures, justified one-offs)::

    x = jax.device_get(v)  # shardlint: ignore[SL201] -- why it is fine

A pragma on a ``def`` line covers the whole function.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Dict, List, Optional, Set, Tuple

from . import boundaries
from .findings import AnalysisReport, Finding

__all__ = ["lint_source", "lint_paths", "scan_program_source"]

# modules where bare jax.jit is the implementation itself
_BARE_JIT_MODULES = (
    "core/jit.py",            # ht.jit IS the wrapper over jax.jit
    "core/communication.py",  # jit_sharded_mesh, the sanctioned pin helper
)

# op modules whose public functions rule SL203 holds to the sanitation
# contract (the reference's "every public op validates via sanitation.py")
_OP_MODULES = (
    "core/arithmetics.py",
    "core/complex_math.py",
    "core/exponential.py",
    "core/logical.py",
    "core/manipulations.py",
    "core/relational.py",
    "core/rounding.py",
    "core/statistics.py",
    "core/trigonometrics.py",
)

_PRAGMA = re.compile(r"#\s*shardlint:\s*ignore\[([A-Z0-9,\s*]+)\]")


def _pragmas_of(src: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _PRAGMA.search(line)
        if m:
            out[i] = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
    return out


def _call_name(func: ast.AST) -> str:
    """Terminal name of a call target: ``jax.device_get`` -> device_get."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


class _Scope:
    __slots__ = ("stack", "def_lines")

    def __init__(self, stack: Tuple[str, ...], def_lines: Tuple[int, ...]):
        self.stack = stack
        self.def_lines = def_lines

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)

    def is_private(self) -> bool:
        return any(part.startswith("_") for part in self.stack)


def _walk_scoped(tree: ast.AST):
    """Yield (node, scope) for every node, tracking the enclosing
    function/class chain and the line numbers of the enclosing defs
    (pragma anchors)."""
    todo = [(tree, _Scope((), ()))]
    while todo:
        node, scope = todo.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                inner = _Scope(scope.stack + (child.name,), scope.def_lines + (child.lineno,))
                yield child, scope  # the def itself belongs to the outer scope
                todo.append((child, inner))
            else:
                yield child, scope
                todo.append((child, scope))


def _suppressed(rule: str, lineno: int, scope: _Scope, pragmas: Dict[int, Set[str]]) -> bool:
    for anchor in (lineno,) + scope.def_lines:
        rules = pragmas.get(anchor)
        if rules and (rule in rules or "*" in rules):
            return True
    return False


def _module_is_private(rel: str) -> bool:
    return any(part.startswith("_") for part in rel.replace("\\", "/").split("/"))


def _lint_sl203(tree: ast.Module, rel: str, pragmas) -> List[Finding]:
    """Public op functions must sanitize or delegate to code that does."""
    top_fns = {n.name for n in tree.body if isinstance(n, ast.FunctionDef)}
    # names imported from sibling modules (`from .dndarray import DNDarray`,
    # `from . import _operations`) — calling one is delegation to a routed
    # surface
    imported: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom):
            imported.update(a.asname or a.name for a in n.names)
    findings: List[Finding] = []
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name.startswith("_"):
            continue
        if _suppressed("SL203", fn.lineno, _Scope((fn.name,), (fn.lineno,)), pragmas):
            continue
        routed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name.startswith("sanitize") or name == "scalar_to_1d":
                routed = True
                break
            # _operations.__binary_op / __reduce_op / ... — the wrappers
            # sanitize on entry
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "_operations"
            ):
                routed = True
                break
            # delegation to another routed surface: a sibling public op of
            # this module, or any imported sibling helper/op
            if isinstance(node.func, ast.Name) and (
                node.func.id in top_fns or node.func.id in imported
            ):
                routed = True
                break
        if not routed:
            findings.append(
                Finding(
                    "SL203",
                    "warning",
                    f"public op {fn.name!r} neither calls a sanitize_* helper "
                    "nor delegates to a routed op (core/sanitation.py contract)",
                    path=rel,
                    line=fn.lineno,
                )
            )
    return findings


def lint_source(src: str, rel: str) -> List[Finding]:
    """Lint one module's source. ``rel`` is the repo-relative posix path
    (what declarations in boundaries.py and module allowlists match on).
    """
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("SL201", "error", f"unparseable module: {e}", path=rel, line=e.lineno)]
    pragmas = _pragmas_of(src)
    rel = rel.replace("\\", "/")
    findings: List[Finding] = []
    module_private = _module_is_private(rel)
    jit_module_ok = any(rel.endswith(sfx) for sfx in _BARE_JIT_MODULES)

    for node, scope in _walk_scoped(tree):
        # SL201 — host sync
        if isinstance(node, ast.Call) and _call_name(node.func) == "device_get":
            declared, _cat = boundaries.is_declared_sync(rel, scope.qualname)
            if not declared and not _suppressed("SL201", node.lineno, scope, pragmas):
                where = scope.qualname or "<module>"
                findings.append(
                    Finding(
                        "SL201",
                        "error",
                        f"jax.device_get in {where} is not a declared host "
                        "boundary — declare it in heat_tpu/analysis/"
                        "boundaries.py (named HOST_BOUNDARIES entry for a "
                        "compute-path sync) or mark the line with "
                        "`# shardlint: ignore[SL201] -- reason`",
                        path=rel,
                        line=node.lineno,
                    )
                )
        # SL202 — bare jax.jit (call, decorator, or bare reference alike)
        if _is_jax_jit(node):
            allowed = module_private or jit_module_ok or scope.is_private()
            if not allowed and not _suppressed("SL202", node.lineno, scope, pragmas):
                where = scope.qualname or "<module>"
                findings.append(
                    Finding(
                        "SL202",
                        "error",
                        f"bare jax.jit in public scope {where} — route through "
                        "ht.jit (donation/telemetry hooks) or move the program "
                        "builder into a _-private function",
                        path=rel,
                        line=node.lineno,
                    )
                )
        # `from jax import jit` hides the SL202 pattern from review
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit" and not _suppressed("SL202", node.lineno, scope, pragmas):
                    findings.append(
                        Finding(
                            "SL202",
                            "error",
                            "`from jax import jit` aliases bare jax.jit past "
                            "review — import jax and use a private builder, or "
                            "use ht.jit",
                            path=rel,
                            line=node.lineno,
                        )
                    )

    if any(rel.endswith(sfx) for sfx in _OP_MODULES):
        findings += _lint_sl203(tree, rel, pragmas)
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def _iter_py_files(path: str):
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def lint_paths(paths, root: Optional[str] = None) -> AnalysisReport:
    """Lint every ``.py`` file under ``paths``; relative anchors are
    computed against ``root`` (default: current directory)."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    n_files = 0
    for path in paths:
        for fp in _iter_py_files(path):
            n_files += 1
            with open(fp, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
            findings += lint_source(src, rel)
    return AnalysisReport(findings, context={"files": n_files, "pass": "srclint"})


# --------------------------------------------------------------------- #
# user-program scan (pass 1 uses this on the checked fn's source)       #
# --------------------------------------------------------------------- #

_HOST_ATTR_CALLS = ("item", "numpy", "block_until_ready")


def scan_program_source(fn) -> List[Finding]:
    """Best-effort host-sync scan (rule SL106) of a checked program's
    SOURCE — catches syncs the trace cannot see because they sit in an
    untaken branch (a debug print, a logging arm). Silently returns []
    when source is unavailable (builtins, compiled callables, REPL).
    """
    import inspect
    import textwrap

    target = inspect.unwrap(fn)
    try:
        src = textwrap.dedent(inspect.getsource(target))
        tree = ast.parse(src)
        base = inspect.getsourcefile(target) or "<source>"
        first = target.__code__.co_firstlineno if hasattr(target, "__code__") else 1
    except (TypeError, OSError, SyntaxError, AttributeError):
        return []
    findings: List[Finding] = []

    def flag(node, severity, what):
        findings.append(
            Finding(
                "SL106",
                severity,
                f"{what} inside the checked program — a host round-trip "
                "serializes dispatch and breaks tracing (run it eagerly, "
                "outside, or behind a declared boundary)",
                path=base,
                line=first + node.lineno - 1,
                op=what.split("(")[0],
            )
        )

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name == "device_get":
            flag(node, "error", "jax.device_get(...)")
        elif name in _HOST_ATTR_CALLS and isinstance(node.func, ast.Attribute):
            flag(node, "error", f".{name}()")
        elif name in ("float", "int", "bool") and node.args and isinstance(
            node.args[0], (ast.Call, ast.Attribute)
        ):
            # heuristic: the AST cannot tell a device value from a host
            # one (int(x.ndim) is fine), so casts report, never gate
            flag(node, "warning", f"{name}(<maybe-device value>)")
        elif name in ("asarray", "array") and isinstance(node.func, ast.Attribute) and (
            isinstance(node.func.value, ast.Name) and node.func.value.id in ("np", "numpy")
        ) and node.args and not isinstance(node.args[0], (ast.Constant, ast.List, ast.Tuple)):
            flag(node, "warning", "np.asarray(<device value>)")
    return findings
