"""ONE parser for HLO replica groups / ppermute source-target pairs.

Two passes reason about the group structure of compiled collectives:
SL107 (ircheck's cross-tier rule, PR 8) classifies which tier a
collective's groups ride, and SL502/SL503 (commcheck, pass 5) prove the
groups are *congruent* — a partition of the mesh, a permutation of the
axis group. Until ISSUE 14 the parser lived inside ircheck; this module
is the shared home, so a "cross-tier" and an "incongruent" verdict can
never disagree about what the same HLO line says. All three textual
forms are covered:

- ``replica_groups={{0,1},{2,3}}`` — explicit groups;
- ``replica_groups=[2,4]<=[8]`` — the iota form (rows x cols reshape of
  ``[0, total)``, row-major: group ``r`` is ``[r*cols, (r+1)*cols)``);
- ``source_target_pairs={{0,1},{1,2}}`` — collective-permute pairs.

Parsers return ``None`` — never guess — when a line carries none of the
forms; callers treat ``None`` as "no verdict" (conservative).
"""

from __future__ import annotations

import re

from typing import List, Optional, Tuple

__all__ = [
    "parse_groups",
    "parse_replica_groups",
    "parse_source_target_pairs",
    "partition_defect",
    "permutation_defect",
]

_REPLICA_GROUPS = re.compile(r"replica_groups=\{((?:\{[0-9, ]*\},?)+)\}")
_REPLICA_IOTA = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+)\]")
_SOURCE_TARGETS = re.compile(r"source_target_pairs=\{((?:\{[0-9, ]*\},?)+)\}")
_GROUP = re.compile(r"\{([0-9, ]*)\}")


def _int_groups(blob: str) -> List[List[int]]:
    return [
        [int(v) for v in g.split(",") if v.strip()] for g in _GROUP.findall(blob)
    ]


def parse_replica_groups(hlo_line: str) -> Optional[List[List[int]]]:
    """The replica groups of one HLO collective line, as lists of device
    ids — explicit or iota form; ``None`` when the line carries neither
    (including ``replica_groups={}``, the all-devices default)."""
    m = _REPLICA_GROUPS.search(hlo_line)
    if m:
        return _int_groups(m.group(1))
    m = _REPLICA_IOTA.search(hlo_line)
    if m:
        rows, cols, total = int(m.group(1)), int(m.group(2)), int(m.group(3))
        if rows * cols == total:
            return [list(range(r * cols, (r + 1) * cols)) for r in range(rows)]
    return None


def parse_source_target_pairs(hlo_line: str) -> Optional[List[Tuple[int, int]]]:
    """The ``source_target_pairs`` of a collective-permute line as
    ``(source, target)`` tuples, or ``None``. Degenerate entries (a pair
    with fewer than two ids) are kept as-is by returning ``None`` for
    the whole line — a malformed dump is "no verdict", not a guess."""
    m = _SOURCE_TARGETS.search(hlo_line)
    if not m:
        return None
    pairs = []
    for g in _int_groups(m.group(1)):
        if len(g) != 2:
            return None
        pairs.append((g[0], g[1]))
    return pairs


def parse_groups(hlo_line: str) -> Optional[list]:
    """SL107's historical merged view: replica groups OR source-target
    pairs (pairs read as 2-element groups), whichever the line carries —
    ``None`` for neither. Kept bit-compatible with the pre-ISSUE-14
    ircheck parser so the cross-tier classification cannot move."""
    m = _REPLICA_GROUPS.search(hlo_line) or _SOURCE_TARGETS.search(hlo_line)
    if m:
        return _int_groups(m.group(1))
    return parse_replica_groups(hlo_line)


def permutation_defect(
    pairs: List[Tuple[int, int]], n_dev: Optional[int] = None
) -> Optional[str]:
    """Why a ``source_target_pairs`` list is NOT a permutation of its
    axis group — the SL502 ppermute arm. ``None`` = congruent. A
    *partial* permutation over a subset is fine as long as the senders
    and receivers are the same devices (the odd-even sort rounds swap
    disjoint partner pairs); the hang shapes are: a duplicate source
    (undefined), a duplicate target (two blocks, one buffer), an id
    outside the mesh, and a source/receiver mismatch (some device waits
    for a block that never leaves, or sends into a peer that never
    posted a receive)."""
    if not pairs:
        return None
    sources = [s for s, _ in pairs]
    targets = [t for _, t in pairs]
    if len(set(sources)) != len(sources):
        dup = sorted({s for s in sources if sources.count(s) > 1})
        return f"duplicate source device(s) {dup} in source_target_pairs"
    if len(set(targets)) != len(targets):
        dup = sorted({t for t in targets if targets.count(t) > 1})
        return f"duplicate target device(s) {dup} in source_target_pairs"
    if n_dev:
        out = sorted({i for i in sources + targets if i < 0 or i >= n_dev})
        if out:
            return f"device id(s) {out} outside the {n_dev}-device mesh"
    if set(sources) != set(targets):
        waiting = sorted(set(targets) - set(sources))
        silent = sorted(set(sources) - set(targets))
        return (
            f"pairs are not a permutation of the axis group: device(s) "
            f"{waiting or silent} receive without sending (or send without "
            "receiving) — the ring never closes"
        )
    return None


def partition_defect(
    groups: List[List[int]], n_dev: Optional[int] = None
) -> Optional[str]:
    """Why a ``replica_groups`` list does NOT partition the mesh — the
    SL502 grouped-collective arm. ``None`` = congruent. Every device
    must appear in exactly one group (XLA's contract for grouped
    collectives): a device in two groups issues twice, a device in none
    never matches its peers' collective — both are hangs on TPU, not
    errors. With ``n_dev`` unknown (no ``num_partitions`` header) only
    duplication is checked, never coverage — conservative."""
    if not groups:
        return None
    flat = [i for g in groups for i in g]
    if len(set(flat)) != len(flat):
        dup = sorted({i for i in flat if flat.count(i) > 1})
        return f"device(s) {dup} appear in more than one replica group"
    if n_dev:
        out = sorted({i for i in flat if i < 0 or i >= n_dev})
        if out:
            return f"device id(s) {out} outside the {n_dev}-device mesh"
        missing = sorted(set(range(n_dev)) - set(flat))
        if missing:
            return (
                f"replica groups do not partition the mesh: device(s) "
                f"{missing} belong to no group and never match the "
                "collective their peers issued"
            )
    return None
