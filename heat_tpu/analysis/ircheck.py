"""Pass 1: IR lint — ``ht.analysis.check(fn, *args)``.

Traces and compiles ``fn`` for the example arguments exactly the way a
real dispatch would (the :func:`~heat_tpu.observability.hlo` machinery:
DNDarray leaves feed physical arrays, metadata rebuilds at trace time),
then walks the jaxpr and the compiled StableHLO and emits structured
:class:`~heat_tpu.analysis.findings.Finding`\\ s. Nothing executes on
device — the whole pass is compile-only, cheap enough for tests and CI.

The point (arxiv 2112.01075, arxiv 2112.09017): reshard cost is a
static property of source/target shardings, and TPU-scale linear
algebra lives or dies on every intermediate staying distributed — both
are checkable *here*, before any TPU minute is spent. The rules:

========  ========  ====================================================
rule      severity  fires when
========  ========  ====================================================
SL101     warn/err  an all-to-all (or a hand-rolled collective-permute
                    chain hop) moves ≥ ``min_bytes`` (err when it moves
                    ≥ ``replicate_frac`` of the largest input)
SL102     warn/err  an all-gather materializes ≥ ``min_bytes`` (same
                    escalation — a full-operand gather is an error)
SL103     warning   an all-gather result feeds a ``reduce``
SL104     warning   an inexact value widens past core/types.py
                    promotion of the program inputs; its NARROWING arm
                    (error) fires when an unscaled float→int8 cast
                    feeds a collective — the sanctioned dtype narrowing
                    is the stamped block-quantized wire codec
                    (``heat_tpu.kernels.quant``), which downgrades to
                    info
SL105     warning   an output aliases an argument's aval but the buffer
                    is not donated (cross-checked against ht.jit's
                    donation bookkeeping)
SL106     error     the program syncs the host (seen in source, or the
                    trace aborts on a concretization error); ambiguous
                    ``int()``/``float()`` casts report as warnings
SL107     warn/err  cross-tier collective not decomposed (ISSUE 8): at
                    a two-tier topology, a FLAT collective whose
                    replica groups (or ppermute source-target pairs)
                    span slices moves ≥ ``min_bytes`` across DCN — the
                    whole payload completes at the slow tier. The
                    sanctioned forms are the planner's
                    ``hierarchical-a2a`` programs and the hierarchical
                    DP wire, whose stamped collectives (and the
                    library's documented ring schedules) downgrade to
                    info. Evaluated only when a tiered topology is in
                    effect (``topology=`` arg or ``HEAT_TPU_TOPOLOGY``).
========  ========  ====================================================

The contracts the repo already pins stay clean by construction: TSQR's
one p·K² R-stack all-gather and ring attention's two ppermutes sit far
under ``min_bytes`` at any sane K, and the hSVD level-0 sketch compiles
to zero collectives.
"""

from __future__ import annotations

import re

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .findings import AnalysisReport, Finding

__all__ = ["check"]



def _nbytes(shape, dtype) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n * np.dtype(dtype).itemsize


# ONE dtype vocabulary (analysis/_dtypes.py, ISSUE 17) shared with
# numcheck's SL601-SL603 precision rules — the widening/narrowing
# classification of a cast is decided in exactly one place
from ._dtypes import effective_itemsize as _effective_itemsize
from ._dtypes import (
    INT8_DTYPES as _INT8_DTYPES,
    lossy_narrowing as _lossy_narrowing,
    promotion_ceiling as _promotion_ceiling,
    widens_past as _widens_past,
)


def _walk_jaxprs(jaxpr):
    """Yield every eqn of ``jaxpr`` and its nested sub-jaxprs (pjit /
    scan / cond / shard_map bodies)."""
    from jax.extend import core as jex_core  # jaxpr types live here on 0.4.x

    todo = [jaxpr]
    seen = set()
    while todo:
        jx = todo.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn
            for val in eqn.params.values():
                for sub in _as_jaxprs(val, jex_core):
                    todo.append(sub)


def _as_jaxprs(val, jex_core):
    out = []
    vals = val if isinstance(val, (list, tuple)) else (val,)
    for v in vals:
        closed = getattr(v, "jaxpr", None)
        if closed is not None and hasattr(v, "consts"):  # ClosedJaxpr
            out.append(closed)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            out.append(v)
    return out


def _trace_errors():
    import jax

    return (
        jax.errors.ConcretizationTypeError,
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerBoolConversionError,
        jax.errors.TracerIntegerConversionError,
    )


def _lower_checked(fn, args, kwargs, findings: List[Finding]):
    """Trace and compile-only lower the checked program — the ONE
    definition of the trace-abort contract shared by every pass entry
    (``check``, ``commcheck``): a host-read abort appends an SL106
    finding and returns ``None``, so the entry points can never drift
    on which malformed programs produce a report instead of a raise.
    Returns ``(closed_jaxpr, compiled)`` on success."""
    import jax

    from ..observability.hlo import _build_traceable

    kind, target, traced_in = _build_traceable(fn, args, kwargs)
    try:
        if kind == "lower":
            try:
                closed = jax.make_jaxpr(target)(*args, **kwargs)
            except TypeError:
                # make_jaxpr traces EVERY argument; a jitted fn with
                # static (non-array) args needs the AOT trace, which
                # respects the jit's own static_argnums
                closed = target.trace(*args, **kwargs).jaxpr
            compiled = target.lower(*args, **kwargs).compile()
        else:
            closed = jax.make_jaxpr(target)(*traced_in)
            # compile-only lowering of the CHECKED program — never
            # dispatched, so ht.jit's hooks have nothing to observe here
            compiled = jax.jit(target).lower(*traced_in).compile()  # shardlint: ignore[SL202]
    except _trace_errors() as e:
        findings.append(
            Finding(
                "SL106",
                "error",
                "trace aborted: the program reads device VALUES on the host "
                f"(concretization) — {type(e).__name__}: {str(e).splitlines()[0]}",
            )
        )
        return None
    except TypeError as e:
        if "ht.jit" in str(e) and "host" in str(e):
            findings.append(
                Finding("SL106", "error", f"trace aborted by a host read: {e}")
            )
            return None
        raise
    return closed, compiled


# ONE parser (analysis/_groups.py, ISSUE 14) shared with commcheck's
# SL502/SL503 congruence rules — the cross-tier and the incongruent
# verdicts can never disagree about what the same HLO line says
from ._groups import parse_groups as _parse_groups


def check(
    fn: Callable,
    *args,
    mesh=None,
    min_bytes: int = 1 << 20,
    replicate_frac: float = 0.5,
    donate_argnums: Optional[Tuple[int, ...]] = None,
    scan_source: bool = True,
    topology=None,
    **kwargs,
) -> AnalysisReport:
    """Statically analyze the program ``fn(*args, **kwargs)`` compiles to.

    ``fn`` may be a public heat_tpu function over DNDarrays, an
    ``ht.jit``-wrapped function, or an already-jitted jax callable; the
    arguments are example inputs fixing shapes/shardings (same contract
    as :func:`ht.observability.collective_counts`). Compile-only.

    Parameters
    ----------
    mesh : optional ``jax.sharding.Mesh`` the program is meant for —
        recorded in the report context (DNDarray arguments already carry
        their mesh via their communicator).
    min_bytes : collectives moving less than this are structural, not
        findings (default 1 MiB — TSQR's R-stack gather passes clean).
    replicate_frac : an all-gather/all-to-all moving at least this
        fraction of the largest input escalates to ``error``.
    donate_argnums : positional args whose buffers the caller donates at
        dispatch time; defaults to the checked ``ht.jit`` wrapper's own
        donation bookkeeping when present.
    scan_source : also scan ``fn``'s source for host syncs hiding in
        untaken branches (rule SL106).
    topology : two-tier topology override for rule SL107 (``"SxC"``
        string, ``core.communication.Topology``, or ``(S, C)`` tuple);
        the default ``None`` resolves the ambient ``HEAT_TPU_TOPOLOGY``
        per collective (flat topologies never fire the rule).

    Returns an :class:`AnalysisReport`; ``report.ok`` is False iff an
    error-severity finding gates.
    """
    import jax

    from ..observability.hlo import (
        _COLLECTIVE_LINE,
        _count_ops,
        _shaped_bytes,
    )

    findings: List[Finding] = []
    context: Dict[str, Any] = {"pass": "ircheck", "min_bytes": int(min_bytes)}
    if mesh is not None:
        context["mesh_devices"] = int(np.asarray(mesh.devices).size)

    if scan_source:
        from .srclint import scan_program_source

        findings += scan_program_source(fn)

    lowered = _lower_checked(fn, args, kwargs, findings)
    if lowered is None:
        return AnalysisReport(findings, context)
    closed, compiled = lowered

    # ---- SL401: use-after-donate (pass 4 folded into the IR check) ----
    from .effectcheck import scan_jaxpr_donation

    findings += scan_jaxpr_donation(
        closed, label=getattr(fn, "__name__", "") or ""
    )

    in_avals = [(tuple(a.shape), str(a.dtype)) for a in closed.in_avals]
    out_avals = [(tuple(a.shape), str(a.dtype)) for a in closed.out_avals]
    in_bytes = [_nbytes(s, d) for s, d in in_avals]
    max_in = max(in_bytes, default=0)
    context["max_input_bytes"] = int(max_in)
    err_bytes = max(int(min_bytes), int(replicate_frac * max_in))

    text = compiled.as_text()
    context["collective_counts"] = {k: v for k, v in _count_ops(text).items() if v}

    # ---- SL501-SL503: collective congruence (pass 5 folded in) --------
    from .commcheck import scan_hlo_congruence, scan_jaxpr_divergence

    _label = getattr(fn, "__name__", "") or ""
    findings += scan_jaxpr_divergence(closed, label=_label)
    findings += scan_hlo_congruence(text)

    # ---- SL601-SL603: precision flow (pass 6 folded in) ---------------
    # SL604 (f64 under x64-off) stays standalone-only: it is a SOURCE
    # rule a jaxpr cannot witness, and folding it would re-flag every
    # sanctioned widening fixture SL104 already prices
    from .numcheck import fn_pragmas, scan_jaxpr_precision

    findings += scan_jaxpr_precision(
        closed, label=_label, pragmas=fn_pragmas(fn)
    )

    # ---- SL101 / SL102: large resharding collectives -------------------
    from .boundaries import (
        planned_reshard_plan_id,
        ring_schedule_module,
        wire_codec_stamped,
    )

    gather_names: List[Tuple[str, int]] = []
    for m in _COLLECTIVE_LINE.finditer(text):
        ssa, result_type, op = m.group(1), m.group(2), m.group(3)
        nbytes = _shaped_bytes(result_type)
        if op == "all-gather":
            gather_names.append((ssa, nbytes))
        if op not in ("all-to-all", "all-gather", "collective-permute") or nbytes < min_bytes:
            continue
        rule = "SL102" if op == "all-gather" else "SL101"
        # planner-issued reshards (redistribution/executor.py programs run
        # under jax.named_scope("redist_plan_<id>") — including ISSUE 6's
        # software-pipelined ppermute chains — and the collective-matmul
        # rings under jax.named_scope("cmatmul_ring_<tag>"), stamping the
        # marker into the instruction's op_name metadata) are the
        # budgeted, cost-modeled movement itself — report them at info
        # severity with the stamp attached instead of flagging the
        # subsystems' own schedules (see boundaries.PLANNER_MODULES)
        line_end = text.find("\n", m.end())
        full_line = text[m.start() : len(text) if line_end == -1 else line_end]
        plan_id = planned_reshard_plan_id(full_line)
        if plan_id is not None:
            if plan_id.startswith("cmatmul:"):
                msg = (
                    f"planned collective-matmul movement ({plan_id}): {op} "
                    f"moves ~{nbytes} B ({ssa}) inside a stamped "
                    "heat_tpu.kernels.cmatmul ring — the decomposed "
                    "gather/reduction of the linalg overlap forms "
                    "(HEAT_TPU_REDIST_OVERLAP)"
                )
            else:
                msg = (
                    f"planned reshard (redist plan {plan_id}): {op} moves "
                    f"~{nbytes} B ({ssa}) under the redistribution "
                    "planner's peak-memory budget — inspect with "
                    "ht.redistribution.explain"
                )
            findings.append(Finding(rule, "info", msg, op=op, nbytes=nbytes))
            continue
        if op == "collective-permute":
            # the library's own DOCUMENTED ring schedules (sort
            # networks, halo exchange, ring attention) rotate blocks by
            # design — info, keyed on source_file since shard_map bodies
            # carry no stampable named scope. Hand-rolled loops in user
            # code still fall through to full severity.
            blessed = ring_schedule_module(full_line)
            if blessed is not None:
                findings.append(
                    Finding(
                        rule,
                        "info",
                        f"ring schedule ({blessed}): a collective-permute "
                        f"hop ships ~{nbytes} B ({ssa}) — the documented "
                        "block rotation of the library's own distributed "
                        "algorithm, not a relayout accident",
                        op=op,
                        nbytes=nbytes,
                    )
                )
                continue
        severity = "error" if nbytes >= err_bytes else "warning"
        what = {
            "all-to-all": "implicit reshard: an all-to-all relayouts",
            "collective-permute": (
                "implicit reshard: a hand-rolled collective-permute hop ships"
            ),
            "all-gather": "replicated materialization: an all-gather assembles",
        }[op]
        findings.append(
            Finding(
                rule,
                severity,
                f"{what} ~{nbytes} B ({ssa}); largest input is {max_in} B — "
                "align the operand's split with the op (resplit once, "
                "upstream, or keep the intermediate distributed)",
                op=op,
                nbytes=nbytes,
            )
        )

    # ---- SL107: cross-tier collective not decomposed (ISSUE 8) ---------
    # at a tiered topology, a flat collective whose replica groups span
    # slices pushes its WHOLE payload across DCN — the planner's
    # hierarchical-a2a (intra-slice pivot + inter-slice exchange) is the
    # decomposed form; its stamped programs (and the hierarchical DP
    # wire) report at info, as do the library's documented ring
    # schedules. The mesh size comes from the compiled module's own
    # num_partitions (a subgroup collective's ids can omit the top
    # devices, so max-id+1 would mis-resolve the topology and silently
    # skip genuinely DCN-crossing subgroup exchanges); max-id+1 is only
    # the fallback when the header is absent.
    from ..core import communication as _communication

    _num_parts = re.search(r"num_partitions=(\d+)", text)
    _module_n_dev = int(_num_parts.group(1)) if _num_parts else 0

    def _sl107_topology(n_dev: int):
        if topology is None:
            return _communication.topology_for(n_dev)
        return _communication.topology_for(n_dev, topology)

    for m in _COLLECTIVE_LINE.finditer(text):
        ssa, result_type, op = m.group(1), m.group(2), m.group(3)
        nbytes = _shaped_bytes(result_type)
        if nbytes < min_bytes:
            continue
        line_end = text.find("\n", m.end())
        full_line = text[m.start() : len(text) if line_end == -1 else line_end]
        grps = _parse_groups(full_line)
        if not grps:
            continue
        n_dev = _module_n_dev or (max((i for g in grps for i in g), default=-1) + 1)
        topo = _sl107_topology(n_dev)
        if not topo.tiered:
            continue
        if op == "collective-permute":
            spanning = any(len(g) >= 2 and topo.crosses(g[0], g[1]) for g in grps)
        else:
            spanning = any(topo.spans(g) for g in grps)
        if not spanning:
            continue
        plan_id = planned_reshard_plan_id(full_line)
        if plan_id is None and wire_codec_stamped(full_line):
            plan_id = "wire-codec"
        if plan_id is not None:
            findings.append(
                Finding(
                    "SL107",
                    "info",
                    f"planned cross-tier movement ({plan_id}): {op} crosses "
                    f"slices at {topo} with ~{nbytes} B ({ssa}) — the "
                    "decomposed/budgeted DCN hop itself (hierarchical-a2a "
                    "ships pre-packed per-slice rows; inspect with "
                    "ht.redistribution.explain)",
                    op=op,
                    nbytes=nbytes,
                )
            )
            continue
        blessed = ring_schedule_module(full_line)
        if blessed is not None:
            findings.append(
                Finding(
                    "SL107",
                    "info",
                    f"documented ring schedule ({blessed}) crosses slices at "
                    f"{topo}: a {op} ships ~{nbytes} B over DCN on the "
                    "wraparound edges — the algorithm's block rotation, "
                    "priced (not flagged) at the tier penalty",
                    op=op,
                    nbytes=nbytes,
                )
            )
            continue
        severity = "error" if nbytes >= err_bytes else "warning"
        findings.append(
            Finding(
                "SL107",
                severity,
                f"cross-tier collective not decomposed: a flat {op} whose "
                f"replica groups span slices at {topo} moves ~{nbytes} B — "
                "every byte completes at DCN speed (~8x ICI). Decompose it "
                "hierarchically: intra-slice pivot + inter-slice exchange "
                "(the redistribution planner's hierarchical-a2a, or "
                "kernels.quant.hierarchical_allreduce_sum for gradient "
                "all-reduces)",
                op=op,
                nbytes=nbytes,
            )
        )

    # ---- SL103: all-gather feeding a reduction -------------------------
    # consumer shapes differ by backend: a direct `reduce(`, the CPU
    # `reduce-window` ladder, or a `call` into a %parallel_reduce-*
    # computation — all carry a "reduce" token on the consuming line.
    # metadata={op_name=...} trailers are stripped first: a consumer whose
    # source location merely MENTIONS reduce is not a reduction, and a
    # gather already feeding reduce-scatter needs no reduce-scatter advice
    lines = [ln.split(" metadata=")[0] for ln in text.splitlines()]
    for ssa, nbytes in gather_names:
        operand = re.compile(re.escape(ssa) + r"(?![\w.\-])")
        for line in lines:
            if "reduce" not in line or "all-reduce" in line or "reduce-scatter" in line:
                continue
            lhs = line.strip().removeprefix("ROOT ").startswith(ssa)
            if not lhs and operand.search(line):
                findings.append(
                    Finding(
                        "SL103",
                        "warning",
                        f"all-gather result {ssa} (~{nbytes} B) feeds a "
                        "reduction — a reduce-scatter (or local reduce + "
                        "small all-reduce) moves O(1/p) of the bytes",
                        op="all-gather",
                        nbytes=nbytes,
                    )
                )
                break

    # ---- SL104: dtype widening beyond input promotion ------------------
    ceiling = _promotion_ceiling(d for _, d in in_avals)
    seen_widen = set()
    for eqn in _walk_jaxprs(closed.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src_dt = np.dtype(eqn.invars[0].aval.dtype)
        dst_dt = np.dtype(eqn.params.get("new_dtype"))
        if _widens_past(src_dt, dst_dt, ceiling) and (src_dt.name, dst_dt.name) not in seen_widen:
            seen_widen.add((src_dt.name, dst_dt.name))
            findings.append(
                Finding(
                    "SL104",
                    "warning",
                    f"dtype widening {src_dt.name} -> {dst_dt.name}: wider "
                    "than core/types.py promotion of any input "
                    f"(ceiling {ceiling * 8}-bit) — likely an accidental "
                    "64-bit constant or astype",
                    op="convert_element_type",
                )
            )

    # ---- SL104 (narrowing arm): float->int8 feeding a collective -------
    # an UNSCALED astype(int8) before a psum/all-to-all truncates the
    # payload and wraps the reduction — the accident gradient
    # compression invites. The sanctioned narrowing is the
    # block-quantized wire codec (kernels/quant.py), whose encode/decode
    # bodies run under jax.named_scope("wire_codec_<mode>"): the stamp
    # rides the eqn's name_stack, and stamped converts report at info
    # (wire_codec_stamped imported with the SL101 boundary helpers).
    from jax.extend import core as jex_core

    collective_prims = {
        "psum", "all_to_all", "all_gather", "ppermute", "pmax", "pmin",
        "psum_scatter", "reduce_scatter",
    }
    passthrough_prims = {
        "concatenate", "reshape", "transpose", "squeeze", "broadcast_in_dim",
        "slice", "dynamic_slice", "pad", "rev", "select_n", "copy",
        # jnp.where/clip/round wrap their select/round bodies in nested
        # pjit eqns: the outer walk continues through the pjit's OWN
        # invars (the operands), which is exactly the dataflow step
        "pjit", "custom_jvp_call", "custom_vjp_call",
    }
    seen_narrow = set()
    # ONE producer map over every (sub-)jaxpr: vars are unique objects,
    # so the map lets the backward walk cross call boundaries — a
    # convert hiding inside a nested pjit is reached by stepping from
    # the pjit eqn onto its sub-jaxpr's OUTVARS (the value the outer
    # program actually consumes), not just its outer operands.
    producers = {}
    collective_eqns = []
    todo_jx, seen_jx = [closed.jaxpr], set()
    while todo_jx:
        jx = todo_jx.pop()
        if id(jx) in seen_jx:
            continue
        seen_jx.add(id(jx))
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                producers[id(ov)] = eqn
            if eqn.primitive.name in collective_prims:
                collective_eqns.append(eqn)
            for val in eqn.params.values():
                todo_jx.extend(_as_jaxprs(val, jex_core))

    def _sub_outvar_for(eqn, v):
        """The sub-jaxpr outvar that PRODUCES the outer var ``v`` of a
        call eqn (pjit/custom_*): call outvars map 1:1 onto the
        sub-jaxpr's outvars by position, so only the index-matched one
        continues the walk — a sibling output of the same jit wrapper
        is not on the collective's dataflow path."""
        try:
            idx = next(i for i, ov in enumerate(eqn.outvars) if ov is v)
        except StopIteration:
            return []
        out = []
        for val in eqn.params.values():
            for sub in _as_jaxprs(val, jex_core):
                outvars = getattr(sub, "jaxpr", sub).outvars
                if idx < len(outvars):
                    out.append(outvars[idx])
        return out

    for eqn in collective_eqns:
        stack = [(v, 0) for v in eqn.invars]
        visited = set()
        while stack:
            v, depth = stack.pop()
            if depth > 12 or isinstance(v, jex_core.Literal) or id(v) in visited:
                continue
            visited.add(id(v))
            src = producers.get(id(v))
            if src is None:
                continue
            name = src.primitive.name
            if name == "convert_element_type":
                src_dt = np.dtype(src.invars[0].aval.dtype)
                dst_dt = np.dtype(src.params.get("new_dtype"))
                if _lossy_narrowing(src_dt, dst_dt):
                    stamped = wire_codec_stamped(str(src.source_info.name_stack))
                    dkey = (src_dt.name, dst_dt.name, eqn.primitive.name, stamped)
                    if dkey in seen_narrow:
                        continue
                    seen_narrow.add(dkey)
                    if stamped:
                        findings.append(
                            Finding(
                                "SL104",
                                "info",
                                f"sanctioned wire-codec narrowing: {src_dt.name} "
                                f"-> {dst_dt.name} feeds a {eqn.primitive.name} "
                                "inside a wire_codec-stamped encode "
                                "(heat_tpu.kernels.quant) — the block-quantized "
                                "collective payload, scale per tile",
                                op="convert_element_type",
                            )
                        )
                    else:
                        findings.append(
                            Finding(
                                "SL104",
                                "error",
                                f"lossy dtype narrowing {src_dt.name} -> "
                                f"{dst_dt.name} feeds a {eqn.primitive.name}: an "
                                "unscaled astype before a collective truncates "
                                "the payload (int8 sums wrap) — use the "
                                "block-quantized wire codec "
                                "(heat_tpu.kernels.quant) or ship full width",
                                op="convert_element_type",
                            )
                        )
                continue  # a convert ends the walk either way
            if name in passthrough_prims:
                stack.extend((u, depth + 1) for u in src.invars)
                # a call primitive's RESULT is produced by its
                # sub-jaxpr's outvars: step inside (index-matched) so a
                # convert hiding in a nested jit wrapper is reached,
                # while the wrapper's unrelated sibling outputs are not
                if name in ("pjit", "custom_jvp_call", "custom_vjp_call"):
                    stack.extend((u, depth + 1) for u in _sub_outvar_for(src, v))

    # ---- SL105: aliasable output not donated ---------------------------
    # with explicit donation bookkeeping the per-aval check below is the
    # authority (a PARTIALLY donated program still has missed donations to
    # report); only without it does module-level aliasing mean "the caller
    # already donated through raw jax.jit" and silence the rule. The
    # donation resolver is SHARED with memcheck's SL302 (analysis._donation)
    # so "should donate" and "donation dropped" can never disagree about
    # what was declared.
    from ._donation import donated_avals as _donated_avals_shared

    donated = _donated_avals_shared(fn, args, donate_argnums)
    have_bookkeeping = bool(donated) or donate_argnums is not None
    if have_bookkeeping or "input_output_alias" not in text:
        in_set = set(in_avals)
        flagged = set()
        for shape, dtype in out_avals:
            aval = (shape, dtype)
            nbytes = _nbytes(shape, dtype)
            if (
                nbytes >= min_bytes
                and aval in in_set
                and aval not in donated
                and aval not in flagged
            ):
                flagged.add(aval)
                findings.append(
                    Finding(
                        "SL105",
                        "warning",
                        f"an output of shape {shape} {dtype} (~{nbytes} B) "
                        "aliases an argument's aval but the buffer is not "
                        "donated — pass donate_argnums to ht.jit so the "
                        "pipeline reuses the input HBM",
                        nbytes=nbytes,
                    )
                )

    findings.sort(key=lambda f: ({"error": 0, "warning": 1, "info": 2}[f.severity], f.rule))
    return AnalysisReport(findings, context)
