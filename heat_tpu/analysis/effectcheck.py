"""Pass 4: effect lint — ``gatecheck`` + ``racecheck``.

The first three analyzer passes prove properties of one compiled
program (ircheck), the source tree's jax hygiene (srclint), and one
program's memory (memcheck). This pass proves the properties that sit
BETWEEN programs — the ones benchmarks never catch because every
individual program is correct:

- **SL401 use-after-donate** — jaxpr dataflow on the shared donation
  resolver (:mod:`~heat_tpu.analysis._donation`): an operand whose
  buffer a call donates (``donated_invars``) is read — or returned —
  by anything AFTER that call. The donating program may have already
  overwritten the bytes in place; on real hardware the read returns
  garbage nondeterministically, which is why the rule is static.
- **SL402 gate/cache-key staleness** — the rule that mechanizes the
  convention every PR since 5 carried by hand ("the gate is a component
  of every program cache key"): a ``HEAT_TPU_*`` read (a registered
  accessor, ``gates.get``, or a raw read) reachable from an
  ``lru_cache``-wrapped or dict-cached program builder whose cache key
  does not carry the gate. The registry (:mod:`heat_tpu.core.gates`)
  declares, per gate, the conventional parameter names its resolved
  value travels under (``key_params``) — a builder keys on a gate by
  taking one of them as a parameter (lru caches key on parameters), or
  by folding the gate-derived local into the dict-cache key tuple.
- **SL403 raw-gate-read** — ``os.environ`` consulted for a
  ``HEAT_TPU_*`` name anywhere outside ``core/gates.py``. The registry
  is the one sanctioned read site; a raw read bypasses declaration,
  legal-value documentation, AND the AOT stamp derivation.
- **SL404 lock-discipline race lint** — over the threaded classes (a
  class that spawns a ``threading.Thread`` on one of its own methods,
  or that owns locks): an attribute written on the worker path and
  touched on a client path must have ONE lock covering all its accesses
  on both paths; in lock-owning classes, an attribute guarded at some
  sites and bare at others is flagged the same way. Deliberate
  lock-free designs are declared, reviewably, with
  ``# racecheck: guarded-by(<what>) -- reason`` on any access (or
  ``__init__`` assignment) line of the attribute.
- **SL406 swallowed-worker-exception** — over the same threaded
  classes: a worker-path ``except Exception`` (or bare ``except``)
  whose handler neither re-raises, nor resolves a future
  (``set_exception``/``set_result`` — directly or via an intra-class
  helper that does), nor forwards the caught object into any call (the
  queue-forwarding idiom). That silent-swallow shape is exactly what a
  failover path must never have: the client's future never resolves
  and the failure becomes a hang (ISSUE 13 — added alongside the
  dispatcher's drain path, whose handlers all fail their owned futures
  typed and are pinned clean).
- **SL405 pipeline-protocol** — the depth-2 double-buffer skeletons
  (``executor._run_laps``, ``staging.stream_windows``, and anything
  shaped like them): a loop that claims depth 2 (prologue prefetch of
  lap 0) must issue lap k+1 BEFORE consuming lap k, must never consume
  the lap it just issued (the unfenced buffer), and must consume the
  final carried lap after the loop. :func:`check_plan_protocol` is the
  dynamic half: a Schedule's overlap/staging annotation must describe a
  real depth-2 structure (tagged laps >= 2, critical path < sequential)
  — swept over every golden plan form in tier-1.

Scope and honesty: SL402's reachability is the intra-module call graph
(a bare call to a function defined in the same module, plus direct
calls to registered accessors wherever they were imported from) — the
resolution-at-the-caller idiom the executor uses (resolve the gate in
``execute()``, pass ``pipelined``/``wire``/``topo`` into the cached
builder) is exactly what the rule rewards. SL404 analyzes ``self.``
attributes per class (module-level globals under module-level locks are
srclint's concern, not modeled here).

Inline escape hatch, same grammar as the other passes::

    x = os.environ.get("HEAT_TPU_OOC")  # shardlint: ignore[SL403] -- why

CLI: ``python scripts/lint.py heat_tpu/ --pass effectcheck``
(text/json/sarif; error severity gates the ci.sh leg). Rule catalog:
:data:`heat_tpu.analysis.findings.RULES` / docs/PERF.md § Static
analysis.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Any, Dict, List, Optional, Set, Tuple

from ..core import gates as _gates
from .findings import AnalysisReport, Finding
from .srclint import (
    _call_name,
    _iter_py_files,
    _pragmas_of,
    _suppressed,
    _walk_scoped,
    _Scope,
)

__all__ = [
    "check_donation",
    "check_plan_protocol",
    "lint_paths",
    "lint_source",
    "scan_jaxpr_donation",
]


# --------------------------------------------------------------------- #
# SL401 — use-after-donate (jaxpr dataflow)                             #
# --------------------------------------------------------------------- #
def _is_var(v) -> bool:
    return type(v).__name__ != "Literal"


def _donating_invars(eqn) -> List[Any]:
    """The invars an equation DONATES: the positions its
    ``donated_invars`` param marks (pjit and friends carry it)."""
    flags = eqn.params.get("donated_invars")
    if not flags:
        return []
    return [v for v, d in zip(eqn.invars, flags) if d and _is_var(v)]


def _eqn_name(eqn) -> str:
    name = getattr(eqn.primitive, "name", str(eqn.primitive))
    inner = eqn.params.get("name") or getattr(
        eqn.params.get("jaxpr"), "jaxpr", None
    )
    if isinstance(inner, str):
        return f"{name}[{inner}]"
    return name


def scan_jaxpr_donation(closed, label: str = "") -> List[Finding]:
    """Rule SL401 over one (closed) jaxpr: walk the equations in
    program order; every invar a call-equation donates is DEAD past
    that equation — a later read, or returning it, is a use of a buffer
    the donating program may already have overwritten in place. Returns
    findings (empty = clean). Top-level dataflow: donation inside a
    nested call kills the var for the REST of the enclosing program,
    which is the level the bug class lives at (an eager caller reusing
    an array it passed to a donating ``ht.jit`` program)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    findings: List[Finding] = []
    dead: Dict[Any, Tuple[int, str]] = {}
    where = f" in {label}" if label else ""
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v) and v in dead:
                d_idx, d_name = dead[v]
                aval = getattr(v, "aval", None)
                findings.append(
                    Finding(
                        "SL401",
                        "error",
                        f"use-after-donate{where}: operand "
                        f"{aval if aval is not None else v} was donated by "
                        f"step #{d_idx} ({d_name}) and is read again by step "
                        f"#{idx} ({_eqn_name(eqn)}) — the donating program "
                        "may have overwritten the buffer in place; keep a "
                        "copy, or stop donating it",
                        op=_eqn_name(eqn),
                    )
                )
        for v in _donating_invars(eqn):
            dead.setdefault(v, (idx, _eqn_name(eqn)))
    for v in jaxpr.outvars:
        if _is_var(v) and v in dead:
            d_idx, d_name = dead[v]
            findings.append(
                Finding(
                    "SL401",
                    "error",
                    f"use-after-donate{where}: a donated operand (donated by "
                    f"step #{d_idx}, {d_name}) is RETURNED from the program — "
                    "the caller receives a buffer the callee was told it may "
                    "destroy",
                    op=d_name,
                )
            )
    return findings


def check_donation(fn, *args, donate_argnums=None, **kwargs) -> AnalysisReport:
    """Trace ``fn(*args, **kwargs)`` (same argument contract as
    :func:`ht.analysis.check`) and run rule SL401 over its jaxpr. The
    checked fn's OWN donation — resolved through the shared
    ``analysis/_donation.py`` resolver, so this pass and SL105/SL302
    can never disagree about what was donated — is recorded in the
    report context; inner donating calls are the dataflow subjects."""
    import jax

    from . import _donation
    from ..observability.hlo import _build_traceable

    kind, target, traced_in = _build_traceable(fn, args, kwargs)
    if kind == "lower":
        try:
            closed = jax.make_jaxpr(target)(*args, **kwargs)
        except TypeError:
            closed = target.trace(*args, **kwargs).jaxpr
    else:
        closed = jax.make_jaxpr(target)(*traced_in)
    label = getattr(fn, "__name__", "")
    findings = scan_jaxpr_donation(closed, label=label)
    context = {
        "pass": "effectcheck/donation",
        "donate_argnums": list(
            _donation.declared_donate_argnums(fn, donate_argnums)
        ),
    }
    return AnalysisReport(findings, context)


# --------------------------------------------------------------------- #
# shared source-pass helpers                                            #
# --------------------------------------------------------------------- #
_RACECHECK = re.compile(r"#\s*racecheck:\s*guarded-by\(([^)]*)\)")

_GATES_MODULE = "core/gates.py"


def _racecheck_pragmas(src: str) -> Dict[int, str]:
    """line -> declared guard ('worker-loop', a lock name, ...)."""
    out: Dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _RACECHECK.search(line)
        if m:
            out[i] = m.group(1).strip()
    return out


def _is_os_environ(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


def _gate_literal(node: ast.AST, consts: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The gate name a node denotes: a ``HEAT_TPU_*`` string literal, or
    a Name bound at module level to one (``OVERLAP_ENV``-style constants
    — the codebase's historical read idiom, resolved via ``consts``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) and node.value.startswith(_gates.PREFIX):
        return node.value
    if consts and isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _module_gate_consts(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "HEAT_TPU_..."`` constant bindings."""
    out: Dict[str, str] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Constant):
            v = n.value.value
            if isinstance(v, str) and v.startswith(_gates.PREFIX):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = v
    return out


def _fn_param_names(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _is_cached_builder(fn: ast.FunctionDef) -> bool:
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if _call_name(target) in ("lru_cache", "cache"):
            return True
    return False


# --------------------------------------------------------------------- #
# SL403 — raw env read bypassing the registry                           #
# --------------------------------------------------------------------- #
def _gate_scoped_enumerations(tree: ast.Module) -> Set[int]:
    """ids of ``os.environ`` enumeration calls (items/keys/values) whose
    enclosing function — or the module top level — names the gate
    prefix in a string literal: the hand-rolled fingerprint-scan shape
    SL403 retires. Enumerations with no gate prefix in scope (a generic
    env diagnostic) are not gate reads and stay unflagged."""

    def has_prefix(node) -> bool:
        return any(
            isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and _gates.PREFIX[:-1] in n.value
            for n in ast.walk(node)
        )

    out: Set[int] = set()
    fns = [n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    in_fn: Set[int] = set()
    for fn in fns:
        calls = [
            n
            for n in ast.walk(fn)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in ("items", "keys", "values")
            and _is_os_environ(n.func.value)
        ]
        in_fn.update(id(c) for c in calls)
        if calls and has_prefix(fn):
            out.update(id(c) for c in calls)
    if has_prefix(tree):
        for n in ast.walk(tree):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("items", "keys", "values")
                and _is_os_environ(n.func.value)
                and id(n) not in in_fn  # module-level enumeration
            ):
                out.add(id(n))
    return out


def _lint_sl403(tree: ast.Module, rel: str, pragmas) -> List[Finding]:
    if rel.endswith(_GATES_MODULE):
        return []  # the one sanctioned read site
    enum_hits = _gate_scoped_enumerations(tree)
    consts = _module_gate_consts(tree)
    findings: List[Finding] = []

    def flag(node, scope, what: str) -> None:
        if _suppressed("SL403", node.lineno, scope, pragmas):
            return
        where = scope.qualname or "<module>"
        findings.append(
            Finding(
                "SL403",
                "error",
                f"raw gate read in {where}: {what} bypasses the gate "
                "registry — read it through heat_tpu.core.gates.get "
                "(declare the gate there first if it is new)",
                path=rel,
                line=node.lineno,
            )
        )

    for node, scope in _walk_scoped(tree):
        # os.environ.get / os.getenv with a literal gate name
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("get", "getenv", "setdefault", "pop"):
                env_call = (
                    _is_os_environ(f.value)
                    or (f.attr == "getenv" and isinstance(f.value, ast.Name) and f.value.id == "os")
                )
                if env_call and node.args:
                    g = _gate_literal(node.args[0], consts)
                    if g:
                        flag(node, scope, f"os.environ read of {g!r}")
            # os.environ.items()/keys()/values() in a scope that names the
            # gate prefix: the hand-rolled fingerprint scan SL403 retires
            # (prefix-free enumerations are not gate reads and pass)
            elif id(node) in enum_hits:
                flag(node, scope, "os.environ enumeration over HEAT_TPU_* names (gate fingerprints derive from gates.aot_fingerprint)")
        # os.environ[<gate literal>] (read or write)
        elif isinstance(node, ast.Subscript) and _is_os_environ(node.value):
            g = _gate_literal(node.slice, consts)
            if g:
                flag(node, scope, f"os.environ[{g!r}]")
        # <gate literal> in os.environ
        elif isinstance(node, ast.Compare) and any(
            _is_os_environ(c) for c in node.comparators
        ):
            g = _gate_literal(node.left, consts)
            if g:
                flag(node, scope, f"{g!r} in os.environ (gates.is_set)")
    return findings


# --------------------------------------------------------------------- #
# SL402 — gate/cache-key staleness                                      #
# --------------------------------------------------------------------- #
def _gate_reads_of(fn: ast.FunctionDef, acc_map, prog_gates, consts=None) -> Dict[str, int]:
    """gate name -> first read line inside ``fn``'s own body (accessor
    calls, ``gates.get`` with a literal or module-constant name, raw env
    reads)."""
    reads: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name in acc_map:
            for g in acc_map[name]:
                if g in prog_gates:
                    reads.setdefault(g, node.lineno)
        elif name in ("get", "is_set", "getenv") and node.args:
            g = _gate_literal(node.args[0], consts)
            if g and g in prog_gates:
                reads.setdefault(g, node.lineno)
    return reads


def _module_dicts(tree: ast.Module) -> Set[str]:
    """Module-level names bound to dict displays — the hand-rolled
    program/plan caches SL402's second detector covers."""
    out: Set[str] = set()
    for n in tree.body:
        targets: List[ast.expr] = []
        value = None
        if isinstance(n, ast.Assign):
            targets, value = n.targets, n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        if value is None:
            continue
        is_dict = isinstance(value, ast.Dict) or (
            isinstance(value, ast.Call) and _call_name(value.func) == "dict"
        )
        if is_dict:
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _lint_sl402(tree: ast.Module, rel: str, pragmas) -> List[Finding]:
    acc_map = _gates.accessor_gates()
    prog_gates = {s.name for s in _gates.affecting_programs()}
    consts = _module_gate_consts(tree)
    findings: List[Finding] = []
    mod_fns = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}

    # ---- detector 1: lru-cached builder reaching an ambient read ----- #
    for fn in mod_fns.values():
        if not _is_cached_builder(fn):
            continue
        params = _fn_param_names(fn)
        # intra-module closure: the builder plus the same-module helpers
        # it (transitively) calls by bare name
        seen, todo = {fn.name}, [fn]
        reads: Dict[str, Tuple[int, str]] = {}
        while todo:
            cur = todo.pop()
            for g, line in _gate_reads_of(cur, acc_map, prog_gates, consts).items():
                reads.setdefault(g, (line, cur.name))
            for node in ast.walk(cur):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    callee = mod_fns.get(node.func.id)
                    if callee is not None and callee.name not in seen:
                        seen.add(callee.name)
                        todo.append(callee)
        for g, (line, via) in sorted(reads.items()):
            if params & set(_gates.GATES[g].key_params):
                continue  # the gate's resolved value IS cache-key material
            scope = _Scope((fn.name,), (fn.lineno,))
            if _suppressed("SL402", line, scope, pragmas):
                continue
            at = fn.name if via == fn.name else f"{fn.name} (via {via})"
            findings.append(
                Finding(
                    "SL402",
                    "error",
                    f"stale-key hazard: cached program builder {at!r} reads "
                    f"{g} ambiently — a gate flip would keep serving the "
                    "program compiled under the old value. Resolve the gate "
                    "at the caller and pass it as a parameter (conventional "
                    f"names: {', '.join(_gates.GATES[g].key_params) or 'declare key_params in core/gates.py'})",
                    path=rel,
                    line=line,
                )
            )

    # ---- detector 2: dict-cached builder whose key drops a gate ------ #
    caches = _module_dicts(tree)
    if caches:
        for fn in mod_fns.values():
            key_names: Set[str] = set()
            uses_cache = False
            for node in ast.walk(fn):
                key_expr = None
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "setdefault", "pop")
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in caches
                    and node.args
                ):
                    key_expr = node.args[0]
                elif (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in caches
                ):
                    key_expr = node.slice
                if key_expr is not None:
                    uses_cache = True
                    key_names |= _names_in(key_expr)
            if not uses_cache:
                continue
            # key composition: names flowing into locals that the key uses
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and all(
                    isinstance(t, ast.Name) for t in node.targets
                ):
                    if any(t.id in key_names for t in node.targets):
                        key_names |= _names_in(node.value)
            # gate-derived locals: assigned from an accessor/registry read
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                target_names = [t.id for t in node.targets if isinstance(t, ast.Name)]
                if not target_names:
                    continue
                for call in ast.walk(node.value):
                    if not isinstance(call, ast.Call):
                        continue
                    name = _call_name(call.func)
                    hit = [
                        g for g in acc_map.get(name, ()) if g in prog_gates
                    ]
                    if name in ("get", "is_set") and call.args:
                        g = _gate_literal(call.args[0], consts)
                        if g and g in prog_gates:
                            hit.append(g)
                    for g in hit:
                        if set(target_names) & key_names:
                            continue  # the resolved value rides in the key
                        scope = _Scope((fn.name,), (fn.lineno,))
                        if _suppressed("SL402", node.lineno, scope, pragmas):
                            continue
                        findings.append(
                            Finding(
                                "SL402",
                                "error",
                                f"stale-key hazard: {fn.name!r} resolves {g} "
                                f"into {'/'.join(target_names)!r} but the "
                                "dict-cache key it looks programs up under "
                                "never includes it — a gate flip would serve "
                                "the entry cached under the old value",
                                path=rel,
                                line=node.lineno,
                            )
                        )
    return findings


# --------------------------------------------------------------------- #
# SL404 — lock-discipline race lint                                     #
# --------------------------------------------------------------------- #
_SYNC_TYPES = frozenset({
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event", "local",
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
})
_LOCK_TYPES = frozenset({"Lock", "RLock", "Condition"})
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "remove", "clear", "update", "add", "discard", "setdefault",
    "sort", "reverse",
})
_PUBLIC_DUNDERS = frozenset({
    "__enter__", "__exit__", "__iter__", "__next__", "__call__", "__del__",
    "__len__", "__contains__",
})


class _Access:
    __slots__ = ("attr", "method", "write", "lineno", "locks")

    def __init__(self, attr, method, write, lineno, locks):
        self.attr = attr
        self.method = method
        self.write = write
        self.lineno = lineno
        self.locks = frozenset(locks)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_accesses(method: ast.FunctionDef, lock_attrs: Set[str]):
    """Every ``self.X`` touch in ``method`` with the lexically held
    locks, plus the intra-class calls (``self.m(...)``) with the locks
    held at the call site."""
    accesses: List[_Access] = []
    calls: List[Tuple[str, frozenset]] = []
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(method):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    def held(node) -> Set[str]:
        out: Set[str] = set()
        cur = parents.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    attr = _self_attr(item.context_expr)
                    if attr in lock_attrs:
                        out.add(attr)
            cur = parents.get(id(cur))
        return out

    for node in ast.walk(method):
        attr = _self_attr(node)
        if attr is not None:
            parent = parents.get(id(node))
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            method_called = None
            if (
                isinstance(parent, ast.Attribute)
                and parent.value is node
                and isinstance(parents.get(id(parent)), ast.Call)
                and parents[id(parent)].func is parent
            ):
                method_called = parent.attr
            if method_called in _MUTATORS:
                # self.X.append(...) and friends mutate the container
                # through a Load-context read
                write = True
            if isinstance(parent, ast.Subscript) and parent.value is node:
                # self.X[...] = / del self.X[...]
                if isinstance(parent.ctx, (ast.Store, ast.Del)):
                    write = True
            accesses.append(_Access(attr, method.name, write, node.lineno, held(node)))
        # self.m(...) call edges (m resolved against the class below)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            calls.append((node.func.attr, frozenset(held(node))))
        # getattr(self, "attr", ...) reads
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "getattr"
            and len(node.args) >= 2
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id == "self"
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            accesses.append(
                _Access(node.args[1].value, method.name, False, node.lineno, held(node))
            )
    return accesses, calls


def _closure(roots: Set[str], edges: Dict[str, Set[str]]) -> Set[str]:
    out, todo = set(roots), list(roots)
    while todo:
        cur = todo.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in out:
                out.add(nxt)
                todo.append(nxt)
    return out


def _lint_sl404(tree: ast.Module, rel: str, pragmas, guards: Dict[int, str]) -> List[Finding]:
    findings: List[Finding] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        methods = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
        if not methods:
            continue
        # lock/sync attribute discovery (any method, usually __init__)
        lock_attrs: Set[str] = set()
        sync_attrs: Set[str] = set()
        init_assign_lines: Dict[str, List[int]] = {}
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    tname = _call_name(node.value.func)
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        if tname in _LOCK_TYPES:
                            lock_attrs.add(attr)
                            sync_attrs.add(attr)
                        elif tname in _SYNC_TYPES:
                            sync_attrs.add(attr)
                if m.name == "__init__" and isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            init_assign_lines.setdefault(attr, []).append(node.lineno)
        # worker roots: threading.Thread(target=self.m)
        worker_roots: Set[str] = set()
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and _call_name(node.func) == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            attr = _self_attr(kw.value)
                            if attr in methods:
                                worker_roots.add(attr)
                    for a in node.args:
                        attr = _self_attr(a)
                        if attr in methods:
                            worker_roots.add(attr)
        if not worker_roots and not lock_attrs:
            continue

        accesses: List[_Access] = []
        call_edges: Dict[str, Set[str]] = {}
        call_sites: List[Tuple[str, str, frozenset]] = []
        for name, m in methods.items():
            acc, calls = _collect_accesses(m, lock_attrs)
            accesses += acc
            for callee, locks in calls:
                if callee in methods:
                    call_edges.setdefault(name, set()).add(callee)
                    call_sites.append((name, callee, locks))

        # lock inheritance: a method whose EVERY intra-class call site
        # holds lock L is, for discipline purposes, under L (the
        # telemetry `_prune_locked` pattern: mutate inside a helper,
        # lock at the one caller). Fixpoint: a call site contributes the
        # locks it lexically holds plus what its caller inherited.
        inherited: Dict[str, frozenset] = {}
        for _ in range(len(methods) + 1):
            changed = False
            for callee in {c for _, c, _ in call_sites}:
                inh = None
                for caller, c, locks in call_sites:
                    if c != callee:
                        continue
                    eff = locks | inherited.get(caller, frozenset())
                    inh = eff if inh is None else (inh & eff)
                inh = inh or frozenset()
                if inherited.get(callee) != inh:
                    inherited[callee] = inh
                    changed = True
            if not changed:
                break

        worker = _closure(worker_roots, call_edges)
        public_roots = {
            n for n in methods
            if (not n.startswith("_") or n in _PUBLIC_DUNDERS) and n != "__init__"
        } - worker_roots
        client = _closure(public_roots, call_edges)

        by_attr: Dict[str, List[_Access]] = {}
        for a in accesses:
            if a.attr in sync_attrs or a.attr in methods:
                continue  # sync objects and method references are not data
            by_attr.setdefault(a.attr, []).append(a)

        def annotated(attr: str, accs: List[_Access]) -> bool:
            lines = {a.lineno for a in accs} | set(init_assign_lines.get(attr, ()))
            if any(line in guards for line in lines):
                return True
            scope = _Scope((cls.name,), (cls.lineno,))
            return any(_suppressed("SL404", line, scope, pragmas) for line in lines)

        for attr, accs in sorted(by_attr.items()):
            writes_outside_init = [
                a for a in accs if a.write and a.method != "__init__"
            ]
            if not writes_outside_init:
                continue
            live = [a for a in accs if a.method != "__init__"]
            eff = {
                id(a): a.locks | inherited.get(a.method, frozenset()) for a in live
            }
            if worker_roots:
                w_acc = [a for a in live if a.method in worker]
                c_acc = [a for a in live if a.method in client]
                if w_acc and c_acc:
                    w_locks = frozenset.intersection(*[frozenset(eff[id(a)]) for a in w_acc])
                    c_locks = frozenset.intersection(*[frozenset(eff[id(a)]) for a in c_acc])
                    if not (w_locks & c_locks) and not annotated(attr, live):
                        sample = writes_outside_init[0]
                        findings.append(
                            Finding(
                                "SL404",
                                "error",
                                f"unguarded shared attribute {cls.name}.{attr}: "
                                f"written on the worker path "
                                f"({sorted({a.method for a in w_acc if a.write}) or sorted({a.method for a in w_acc})}) "
                                f"and touched on the client path "
                                f"({sorted({a.method for a in c_acc})}) with no "
                                "common lock — guard both sides with one lock, "
                                "or declare the design with "
                                "`# racecheck: guarded-by(<what>) -- reason`",
                                path=rel,
                                line=sample.lineno,
                            )
                        )
                    continue
            if lock_attrs:
                guarded = [a for a in live if eff[id(a)]]
                bare = [a for a in live if not eff[id(a)]]
                if guarded and bare and not annotated(attr, live):
                    findings.append(
                        Finding(
                            "SL404",
                            "error",
                            f"mixed lock discipline on {cls.name}.{attr}: "
                            f"guarded at {sorted({a.method for a in guarded})} "
                            f"but bare at {sorted({a.method for a in bare})} — "
                            "hold the same lock everywhere, or declare the "
                            "lock-free design with `# racecheck: "
                            "guarded-by(<what>) -- reason`",
                            path=rel,
                            line=bare[0].lineno,
                        )
                    )
    return findings


# --------------------------------------------------------------------- #
# SL406 — swallowed worker exceptions (the failover-path hazard)        #
# --------------------------------------------------------------------- #
_BROAD_EXC = frozenset({"Exception", "BaseException"})
_RESOLVERS = frozenset({"set_exception", "set_result"})


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    """Does the handler catch Exception/BaseException or everything?"""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = node.attr if isinstance(node, ast.Attribute) else getattr(node, "id", None)
        if name in _BROAD_EXC:
            return True
    return False


#: sinks that FORMAT an exception instead of delivering it: passing the
#: caught object to a logger or print is exactly the log-and-continue
#: swallow the rule exists to catch — the object reaches an operator's
#: eyes (maybe), never the waiting client.
_LOGGING_SINKS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print",
})


def _is_logging_call(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Name):
        return node.func.id in _LOGGING_SINKS
    if isinstance(node.func, ast.Attribute):
        return node.func.attr in _LOGGING_SINKS
    return False


def _resolves_or_forwards(body: List[ast.stmt], exc_name: Optional[str]) -> bool:
    """Does a handler body surface the failure? — a re-``raise``, a
    future resolution (``.set_exception``/``.set_result``), or the
    caught exception object forwarded into a NON-LOGGING call (the
    partial-dataset queue-forwarding idiom). Passing the object to a
    logger/``print`` does NOT count: log-and-continue is the flagship
    swallow — the client's future still never resolves."""
    for node in ast.walk(ast.Module(body=body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _RESOLVERS
            ):
                return True
            if exc_name is not None and not _is_logging_call(node):
                args = list(node.args) + [kw.value for kw in node.keywords]
                for a in args:
                    if any(
                        isinstance(n, ast.Name) and n.id == exc_name
                        for n in ast.walk(a)
                    ):
                        return True
    return False


def _direct_resolver_methods(methods: Dict[str, ast.FunctionDef]) -> Set[str]:
    """Class methods whose body itself resolves futures or raises —
    calling one of these from a handler surfaces the failure (the
    dispatcher's ``_fail_queued`` shape)."""
    out: Set[str] = set()
    for name, m in methods.items():
        for node in ast.walk(m):
            if isinstance(node, ast.Raise):
                out.add(name)
                break
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RESOLVERS
            ):
                out.add(name)
                break
    return out


def _lint_sl406(tree: ast.Module, rel: str, pragmas) -> List[Finding]:
    findings: List[Finding] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        methods = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
        if not methods:
            continue
        # worker roots + intra-class call closure (the SL404 discovery)
        worker_roots: Set[str] = set()
        call_edges: Dict[str, Set[str]] = {}
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and _call_name(node.func) == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target" and _self_attr(kw.value) in methods:
                            worker_roots.add(_self_attr(kw.value))
                    for a in node.args:
                        if _self_attr(a) in methods:
                            worker_roots.add(_self_attr(a))
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                ):
                    call_edges.setdefault(m.name, set()).add(node.func.attr)
        if not worker_roots:
            continue
        worker = _closure(worker_roots, call_edges)
        resolvers = _direct_resolver_methods(methods)
        for name in sorted(worker):
            method = methods.get(name)
            if method is None:
                continue
            for node in ast.walk(method):
                if not isinstance(node, ast.Try):
                    continue
                for h in node.handlers:
                    if not _catches_broad(h):
                        continue
                    if _resolves_or_forwards(h.body, h.name):
                        continue
                    # one level of intra-class indirection: a handler
                    # delegating to a method that itself resolves/raises
                    # (the dispatcher's _fail_queued shape) is surfaced
                    called = {
                        n.func.attr
                        for n in ast.walk(ast.Module(body=h.body, type_ignores=[]))
                        if isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "self"
                    }
                    if called & resolvers:
                        continue
                    scope = _Scope((cls.name, name), (cls.lineno, method.lineno))
                    if _suppressed("SL406", h.lineno, scope, pragmas):
                        continue
                    findings.append(
                        Finding(
                            "SL406",
                            "error",
                            f"swallowed worker exception in {cls.name}.{name}: "
                            "the worker-thread path catches "
                            f"{'everything' if h.type is None else 'Exception'} "
                            "and neither re-raises, resolves a future "
                            "(set_exception/set_result), nor forwards the "
                            "caught object — a failover path that swallows "
                            "its failure turns it into a client-side hang; "
                            "fail the owned futures typed, or forward the "
                            "exception to the consumer",
                            path=rel,
                            line=h.lineno,
                        )
                    )
    return findings


# --------------------------------------------------------------------- #
# SL405 — pipeline-protocol (issue/consume ordering)                    #
# --------------------------------------------------------------------- #
def _flat_stmts(body: List[ast.stmt]) -> List[Tuple[ast.stmt, bool]]:
    """Statements of a loop body in source order, flattened through If
    arms; the bool marks 'conditional' (inside an If)."""
    out: List[Tuple[ast.stmt, bool]] = []
    for st in body:
        if isinstance(st, ast.If):
            for inner in st.body + st.orelse:
                out.append((inner, True))
        else:
            out.append((st, False))
    return out


def _calls_to(node: ast.AST, name: str) -> List[ast.Call]:
    return [
        n for n in ast.walk(node)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Name)
        and n.func.id == name
    ]


def _lint_sl405(tree: ast.Module, rel: str, pragmas) -> List[Finding]:
    findings: List[Finding] = []
    for fn in [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]:
        params = _fn_param_names(fn)
        if "consume" not in params and "place" not in params:
            continue
        consume_name = "consume" if "consume" in params else "place"
        scope = _Scope((fn.name,), (fn.lineno,))

        def flag(line, msg):
            if not _suppressed("SL405", line, scope, pragmas):
                findings.append(Finding("SL405", "error", msg, path=rel, line=line))

        # walk every statement block looking for [prologue assign][for]
        blocks = [fn.body] + [
            n.body for n in ast.walk(fn) if isinstance(n, (ast.If, ast.For, ast.While))
        ] + [n.orelse for n in ast.walk(fn) if isinstance(n, (ast.If, ast.For, ast.While)) if n.orelse]
        for block in blocks:
            for i, st in enumerate(block):
                if not isinstance(st, ast.For):
                    continue
                # prologue prefetch: `V = P(...)` directly before the loop
                producer = carried = None
                for prev in reversed(block[:i]):
                    if (
                        isinstance(prev, ast.Assign)
                        and len(prev.targets) == 1
                        and isinstance(prev.targets[0], ast.Name)
                        and isinstance(prev.value, ast.Call)
                        and isinstance(prev.value.func, ast.Name)
                    ):
                        producer = prev.value.func.id
                        carried = prev.targets[0].id
                        break
                    if isinstance(prev, (ast.Assign, ast.Expr, ast.AugAssign)):
                        continue
                    break
                if producer is None or producer == consume_name:
                    continue  # not a depth-2 claimant
                stmts = _flat_stmts(st.body)
                first_issue = first_consume = None
                issue_conditional = True
                inloop_var = None
                consume_call = None
                for stmt, cond in stmts:
                    if first_issue is None and _calls_to(stmt, producer):
                        first_issue = stmt.lineno
                        issue_conditional = cond
                        if (
                            isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)
                        ):
                            inloop_var = stmt.targets[0].id
                    if first_consume is None:
                        cc = _calls_to(stmt, consume_name)
                        if cc:
                            first_consume = stmt.lineno
                            consume_call = cc[0]
                if first_consume is None:
                    continue  # consume happens elsewhere: out of pattern
                if first_issue is None or first_consume < first_issue:
                    flag(
                        first_consume,
                        f"{fn.name}: depth-2 pipeline consumes lap k before "
                        f"issuing lap k+1 (prologue prefetches {carried!r} "
                        f"via {producer!r}, but the loop body runs "
                        f"{consume_name!r} first) — the overlap the plan's "
                        "annotation promises never happens",
                    )
                    continue
                if inloop_var is not None and consume_call is not None:
                    consumed = _names_in(consume_call)
                    if inloop_var in consumed and carried not in consumed:
                        flag(
                            first_consume,
                            f"{fn.name}: the loop consumes {inloop_var!r} — "
                            "the lap it JUST issued — instead of the carried "
                            f"previous lap {carried!r}: an unfenced read of "
                            "an in-flight buffer (and zero overlap)",
                        )
                        continue
                if not issue_conditional:
                    tail = block[i + 1:]
                    if not any(_calls_to(t, consume_name) for t in tail):
                        flag(
                            st.lineno,
                            f"{fn.name}: the final prefetched lap "
                            f"({carried!r}) is never consumed after the loop "
                            "— the last lap's result is dropped",
                        )
    return findings


# --------------------------------------------------------------------- #
# the source pass                                                       #
# --------------------------------------------------------------------- #
def lint_source(src: str, rel: str) -> List[Finding]:
    """Run the SL402–SL405 source rules over one module. ``rel`` is the
    repo-relative posix path (what the gates-module exemption and module
    scoping match on)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        # same rule id + message srclint uses for this condition, so the
        # two passes report an unparseable module identically
        return [Finding("SL201", "error", f"unparseable module: {e}", path=rel, line=e.lineno)]
    rel = rel.replace("\\", "/")
    pragmas = _pragmas_of(src)
    guards = _racecheck_pragmas(src)
    findings: List[Finding] = []
    findings += _lint_sl403(tree, rel, pragmas)
    findings += _lint_sl402(tree, rel, pragmas)
    findings += _lint_sl404(tree, rel, pragmas, guards)
    findings += _lint_sl405(tree, rel, pragmas)
    findings += _lint_sl406(tree, rel, pragmas)
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def lint_paths(paths, root: Optional[str] = None) -> AnalysisReport:
    """Pass 4 over every ``.py`` file under ``paths`` (the effectcheck
    face of ``scripts/lint.py``)."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    n_files = 0
    for path in paths:
        for fp in _iter_py_files(path):
            n_files += 1
            with open(fp, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
            findings += lint_source(src, rel)
    return AnalysisReport(findings, context={"files": n_files, "pass": "effectcheck"})


# --------------------------------------------------------------------- #
# SL405, dynamic half — plan-annotation protocol                        #
# --------------------------------------------------------------------- #
def check_plan_protocol(sched) -> List[Finding]:
    """The Schedule-side SL405 check: an overlap/staging annotation must
    describe a realizable depth-2 pipeline — depth exactly 2, every
    group's laps >= 2, every group tag borne by tagged steps, and a
    critical path strictly below the sequential model (otherwise the
    annotation promises an overlap the executor cannot deliver). Swept
    over every golden plan form (flat/2x4/2x8, quant on+off, staged) in
    tier-1; returns findings (empty = clean)."""
    findings: List[Finding] = []

    def flag(msg):
        findings.append(
            Finding("SL405", "error", f"plan {sched.plan_id}: {msg}")
        )

    step_tags = {st.overlap for st in sched.steps if st.overlap is not None}
    overlap = getattr(sched, "overlap", None)
    if overlap:
        if overlap.get("depth") != 2:
            flag(f"overlap annotation at depth {overlap.get('depth')} — the executor implements depth 2")
        for g in overlap.get("groups", ()):
            if int(g.get("laps", 0)) < 2:
                flag(f"overlap group {g.get('tag')!r} has {g.get('laps')} lap(s) — nothing to pipeline")
            if g.get("tag") not in step_tags:
                flag(f"overlap group {g.get('tag')!r} tags no step — the issue/consume loop it models does not exist")
        cp, seq = overlap.get("critical_path_bytes", 0), overlap.get("sequential_bytes", 0)
        if seq and cp >= seq:
            flag(f"overlap critical path {cp} >= sequential {seq} — the annotation models no gain yet was kept")
    staging = getattr(sched, "staging", None)
    if staging:
        if staging.get("depth") != 2:
            flag(f"staging annotation at depth {staging.get('depth')} — stream_windows implements depth 2")
        n = int(staging.get("n_windows", 0))
        if n < 1:
            flag("staging annotation with no windows")
        model = staging.get("model", {})
        cp, seq = model.get("critical_path_s", 0.0), model.get("sequential_s", 0.0)
        if n > 1 and seq and cp >= seq:
            flag(f"staging critical path {cp} >= sequential {seq} at {n} windows — depth-2 prefetch models no gain")
    return findings
