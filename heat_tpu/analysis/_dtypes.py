"""ONE dtype-classification vocabulary for the analyzer passes.

Pass 1's SL104 widening/narrowing arms (ircheck) and pass 6's
SL601–SL603 precision-flow rules (numcheck) both have to answer the
same questions about a cast or an accumulation dtype: how many REAL
bits of precision does this dtype carry (complex64 carries f32
precision, not f64), is this convert a widening past the program
inputs' promotion ceiling, is it the lossy float→int8 shape the wire
codec sanctions, is it one of the MXU's low-precision accumulation
formats. Like ``_groups.py`` (the one replica-group parser shared by
SL107 and SL502) and ``_donation.py`` (the one donation resolver shared
by SL105/SL302/SL401), this module is the shared home — the IR-lint
and the precision-lint verdicts can never disagree about what the same
cast means.

Pure functions over ``np.dtype``-coercible values (jax's ``bfloat16``
is registered with numpy via ml_dtypes, so ``np.dtype`` handles every
aval dtype the walks see). No jax imports.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

__all__ = [
    "INT8_DTYPES",
    "LOW_PRECISION_FLOATS",
    "effective_itemsize",
    "is_inexact",
    "is_low_precision",
    "lossy_narrowing",
    "promotion_ceiling",
    "widens_past",
]

#: the lossy-narrowing targets of SL104's narrowing arm: an unscaled
#: astype to one of these ahead of a collective truncates the payload
#: (the sanctioned narrowing is the block-quantized wire codec)
INT8_DTYPES = (np.dtype(np.int8), np.dtype(np.uint8))

#: the MXU's low-precision accumulation formats — a ``dot_general`` /
#: ``reduce_sum`` / scan carry accumulating IN one of these compounds
#: ~1e-2 relative error per pass (rule SL601); f32 is the sanctioned
#: accumulator (``preferred_element_type=jnp.float32`` or an upcast)
LOW_PRECISION_FLOATS = ("bfloat16", "float16")


def effective_itemsize(dtype) -> int:
    """Precision per real component: complex64 carries f32 precision."""
    dt = np.dtype(dtype)
    return dt.itemsize // 2 if dt.kind == "c" else dt.itemsize


def is_inexact(dtype) -> bool:
    """Float or complex — the dtypes precision rules reason about."""
    return np.dtype(dtype).kind in "fc"


def is_low_precision(dtype) -> bool:
    """Is ``dtype`` one of the MXU low-precision accumulation formats
    (:data:`LOW_PRECISION_FLOATS`)?"""
    return np.dtype(dtype).name in LOW_PRECISION_FLOATS


def promotion_ceiling(in_dtypes: Iterable, default: int = 4) -> int:
    """The widest effective itemsize core/types.py promotion of the
    program INPUTS can yield — SL104's widening ceiling. ``default``
    (f32) applies when no input is inexact."""
    widths = [effective_itemsize(d) for d in in_dtypes if is_inexact(d)]
    return max(widths, default=default)


def widens_past(src_dtype, dst_dtype, ceiling: int) -> bool:
    """Is ``src → dst`` an inexact widening past ``ceiling`` bytes of
    per-component precision (SL104's widening arm)? Non-inexact casts
    never classify."""
    src_dt, dst_dt = np.dtype(src_dtype), np.dtype(dst_dtype)
    if src_dt.kind not in "fc" or dst_dt.kind not in "fc":
        return False
    src_w, dst_w = effective_itemsize(src_dt), effective_itemsize(dst_dt)
    return dst_w > src_w and dst_w > ceiling


def lossy_narrowing(src_dtype, dst_dtype) -> bool:
    """Is ``src → dst`` the lossy float→int8 narrowing shape (SL104's
    narrowing arm: an unscaled truncation, unless wire_codec-stamped)?"""
    return np.dtype(src_dtype).kind in "fc" and np.dtype(dst_dtype) in INT8_DTYPES
