"""Finding / report model shared by both analyzer passes.

A finding is one violation of one rule at one site. IR findings
(:mod:`~heat_tpu.analysis.ircheck`) anchor on a collective/equation in a
compiled program and carry byte estimates; source findings
(:mod:`~heat_tpu.analysis.srclint`) anchor on ``file:line``. Severity is
the CI contract: ``error`` findings gate (``scripts/lint.py`` exits
nonzero, the ci.sh leg fails), ``warning``/``info`` report only.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["SEVERITIES", "Finding", "AnalysisReport", "RULES"]

SEVERITIES = ("error", "warning", "info")

# rule id -> one-line contract. SL1xx = IR lint (compiled-program rules),
# SL2xx = source lint (repo-invariant rules), SL3xx = memory lint (the
# memcheck abstract interpreter), SL4xx = effect lint (effectcheck),
# SL5xx = collective-congruence lint (commcheck), SL6xx = precision lint
# (numcheck — the wrong-number class). docs/PERF.md carries the
# narrative catalog; this dict is the machine-readable index the CLI and
# tests key on.
RULES: Dict[str, str] = {
    "SL101": "implicit-reshard: a large operand crosses the mesh through an "
             "all-to-all the algorithm did not ask for (input split disagrees "
             "with the op's expected split)",
    "SL102": "replicated-materialization: an all-gather materializes a "
             "(near-)replicated copy of a sharded operand above the size "
             "threshold",
    "SL103": "allgather-feeds-reduction: an all-gather result is consumed by "
             "a reduction — reduce-scatter (or a local reduce + small "
             "all-reduce) moves O(1/p) of the bytes",
    "SL104": "dtype-widening: a value is converted to a wider inexact dtype "
             "than core/types.py promotion of the program inputs yields",
    "SL105": "missed-donation: an output aliases an argument's shape/dtype "
             "but the argument's buffer is not donated — the program holds "
             "both copies live in HBM",
    "SL106": "host-sync: the checked program reads device values on the host "
             "(jax.device_get / .item() / .numpy() / float(...) on a device "
             "value) — a round-trip that serializes the dispatch pipeline. "
             "The serving budget (ISSUE 9) is the strictest instance: a "
             "request handler's dispatch→result path must contain ZERO "
             "undeclared syncs — one blocking read stalls every queued "
             "request behind it (the dispatcher's own completion fence is "
             "block_until_ready: synchronizes, never transfers)",
    "SL107": "cross-tier-collective: at a two-tier topology, a flat "
             "collective whose replica groups span slices ships its whole "
             "payload at DCN speed — decompose it hierarchically (intra-slice "
             "pivot + inter-slice exchange; the planner's hierarchical-a2a)",
    "SL201": "host-sync (library): jax.device_get outside a declared host "
             "boundary (analysis/boundaries.py) — new syncs must be declared",
    "SL202": "bare-jit: jax.jit outside a private program builder — public "
             "surfaces must route through ht.jit so donation/telemetry hooks "
             "apply",
    "SL203": "unsanitized-public-op: a public op function does not route its "
             "inputs through core/sanitation.py (or delegate to a routed op)",
    "SL301": "hbm-overcommit: the liveness-based static peak-HBM estimate of "
             "the compiled program exceeds the per-device budget "
             "(HEAT_TPU_HBM_BYTES; v5e 16 GiB default) — the program cannot "
             "fit at dispatch, reject it at compile time (serving admission "
             "raises ServingOverloaded(reason='hbm-estimate') from the same "
             "number)",
    "SL302": "dropped-donation: donation was declared but the compiled "
             "executable's input_output_aliases never reuse the donated "
             "buffer — both copies stay live in HBM while the caller "
             "believes one was reclaimed (the executable-level upgrade of "
             "SL105's 'should donate')",
    "SL303": "replicated-live-range: a replicated value above the size "
             "threshold stays live across >= 2 collective steps — a "
             "per-device materialization whose residency the redistribution "
             "planner's transient peak accounting never sees",
    "SL401": "use-after-donate: a donated operand (the shared "
             "analysis/_donation.py resolution) is read — or returned — "
             "after the call that donates its buffer; the donating program "
             "may already have overwritten the bytes in place",
    "SL402": "gate-staleness: a HEAT_TPU_* gate read is reachable from an "
             "lru-/dict-cached program builder without being a component of "
             "that cache's key — a gate flip then serves a stale compiled "
             "program (the rule that mechanizes the 'gate in every program "
             "cache key' convention; key material travels under the gate's "
             "declared key_params, core/gates.py)",
    "SL403": "raw-gate-read: os.environ consulted for a HEAT_TPU_* name "
             "outside core/gates.py — every gate read must route through "
             "the registry (gates.get), where declaration, legal values and "
             "cache-key derivation live",
    "SL404": "lock-discipline: an attribute written on a worker-thread path "
             "and touched on a client path (or guarded at some sites and "
             "bare at others) without one common lock — annotate "
             "deliberate lock-free designs with "
             "`# racecheck: guarded-by(<what>) -- reason`",
    "SL405": "pipeline-protocol: a depth-2 double-buffer loop (prologue "
             "prefetch + issue/consume rotation) that consumes lap k before "
             "issuing lap k+1, consumes the lap it just issued, or drops "
             "the final carried lap — the overlap the plan's annotation "
             "promises never happens (or reads an unfenced buffer)",
    "SL406": "swallowed-worker-exception: a worker-thread path catches "
             "Exception (or everything) without re-raising, resolving a "
             "future (set_exception/set_result), or forwarding the caught "
             "object — the silent-swallow shape that turns a failover "
             "path's error into a hang: the client's future never "
             "resolves and no supervisor ever hears about the failure",
    "SL501": "divergent-collective: a collective under a lax.cond/while "
             "predicate not provably replicated across the shard_map "
             "devices — devices branch apart (or exit the loop on "
             "different iterations) and the collective never matches: on "
             "TPU that is a silent hang, not an error. Make the predicate "
             "a full-axis reduction of the local condition",
    "SL502": "incomplete-permute: a compiled collective whose group "
             "structure is incongruent — ppermute source_target_pairs "
             "that are not a permutation of the axis group, or "
             "replica_groups that do not partition the mesh — some device "
             "waits forever. Documented ring schedules and plan-stamped "
             "programs downgrade to info (boundaries machinery)",
    "SL503": "collective-order-divergence: two collectives whose "
             "inter-device issue order can differ — error on a "
             "cross-group dependency cycle in the per-axis-group channel "
             "graph (divergent cond branches issuing matched collectives "
             "in opposite orders), warning on unordered independent "
             "collectives over partially overlapping group partitions",
    "SL504": "unfenced-entry: an executor/dispatcher entry point that "
             "issues collectives without the WorldChangedError "
             "epoch-fence check (elastic.check_world/check_epoch) "
             "reachable on entry — work dispatched across a world "
             "re-resolution hangs instead of failing typed "
             "(commcheck.FENCED_DISPATCH_MODULES scopes the rule)",
    "SL601": "low-precision accumulation: a dot_general/reduce_sum/scan "
             "carry accumulates in bf16/f16 over a contraction/reduction "
             "extent >= the HEAT_TPU_NUMCHECK_ACC_DIM threshold (default "
             "1024) without an f32 preferred_element_type/upcast — each "
             "step compounds ~1e-2 relative error (warning; extent >= "
             "65536 escalates to error)",
    "SL602": "cancellation-prone form: a subtraction of products sharing "
             "an operand (the Gauss 3-multiply shape) lowered at DEFAULT "
             "MXU precision — the planar-complex 13% on-chip defect "
             "class (error; precision=HIGHEST-stamped forms and a "
             "`# numcheck: ignore[SL602] -- reason` pragma downgrade to "
             "info). The source arm holds core/complex_planar.py to "
             "numcheck.PLANAR_PRECISION_POLICY",
    "SL603": "low-precision carry cast: a bf16/f16 cast feeds a "
             "loop-carried accumulator — a scan/while carry slot, or a "
             "program output down-cast while shape-matching the float32 "
             "input it derives from (EF carries, running means: the "
             "KMeans bf16-counts bug as a rule; error — the residual an "
             "EF carry stores IS the low-order bits the cast drops)",
    "SL604": "f64-under-x64-off: the checked program's source requests "
             "float64/complex128 while the platform x64 policy "
             "(core/devices.py) is disabled — the dtype silently "
             "degrades to f32 at trace time, so only a source scan can "
             "see it (warning; call ht.use_x64(True) or request f32 "
             "explicitly)",
    "SL605": "tolerance-budget mismatch: a redistribution plan's "
             "composed per-step error bound (quantize/dequantize tol "
             "across laps, exact-bit staging/relayout/overlap steps, "
             "dcn-tier-only codec legs in hierarchical plans) does not "
             "equal the schedule-level quant.tol annotation — the "
             "verify_plan `tolerance` invariant as a finding "
             "(check_tolerance; error)",
}


class Finding:
    """One rule violation.

    Attributes
    ----------
    rule : str — rule id (key of :data:`RULES`).
    severity : str — ``error`` | ``warning`` | ``info``.
    message : str — human-readable, with the concrete site/op/bytes.
    path / line : source anchor (source lint; ``None`` for IR findings).
    op : the HLO op or jaxpr primitive the finding anchors on (IR lint).
    nbytes : byte estimate of the flagged movement/materialization.
    """

    __slots__ = ("rule", "severity", "message", "path", "line", "op", "nbytes")

    def __init__(self, rule, severity, message, path=None, line=None, op=None, nbytes=None):
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, got {severity!r}")
        self.rule: str = rule
        self.severity: str = severity
        self.message: str = message
        self.path: Optional[str] = path
        self.line: Optional[int] = line
        self.op: Optional[str] = op
        self.nbytes: Optional[int] = nbytes

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "path": self.path,
            "line": self.line,
            "op": self.op,
            "nbytes": self.nbytes,
        }

    def __repr__(self) -> str:
        where = f"{self.path}:{self.line}: " if self.path else ""
        return f"[{self.rule}/{self.severity}] {where}{self.message}"


class AnalysisReport:
    """Findings of one analyzer run plus the context they were made in."""

    def __init__(self, findings: List[Finding], context: Optional[Dict[str, Any]] = None):
        self.findings: List[Finding] = list(findings)
        self.context: Dict[str, Any] = dict(context or {})

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    @property
    def ok(self) -> bool:
        """True when nothing gates (no error-severity findings)."""
        return not self.errors

    @property
    def rule_ids(self) -> List[str]:
        """Distinct rule ids present, sorted."""
        return sorted({f.rule for f in self.findings})

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "rule_ids": self.rule_ids,
            "findings": [f.as_dict() for f in self.findings],
            "context": dict(self.context),
        }

    def __iter__(self):
        return iter(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    def __repr__(self) -> str:
        n_err, n_warn = len(self.errors), len(self.warnings)
        return (
            f"AnalysisReport({len(self.findings)} findings: "
            f"{n_err} error, {n_warn} warning; rules={self.rule_ids})"
        )
