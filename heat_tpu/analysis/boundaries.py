"""Declared host boundaries — the whitelist the host-sync rules check.

The repo invariant (rule SL201) is: device values never round-trip
through the host inside library code, because one ``jax.device_get``
serializes the dispatch pipeline and, in a multi-host world, reads only
the addressable shards. Every legitimate sync must therefore be
DECLARED here, in one reviewable file, in the category that states
*why* it is allowed:

- :data:`HOST_MODULES` — whole modules whose contract IS host transfer
  (file I/O). Everything in them is exempt.
- :data:`HOST_FUNCS` — functions whose API contract is to produce or
  ingest a HOST value (``.numpy()`` export, ``__repr__``, host complex
  assembly). Calling them eagerly is the point; they are unreachable
  from traced code by construction (tracing them raises).
- :data:`DATA_DEPENDENT_BOUNDARIES` — eager-only ops whose OUTPUT SHAPE
  depends on data (``unique``/``nonzero`` counts, hSVD adaptive rank).
  The host read is what makes the result shape concrete; these ops are
  documented as untraceable (core/jit.py limitation #1).
- :data:`HOST_BOUNDARIES` — the narrow category: a deliberate host
  round-trip inside an otherwise traceable compute path. Each entry is
  NAMED so tests can pin the exact population; tier-1 asserts the only
  ``core/`` entry is ``percentile-q``. Adding a sync to a compute path
  means adding a named entry here — the diff is the declaration.

Matching is by (posix path suffix, dotted enclosing-scope qualname);
line numbers are deliberately not part of a declaration so unrelated
edits to a file do not invalidate it.
"""

from __future__ import annotations

import re

from typing import Dict, Optional, Tuple

__all__ = [
    "HOST_MODULES",
    "HOST_FUNCS",
    "DATA_DEPENDENT_BOUNDARIES",
    "HOST_BOUNDARIES",
    "PLANNER_MODULES",
    "RING_SCHEDULE_MODULES",
    "WIRE_CODEC_MARKER",
    "is_declared_sync",
    "planned_reshard_plan_id",
    "ring_schedule_module",
    "wire_codec_stamped",
]

# modules that are host I/O by contract (posix path suffixes)
HOST_MODULES: Tuple[str, ...] = (
    "core/io.py",       # save/load: hyperslab writes are host-side by nature
    "core/printing.py", # __str__ formatting renders on the host
    # checkpointing IS host I/O: durable state must cross to the host
    # to reach the persistent store (slab-streamed, ISSUE 13)
    "resilience/checkpoint.py",
)

# (path suffix, qualname) -> reason. Host-value producers/ingesters.
HOST_FUNCS: Dict[Tuple[str, str], str] = {
    ("core/dndarray.py", "DNDarray.__host_logical"): (
        "the single funnel behind .numpy()/.item()/float(): its contract "
        "is a host copy of the logical array"
    ),
    ("core/complex_planar.py", "host_complex"): (
        "assembles a host numpy complex array from the device plane pair "
        "(the planar analog of DNDarray.__host_logical)"
    ),
    ("core/complex_planar.py", "array_factory"): (
        "ingestion: normalizes arbitrary host/device input to planes at "
        "array-construction time (eager by definition)"
    ),
    ("sparse/dcsr_matrix.py", "DCSR_matrix.counts_displs_nnz"): (
        "exports the per-device nnz partition as host ints (metadata "
        "export API, the analog of the reference's counts/displs query)"
    ),
    ("sparse/dcsr_matrix.py", "DCSR_matrix.__repr__"): (
        "debug rendering of the CSR triple on the host"
    ),
    ("sparse/dbcsr_matrix.py", "DBCSR_matrix._to_scipy_bsr"): (
        "export: reassembles the global scipy BSR on the host (the "
        "brick analog of DNDarray.__host_logical — every .to_scipy()/"
        "oracle comparison funnels through it)"
    ),
    ("sparse/dbcsr_matrix.py", "sparse_dbcsr_matrix"): (
        "ingestion factory: normalizes arbitrary host/device/DCSR input "
        "to slab-laid bricks at construction time (the sparse analog of "
        "complex_planar.array_factory — eager by definition)"
    ),
    ("graph/pagerank.py", "_adjacency_to_scipy"): (
        "ingestion: normalizes any adjacency form (DBCSR/DCSR/DNDarray/"
        "host) to a host scipy CSR once at solve setup — the graph "
        "solvers build their brick operator from the host copy"
    ),
    ("preprocessing/sparse_encoders.py", "TfidfTransformer._counts_csr"): (
        "ingestion: normalizes fit() input to a host scipy CSR of term "
        "counts — document-frequency statistics are host-side by "
        "contract (fit is the eager estimation phase)"
    ),
    ("preprocessing/sparse_encoders.py", "OneHotEncoder.stream_transform"): (
        "slab-streamed transform whose contract is a HOST result: each "
        "window's encoded block is written back into the host output "
        "buffer (stage_out of the staging schedule it proves first)"
    ),
    ("preprocessing/sparse_encoders.py", "TfidfTransformer.stream_transform"): (
        "slab-streamed transform whose contract is a HOST result: the "
        "reweighted window lands in the host output buffer (stage_out "
        "of the proven staging schedule)"
    ),
    ("core/linalg/factorizations.py", "_solve_host_rhs"): (
        "staged solve against a host-resident RHS panel whose contract "
        "is a HOST result (ISSUE 19): each column window's solution is "
        "written back into the host output buffer (stage_out of the "
        "staging schedule it registers — the stream_transform pattern)"
    ),
    ("core/linalg/svd.py", "_svd_host"): (
        "staged values-only svd of a host-resident operand whose "
        "contract is a HOST-derived result (ISSUE 19): the Gram-pass "
        "singular values cross to the host once at the end of the "
        "stream (O(n) scalars against the O(mn) windowed operand)"
    ),
}

# (path suffix, qualname) -> reason. Eager-only data-dependent-shape ops.
DATA_DEPENDENT_BOUNDARIES: Dict[Tuple[str, str], str] = {
    ("core/parallel.py", "_host_counts"): (
        "unique/nonzero/compaction need the GLOBAL selected count on the "
        "host to size their output arrays — the documented eager-only "
        "boundary for data-dependent shapes"
    ),
    ("core/parallel.py", "distributed_unique"): (
        "the merged-unique total sizes the result; shape is data"
    ),
    ("core/parallel.py", "distributed_unique_rows"): (
        "the merged rows-unique total sizes the result; shape is data "
        "(the axis-mode twin of distributed_unique — ISSUE 11 satellite)"
    ),
    ("core/linalg/svdtools.py", "_hsvd_impl"): (
        "adaptive-rank hSVD reads the singular values to choose the rank "
        "the next merge level keeps — the rank IS data-dependent output "
        "shape (reference svdtools.py truncates on the host identically)"
    ),
    ("core/linalg/factorizations.py", "_projector_rank"): (
        "spectral divide-and-conquer eigh reads the projector trace to "
        "size the two subspace bases — the split rank IS data-dependent "
        "output shape (ISSUE 19; same category as hSVD's adaptive rank)"
    ),
}

# name -> (path suffix, qualname, reason). The NAMED whitelist: deliberate
# syncs inside otherwise traceable compute paths. Keep this list short —
# tier-1 pins its exact core/ population.
HOST_BOUNDARIES: Dict[str, Tuple[str, str, str]] = {
    "percentile-q": (
        "core/statistics.py",
        "percentile",
        "q is read to the host ONCE so the two bracketing ranks per "
        "percentile are static (they shape the program: two cross-shard "
        "row fetches instead of a gather); a traced q is rejected with a "
        "TypeError before this read",
    ),
    "sort-autotune-sync": (
        "kernels/sort.py",
        "_sync_scalar",
        "the sort-kernel autotuner times candidate local-sort paths ONCE "
        "per (n, dtype) and caches the winner; the scalar read-back is "
        "the completion fence for each timed probe (block_until_ready is "
        "a no-op over the remote tunnel — bench.py methodology). Runs "
        "only eagerly on TPU, never inside a trace",
    ),
    "optimizer-checkpoint-export": (
        "optim/dp_optimizer.py",
        "DataParallelOptimizer.checkpoint_state",
        "checkpoint export IS host transfer by contract (ISSUE 13): the "
        "base PRNG key crosses to the host so the resilience envelope "
        "can persist it; the array leaves stream through the checkpoint "
        "module's own slab writers (a declared host module)",
    ),
    "optimizer-checkpoint-restore": (
        "optim/dp_optimizer.py",
        "DataParallelOptimizer.load_checkpoint_state",
        "checkpoint restore's world-resize fold: the restored EF carry "
        "is folded row-wise on the host (r -> r % p_new, sum-preserving) "
        "before re-sharding onto the survivors — an eager, "
        "recovery-path-only transfer",
    ),
    "resilience-state-validate": (
        "resilience/elastic.py",
        "_finite_state",
        "the poisoned-collective detector of the elastic streaming loop "
        "(ISSUE 13): after each window update the (k, d) centers — a "
        "scalar-class array — are read to the host and checked finite; "
        "the read IS the detection, and it only runs when the elastic "
        "runtime is engaged (a ckpt/watcher/chaos hook was handed in), "
        "never on the default or HEAT_TPU_RESILIENCE=0 paths",
    ),
    "relayout-autotune-sync": (
        "kernels/relayout.py",
        "_sync_scalar",
        "the relayout-kernel autotuner times the XLA pack/unpack "
        "formulation against the Pallas tiled-copy kernel ONCE per shape "
        "signature and caches the winner (XLA is the floor); the scalar "
        "read-back is the completion fence per timed probe. Runs only "
        "eagerly on TPU at executor program-BUILD time, never inside a "
        "trace",
    ),
    "pagerank-stream-fixpoint": (
        "graph/pagerank.py",
        "pagerank_stream",
        "the streamed power iteration keeps the rank vector "
        "HOST-resident between slab-window sweeps (the edge list never "
        "fits on device — that is the point of the streamed form): one "
        "(n,)-vector readback per sweep funds the exact dangling-mass "
        "correction and the full-vector l1 convergence test; edge slabs "
        "themselves never round-trip",
    ),
    "spectral-ritz-extract": (
        "graph/spectral.py",
        "spectral_embedding",
        "Ritz extraction: the (m,) Lanczos alpha/beta coefficients are "
        "read to the host ONCE to assemble and eigh the m-by-m "
        "tridiagonal — an O(m^2) host solve against the O(n*m) device "
        "sweep; only scalar-class vectors cross, the Krylov basis stays "
        "on device for the final V @ W",
    ),
}


# ---------------------------------------------------------------------- #
# planner-issued reshards (rules SL101/SL102)                             #
# ---------------------------------------------------------------------- #
# Modules whose WHOLE PURPOSE is to launch resharding collectives: the
# redistribution executor compiles the planner's schedules (including
# the software-pipelined chunk loops and ppermute rings of ISSUE 6), and
# the collective-matmul kernels decompose the linalg all-gathers /
# reductions into ppermute chains consumed block-by-block — in both, the
# all-to-alls/all-gathers/collective-permutes ARE the budgeted,
# cost-modeled movement itself, not an accident of operand layout. The
# IR lint must not flag the subsystems' own programs as implicit
# reshards — it reports them at info severity with the stamp attached
# instead.
PLANNER_MODULES: Tuple[str, ...] = (
    "redistribution/executor.py",
    "kernels/cmatmul.py",
)

# every executor program runs under jax.named_scope("redist_plan_<id>")
# (12 hex chars: the Schedule.plan_id sha1 prefix) and every
# collective-matmul ring under jax.named_scope("cmatmul_ring_<tag>"), so
# the stamp lands in the HLO op_name metadata of each collective the
# program launches — the markers the IR lint keys on
_PLAN_MARKER = re.compile(r"redist_plan_([0-9a-f]{12})")
_CMATMUL_MARKER = re.compile(r"cmatmul_ring_([0-9a-z_]+)")


def planned_reshard_plan_id(hlo_line: str) -> Optional[str]:
    """The plan stamp on an HLO instruction line — a redistribution
    ``plan_id`` or a ``cmatmul:<tag>`` collective-matmul marker — or
    ``None`` when the collective is not planner-issued. ``ircheck`` uses
    this to downgrade SL101/SL102 findings on stamped programs to info
    severity (with the stamp attached) instead of flagging the
    subsystems' own schedules. An UNSTAMPED hand-rolled ppermute loop
    carries no marker and trips the rule at full severity (golden
    bad-fixture in ``tests/analysis_fixtures.py``)."""
    m = _PLAN_MARKER.search(hlo_line)
    if m:
        return m.group(1)
    m = _CMATMUL_MARKER.search(hlo_line)
    return f"cmatmul:{m.group(1)}" if m else None


# The wire codec (kernels/quant.py) wraps every encode/decode body in
# jax.named_scope("wire_codec_<mode>"); the stamp rides each traced
# eqn's name_stack the same way the executor's redist_plan scopes ride
# the HLO op_name. SL104's narrowing arm keys on it: a STAMPED
# float->int8 convert before a collective is the sanctioned
# block-quantized payload (info), an unstamped one is the
# gradient-compression accident the rule exists for (error —
# golden bad-fixture ``tests/analysis_fixtures.int8_wire_program``).
WIRE_CODEC_MARKER = "wire_codec_"


def wire_codec_stamped(name_stack: str) -> bool:
    """Does a traced eqn's name_stack carry the wire-codec stamp?"""
    return WIRE_CODEC_MARKER in name_stack


# Modules whose ppermute chains are DOCUMENTED ring schedules — the
# algorithm, not a relayout accident: the distributed sort networks and
# stencil/halo exchanges (core/parallel.py), the convolution halo
# exchange (core/signal.py), and ring attention's K/V rotation
# (nn/attention.py). SL101's collective-permute arm reports their hops
# at info severity, keyed on the instruction's source_file metadata
# (these bodies run under shard_map, not a stampable named scope); a
# hand-rolled ppermute loop anywhere else still trips the rule at full
# severity. (The other two library ppermute sites —
# redistribution/executor.py and kernels/cmatmul.py — stamp named
# scopes instead, see PLANNER_MODULES.)
RING_SCHEDULE_MODULES: Tuple[str, ...] = (
    "heat_tpu/core/parallel.py",
    "heat_tpu/core/signal.py",
    "heat_tpu/nn/attention.py",
)

_SOURCE_FILE = re.compile(r'source_file="([^"]+)"')


def ring_schedule_module(hlo_line: str) -> Optional[str]:
    """The blessed ring-schedule module a collective-permute instruction
    was traced from (its HLO ``source_file`` metadata ends with an entry
    of :data:`RING_SCHEDULE_MODULES`), or ``None``."""
    m = _SOURCE_FILE.search(hlo_line)
    if not m:
        return None
    path = _norm(m.group(1))
    for suffix in RING_SCHEDULE_MODULES:
        if path.endswith(suffix):
            return suffix
    return None


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def is_declared_sync(path: str, qualname: str) -> Tuple[bool, str]:
    """Is a host sync at (file, enclosing scope) declared?

    Returns ``(declared, category-or-name)``. ``qualname`` is the dotted
    enclosing-scope chain (``Class.method``, ``outer.inner``); a
    declaration for ``outer`` covers syncs in its nested functions (the
    boundary owns its helpers).
    """
    p = _norm(path)
    for suffix in HOST_MODULES:
        if p.endswith(suffix):
            return True, f"host-module:{suffix}"
    parts = qualname.split(".") if qualname else []
    prefixes = {".".join(parts[: i + 1]) for i in range(len(parts))}

    def _match(decls):
        for (suffix, qn), _reason in decls.items():
            if p.endswith(suffix) and (qn == qualname or qn in prefixes):
                return qn
        return None

    qn = _match(HOST_FUNCS)
    if qn:
        return True, f"host-func:{qn}"
    qn = _match(DATA_DEPENDENT_BOUNDARIES)
    if qn:
        return True, f"data-dependent:{qn}"
    for name, (suffix, qn, _reason) in HOST_BOUNDARIES.items():
        if p.endswith(suffix) and (qn == qualname or qn in prefixes):
            return True, name
    return False, ""
