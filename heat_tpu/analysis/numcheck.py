"""Pass 6: precision lint (``numcheck``) — the wrong-number class,
mechanized.

Pass 5 (commcheck) mechanized the class of programs that HANG a TPU
mesh; this pass mechanizes the class that silently returns WRONG
numbers. The motivating defect is real: planar-complex matmul at
default MXU precision returned up to 13% relative error on chip (the
Gauss 3-multiply form recovers the imaginary part by cancellation,
which bf16 MXU passes amplify into garbage — the round-5 live defect
PR 5 fixed by hand). The CPU-mesh suite structurally cannot see this
class — on CPU every matmul runs f32 — so the rules are STATIC: a
dtype-and-precision walk over the traced jaxpr, plus a source policy
check, before any TPU minute is spent.

========  ========  ====================================================
rule      severity  fires when
========  ========  ====================================================
SL601     warn/err  low-precision accumulation: a ``dot_general`` /
                    ``reduce_sum`` / scan carry accumulates in
                    bf16/f16 over a contraction/reduction extent >=
                    the threshold (default 1024,
                    ``HEAT_TPU_NUMCHECK_ACC_DIM`` via the gates
                    registry) without an f32
                    ``preferred_element_type``/upcast; extents >=
                    65536 escalate to error
SL602     error     cancellation-prone form: subtraction of two
                    products sharing an operand (the Gauss 3-multiply
                    shape) lowered at DEFAULT precision — the
                    planar-complex 13% defect class.
                    ``precision=HIGHEST``-stamped forms and a
                    ``# numcheck: ignore[SL602] -- reason`` pragma
                    downgrade to info. The source arm (``lint_paths``,
                    the ``--pass numcheck`` CLI) enforces
                    :data:`PLANAR_PRECISION_POLICY` over
                    core/complex_planar.py itself: deleting the PR 5
                    ``precision="highest"`` default is caught here
SL603     error     low-precision cast feeding a loop-carried
                    accumulator: a bf16/f16 convert feeds a
                    scan/while carry slot, or a program output is
                    down-cast to bf16/f16 while shape-matching a
                    float32 input it derives from (the cross-step
                    EF-carry / running-mean idiom — the KMeans
                    bf16-counts bug PR 11 fixed by hand, as a rule)
SL604     warning   f64 request under the x64-disabled platform
                    policy (core/devices.py): the dtype silently
                    degrades to f32 at trace time, so the jaxpr never
                    shows it — a SOURCE scan of the checked program
========  ========  ====================================================

The dtype vocabulary (what counts as low-precision, widening,
narrowing) is shared with ircheck's SL104 arms through
``analysis/_dtypes.py`` — the two passes can never disagree on a
cast's classification. The IR rules (SL601–SL603) fold into
:func:`ht.analysis.check <heat_tpu.analysis.ircheck.check>`; the
standalone entry :func:`numcheck` additionally runs the SL604 source
scan. The plan-side dynamic half — the ``tolerance`` invariant of
``verify_plan`` and :func:`~heat_tpu.analysis.planverify.check_tolerance`
(rule SL605) — lives in :mod:`~heat_tpu.analysis.planverify`.
"""

from __future__ import annotations

import ast
import re

from typing import Any, Dict, FrozenSet, List, Optional, Set

import numpy as np

from . import _dtypes
from .findings import AnalysisReport, Finding

__all__ = [
    "PLANAR_PRECISION_POLICY",
    "lint_paths",
    "lint_source",
    "numcheck",
    "scan_jaxpr_precision",
    "scan_precision_source",
]

#: the per-op planar-complex precision policy (VERDICT r5 leftover,
#: docs/MIGRATING.md "Complex platform policy" / docs/PERF.md): which
#: planar ops MUST default to ``precision="highest"`` (their Gauss
#: decomposition recovers a component by cancellation of MXU products)
#: vs tolerate the default (elementwise VPU f32 arithmetic — no MXU
#: pass to lose precision on). The numcheck source arm enforces the
#: "highest" rows over core/complex_planar.py itself.
PLANAR_PRECISION_POLICY: Dict[str, str] = {
    "matmul": "highest",   # Gauss 3-multiply: C_i = P3-P1-P2 by cancellation
    "dot": "highest",      # 2-D routes through matmul (1-D is VPU elementwise)
    "vdot": "default",     # conj-multiply + sum: VPU f32, no MXU pass
    "vecdot": "default",   # same elementwise family
    "outer": "default",    # broadcast multiply: VPU f32
}

#: the module the SL602 source arm holds to the policy table
_PLANAR_MODULE = "core/complex_planar.py"

#: SL601 extent at which a low-precision accumulation escalates from
#: warning to error: 65536 bf16 accumulation steps compound ~1e-2
#: relative error past any usable tolerance
_SL601_ERROR_EXTENT = 65536

_NUMCHECK_PRAGMA = re.compile(r"#\s*numcheck:\s*ignore\[([A-Z0-9,\s*]+)\]")

#: shape-transparent primitives the backward walks step through — the
#: same dataflow vocabulary as ircheck's narrowing walk
_PASSTHROUGH = {
    "concatenate", "reshape", "transpose", "squeeze", "broadcast_in_dim",
    "slice", "dynamic_slice", "pad", "rev", "select_n", "copy",
    "convert_element_type",
}


def _acc_dim_threshold() -> int:
    """The SL601 reduction-extent threshold — the registry-declared
    ``HEAT_TPU_NUMCHECK_ACC_DIM`` knob (read-only analyzer tuning:
    changes which findings fire, never any program)."""
    from ..core import gates

    raw = gates.get("HEAT_TPU_NUMCHECK_ACC_DIM", "1024")
    try:
        return max(1, int(raw))
    except (TypeError, ValueError):
        return 1024


def _pragmas_of(src: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _NUMCHECK_PRAGMA.search(line)
        if m:
            out[i] = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
    return out


def fn_pragmas(fn) -> FrozenSet[str]:
    """Rule ids a ``# numcheck: ignore[...]`` pragma anywhere in the
    checked function's source suppresses — function-level coverage
    (the IR findings carry no source lines to anchor finer). Returns
    an empty set when source is unavailable."""
    import inspect

    try:
        src = inspect.getsource(inspect.unwrap(fn))
    except (TypeError, OSError, AttributeError):
        return frozenset()
    rules: Set[str] = set()
    for toks in _pragmas_of(src).values():
        rules |= toks
    return frozenset(rules)


# --------------------------------------------------------------------- #
# the jaxpr walk (SL601 / SL602 / SL603)                                #
# --------------------------------------------------------------------- #
def _index_jaxpr(jaxpr):
    """One pass over every (sub-)jaxpr: the eqn list in traversal order
    and the producer map keyed ``id(var)`` (vars are unique objects, so
    the map lets backward walks cross call boundaries — the ircheck
    narrowing-arm idiom)."""
    from .ircheck import _as_jaxprs
    from jax.extend import core as jex_core

    eqns = []
    producers: Dict[int, Any] = {}
    todo, seen = [jaxpr], set()
    while todo:
        jx = todo.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            eqns.append(eqn)
            for ov in eqn.outvars:
                producers[id(ov)] = eqn
            for val in eqn.params.values():
                todo.extend(_as_jaxprs(val, jex_core))
    return eqns, producers


def _extent(shape, dims) -> int:
    n = 1
    for d in dims:
        n *= int(shape[int(d)])
    return n


def _is_literal(v) -> bool:
    from jax.extend import core as jex_core

    return isinstance(v, jex_core.Literal)


def _precision_is_highest(prec) -> bool:
    """Does a ``dot_general`` precision param guarantee exact f32 MXU
    products? The stamped forms carry ``Precision.HIGHEST`` (possibly
    as a per-operand pair); ``None`` is the platform default — bf16
    passes on TPU."""
    return prec is not None and "HIGHEST" in str(prec).upper()


def _scan_sl601(eqns, threshold: int, findings: List[Finding]) -> None:
    seen = set()

    def fire(op: str, dt, extent: int, fix: str) -> None:
        key = (op, np.dtype(dt).name, extent)
        if key in seen:
            return
        seen.add(key)
        severity = "error" if extent >= _SL601_ERROR_EXTENT else "warning"
        findings.append(
            Finding(
                "SL601",
                severity,
                f"low-precision accumulation: a {op} accumulates in "
                f"{np.dtype(dt).name} over a reduction extent of {extent} "
                f"(threshold {threshold}) — each step compounds ~1e-2 "
                f"relative error; {fix}",
                op=op,
            )
        )

    for eqn in eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            # accumulation dtype = preferred_element_type when stamped,
            # else the output aval (the MXU accumulates in the out type)
            acc_dt = eqn.params.get("preferred_element_type")
            if acc_dt is None:
                acc_dt = eqn.outvars[0].aval.dtype
            if not _dtypes.is_low_precision(acc_dt):
                continue
            (lhs_contract, _), _ = eqn.params["dimension_numbers"]
            extent = _extent(eqn.invars[0].aval.shape, lhs_contract)
            if extent >= threshold:
                fire(
                    "dot_general", acc_dt, extent,
                    "pass preferred_element_type=jnp.float32 (accumulate "
                    "f32, store narrow)",
                )
        elif name in ("reduce_sum", "reduce"):
            # reduce_sum carries axes=; the generic monoid reduce
            # (lax.reduce with an add computation) carries dimensions=
            in_dt = eqn.invars[0].aval.dtype
            if not _dtypes.is_low_precision(in_dt):
                continue
            if name == "reduce":
                body = eqn.params.get("jaxpr")
                body_eqns = getattr(getattr(body, "jaxpr", body), "eqns", [])
                if [e.primitive.name for e in body_eqns] != ["add"]:
                    continue  # min/max/etc monoids don't accumulate error
                dims = eqn.params.get("dimensions", ())
            else:
                dims = eqn.params.get("axes", ())
            extent = _extent(eqn.invars[0].aval.shape, dims)
            if extent >= threshold:
                fire(
                    name, in_dt, extent,
                    "upcast the operand (.astype(jnp.float32)) before the "
                    "sum and narrow the result",
                )
        elif name == "scan":
            length = int(eqn.params.get("length") or 0)
            if length < threshold:
                continue
            sub = eqn.params.get("jaxpr")
            in_avals = getattr(sub, "in_avals", None)
            if in_avals is None:
                continue
            ncon = int(eqn.params.get("num_consts", 0))
            ncar = int(eqn.params.get("num_carry", 0))
            for aval in in_avals[ncon : ncon + ncar]:
                dt = getattr(aval, "dtype", None)
                if dt is not None and _dtypes.is_low_precision(dt):
                    fire(
                        "scan", dt, length,
                        "carry the accumulator in float32 and cast only "
                        "the per-step payload",
                    )


def _scan_sl602(eqns, producers, pragmas: FrozenSet[str], findings: List[Finding]) -> None:
    def collect_dots(v, depth: int = 0, visited=None):
        """The dot_general producers a value resolves to (keyed by eqn
        identity — eqn objects are not reliably hashable), walking back
        through the arithmetic of the Gauss form (sub/add/neg) and the
        shape-transparent primitives."""
        if visited is None:
            visited = set()
        if depth > 8 or _is_literal(v) or id(v) in visited:
            return {}
        visited.add(id(v))
        src = producers.get(id(v))
        if src is None:
            return {}
        name = src.primitive.name
        if name == "dot_general":
            return {id(src): src}
        if name in _PASSTHROUGH or name in ("sub", "add", "neg", "mul"):
            out = {}
            for u in src.invars:
                out.update(collect_dots(u, depth + 1, visited))
            return out
        return {}

    def operand_roots(dot_eqn) -> Set[int]:
        """Terminal ancestor var ids of a dot's operands (walked through
        the shape-transparent primitives and adds — ``ar + ai`` shares
        the roots of both addends, which is exactly how the Gauss form
        shares operands between its three products)."""
        roots: Set[int] = set()
        stack = [(u, 0) for u in dot_eqn.invars]
        visited: Set[int] = set()
        while stack:
            v, depth = stack.pop()
            if depth > 8 or _is_literal(v) or id(v) in visited:
                continue
            visited.add(id(v))
            src = producers.get(id(v))
            if src is None or src.primitive.name not in (
                _PASSTHROUGH | {"add", "sub", "neg"}
            ):
                roots.add(id(v))
                continue
            stack.extend((u, depth + 1) for u in src.invars)
        return roots

    seen = set()
    for eqn in eqns:
        if eqn.primitive.name != "sub":
            continue
        dots_l = collect_dots(eqn.invars[0])
        dots_r = collect_dots(eqn.invars[1])
        if not dots_l or not dots_r:
            continue
        merged = dict(dots_l)
        merged.update(dots_r)
        dots = list(merged.values())
        if len(dots) < 2:
            continue
        shared = False
        for dl in dots_l.values():
            rl = operand_roots(dl)
            for dr in dots_r.values():
                if dl is dr:
                    continue
                if rl & operand_roots(dr):
                    shared = True
                    break
            if shared:
                break
        if not shared:
            continue
        key = frozenset(merged)
        if key in seen:
            continue
        seen.add(key)
        all_highest = all(
            _precision_is_highest(d.params.get("precision")) for d in dots
        )
        out_dt = np.dtype(eqn.outvars[0].aval.dtype)
        if all_highest:
            findings.append(
                Finding(
                    "SL602",
                    "info",
                    "cancellation-prone form at precision=HIGHEST: a "
                    f"subtraction of {len(dots)} products sharing an operand "
                    "(the Gauss 3-multiply shape) — exact f32 MXU products, "
                    "the sanctioned lowering of the planar-complex policy",
                    op="sub",
                )
            )
        else:
            severity = "info" if "SL602" in pragmas else "error"
            findings.append(
                Finding(
                    "SL602",
                    severity,
                    "cancellation-prone form at DEFAULT precision: a "
                    f"{out_dt.name} subtraction of {len(dots)} products "
                    "sharing an operand (the Gauss 3-multiply shape) — on "
                    "TPU the products run as bf16 MXU passes (~1e-2 "
                    "relative) and the cancellation amplifies that into "
                    "catastrophic relative error (the planar-complex 13% "
                    "on-chip defect). Stamp the dots precision='highest' "
                    "(jax.lax.Precision.HIGHEST), or annotate "
                    "`# numcheck: ignore[SL602] -- reason` if the inputs "
                    "provably cannot cancel",
                    op="sub",
                )
            )


def _scan_sl603(jaxpr, eqns, producers, findings: List[Finding]) -> None:
    low = _dtypes.is_low_precision

    def deriving_lowcast(v, depth_cap: int = 8):
        """The convert_element_type eqn (>=32-bit float → bf16/f16)
        a value resolves to through the shape-transparent primitives."""
        stack, visited = [(v, 0)], set()
        while stack:
            u, depth = stack.pop()
            if depth > depth_cap or _is_literal(u) or id(u) in visited:
                continue
            visited.add(id(u))
            src = producers.get(id(u))
            if src is None:
                continue
            name = src.primitive.name
            if name == "convert_element_type":
                src_dt = np.dtype(src.invars[0].aval.dtype)
                dst_dt = np.dtype(src.params.get("new_dtype"))
                if (
                    src_dt.kind == "f"
                    and _dtypes.effective_itemsize(src_dt) >= 4
                    and low(dst_dt)
                ):
                    return src
                continue
            if name in _PASSTHROUGH:
                stack.extend((w, depth + 1) for w in src.invars)
        return None

    def fire(dst_dt, src_dt, what: str) -> None:
        findings.append(
            Finding(
                "SL603",
                "error",
                f"low-precision cast feeds a loop-carried accumulator: a "
                f"{src_dt.name} value is cast to {dst_dt.name} and {what} — "
                "the accumulator loses ~3 decimal digits per lap (the "
                "KMeans bf16-counts class, and the death of an EF carry: "
                "the residual it stores IS the low-order bits the cast "
                "throws away). Keep the carry in float32; cast only the "
                "transient wire/compute payload",
                op="convert_element_type",
            )
        )

    # arm A: a low-precision cast feeding a scan/while carry slot
    for eqn in eqns:
        name = eqn.primitive.name
        if name == "scan":
            ncon = int(eqn.params.get("num_consts", 0))
            ncar = int(eqn.params.get("num_carry", 0))
            carry_ins = eqn.invars[ncon : ncon + ncar]
        elif name == "while":
            ncon = int(eqn.params.get("cond_nconsts", 0)) + int(
                eqn.params.get("body_nconsts", 0)
            )
            carry_ins = eqn.invars[ncon:]
        else:
            continue
        for cv in carry_ins:
            dt = getattr(getattr(cv, "aval", None), "dtype", None)
            if dt is None or not low(dt):
                continue
            conv = deriving_lowcast(cv)
            if conv is not None:
                fire(
                    np.dtype(conv.params.get("new_dtype")),
                    np.dtype(conv.invars[0].aval.dtype),
                    f"carried through a {name} loop",
                )

    # arm B: the CROSS-program carry (EF residuals, running means ride
    # ht.jit boundaries, so no in-jaxpr loop exists): a program OUTPUT
    # down-cast to bf16/f16 whose shape matches a float32 input it
    # derives from — the caller feeds it back next step
    float_ins = [
        v
        for v in jaxpr.invars
        if getattr(getattr(v, "aval", None), "dtype", None) is not None
        and np.dtype(v.aval.dtype).kind == "f"
        and _dtypes.effective_itemsize(v.aval.dtype) >= 4
    ]
    for ov in jaxpr.outvars:
        src = producers.get(id(ov))
        if src is None or src.primitive.name != "convert_element_type":
            continue
        src_dt = np.dtype(src.invars[0].aval.dtype)
        dst_dt = np.dtype(src.params.get("new_dtype"))
        if not (src_dt.kind == "f" and _dtypes.effective_itemsize(src_dt) >= 4 and low(dst_dt)):
            continue
        shape = tuple(ov.aval.shape)
        matches = [v for v in float_ins if tuple(v.aval.shape) == shape]
        if not matches:
            continue
        # does the cast value DERIVE from one of the shape-matched
        # inputs? generic dataflow walk, call eqns step both onto their
        # operands and (index-matched) into their sub-jaxprs
        want = {id(v) for v in matches}
        stack, visited, derives = [(src.invars[0], 0)], set(), False
        while stack and not derives:
            v, depth = stack.pop()
            if depth > 25 or _is_literal(v) or id(v) in visited:
                continue
            visited.add(id(v))
            if id(v) in want:
                derives = True
                break
            producer = producers.get(id(v))
            if producer is None:
                continue
            stack.extend((u, depth + 1) for u in producer.invars)
        if derives:
            fire(dst_dt, src_dt, "returned shape-matching the float32 input it derives from (a cross-step carry)")


def scan_jaxpr_precision(
    closed,
    label: str = "",
    acc_dim: Optional[int] = None,
    pragmas: FrozenSet[str] = frozenset(),
) -> List[Finding]:
    """The pass-6 IR rules (SL601–SL603) over one (closed) jaxpr —
    what :func:`ht.analysis.check` folds in and :func:`numcheck` runs
    standalone. Pure jaxpr walk: descends pjit/scan/cond/shard_map
    bodies through the shared producer map, never executes anything."""
    jaxpr = getattr(closed, "jaxpr", closed)
    threshold = acc_dim if acc_dim is not None else _acc_dim_threshold()
    findings: List[Finding] = []
    eqns, producers = _index_jaxpr(jaxpr)
    _scan_sl601(eqns, threshold, findings)
    _scan_sl602(eqns, producers, pragmas, findings)
    _scan_sl603(jaxpr, eqns, producers, findings)
    return findings


# --------------------------------------------------------------------- #
# the source scans (SL604 + the SL602 policy arm)                       #
# --------------------------------------------------------------------- #
_F64_NAMES = ("float64", "complex128")


def scan_precision_source(fn, x64_enabled: Optional[bool] = None) -> List[Finding]:
    """Rule SL604: f64 requests in the checked program's SOURCE under
    the x64-disabled platform policy (core/devices.py). The jaxpr
    cannot carry this rule — with x64 off the request silently degrades
    to f32 AT TRACE TIME, so the trace shows float32 and the precision
    loss is invisible downstream. Best-effort like srclint's host-sync
    scan: silently returns [] when source is unavailable.

    ``x64_enabled`` defaults to the live :func:`core.devices.use_x64`
    policy (True on cpu/gpu, False on TPU — where the rule matters);
    pass an explicit bool to audit for a target platform.
    """
    import inspect
    import textwrap

    if x64_enabled is None:
        from ..core import devices

        x64_enabled = devices.use_x64()
    if x64_enabled:
        return []  # 64-bit requests are honored: nothing degrades

    target = inspect.unwrap(fn)
    try:
        src = textwrap.dedent(inspect.getsource(target))
        tree = ast.parse(src)
        base = inspect.getsourcefile(target) or "<source>"
        first = target.__code__.co_firstlineno if hasattr(target, "__code__") else 1
    except (TypeError, OSError, SyntaxError, AttributeError):
        return []
    pragmas = _pragmas_of(src)
    suppressed = {r for toks in pragmas.values() for r in toks}
    if "SL604" in suppressed or "*" in suppressed:
        return []
    findings: List[Finding] = []
    seen_lines: Set[int] = set()
    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Attribute) and node.attr in _F64_NAMES:
            name = node.attr
        elif isinstance(node, ast.Name) and node.id in _F64_NAMES:
            name = node.id
        elif isinstance(node, ast.Constant) and node.value in _F64_NAMES:
            name = node.value
        if name is None or node.lineno in seen_lines:
            continue
        seen_lines.add(node.lineno)
        findings.append(
            Finding(
                "SL604",
                "warning",
                f"f64 request ({name}) under the x64-disabled platform "
                "policy — the dtype silently degrades to float32 at trace "
                "time (core/devices.py: TPU runs with x64 off; "
                "types.degrade64). If the extra precision is load-bearing, "
                "call ht.use_x64(True) explicitly; otherwise request "
                "float32 and make the narrowing visible",
                path=base,
                line=first + node.lineno - 1,
                op=name,
            )
        )
    return findings


def _defaults_highest(fn_node: ast.FunctionDef) -> bool:
    """Does the op guarantee ``precision="highest"`` when the caller
    passes nothing — a ``precision="highest"`` default parameter, or
    the ``if precision is None: precision = "highest"`` resolution?"""
    args = fn_node.args
    names = [a.arg for a in args.args + args.kwonlyargs]
    defaults = list(args.defaults) + list(args.kw_defaults)
    pos_with_default = args.args[len(args.args) - len(args.defaults):] if args.defaults else []
    for a, dflt in list(zip(pos_with_default, args.defaults)) + list(
        zip(args.kwonlyargs, args.kw_defaults)
    ):
        if (
            a.arg == "precision"
            and isinstance(dflt, ast.Constant)
            and str(dflt.value).lower() == "highest"
        ):
            return True
    if "precision" not in names:
        return False
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            tgts = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if (
                "precision" in tgts
                and isinstance(node.value, ast.Constant)
                and str(node.value.value).lower() == "highest"
            ):
                return True
    return False


def _delegates_to_highest(fn_node: ast.FunctionDef) -> bool:
    """Does the op route through a sibling policy-"highest" op (a BARE
    name call — ``matmul(a, b)``; attribute calls like ``jnp.matmul``
    are the raw primitive, not the policy surface)?"""
    highest = {op for op, pol in PLANAR_PRECISION_POLICY.items() if pol == "highest"}
    for node in ast.walk(fn_node):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in highest
            and node.func.id != fn_node.name
        ):
            return True
    return False


def lint_source(src: str, rel: str) -> List[Finding]:
    """The SL602 source arm over one module: every op
    :data:`PLANAR_PRECISION_POLICY` marks "highest" must default its
    MXU precision to HIGHEST (or delegate to a sibling op that does).
    Scoped to core/complex_planar.py — the module whose Gauss
    decomposition IS the cancellation-prone form; other modules return
    no findings."""
    rel = rel.replace("\\", "/")
    if not rel.endswith(_PLANAR_MODULE):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("SL602", "error", f"unparseable module: {e}", path=rel, line=e.lineno)]
    pragmas = _pragmas_of(src)
    top_fns = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    findings: List[Finding] = []
    for op in sorted(PLANAR_PRECISION_POLICY):
        if PLANAR_PRECISION_POLICY[op] != "highest":
            continue
        fn_node = top_fns.get(op)
        if fn_node is None:
            findings.append(
                Finding(
                    "SL602",
                    "error",
                    f"PLANAR_PRECISION_POLICY names op {op!r} 'highest' but "
                    f"{_PLANAR_MODULE} defines no such function — the policy "
                    "table and the module drifted apart",
                    path=rel,
                    line=1,
                )
            )
            continue
        if _defaults_highest(fn_node) or _delegates_to_highest(fn_node):
            continue
        toks = pragmas.get(fn_node.lineno, set())
        severity = "info" if ("SL602" in toks or "*" in toks) else "error"
        findings.append(
            Finding(
                "SL602",
                severity,
                f"planar op {op!r} does not default precision to 'highest': "
                "the Gauss 3-multiply form recovers the imaginary part by "
                "cancellation of MXU products, which default (bf16) "
                "precision turns into up to 13% relative error on chip — "
                "the PR 5 live defect. Restore the `if precision is None: "
                "precision = \"highest\"` default (callers opt INTO speed "
                "explicitly)",
                path=rel,
                line=fn_node.lineno,
            )
        )
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def lint_paths(paths, root: Optional[str] = None) -> AnalysisReport:
    """The ``--pass numcheck`` tree arm: run :func:`lint_source` over
    every ``.py`` file under ``paths`` (relative anchors against
    ``root``). Today this is the planar precision-policy enforcement —
    the IR rules need example arguments and ride
    :func:`ht.analysis.check` / :func:`numcheck` instead."""
    import os

    from .srclint import _iter_py_files

    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    n_files = 0
    for path in paths:
        for fp in _iter_py_files(path):
            n_files += 1
            with open(fp, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
            findings += lint_source(src, rel)
    return AnalysisReport(findings, context={"files": n_files, "pass": "numcheck"})


# --------------------------------------------------------------------- #
# the standalone entry                                                  #
# --------------------------------------------------------------------- #
def numcheck(
    fn,
    *args,
    acc_dim: Optional[int] = None,
    x64: Optional[bool] = None,
    **kwargs,
) -> AnalysisReport:
    """Precision-flow analysis of the program ``fn(*args, **kwargs)``
    compiles to (analyzer pass 6, standalone).

    Same calling contract as :func:`ht.analysis.check`: ``fn`` may be a
    public heat_tpu function over DNDarrays, an ``ht.jit`` wrapper, or
    a jax callable; the arguments are example inputs fixing
    shapes/dtypes. Compile-only — nothing executes on device. Runs the
    SL601–SL603 jaxpr rules plus the SL604 f64-policy source scan (the
    one rule :func:`check` cannot fold: with x64 off the request
    degrades at trace time and never reaches the jaxpr).

    Parameters
    ----------
    acc_dim : SL601 reduction-extent threshold override (default: the
        ``HEAT_TPU_NUMCHECK_ACC_DIM`` gate, 1024).
    x64 : SL604 policy override — audit as if the x64 policy were
        this value (default: the live ``core.devices.use_x64()``).

    Returns an :class:`AnalysisReport`; ``report.ok`` is False iff an
    error-severity finding gates.
    """
    from .ircheck import _lower_checked

    findings: List[Finding] = []
    threshold = acc_dim if acc_dim is not None else _acc_dim_threshold()
    context: Dict[str, Any] = {"pass": "numcheck", "acc_dim": int(threshold)}
    findings += scan_precision_source(fn, x64_enabled=x64)
    lowered = _lower_checked(fn, args, kwargs, findings)
    if lowered is not None:
        closed, _compiled = lowered
        findings += scan_jaxpr_precision(
            closed,
            label=getattr(fn, "__name__", "") or "",
            acc_dim=threshold,
            pragmas=fn_pragmas(fn),
        )
    findings.sort(
        key=lambda f: ({"error": 0, "warning": 1, "info": 2}[f.severity], f.rule)
    )
    return AnalysisReport(findings, context)
