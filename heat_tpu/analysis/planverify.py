"""Schedule-IR plan verifier — ``ht.analysis.verify_plan``.

The redistribution planner's golden matrix is pinned today by byte-level
dump diffing (ci.sh runs ``scripts/redist_plans.py`` twice and diffs):
that catches nondeterminism, but a plan that is *deterministically
wrong* — corrupted accounting, a dropped dequantize step, a tier label
that contradicts the topology — would diff clean forever. This module
closes that gap: it symbolically executes a
:class:`~heat_tpu.redistribution.schedule.Schedule` (or its parsed
canonical-JSON dict) over abstract shard shapes and PROVES the plan
well-formed, invariant by invariant:

``composition``
    the step sequence is one that takes ``spec.src`` to ``spec.dst``:
    per-strategy symbolic templates over the step kinds (an a2a plan is
    laps of slice→all-to-all→scatter; a pivot is stage-in → local
    reshape → stage-out; a ring is exactly ``p-1`` ppermute hops; a
    hierarchical plan alternates intra-slice/inter-slice exchanges),
    with the spec-side preconditions (splits, reshape validity) checked
    so the matched template provably ends at ``(out_shape, dst_split)``.
``conservation``
    per-step byte conservation: the collective payloads re-derived from
    the spec's geometry (padded shard bytes, crossing fractions, lap
    counts) equal the plan's recorded movement — exactly, including the
    chunking floor-division the planner applies.
``accounting``
    the recorded ``peak_bytes``/``bytes_moved``/``bytes_copied``/
    ``collective_counts``/``within_budget`` fields equal what the steps
    recompute to (the liveness-based peak of the step list — see
    :meth:`Schedule.liveness`).
``quant-pairing``
    every wire-codec collective sits inside a quantize → collective →
    dequantize triple, codec steps appear iff the schedule carries a
    ``quant`` annotation, and the annotation's ``bytes_raw``/
    ``bytes_sent``/``ratio`` arithmetic is consistent (``wire_ratio``
    is recomputed, not trusted).
``tier-labels``
    tier labels are consistent with the ``topology`` annotation (and
    with an explicitly expected ``topology=`` argument): flat plans
    carry no tiers, tiered flat-structure plans ride DCN end to end,
    hierarchical plans carry both tiers in intra/inter order, and
    ``n_slices * chips_per_slice == mesh_size``.
``overlap-structure``
    pipeline groups are well-formed laps: each group's tag anchors the
    right number of collective laps, and the depth-2 critical-path
    arithmetic (``w + (laps-1)·max(w, c) + c``; the tiered
    ``max(ici, dcn·penalty, copy)`` form) reproduces the annotation.
``staging``
    out-of-core window schedules (ISSUE 11, ``host-staging`` plans):
    every ``stage_in`` pairs with its ``stage_out`` on writeback
    passes, each pass's windows conserve the operand exactly, the
    recorded depth-2 slab occupancies match the window+prefetch
    recompute, the resident working set plus the slab peak fits
    ``tiers.capacity("hbm")``, and the annotation's lattice time model
    (``tiers.transfer_time`` over the pcie/hbm edges) is reproduced.
``progress``
    the collective-congruence replay (ISSUE 14, pass 5's dynamic half):
    a symbolic per-device execution of the schedule proving every
    participant can RUN it to completion — every collective step's
    group structure is congruent across participants (hierarchical
    ici/dcn pairs ride partitions of the mesh, ``S·C == p``), every
    ring closes in exactly ``p-1`` hops (the replay delivers all ``p``
    blocks), each hierarchical lap's intra/inter halves carry the SAME
    chunk index (a split pair leaves one tier waiting on an unissued
    lap), and every depth-2 overlap group issues its laps in exactly
    the order the double-buffer consumes them (``0..laps-1`` — a
    reordered lap makes the consume slot read an unissued buffer).
    Available standalone as :func:`check_progress` — what the MPMD
    stage-graph verifier will consume per stage.
``calibration``
    the stamped lattice profile (ISSUE 16): a plan priced under
    ``HEAT_TPU_LATTICE_PROFILE`` carries ``{profile_id, edges}`` —
    the stamp must be well-formed (non-empty id, known edges, positive
    prices) and the numbers DERIVED from the prices elsewhere in the
    plan must agree (the topology annotation's ``dcn_penalty`` is the
    recorded ici/dcn ratio; the staging model recompute above uses the
    recorded pcie/hbm prices). Environment-independent: a dumped
    calibrated plan verifies on a container with no profile.
``tolerance``
    the error-bound recomputation (ISSUE 17, pass 6's dynamic half):
    the end-to-end error bound recomputed from the recorded per-step
    tolerances — each quantize step contributes the codec's pinned
    ``tolerance(mode)`` to the disjoint payload leg it encodes (one
    ``(overlap, chunk)`` lap, one ring hop block, one standalone
    phase), staging/relayout/overlap steps are exact-bit, and in a
    hierarchical plan only dcn-tier crossings may carry the codec (the
    PR 8 policy) — must equal the schedule-level ``quant.tol``
    annotation, which itself must equal
    ``kernels.quant.tolerance(mode)``. Every encoded crossing must be
    codec-sandwiched and attributed (``[<mode> wire]``), and no
    exact-bit plan may claim one. Available standalone as
    :func:`check_tolerance` (SL605 findings) — the budget contract the
    Newton–Schulz and MPMD tolerance consumers read.
``plan-id``
    the ``plan_id`` is the sha1 of the canonical serialization — a
    hand-edited or bit-rotted dump cannot keep its id.

Runs in pure Python (no mesh, no jax device work), so the ci.sh
determinism leg sweeps it over every dumped golden plan — flat, 2x4,
2x8, quant on and off — and tier-1 pins the same sweep in-process.
"""

from __future__ import annotations

import hashlib
import json

from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = [
    "PlanVerificationError", "check_progress", "check_tolerance",
    "verify_plan",
]

_COLLECTIVE_KINDS = ("all_to_all", "all_gather", "ppermute")
_LOCAL_KINDS = (
    "slice", "pad", "reshape", "concat", "pack", "unpack",
    "quantize", "dequantize",
)
_CODEC_KINDS = ("quantize", "dequantize")
# ISSUE 11: the out-of-core staging transfers (redistribution.staging)
# — they move bytes across the pcie edge of the memory-tier lattice but
# launch no collective, so they sit in neither class above
_STAGING_KINDS = ("stage_in", "stage_out")


class PlanVerificationError(ValueError):
    """One violated plan invariant, named.

    Attributes
    ----------
    invariant : the violated invariant's name (``composition``,
        ``conservation``, ``accounting``, ``quant-pairing``,
        ``tier-labels``, ``overlap-structure``, ``staging``,
        ``progress``, ``tolerance``, ``plan-id``, ``step-kinds``).
    detail : what exactly failed, with the offending numbers.
    plan_id : the plan's id when known.
    """

    def __init__(self, invariant: str, detail: str, plan_id: Optional[str] = None):
        self.invariant = invariant
        self.detail = detail
        self.plan_id = plan_id
        where = f"plan {plan_id} " if plan_id else "plan "
        super().__init__(f"{where}violates invariant '{invariant}': {detail}")


def _pad_extent(n: int, p: int) -> int:
    from ..core import _padding

    return _padding.pad_extent(int(n), int(p))


def _prod(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _itemsize(dtype: str) -> int:
    import numpy as np

    return np.dtype(dtype).itemsize


def _as_plan_dict(plan) -> Dict[str, Any]:
    from ..redistribution.schedule import Schedule

    if isinstance(plan, Schedule):
        return plan.as_dict()
    if isinstance(plan, str):
        plan = json.loads(plan)
    if not isinstance(plan, dict):
        raise TypeError(
            f"verify_plan expects a Schedule, a plan dict, or its JSON "
            f"serialization — got {type(plan).__name__}"
        )
    return plan


def _expected_topology(topology) -> Union[None, str, Tuple[int, int]]:
    """Normalize the expected-topology argument: ``None`` = no
    expectation (self-consistency only), ``"flat"`` = must be untiered,
    ``"SxC"``/``(S, C)``/``Topology`` = must match."""
    if topology is None:
        return None
    if isinstance(topology, str):
        t = topology.strip().lower()
        if t in ("flat", "1", ""):
            return "flat"
        parts = t.split("x")
        if len(parts) == 2 and parts[0].isdigit() and parts[1].isdigit():
            return (int(parts[0]), int(parts[1]))
        raise ValueError(f"verify_plan: unknown topology expectation {topology!r}")
    if isinstance(topology, tuple):
        return (int(topology[0]), int(topology[1]))
    n_slices = getattr(topology, "n_slices", None)
    chips = getattr(topology, "chips_per_slice", None)
    if n_slices is not None and chips is not None:
        return (int(n_slices), int(chips)) if int(n_slices) > 1 else "flat"
    raise TypeError(f"verify_plan: cannot interpret topology {topology!r}")


def _stage_local_bytes(shape, axis: int, p: int, item: int) -> int:
    """Per-device bytes of the doubly-padded buffer one pivot stage
    exchanges (the planner's stage geometry: the stage's split axis
    padded to divide the mesh)."""
    padded = [
        _pad_extent(d, p) if ax == axis else int(d) for ax, d in enumerate(shape)
    ]
    return _prod(padded) // p * item


def verify_plan(
    plan,
    topology=None,
    raise_on_violation: bool = True,
) -> Dict[str, Any]:
    """Verify one Schedule-IR plan against its invariants.

    Parameters
    ----------
    plan : a :class:`~heat_tpu.redistribution.schedule.Schedule`, the
        dict of its ``as_dict()``/canonical serialization, or that
        serialization as a JSON string (what ``scripts/redist_plans.py``
        dumps — the ci.sh sweep feeds those lines straight in).
    topology : optional EXPECTED topology — ``"flat"`` (the plan must be
        untiered), an ``"SxC"`` string / ``(S, C)`` tuple /
        ``core.communication.Topology`` (the plan's annotation must
        match). Default ``None`` checks self-consistency only.
    raise_on_violation : raise :class:`PlanVerificationError` on the
        first violated invariant (the CI mode — the violated invariant
        is named in the exception); with ``False`` all violations are
        collected into the returned report.

    Returns ``{"ok", "plan_id", "strategy", "checks", "violations"}``;
    ``checks`` lists every invariant that was evaluated.
    """
    d = _as_plan_dict(plan)
    plan_id = d.get("plan_id")
    violations: List[PlanVerificationError] = []

    def fail(invariant: str, detail: str) -> None:
        err = PlanVerificationError(invariant, detail, plan_id=plan_id)
        if raise_on_violation:
            raise err
        violations.append(err)

    spec = d.get("spec") or {}
    strategy = d.get("strategy", "")
    steps: List[Dict[str, Any]] = list(d.get("steps") or [])
    gshape = tuple(int(v) for v in (spec.get("gshape") or ()))
    out_shape = (
        tuple(int(v) for v in spec["reshape_to"])
        if spec.get("reshape_to") is not None
        else gshape
    )
    is_reshape = spec.get("reshape_to") is not None
    src = spec.get("src_split")
    dst = spec.get("dst_split")
    p = int(spec.get("mesh_size", 1))
    item = _itemsize(spec.get("dtype", "float32"))
    size = _prod(gshape)

    # ---- step-kinds: the vocabulary itself ----------------------------
    for k, st in enumerate(steps):
        kind = st.get("kind")
        if (
            kind not in _COLLECTIVE_KINDS
            and kind not in _LOCAL_KINDS
            and kind not in _STAGING_KINDS
        ):
            fail("step-kinds", f"step [{k}] has unknown kind {kind!r}")
        if st.get("tier") not in (None, "ici", "dcn", "pcie"):
            fail("step-kinds", f"step [{k}] has unknown tier {st.get('tier')!r}")
        if kind in _STAGING_KINDS and st.get("tier") != "pcie":
            fail(
                "step-kinds",
                f"staging step [{k}] ({kind}) must ride tier 'pcie' — got "
                f"{st.get('tier')!r}",
            )
        if kind not in _STAGING_KINDS and st.get("tier") == "pcie":
            fail(
                "step-kinds",
                f"step [{k}] ({kind}) claims tier 'pcie' — reserved for "
                "stage_in/stage_out",
            )
        for field in ("bytes_moved", "bytes_copied", "peak_bytes"):
            if int(st.get(field, 0)) < 0:
                fail("step-kinds", f"step [{k}] has negative {field}")
        if kind in _LOCAL_KINDS and int(st.get("bytes_moved", 0)) != 0:
            fail(
                "step-kinds",
                f"local step [{k}] ({kind}) claims bytes_moved="
                f"{st['bytes_moved']} — only collectives and staging "
                "transfers move bytes",
            )

    coll = [st for st in steps if st.get("kind") in _COLLECTIVE_KINDS]

    # ---- accounting: the recorded fields vs the steps -----------------
    recomputed_peak = max((int(st.get("peak_bytes", 0)) for st in steps), default=0)
    if int(d.get("peak_bytes", 0)) != recomputed_peak:
        fail(
            "accounting",
            f"recorded peak_bytes={d.get('peak_bytes')} but the liveness "
            f"recompute over the steps gives {recomputed_peak}",
        )
    from ..redistribution.schedule import Schedule as _Schedule

    if isinstance(plan, _Schedule):
        # the liveness hook must agree with the step accounting: resident
        # shards + the recomputed transient peak
        live = plan.liveness()
        live_peak = max((e["transient_bytes"] for e in live), default=0)
        if live_peak != recomputed_peak or plan.liveness_peak_bytes != (
            plan.resident_bytes + recomputed_peak
        ):
            fail(
                "accounting",
                f"Schedule.liveness() peak {live_peak} (+resident "
                f"{plan.resident_bytes}) disagrees with the step "
                f"accounting peak {recomputed_peak}",
            )
    moved = sum(int(st.get("bytes_moved", 0)) for st in steps)
    if int(d.get("bytes_moved", 0)) != moved:
        fail(
            "accounting",
            f"recorded bytes_moved={d.get('bytes_moved')} != step sum {moved}",
        )
    copied = sum(int(st.get("bytes_copied", 0)) for st in steps)
    if int(d.get("bytes_copied", 0)) != copied:
        fail(
            "accounting",
            f"recorded bytes_copied={d.get('bytes_copied')} != step sum {copied}",
        )
    budget = int(d.get("budget_bytes", 0))
    if budget < 1:
        fail("accounting", f"budget_bytes={budget} is not positive")
    if bool(d.get("within_budget")) != (recomputed_peak <= budget):
        fail(
            "accounting",
            f"within_budget={d.get('within_budget')} contradicts peak "
            f"{recomputed_peak} vs budget {budget}",
        )
    counts: Dict[str, int] = {}
    op_of = {"all_to_all": "all-to-all", "all_gather": "all-gather",
             "ppermute": "collective-permute"}
    for st in coll:
        op = op_of[st["kind"]]
        counts[op] = counts.get(op, 0) + 1
    if dict(d.get("collective_counts") or {}) != counts:
        fail(
            "accounting",
            f"recorded collective_counts={d.get('collective_counts')} != "
            f"step census {counts}",
        )

    # ---- quant-pairing ------------------------------------------------
    quant = d.get("quant")
    n_q = sum(1 for st in steps if st.get("kind") == "quantize")
    n_dq = sum(1 for st in steps if st.get("kind") == "dequantize")
    if (n_q or n_dq) and not quant:
        fail(
            "quant-pairing",
            f"{n_q} quantize / {n_dq} dequantize steps but no schedule-"
            "level quant annotation",
        )
    if n_q != n_dq:
        fail("quant-pairing", f"{n_q} quantize steps vs {n_dq} dequantize steps")
    for k, st in enumerate(steps):
        if st.get("kind") == "quantize":
            nxt = steps[k + 1] if k + 1 < len(steps) else None
            nxt2 = steps[k + 2] if k + 2 < len(steps) else None
            if nxt is None or nxt.get("kind") not in _COLLECTIVE_KINDS:
                fail(
                    "quant-pairing",
                    f"quantize step [{k}] is not followed by a collective "
                    "(the encoded wire has no consumer)",
                )
            elif nxt2 is None or nxt2.get("kind") != "dequantize":
                fail(
                    "quant-pairing",
                    f"wire-codec collective [{k + 1}] is not followed by a "
                    "dequantize (the received blocks stay encoded)",
                )
    if quant:
        mode = quant.get("mode")
        if mode not in ("int8", "bf16"):
            fail("quant-pairing", f"unknown wire-codec mode {mode!r}")
        if n_q == 0:
            fail("quant-pairing", "quant annotation present but no quantize step")
        raw_q, sent_q = int(quant.get("bytes_raw", -1)), int(quant.get("bytes_sent", -1))
        if raw_q < sent_q or sent_q < 0:
            fail(
                "quant-pairing",
                f"quant annotation bytes_raw={raw_q} < bytes_sent={sent_q} "
                "(the codec cannot inflate the wire)",
            )
        if sent_q != moved:
            fail(
                "quant-pairing",
                f"quant annotation bytes_sent={sent_q} != the steps' wire "
                f"total {moved}",
            )
        want_ratio = round(sent_q / raw_q, 4) if raw_q else 1.0
        if abs(float(quant.get("ratio", -1)) - want_ratio) > 1e-9:
            fail(
                "quant-pairing",
                f"quant ratio={quant.get('ratio')} != recomputed "
                f"{want_ratio} (wire_ratio arithmetic is not consistent)",
            )

    # ---- tier-labels --------------------------------------------------
    topo = d.get("topology")
    expected = _expected_topology(topology)
    if expected == "flat" and topo is not None:
        fail(
            "tier-labels",
            f"expected a flat plan but the schedule carries topology {topo}",
        )
    if isinstance(expected, tuple):
        got = (
            (int(topo["n_slices"]), int(topo["chips_per_slice"])) if topo else None
        )
        # the planner's own resolution semantics: a forced SxC that does
        # not factor THIS spec's mesh falls back to flat, and plans that
        # launch no collectives never carry the annotation at all.
        # Factorization ring schedules (ISSUE 19) are planned topology-
        # blind — every collective is a nearest-neighbour ppermute hop of
        # a pre-declared ring, so a forced topology never annotates them.
        want = (
            expected
            if (
                expected[0] * expected[1] == p
                and coll
                and not strategy.startswith("factorization-")
            )
            else None
        )
        if got != want:
            fail(
                "tier-labels",
                f"expected topology "
                f"{want and f'{want[0]}x{want[1]}' or 'flat'} (from "
                f"{expected[0]}x{expected[1]} over a {p}-device mesh) but "
                f"the schedule carries {got and f'{got[0]}x{got[1]}'}",
            )
    if topo is not None:
        S, C = int(topo.get("n_slices", 0)), int(topo.get("chips_per_slice", 0))
        if S < 2 or C < 1 or S * C != p:
            fail(
                "tier-labels",
                f"topology annotation {S}x{C} does not factor the mesh "
                f"(mesh_size {p})",
            )
        if int(topo.get("dcn_penalty", 0)) < 1:
            fail("tier-labels", f"dcn_penalty={topo.get('dcn_penalty')} is not >= 1")
    tiers = [st.get("tier") for st in coll]
    if topo is None:
        if any(t is not None for t in tiers):
            fail(
                "tier-labels",
                "tier labels present on a flat plan (no topology annotation)",
            )
    else:
        if any(t is None for t in tiers):
            fail(
                "tier-labels",
                "a tiered plan's collectives must all carry a tier label",
            )
        if strategy == "hierarchical-a2a":
            # intra-slice pivot first, inter-slice exchange second — per lap
            if tiers[0::2] != ["ici"] * len(tiers[0::2]) or tiers[1::2] != [
                "dcn"
            ] * len(tiers[1::2]):
                fail(
                    "tier-labels",
                    f"hierarchical-a2a tiers must alternate ici,dcn per lap "
                    f"— got {tiers}",
                )
        elif any(t != "dcn" for t in tiers):
            fail(
                "tier-labels",
                f"a slice-spanning flat-structure plan rides DCN end to end "
                f"— got tiers {tiers}",
            )
    for k, st in enumerate(steps):
        if (
            st.get("kind") not in _COLLECTIVE_KINDS
            and st.get("kind") not in _STAGING_KINDS
            and st.get("tier") is not None
        ):
            fail("tier-labels", f"local step [{k}] ({st['kind']}) carries a tier")

    # ---- composition: src must compose to dst -------------------------
    kinds = [st["kind"] for st in steps if st.get("kind") not in _CODEC_KINDS]
    coll_kinds = [k for k in kinds if k in _COLLECTIVE_KINDS]

    def _compose() -> Optional[str]:
        if strategy == "noop":
            if steps:
                return "a noop plan must have no steps"
            if src != dst or (is_reshape and gshape != out_shape):
                return "a noop plan must not change split or shape"
        elif strategy == "local":
            if p > 1 and size > 0:
                return f"a local plan needs a 1-device mesh or empty array (p={p})"
        elif strategy == "slice":
            if src is not None or dst is None:
                return f"slice serves replicated->split only (src={src}, dst={dst})"
            if coll_kinds:
                return f"slice must launch no collectives — got {coll_kinds}"
        elif strategy == "replicate":
            if dst is not None:
                return f"replicate must end replicated (dst={dst})"
            if coll_kinds != ["all_gather"]:
                return f"replicate is ONE all-gather — got {coll_kinds}"
        elif strategy == "gather-reshape":
            if coll_kinds != ["all_gather"]:
                return f"gather-reshape is ONE all-gather — got {coll_kinds}"
            if is_reshape and "reshape" not in kinds:
                return "gather-reshape never reshapes the gathered array"
        elif strategy == "local-reshape":
            if coll_kinds:
                return f"local-reshape must launch no collectives — got {coll_kinds}"
        elif strategy in ("all-to-all", "chunked-all-to-all"):
            if is_reshape:
                return "a pure-resplit strategy cannot serve a reshape spec"
            if src is None or dst is None or src == dst:
                return f"resplit needs two distinct splits (src={src}, dst={dst})"
            if not coll_kinds or set(coll_kinds) != {"all_to_all"}:
                return f"the exchange must be all-to-all laps — got {coll_kinds}"
            if strategy == "chunked-all-to-all" and len(coll_kinds) < 2:
                return "a chunked plan needs >= 2 laps"
        elif strategy == "ring":
            if is_reshape:
                return "ring serves pure resplits only"
            if coll_kinds != ["ppermute"] * (p - 1):
                return (
                    f"ring is exactly p-1={p - 1} ppermute hops — got "
                    f"{len(coll_kinds)} of {sorted(set(coll_kinds))}"
                )
        elif strategy in ("split0-pivot", "packed-pivot"):
            if not is_reshape:
                return "the pivot serves reshape-with-repartition specs only"
            if kinds.count("reshape") != 1:
                return (
                    f"the pivot has exactly one local reshape at full width "
                    f"— got {kinds.count('reshape')}"
                )
            if not gshape or not out_shape:
                return "the pivot needs non-scalar source and target shapes"
            if gshape[0] % p or out_shape[0] % p:
                return (
                    f"pivot divisibility violated: leading extents "
                    f"{gshape[0]}/{out_shape[0]} must divide p={p}"
                )
            if set(coll_kinds) - {"all_to_all"}:
                return f"pivot stages exchange via all-to-all — got {coll_kinds}"
            piv = kinds.index("reshape")
            n_in = sum(1 for k in kinds[:piv] if k in _COLLECTIVE_KINDS)
            n_out = sum(1 for k in kinds[piv:] if k in _COLLECTIVE_KINDS)
            if (src not in (None, 0)) != (n_in > 0):
                return (
                    f"stage-in mismatch: src_split={src} but {n_in} "
                    "collectives before the pivot reshape"
                )
            if (dst not in (None, 0)) != (n_out > 0):
                return (
                    f"stage-out mismatch: dst_split={dst} but {n_out} "
                    "collectives after the pivot reshape"
                )
        elif strategy == "hierarchical-a2a":
            if topo is None:
                return "hierarchical-a2a requires a topology annotation"
            if set(coll_kinds) != {"all_to_all"}:
                return f"hierarchical laps exchange via all-to-all — got {coll_kinds}"
            if len(coll_kinds) % 2:
                return (
                    f"hierarchical laps come in intra/inter pairs — got "
                    f"{len(coll_kinds)} collectives"
                )
        elif strategy == "host-staging":
            # ISSUE 11: the out-of-core window stream — no mesh
            # movement at all, only pcie staging transfers
            if coll_kinds:
                return f"host-staging launches no collectives — got {coll_kinds}"
            if not kinds or any(k not in _STAGING_KINDS for k in kinds):
                return (
                    "host-staging steps are stage_in/stage_out windows only "
                    f"— got {sorted(set(kinds) - set(_STAGING_KINDS))}"
                )
            if d.get("staging") is None:
                return "host-staging requires a staging annotation"
            if src is not None or dst is not None:
                return (
                    "host-staging streams a host-resident operand — splits "
                    f"must be None (src={src}, dst={dst})"
                )
        elif strategy.startswith("factorization-"):
            # ISSUE 19: the dense-factorization ring schedules
            # (core/linalg/factorizations._factorization_plan) — every
            # collective is a ppermute hop of a pre-declared ring, and
            # the hop census per solver is a pinned contract
            # (tests/test_factorizations.py proves census == plan)
            if is_reshape:
                return "a factorization plan never reshapes its operand"
            if src != 0 or dst != 0:
                return (
                    f"factorization plans serve split-0 operands in place "
                    f"(src={src}, dst={dst})"
                )
            kind_f = strategy[len("factorization-"):]
            want = {
                "polar": 5 * (p - 1),
                "cholesky": p * (p - 1),
                "lu": p * (p - 1) + (p - 1) ** 2,
                "solve-chol": 2 * (p - 1) ** 2,
                "solve-lu": 2 * (p - 1) ** 2,
            }.get(kind_f)
            if want is None:
                return f"unknown factorization kind {kind_f!r}"
            if set(coll_kinds) - {"ppermute"}:
                return (
                    f"factorization rings are ppermute-only — got "
                    f"{sorted(set(coll_kinds))}"
                )
            if len(coll_kinds) != want:
                return (
                    f"factorization-{kind_f} at p={p} is exactly {want} "
                    f"ppermute hop(s) — got {len(coll_kinds)}"
                )
        else:
            return f"unknown strategy {strategy!r}"
        return None

    detail = _compose()
    if detail is not None:
        fail("composition", detail)

    # ---- conservation: movement re-derived from the spec geometry -----
    raw_total = int(quant["bytes_raw"]) if quant else moved

    def _expected_raw() -> Optional[int]:
        if strategy in ("noop", "local", "slice", "local-reshape"):
            return 0
        if strategy == "host-staging":
            # every pass streams the whole operand across pcie once
            # (twice with writeback) — the window partition must
            # conserve it exactly
            sg = d.get("staging") or {}
            return sum(
                size * item * (2 if pm.get("writeback") else 1)
                for pm in (sg.get("passes") or [])
            )
        if strategy in ("replicate", "gather-reshape"):
            return size * item * (p - 1) // p
        if strategy in ("all-to-all", "chunked-all-to-all") or (
            strategy == "hierarchical-a2a" and not is_reshape
        ):
            shape = list(gshape)
            shape[src] = _pad_extent(shape[src], p)
            shape[dst] = _pad_extent(shape[dst], p)
            L = _prod(shape) // p * item
            if strategy == "hierarchical-a2a":
                S, C = int(topo["n_slices"]), int(topo["chips_per_slice"])
                K = max(len(coll_kinds) // 2, 1)
                return (L * (C - 1) // C // K) * K + (L * (S - 1) // S // K) * K
            Cn = max(len(coll_kinds), 1)
            return (L * (p - 1) // p // Cn) * Cn
        if strategy == "ring":
            shape = list(gshape)
            shape[src] = _pad_extent(shape[src], p)
            shape[dst] = _pad_extent(shape[dst], p)
            L = _prod(shape) // p * item
            return (L // p) * (p - 1)
        if strategy in ("split0-pivot", "packed-pivot") or (
            strategy == "hierarchical-a2a" and is_reshape
        ):
            piv = kinds.index("reshape") if "reshape" in kinds else len(kinds)
            pos = [i for i, k in enumerate(kinds) if k in _COLLECTIVE_KINDS]
            n_in = sum(1 for i in pos if i < piv)
            n_out = len(pos) - n_in
            hier = strategy == "hierarchical-a2a"
            total = 0
            for n_stage, shape, axis in (
                (n_in, gshape, src),
                (n_out, out_shape, dst),
            ):
                if not n_stage:
                    continue
                L = _stage_local_bytes(shape, axis, p, item)
                if hier:
                    S, C = int(topo["n_slices"]), int(topo["chips_per_slice"])
                    K = max(n_stage // 2, 1)
                    total += (L * (C - 1) // C // K) * K + (L * (S - 1) // S // K) * K
                else:
                    total += (L * (p - 1) // p // n_stage) * n_stage
            return total
        if strategy.startswith("factorization-"):
            # recompute the ring payloads from the spec geometry exactly
            # as _factorization_plan prices them (norm-ring scalars ride
            # the real component's width on complex dtypes)
            kind_f = strategy[len("factorization-"):]
            rt = (
                item // 2
                if str(spec.get("dtype", "")).startswith("complex")
                else item
            )
            if kind_f == "polar":
                n_cols = gshape[1]
                mc = -(-n_cols // p)
                return (p - 1) * rt + 4 * (p - 1) * mc * n_cols * item
            nb = -(-gshape[0] // p)
            if kind_f == "cholesky":
                return p * (p - 1) * nb * nb * item
            if kind_f == "lu":
                n_pad = nb * p
                return p * (p - 1) * nb * nb * item + sum(
                    (p - 1) * nb * (n_pad - (k + 1) * nb) * item
                    for k in range(p - 1)
                )
            if kind_f in ("solve-chol", "solve-lu"):
                return 2 * (p - 1) ** 2 * nb * gshape[1] * item
        return None

    try:
        expected_raw = _expected_raw()
    except (TypeError, IndexError, KeyError, ZeroDivisionError) as e:
        expected_raw = None
        fail(
            "conservation",
            f"the spec geometry of strategy {strategy} is underivable "
            f"({type(e).__name__}: {e}) — spec and strategy disagree",
        )
    if expected_raw is not None and expected_raw != raw_total:
        fail(
            "conservation",
            f"strategy {strategy} over {spec} must move {expected_raw} raw "
            f"wire bytes per device — the plan records {raw_total}",
        )

    # ---- overlap-structure --------------------------------------------
    overlap = d.get("overlap")
    if overlap:
        if int(overlap.get("depth", 0)) != 2:
            fail("overlap-structure", f"unsupported pipeline depth {overlap.get('depth')}")
        groups = list(overlap.get("groups") or [])
        if not groups:
            fail("overlap-structure", "overlap annotation with no groups")
        seq_sum = sum(int(g.get("sequential_bytes", 0)) for g in groups)
        cp_sum = sum(int(g.get("critical_path_bytes", 0)) for g in groups)
        if int(overlap.get("sequential_bytes", -1)) != seq_sum:
            fail(
                "overlap-structure",
                f"annotation sequential_bytes={overlap.get('sequential_bytes')} "
                f"!= group sum {seq_sum}",
            )
        if int(overlap.get("critical_path_bytes", -1)) != cp_sum:
            fail(
                "overlap-structure",
                f"annotation critical_path_bytes="
                f"{overlap.get('critical_path_bytes')} != group sum {cp_sum}",
            )
        if cp_sum and abs(
            float(overlap.get("model_speedup", -1)) - round(seq_sum / cp_sum, 4)
        ) > 1e-9:
            fail(
                "overlap-structure",
                f"model_speedup={overlap.get('model_speedup')} != recomputed "
                f"{round(seq_sum / cp_sum, 4)}",
            )
        lap_mult = 2 if strategy == "hierarchical-a2a" else 1
        for g in groups:
            tag, laps = g.get("tag"), int(g.get("laps", 0))
            anchored = sum(
                1
                for st in steps
                if st.get("kind") in _COLLECTIVE_KINDS and st.get("overlap") == tag
            )
            if anchored != laps * lap_mult:
                fail(
                    "overlap-structure",
                    f"group {tag!r} models {laps} lap(s) but {anchored} "
                    f"collective step(s) carry the tag (expected "
                    f"{laps * lap_mult})",
                )
            wire, copy = int(g.get("wire_bytes", 0)), int(g.get("copy_bytes", 0))
            seq_g, cp_g = int(g.get("sequential_bytes", -1)), int(
                g.get("critical_path_bytes", -1)
            )
            if seq_g != wire + copy:
                fail(
                    "overlap-structure",
                    f"group {tag!r} sequential_bytes={seq_g} != wire+copy "
                    f"{wire + copy}",
                )
            if laps >= 2:
                if "ici_bytes" in g:
                    pen = int(g.get("dcn_penalty", 1))
                    ici, dcn = int(g.get("ici_bytes", 0)), int(g.get("dcn_bytes", 0))
                    if wire != ici + dcn * pen:
                        fail(
                            "overlap-structure",
                            f"tiered group {tag!r} wire_bytes={wire} != "
                            f"ici + dcn·penalty = {ici + dcn * pen}",
                        )
                    wi, wd, c = ici // laps, dcn * pen // laps, copy // laps
                    want_cp = wi + wd + c + (laps - 1) * max(wi, wd, c)
                else:
                    w, c = wire // laps, copy // laps
                    want_cp = w + (laps - 1) * max(w, c) + c
                if cp_g != want_cp:
                    fail(
                        "overlap-structure",
                        f"group {tag!r} critical_path_bytes={cp_g} != the "
                        f"depth-2 model {want_cp}",
                    )
                if cp_g >= seq_g:
                    fail(
                        "overlap-structure",
                        f"group {tag!r} models no gain (critical path "
                        f"{cp_g} >= sequential {seq_g}) — the planner drops "
                        "such groups",
                    )

    # ---- staging: the out-of-core window schedule (ISSUE 11) ----------
    staging = d.get("staging")
    stage_steps = [st for st in steps if st.get("kind") in _STAGING_KINDS]
    if stage_steps and not staging:
        fail(
            "staging",
            f"{len(stage_steps)} stage_in/stage_out step(s) but no "
            "schedule-level staging annotation",
        )
    if staging:
        if not stage_steps:
            fail("staging", "staging annotation present but no staging step")
        if int(staging.get("depth", 0)) != 2:
            fail("staging", f"unsupported staging depth {staging.get('depth')}")
        if int(staging.get("host_bytes", -1)) != size * item:
            fail(
                "staging",
                f"annotation host_bytes={staging.get('host_bytes')} != the "
                f"operand's {size * item} B",
            )
        if int(staging.get("slab_bytes", -1)) != budget:
            fail(
                "staging",
                f"annotation slab_bytes={staging.get('slab_bytes')} != the "
                f"schedule budget {budget} (the slab IS the staged budget)",
            )
        passes = list(staging.get("passes") or [])
        if not passes:
            fail("staging", "staging annotation with no passes")
        idx = 0
        max_window = 0
        pcie_total = 0
        for pm in passes:
            tag, n = pm.get("tag"), int(pm.get("n_windows", 0))
            wb = bool(pm.get("writeback"))
            per = 2 if wb else 1
            seg = stage_steps[idx : idx + n * per]
            idx += n * per
            if len(seg) != n * per:
                fail(
                    "staging",
                    f"pass {tag!r} declares {n} window(s) "
                    f"({'with' if wb else 'no'} writeback) but the step list "
                    "ran out — stage-in/stage-out pairing is broken",
                )
                break
            win_bytes: List[int] = []
            for k in range(n):
                si = seg[per * k]
                if si.get("kind") != "stage_in":
                    fail(
                        "staging",
                        f"pass {tag!r} window {k}: expected stage_in, got "
                        f"{si.get('kind')}",
                    )
                if wb:
                    so = seg[per * k + 1]
                    if so.get("kind") != "stage_out":
                        fail(
                            "staging",
                            f"pass {tag!r} window {k}: writeback pass must "
                            f"pair stage_in with stage_out, got {so.get('kind')}",
                        )
                    elif int(so.get("bytes_moved", -1)) != int(si.get("bytes_moved", 0)):
                        fail(
                            "staging",
                            f"pass {tag!r} window {k}: stage_out ships "
                            f"{so.get('bytes_moved')} B != the window's "
                            f"{si.get('bytes_moved')} B stage_in",
                        )
                win_bytes.append(int(si.get("bytes_moved", 0)))
            if sum(win_bytes) != size * item:
                fail(
                    "staging",
                    f"pass {tag!r} windows sum to {sum(win_bytes)} B != the "
                    f"operand's {size * item} B — window conservation broken",
                )
            if win_bytes and max(win_bytes) != int(pm.get("window_bytes", -1)):
                fail(
                    "staging",
                    f"pass {tag!r} annotation window_bytes="
                    f"{pm.get('window_bytes')} != max window {max(win_bytes)}",
                )
            if int(pm.get("pcie_bytes", -1)) != sum(win_bytes) * per:
                fail(
                    "staging",
                    f"pass {tag!r} annotation pcie_bytes={pm.get('pcie_bytes')} "
                    f"!= streamed total {sum(win_bytes) * per}",
                )
            # depth-2 slab occupancy: window k's transient is its own
            # bytes plus the prefetched window k+1
            for k in range(n):
                occ = win_bytes[k] + (win_bytes[k + 1] if k + 1 < n else 0)
                for st in seg[per * k : per * k + per]:
                    if int(st.get("peak_bytes", -1)) != occ:
                        fail(
                            "staging",
                            f"pass {tag!r} window {k}: recorded slab occupancy "
                            f"{st.get('peak_bytes')} B != depth-2 recompute "
                            f"{occ} B (this window + the prefetched next)",
                        )
            max_window = max(max_window, max(win_bytes or [0]))
            pcie_total += sum(win_bytes) * per
        if idx != len(stage_steps):
            fail(
                "staging",
                f"{len(stage_steps) - idx} staging step(s) not covered by "
                "any declared pass",
            )
        if int(staging.get("n_windows", -1)) != sum(
            int(pm.get("n_windows", 0)) for pm in passes
        ):
            fail(
                "staging",
                f"annotation n_windows={staging.get('n_windows')} != pass sum "
                f"{sum(int(pm.get('n_windows', 0)) for pm in passes)}",
            )
        if int(staging.get("window_bytes", -1)) != max_window:
            fail(
                "staging",
                f"annotation window_bytes={staging.get('window_bytes')} != "
                f"max window {max_window}",
            )
        # the slab peak must fit the hbm tier next to the resident
        # working set. The budget checked is the one RECORDED in the
        # annotation (the capacity the plan was sized against), so a
        # dumped plan's well-formedness is environment-independent —
        # `staging.prove_fits` re-checks the AMBIENT capacity at
        # execution time, where the current chip is what matters.
        from ..core import tiers as _tiers_mod

        resident = int(staging.get("resident_bytes", 0))
        if resident < 0:
            fail("staging", f"negative resident_bytes {resident}")
        hbm_cap = int(
            staging.get("hbm_capacity_bytes", _tiers_mod.capacity("hbm"))
        )
        if hbm_cap < 1:
            fail("staging", f"annotation hbm_capacity_bytes={hbm_cap} is not positive")
        if resident + recomputed_peak > hbm_cap:
            fail(
                "staging",
                f"staged working set {resident} B + slab peak "
                f"{recomputed_peak} B exceeds the recorded hbm capacity "
                f"{hbm_cap} B — the window schedule does not fit the chip "
                "it was sized for",
            )
        model = staging.get("model") or {}
        # ISSUE 16: a calibrated plan's model was priced at its RECORDED
        # edge prices, not the module constants — recompute from the
        # annotation so verification stays environment-independent (a
        # dumped calibrated plan verifies on a container with no profile)
        _cal_prices = (d.get("calibration") or {}).get("edges") or {}
        want_pcie_s = round(
            pcie_total / float(_cal_prices.get("pcie") or _tiers_mod.PCIE_BPS), 9
        )
        want_hbm_s = round(
            pcie_total / float(_cal_prices.get("hbm") or _tiers_mod.HBM_BPS), 9
        )
        n_total = sum(int(pm.get("n_windows", 0)) for pm in passes)
        seq_s = want_pcie_s + want_hbm_s
        cp_s = max(want_pcie_s, want_hbm_s) + min(want_pcie_s, want_hbm_s) / max(
            n_total, 1
        )
        for field, want in (
            ("pcie_s", want_pcie_s),
            ("hbm_s", want_hbm_s),
            ("sequential_s", round(seq_s, 9)),
            ("critical_path_s", round(cp_s, 9)),
            ("model_speedup", round(seq_s / cp_s, 4) if cp_s else 1.0),
            ("bound_gbps", round(pcie_total / cp_s / 1e9, 3) if cp_s else 0.0),
        ):
            if abs(float(model.get(field, -1)) - want) > 1e-6:
                fail(
                    "staging",
                    f"model {field}={model.get(field)} != the lattice "
                    f"recompute {want} (tiers.transfer_time arithmetic)",
                )

    # ---- calibration: the stamped lattice profile (ISSUE 16) ----------
    # A plan priced under HEAT_TPU_LATTICE_PROFILE carries {profile_id,
    # edges}; the invariant checks the stamp is well-formed and that the
    # derived numbers ELSEWHERE in the plan agree with the recorded
    # prices (the topology annotation's dcn_penalty is the measured
    # ici/dcn ratio). Environment-independent: the plan's own recorded
    # prices are the ground truth, never the ambient gate.
    cal = d.get("calibration")
    if cal is not None:
        pid_c = cal.get("profile_id")
        if not isinstance(pid_c, str) or not pid_c.strip():
            fail(
                "calibration",
                f"calibration annotation without a profile_id stamp ({pid_c!r})",
            )
        cal_edges = cal.get("edges")
        if not isinstance(cal_edges, dict) or not cal_edges:
            fail("calibration", "calibration annotation records no edge prices")
        else:
            from ..core import tiers as _cal_tiers

            for name in sorted(cal_edges):
                if name not in _cal_tiers.EDGES:
                    fail(
                        "calibration",
                        f"calibration price for unknown lattice edge {name!r}",
                    )
                    continue
                try:
                    bps_ok = float(cal_edges[name]) > 0
                except (TypeError, ValueError):
                    bps_ok = False
                if not bps_ok:
                    fail(
                        "calibration",
                        f"calibration edge {name!r} price {cal_edges[name]!r} "
                        "is not a positive bytes/s",
                    )
            if (
                topo is not None
                and cal_edges.get("ici")
                and cal_edges.get("dcn")
            ):
                want_pen = max(
                    1, int(float(cal_edges["ici"]) / float(cal_edges["dcn"]))
                )
                if int(topo.get("dcn_penalty", 0)) != want_pen:
                    fail(
                        "calibration",
                        f"topology dcn_penalty={topo.get('dcn_penalty')} != "
                        f"{want_pen}, the recorded ici/dcn price ratio — the "
                        "plan was priced under a different profile than it "
                        "is stamped with",
                    )

    # ---- progress: the collective-congruence replay (ISSUE 14) --------
    for _rule, defect in _progress_defects(d, steps, coll, p, strategy, topo):
        fail("progress", defect)

    # ---- tolerance: the error-bound recomputation (ISSUE 17) ----------
    for defect in _tolerance_defects(d, steps, quant, strategy, topo):
        fail("tolerance", defect)

    # ---- plan-id: the sha1 of the canonical serialization -------------
    if plan_id is not None:
        stripped = {k: v for k, v in d.items() if k != "plan_id"}
        canonical = json.dumps(stripped, sort_keys=True, separators=(",", ":"))
        want = hashlib.sha1(canonical.encode()).hexdigest()[:12]
        if want != plan_id:
            fail(
                "plan-id",
                f"plan_id {plan_id} != sha1 of the canonical serialization "
                f"({want}) — the plan was edited after stamping",
            )

    checks = [
        "step-kinds", "accounting", "quant-pairing", "tier-labels",
        "composition", "conservation", "overlap-structure", "staging",
        "calibration", "progress", "tolerance", "plan-id",
    ]
    return {
        "ok": not violations,
        "plan_id": plan_id,
        "strategy": strategy,
        "checks": checks,
        "violations": [
            {"invariant": v.invariant, "detail": v.detail} for v in violations
        ],
    }


# --------------------------------------------------------------------- #
# the progress replay (ISSUE 14 — pass 5's dynamic half)                #
# --------------------------------------------------------------------- #
def _progress_defects(
    d: Dict[str, Any],
    steps: List[Dict[str, Any]],
    coll: List[Dict[str, Any]],
    p: int,
    strategy: str,
    topo: Optional[Dict[str, Any]],
) -> List[Tuple[str, str]]:
    """Symbolically replay one schedule per device and return every way
    it fails to make progress, as ``(rule, detail)`` pairs (SL502 for
    incongruent group structure, SL503 for issue-order defects; empty =
    every participant runs the plan to completion). Pure arithmetic over
    the plan dict — no mesh, no jax."""
    defects: List[Tuple[str, str]] = []

    # group congruence: every tiered collective's implied subgroup
    # structure must partition the mesh — the hierarchical ici half
    # rides S groups of C chips, the dcn half C groups of S same-index
    # chips; both partition iff S·C == p
    if topo is not None:
        S, C = int(topo.get("n_slices", 0)), int(topo.get("chips_per_slice", 0))
        if S * C != p or S < 2 or C < 1:
            defects.append((
                "SL502",
                f"group congruence broken: topology {S}x{C} does not "
                f"partition the {p}-device mesh — the subgroup collectives "
                "can never match across participants",
            ))

    # ring closure: after hop d every device holds the block of the
    # member d positions behind it; the ring closes iff the p-1 hops
    # deliver all p distinct offsets
    if strategy == "ring":
        hops = [st for st in steps if st.get("kind") == "ppermute"]
        delivered = {0} | {(k + 1) % p for k in range(len(hops))}
        if len(hops) != p - 1 or len(delivered) != p:
            defects.append((
                "SL502",
                f"ring does not close: {len(hops)} hop(s) deliver "
                f"{len(delivered)} of the {p} blocks — exactly p-1={p - 1} "
                "hops close the ring; any other count leaves a device "
                "waiting on a block that never arrives",
            ))

    # hierarchical lap pairing: each lap's intra-slice (ici) and
    # inter-slice (dcn) halves must carry the SAME chunk index — a
    # split pair means one tier's exchange consumes a lap the other
    # tier has not issued. Paired BY TIER LABEL, not raw step index, so
    # an untiered collective (a warmup gather, a tail flush) can never
    # shift the pairing frame and false-fail every following lap
    if strategy == "hierarchical-a2a":
        ici = [st for st in coll if st.get("tier") == "ici"]
        dcn = [st for st in coll if st.get("tier") == "dcn"]
        if len(ici) != len(dcn):
            defects.append((
                "SL502",
                f"hierarchical lap pairing broken: {len(ici)} intra-slice "
                f"(ici) half(s) vs {len(dcn)} inter-slice (dcn) half(s) — "
                "every lap's ici pivot needs exactly one dcn exchange",
            ))
        else:
            for k, (si, sd) in enumerate(zip(ici, dcn)):
                ci, cd = si.get("chunk"), sd.get("chunk")
                if ci != cd:
                    defects.append((
                        "SL502",
                        f"hierarchical lap pairing broken: intra-slice half "
                        f"of lap {k} carries chunk {ci!r} but its "
                        f"inter-slice half carries chunk {cd!r} — the dcn "
                        "exchange would consume a lap the ici pivot has not "
                        "issued",
                    ))
                    break

    # depth-2 lap replay: each overlap group's tagged laps must be
    # issued in exactly the order the double buffer consumes them
    # (consume of lap k-1 happens at issue of lap k: any gap, dup, or
    # reorder makes the consume slot read an unissued buffer)
    overlap = d.get("overlap")
    if overlap:
        lap_mult = 2 if strategy == "hierarchical-a2a" else 1
        for g in overlap.get("groups") or []:
            tag = g.get("tag")
            tagged = [
                st
                for st in steps
                if st.get("kind") in _COLLECTIVE_KINDS and st.get("overlap") == tag
            ]
            units = [
                tagged[i * lap_mult : (i + 1) * lap_mult]
                for i in range(len(tagged) // lap_mult)
            ]
            for i, unit in enumerate(units):
                chunks = {u.get("chunk") for u in unit}
                if len(chunks) > 1:
                    defects.append((
                        "SL503",
                        f"overlap group {tag!r} lap {i} spans chunks "
                        f"{sorted(chunks, key=repr)} — one lap unit must be "
                        "one chunk",
                    ))
            lap_chunks = [u[0].get("chunk") for u in units if u]
            if any(c is not None for c in lap_chunks):
                want = list(range(len(units)))
                if lap_chunks != want:
                    defects.append((
                        "SL503",
                        f"overlap group {tag!r} issues laps in chunk order "
                        f"{lap_chunks} — the depth-2 double buffer consumes "
                        f"lap k-1 at issue of lap k, so the order must be "
                        f"{want}; as recorded, a consume slot would read an "
                        "unissued lap",
                    ))
    return defects


# --------------------------------------------------------------------- #
# the tolerance recomputation (ISSUE 17 — pass 6's dynamic half)        #
# --------------------------------------------------------------------- #
def _wire_claim(detail: str) -> Optional[str]:
    """The codec mode a collective step's detail claims (the planner's
    ``" [<mode> wire]"`` suffix), or None for an exact-bit wire."""
    for m in ("int8", "bf16"):
        if detail.endswith(f" [{m} wire]"):
            return m
    return None


def _tolerance_defects(d, steps, quant, strategy, topo) -> List[str]:
    """Every tolerance-budget defect of one plan dict, step-named.

    The recomputation: each ``quantize`` step contributes the codec's
    pinned ``tolerance(mode)`` to the payload leg it encodes (the lossy
    rounding happens at encode — the collective ships the encoded bits
    verbatim and the dequantize is exact given them); every other step
    kind — slice/concat/pack/unpack/reshape relayouts, staging
    transfers, overlap bookkeeping — is an exact-bit copy contributing
    0.0. Payload legs are disjoint: a pipelined exchange encodes each
    ``(overlap, chunk)`` lap once, a ring encodes each positional hop
    block once, and in a hierarchical plan only the ``tier="dcn"``
    crossings carry a codec at all (the PR 8 policy — the ICI pivot
    ships exact). ``compose_tolerance`` over a leg therefore yields
    exactly ``tolerance(mode)``, and the end-to-end bound — the max
    over disjoint legs — must equal the schedule-level ``quant.tol``
    annotation (0.0 with no annotation). Cross-iteration accumulation
    is the DP optimizer's error-feedback contract (the f32 EF carry in
    optim/dp_optimizer.py — rule SL603 guards its dtype), not a plan
    property.
    """
    defects: List[str] = []
    q_idx = [k for k, st in enumerate(steps) if st.get("kind") == "quantize"]
    claiming = [
        k
        for k, st in enumerate(steps)
        if st.get("kind") in _COLLECTIVE_KINDS
        and _wire_claim(st.get("detail") or "")
    ]
    mode = (quant or {}).get("mode")
    if not quant:
        # exact-bit plan: no collective may claim an encoded wire (the
        # codec-step census itself is quant-pairing's invariant)
        for k in claiming:
            defects.append(
                f"step [{k}] ({steps[k].get('kind')}) claims an encoded "
                f"wire ('{_wire_claim(steps[k].get('detail') or '')} wire') "
                "but the plan declares no quant annotation — an undeclared "
                "lossy crossing has no tolerance budget"
            )
        return defects
    if mode not in ("int8", "bf16"):
        return defects  # quant-pairing owns the mode vocabulary

    from ..kernels import quant as _quant

    step_tol = float(_quant.tolerance(mode))
    try:
        declared = float(quant.get("tol"))
    except (TypeError, ValueError):
        defects.append(
            f"quant annotation tol={quant.get('tol')!r} is not a number"
        )
        return defects
    if declared != step_tol:
        defects.append(
            f"quant annotation tol={declared!r} != the {mode} codec's "
            f"pinned tolerance {step_tol!r} (kernels.quant.tolerance) — "
            "the declared budget does not match what the codec guarantees"
        )

    sandwiched: List[int] = []
    for k in q_idx:
        st = steps[k]
        det = st.get("detail") or ""
        if not det.startswith(f"{mode}-encode wire blocks"):
            defects.append(
                f"step [{k}] (quantize) detail {det[:40]!r}... does not "
                f"record a {mode} encode — the step's tolerance "
                "contribution cannot be attributed to the declared codec"
            )
        nxt = steps[k + 1] if k + 1 < len(steps) else None
        if nxt is None or nxt.get("kind") not in _COLLECTIVE_KINDS:
            continue  # the sandwich structure itself is quant-pairing's
        if (
            nxt.get("chunk") != st.get("chunk")
            or nxt.get("overlap") != st.get("overlap")
        ):
            defects.append(
                f"step [{k}] (quantize) encodes leg "
                f"(overlap={st.get('overlap')!r}, chunk={st.get('chunk')!r}) "
                f"but the collective it feeds, step [{k + 1}] "
                f"({nxt.get('kind')}), ships "
                f"(overlap={nxt.get('overlap')!r}, chunk={nxt.get('chunk')!r}) "
                "— the encoded payload and the wire crossing disagree, so "
                "the per-leg composition is unprovable"
            )
        sandwiched.append(k + 1)
        ndet = nxt.get("detail") or ""
        if _wire_claim(ndet) != mode:
            defects.append(
                f"step [{k + 1}] ({nxt.get('kind')}) rides between a "
                f"quantize/dequantize pair but does not claim the "
                f"'[{mode} wire]' — the encoded crossing is unattributed"
            )
        if topo is not None and strategy == "hierarchical-a2a" and nxt.get("tier") != "dcn":
            defects.append(
                f"step [{k + 1}] ({nxt.get('kind')}, tier="
                f"{nxt.get('tier')!r}) carries the codec in a hierarchical "
                "plan — the codec policy charges only dcn-tier legs (the "
                "ICI pivot ships exact-bit), so an encoded "
                f"{nxt.get('tier')!r} crossing spends tolerance the "
                "annotation never budgeted"
            )
        nxt2 = steps[k + 2] if k + 2 < len(steps) else None
        if nxt2 is not None and nxt2.get("kind") == "dequantize":
            ddet = nxt2.get("detail") or ""
            if not ddet.startswith(f"{mode}-decode"):
                defects.append(
                    f"step [{k + 2}] (dequantize) detail {ddet[:40]!r}... "
                    f"does not record a {mode} decode — the round-trip "
                    "this leg's tolerance bound prices is not the one "
                    "recorded"
                )

    for k in claiming:
        if k not in sandwiched:
            defects.append(
                f"step [{k}] ({steps[k].get('kind')}) claims an encoded "
                "wire but is not quantize/dequantize-sandwiched — a "
                "crossing outside the codec pairing carries no budgeted "
                "tolerance"
            )

    # ---- per-leg composition: each disjoint payload leg crosses the
    # codec once, so compose_tolerance over its encodes must equal the
    # per-crossing pin; the end-to-end bound is the max over legs
    legs: Dict[Any, List[float]] = {}
    for k in q_idx:
        st = steps[k]
        tag, chunk = st.get("overlap"), st.get("chunk")
        if chunk is not None:
            key = (tag, chunk)
        elif tag is not None:
            nxt = steps[k + 1] if k + 1 < len(steps) else {}
            if nxt.get("kind") == "ppermute":
                key = (tag, "hop", k)  # ring hops ship disjoint blocks
            else:
                key = (tag, None)
        else:
            key = ("solo", k)  # standalone sandwich = its own phase
        legs.setdefault(key, []).append(step_tol)
    for key in sorted(legs, key=repr):
        if len(legs[key]) > 1:
            tag, chunk = key[0], key[1]
            defects.append(
                f"payload leg (overlap={tag!r}, chunk={chunk!r}) is "
                f"encoded {len(legs[key])} times — its composed bound "
                f"{_quant.compose_tolerance(legs[key])!r} exceeds the "
                f"declared per-crossing budget {declared!r} (double-encode)"
            )
    composed = max(
        (_quant.compose_tolerance(tols) for tols in legs.values()),
        default=0.0,
    )
    if q_idx and not defects and composed != declared:
        defects.append(
            f"end-to-end composed bound {composed!r} != the declared "
            f"quant.tol {declared!r}"
        )
    return defects


def check_tolerance(plan) -> list:
    """The plan-side tolerance-budget check (pass 6's dynamic half),
    standalone: recompute one plan's end-to-end error bound from its
    recorded per-step tolerances and return an error-severity SL605
    finding per defect — empty means the composed bound provably equals
    the schedule-level ``quant.tol`` annotation (0.0 for exact-bit
    plans). The same recomputation gates ``verify_plan`` under the
    ``tolerance`` invariant; this entry point mirrors
    :func:`check_progress` so the golden-dump sweeps (and the
    Newton–Schulz / MPMD tolerance-budget consumers the ROADMAP names)
    can collect findings instead of catching exceptions."""
    from .findings import Finding

    d = _as_plan_dict(plan)
    steps = list(d.get("steps") or [])
    defects = _tolerance_defects(
        d, steps, d.get("quant"), d.get("strategy", ""), d.get("topology")
    )
    plan_id = d.get("plan_id")
    return [
        Finding("SL605", "error", f"plan {plan_id}: {defect}")
        for defect in defects
    ]


def check_progress(plan) -> list:
    """The plan-side collective-congruence check (pass 5's dynamic
    half), standalone: replay one Schedule (or plan dict / canonical
    JSON line) per device and return error-severity findings (SL502
    for incongruent group structure, SL503 for issue-order defects) for
    every progress defect — empty means every participant provably runs
    the plan to completion. The same replay gates ``verify_plan`` under the
    ``progress`` invariant; this entry point mirrors
    :func:`~heat_tpu.analysis.effectcheck.check_plan_protocol` so the
    golden-plan sweeps (and the future MPMD stage-graph verifier) can
    collect findings instead of catching exceptions."""
    from .findings import Finding

    d = _as_plan_dict(plan)
    steps = list(d.get("steps") or [])
    coll = [st for st in steps if st.get("kind") in _COLLECTIVE_KINDS]
    p = int((d.get("spec") or {}).get("mesh_size", 1))
    defects = _progress_defects(
        d, steps, coll, p, d.get("strategy", ""), d.get("topology")
    )
    plan_id = d.get("plan_id")
    return [
        Finding(rule, "error", f"plan {plan_id}: {defect}")
        for rule, defect in defects
    ]
