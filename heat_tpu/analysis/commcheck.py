"""Pass 5: commcheck — SPMD collective congruence & progress.

Heat's MPI heritage makes the *mismatched collective* the canonical
failure mode, and on TPU it is not an error but a silent hang: a
``psum`` issued under a predicate that differs across devices, a
``ppermute`` whose pairs leave one device waiting for a block that
never leaves, two subgroup collectives whose issue order differs
between participants — each deadlocks the mesh with nothing on stderr.
PR 13's resilience layer can only *detect* that hang at runtime (the
epoch fence turns it into a typed ``WorldChangedError``); this pass
proves the congruence statically, before any TPU minute is spent, over
the same traced/compiled programs the other passes inspect:

========  ========  ====================================================
rule      severity  fires when
========  ========  ====================================================
SL501     error     divergent-collective: a ``lax.cond``/``while``
                    whose body (transitively) launches a collective is
                    predicated on a value NOT provably replicated across
                    the shard_map body's devices — devices branch apart
                    and the collective never matches (a replication
                    lattice over the jaxpr decides: sharded inputs and
                    ``axis_index`` vary, full-axis ``psum``/
                    ``all_gather`` results are uniform, elementwise ops
                    preserve uniformity)
SL502     error     incomplete-permute: a compiled collective whose
                    group structure is incongruent — ``ppermute``
                    ``source_target_pairs`` that are not a permutation
                    of the axis group (duplicate source/target, ids off
                    the mesh, receivers that never send), or
                    ``replica_groups`` that do not partition the mesh —
                    some device waits forever. The library's documented
                    ring schedules (``boundaries.RING_SCHEDULE_MODULES``)
                    and plan-stamped programs downgrade to info via the
                    existing SL101 machinery
SL503     warn/err  collective-order divergence: two collectives whose
                    inter-device issue order can differ. Error on a
                    cross-group dependency CYCLE in the per-axis-group
                    channel graph (the branches of a divergent ``cond``
                    issue matched collectives in opposite orders);
                    warning on unordered INDEPENDENT collectives whose
                    group partitions partially overlap (the compiler may
                    schedule them differently per participant) — info
                    when plan-stamped (the executor's pipelined laps are
                    ordered by the lap chain)
SL504     warning   unfenced-entry: an executor/dispatcher entry point
                    (``FENCED_DISPATCH_MODULES``) that issues
                    collectives without the PR 13 ``WorldChangedError``
                    epoch-fence check reachable on entry — the lint that
                    keeps future entry points failing *typed* instead of
                    hanging on a re-resolved world
========  ========  ====================================================

The IR rules (SL501–SL503) are folded into :func:`ht.analysis.check`
and available standalone as :func:`ht.analysis.commcheck(fn, *args)
<commcheck>`; the source rule (SL504) rides ``scripts/lint.py --pass
commcheck|all``. The dynamic half — the ``progress`` invariant proving
every *Schedule-IR plan*'s collective steps congruent (rings close in
exactly p-1 hops, hierarchical ici/dcn pairs partition the mesh,
depth-2 lap tags never consume an unissued lap) — lives in
:func:`ht.analysis.check_progress` / ``verify_plan`` and is swept over
every golden plan dump in ci.sh. Together they are the verifier the
ROADMAP's MPMD pipeline item requires ("``verify_plan`` proving the
stage graph") — built now, over every program the repo already ships.
"""

from __future__ import annotations

import ast
import os
import re

from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

from .findings import AnalysisReport, Finding
from .srclint import (
    _call_name,
    _iter_py_files,
    _pragmas_of,
    _suppressed,
    _Scope,
)

__all__ = [
    "FENCED_DISPATCH_MODULES",
    "commcheck",
    "lint_paths",
    "lint_source",
    "scan_hlo_congruence",
    "scan_jaxpr_divergence",
]


# --------------------------------------------------------------------- #
# the replication lattice (SL501 / SL503, jaxpr half)                   #
# --------------------------------------------------------------------- #
#: collectives whose FULL-AXIS result is identical on every participant
_UNIFORM_COLLECTIVES = frozenset(
    {"psum", "psum2", "pmax", "pmin", "all_gather", "all_gather_invariant"}
)
#: collectives whose result is per-device by construction
_VARYING_COLLECTIVES = frozenset(
    {"all_to_all", "ppermute", "psum_scatter", "reduce_scatter"}
)
_ALL_COLLECTIVES = _UNIFORM_COLLECTIVES | _VARYING_COLLECTIVES



def _is_literal(v) -> bool:
    return type(v).__name__ == "Literal"


def _sub_jaxprs(val):
    out = []
    vals = val if isinstance(val, (list, tuple)) else (val,)
    for v in vals:
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(v, "consts"):  # ClosedJaxpr
            out.append(inner)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            out.append(v)
    return out


def _count_collectives(jaxpr) -> int:
    n = 0
    todo, seen = [jaxpr], set()
    while todo:
        jx = todo.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            if eqn.primitive.name in _ALL_COLLECTIVES:
                n += 1
            for val in eqn.params.values():
                todo.extend(_sub_jaxprs(val))
    return n


def _groups_key(eqn) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Canonical group partition of a collective eqn: tuples of device
    indices from ``axis_index_groups`` (``perm`` pairs for ppermute read
    as their participant set per +d class is NOT reconstructed — the
    pair list itself is the key), ``None`` for the full axis."""
    name = eqn.primitive.name
    if name == "ppermute":
        perm = eqn.params.get("perm")
        return tuple((int(s), int(t)) for s, t in perm) if perm else None
    groups = eqn.params.get("axis_index_groups")
    if not groups:
        return None
    return tuple(tuple(int(i) for i in g) for g in groups)


def _partial_overlap(ka, kb) -> bool:
    """Do two group partitions overlap without being identical on the
    overlap — the shape where per-participant issue order can differ?"""
    if ka == kb or (ka is None and kb is None):
        return False
    sa = [frozenset(g) for g in ka] if ka is not None else []
    sb = [frozenset(g) for g in kb] if kb is not None else []
    if ka is None:
        sa = [frozenset().union(*sb)]  # the full axis covers b's devices
    if kb is None:
        sb = [frozenset().union(*sa)]
    for ga in sa:
        for gb in sb:
            if ga & gb and ga != gb:
                return True
    return False


class _Coll:
    __slots__ = ("eqn", "key", "stamped")

    def __init__(self, eqn, key, stamped):
        self.eqn = eqn
        self.key = key
        self.stamped = stamped


def _eqn_stamped(eqn) -> bool:
    # the stamp spellings are DEFINED once, in boundaries.py, next to
    # the named_scope emitters — reusing them here keeps the jaxpr-side
    # downgrade in lockstep with the HLO-side SL101/SL102 downgrade
    from .boundaries import _CMATMUL_MARKER, _PLAN_MARKER

    try:
        stack = str(eqn.source_info.name_stack)
        return bool(_PLAN_MARKER.search(stack) or _CMATMUL_MARKER.search(stack))
    except Exception:
        return False


class _RepInterp:
    """Replication-lattice interpreter over one shard_map body (and its
    nested calls): per-value fact = "provably identical on every device
    of the body's mesh axis". Emits SL501/SL503 findings."""

    def __init__(self, findings: List[Finding], label: str, quiet: bool = False):
        self.findings = findings if not quiet else []
        self.label = label
        self.quiet = quiet

    def _flag(self, finding: Finding) -> None:
        if not self.quiet:
            self.findings.append(finding)

    def run(self, jaxpr, in_facts: List[bool]) -> List[bool]:
        facts: Dict[int, bool] = {}
        for var, f in zip(jaxpr.invars, in_facts):
            facts[id(var)] = bool(f)

        def get(v) -> bool:
            if _is_literal(v):
                return True
            return facts.get(id(v), True)  # constvars: baked-in, uniform

        colls: List[_Coll] = []
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            out_fact: Optional[bool] = None
            if name == "axis_index":
                out_fact = False  # the device-identity source
            elif name in _UNIFORM_COLLECTIVES:
                # full-axis reductions/gathers are uniform; grouped ones
                # are uniform only WITHIN their group — conservatively
                # varying across the mesh
                out_fact = not eqn.params.get("axis_index_groups")
                colls.append(_Coll(eqn, _groups_key(eqn), _eqn_stamped(eqn)))
            elif name in _VARYING_COLLECTIVES:
                out_fact = False
                colls.append(_Coll(eqn, _groups_key(eqn), _eqn_stamped(eqn)))
            elif name == "cond":
                self._cond(eqn, get, facts)
                continue
            elif name == "while":
                self._while(eqn, get, facts)
                continue
            elif name == "scan":
                self._scan(eqn, get, facts)
                continue
            elif name in ("pjit", "closed_call", "core_call", "remat",
                          "checkpoint", "custom_jvp_call", "custom_vjp_call",
                          "custom_vjp_call_jaxpr"):
                sub = self._first_matching_sub(eqn)
                if sub is not None:
                    outs = self.run(sub, [get(v) for v in eqn.invars])
                    for var, f in zip(eqn.outvars, outs):
                        facts[id(var)] = f
                    continue
                out_fact = all(get(v) for v in eqn.invars)
            else:
                out_fact = all(get(v) for v in eqn.invars)
            for var in eqn.outvars:
                facts[id(var)] = bool(out_fact)

        self._order_divergence(jaxpr, colls)
        return [get(v) for v in jaxpr.outvars]

    # ------------------------------------------------------------------ #
    def _first_matching_sub(self, eqn):
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                if len(sub.invars) == len(eqn.invars):
                    return sub
        return None

    def _cond(self, eqn, get, facts) -> None:
        branches = [
            sub for val in (eqn.params.get("branches") or ()) for sub in _sub_jaxprs(val)
        ]
        pred_uniform = get(eqn.invars[0])
        op_facts = [get(v) for v in eqn.invars[1:]]
        n_coll = sum(_count_collectives(b) for b in branches)
        if n_coll and not pred_uniform:
            self._flag(
                Finding(
                    "SL501",
                    "error",
                    f"divergent collective{self._where()}: a cond/switch whose "
                    f"branches launch {n_coll} collective(s) is predicated on a "
                    "value not provably replicated across the shard_map devices "
                    "— devices branch apart and the collective never matches "
                    "(a silent hang on TPU). Make the predicate a full-axis "
                    "reduction (psum/pmax) of the local condition, or hoist "
                    "the collective out of the branch",
                    op="cond",
                )
            )
            # cross-group dependency cycle: matched collectives issued in
            # OPPOSITE orders by two branches — the per-axis-group channel
            # graph of the diverged mesh contains a cycle (A waits on B's
            # group, B waits on A's)
            sigs = []
            for b in branches:
                order = []
                todo = [b]
                while todo:
                    jx = todo.pop(0)
                    for beqn in jx.eqns:
                        if beqn.primitive.name in _ALL_COLLECTIVES:
                            order.append((beqn.primitive.name, _groups_key(beqn)))
                        for val in beqn.params.values():
                            todo.extend(_sub_jaxprs(val))
                sigs.append(order)
            reported = False
            for i in range(len(sigs)):
                for j in range(i + 1, len(sigs)):
                    if reported:
                        break
                    for x in sigs[i]:
                        for y in sigs[i]:
                            if x == y:
                                continue
                            if (
                                x in sigs[j]
                                and y in sigs[j]
                                and sigs[i].index(x) < sigs[i].index(y)
                                and sigs[j].index(x) > sigs[j].index(y)
                            ):
                                self._flag(
                                    Finding(
                                        "SL503",
                                        "error",
                                        f"collective-order divergence{self._where()}: "
                                        f"branches of a divergent cond issue {x[0]} "
                                        f"and {y[0]} in OPPOSITE orders — a "
                                        "cross-group dependency cycle in the "
                                        "per-axis-group channel graph: devices "
                                        "taking different branches each wait for "
                                        "the collective the other has not issued "
                                        "yet (deadlock)",
                                        op="cond",
                                    )
                                )
                                reported = True
                                break
                        if reported:
                            break
        # branch outputs: uniform only if the predicate is uniform AND
        # every branch produces a uniform value at that position
        branch_outs = [self.run(b, list(op_facts)) for b in branches] or [[]]
        for k, var in enumerate(eqn.outvars):
            per_branch = [outs[k] for outs in branch_outs if k < len(outs)]
            facts[id(var)] = bool(pred_uniform and per_branch and all(per_branch))

    def _while(self, eqn, get, facts) -> None:
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond_jx = (_sub_jaxprs(eqn.params.get("cond_jaxpr")) or [None])[0]
        body_jx = (_sub_jaxprs(eqn.params.get("body_jaxpr")) or [None])[0]
        cc = [get(v) for v in eqn.invars[:cn]]
        bc = [get(v) for v in eqn.invars[cn : cn + bn]]
        carry = [get(v) for v in eqn.invars[cn + bn :]]
        if body_jx is not None:
            probe = _RepInterp(self.findings, self.label, quiet=True)
            for _ in range(len(carry) + 2):  # monotone: falls only downward
                nxt = probe.run(body_jx, bc + carry)
                nxt = [a and b for a, b in zip(carry, nxt + carry[len(nxt) :])]
                if nxt == carry:
                    break
                carry = nxt
        pred_uniform = True
        if cond_jx is not None:
            probe = _RepInterp(self.findings, self.label, quiet=True)
            outs = probe.run(cond_jx, cc + carry)
            pred_uniform = bool(outs[0]) if outs else True
        n_coll = sum(_count_collectives(jx) for jx in (cond_jx, body_jx) if jx is not None)
        if n_coll and not pred_uniform:
            self._flag(
                Finding(
                    "SL501",
                    "error",
                    f"divergent collective{self._where()}: a while-loop whose "
                    f"body launches {n_coll} collective(s) has a continuation "
                    "predicate not provably replicated across the shard_map "
                    "devices — devices exit the loop on different iterations "
                    "and the next collective never matches (a silent hang on "
                    "TPU). Reduce the local condition with a full-axis "
                    "psum/pmax so every device agrees on the trip count",
                    op="while",
                )
            )
        if body_jx is not None:
            # final, finding-emitting pass over the stabilized facts
            self.run(body_jx, bc + carry)
        # a divergent predicate means per-device trip counts: even a
        # uniformity-preserving carry (a loop counter) diverges
        for var, f in zip(eqn.outvars, carry + [True] * len(eqn.outvars)):
            facts[id(var)] = bool(f and pred_uniform)

    def _scan(self, eqn, get, facts) -> None:
        sub = (_sub_jaxprs(eqn.params.get("jaxpr")) or [None])[0]
        if sub is None:
            for var in eqn.outvars:
                facts[id(var)] = all(get(v) for v in eqn.invars)
            return
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        consts = [get(v) for v in eqn.invars[:nc]]
        carry = [get(v) for v in eqn.invars[nc : nc + ncar]]
        xs = [get(v) for v in eqn.invars[nc + ncar :]]
        probe = _RepInterp(self.findings, self.label, quiet=True)
        for _ in range(ncar + 2):
            outs = probe.run(sub, consts + carry + xs)
            nxt = [a and b for a, b in zip(carry, outs[:ncar])]
            if nxt == carry:
                break
            carry = nxt
        outs = self.run(sub, consts + carry + xs)  # findings pass
        ys = outs[ncar:]
        for k, var in enumerate(eqn.outvars):
            facts[id(var)] = bool(outs[k]) if k < ncar else bool(
                ys[k - ncar] if k - ncar < len(ys) else True
            )

    # ------------------------------------------------------------------ #
    def _order_divergence(self, jaxpr, colls: List[_Coll]) -> None:
        """SL503, straight-line arm: two INDEPENDENT collectives of this
        body whose group partitions partially overlap — the compiler is
        free to schedule them in different orders on different
        participants. Dependence is the dataflow closure within this
        jaxpr (conservative: an unreachable producer means independent)."""
        if len(colls) < 2:
            return
        producers = {}
        for idx, eqn in enumerate(jaxpr.eqns):
            for ov in eqn.outvars:
                producers[id(ov)] = (idx, eqn)
        pos = {id(c.eqn): k for k, c in enumerate(colls)}

        def depends(b_eqn, a_eqn) -> bool:
            stack = [v for v in b_eqn.invars if not _is_literal(v)]
            seen: Set[int] = set()
            while stack:
                v = stack.pop()
                if id(v) in seen:
                    continue
                seen.add(id(v))
                hit = producers.get(id(v))
                if hit is None:
                    continue
                _, src = hit
                if src is a_eqn:
                    return True
                stack.extend(u for u in src.invars if not _is_literal(u))
            return False

        reported: Set[Tuple] = set()
        for i in range(len(colls)):
            for j in range(i + 1, len(colls)):
                a, b = colls[i], colls[j]
                if not _partial_overlap(a.key, b.key):
                    continue
                if depends(b.eqn, a.eqn):
                    continue
                sig = (a.eqn.primitive.name, a.key, b.eqn.primitive.name, b.key)
                if sig in reported:
                    continue
                reported.add(sig)
                severity = "info" if (a.stamped or b.stamped) else "warning"
                blessing = (
                    " (plan-stamped: the executor's lap chain orders them)"
                    if severity == "info"
                    else ""
                )
                self._flag(
                    Finding(
                        "SL503",
                        severity,
                        f"collective-order divergence{self._where()}: independent "
                        f"{a.eqn.primitive.name} and {b.eqn.primitive.name} ride "
                        "PARTIALLY overlapping group partitions with no dataflow "
                        "ordering between them — participants shared by unequal "
                        "groups may observe the two collectives in different "
                        "issue orders; sequence them explicitly (dataflow or "
                        f"optimization_barrier) or align their groups{blessing}",
                        op=b.eqn.primitive.name,
                    )
                )

    def _where(self) -> str:
        return f" in {self.label}" if self.label else ""


def scan_jaxpr_divergence(closed, label: str = "") -> List[Finding]:
    """Rules SL501/SL503 over one (closed) jaxpr: find every
    ``shard_map`` body — the level where per-device values and explicit
    collectives live — and run the replication-lattice interpreter over
    it. Outside shard_map the partitioner keeps control flow globally
    consistent, so only manual SPMD bodies are candidates. Returns
    findings (empty = congruent)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    findings: List[Finding] = []
    todo, seen = [jaxpr], set()
    while todo:
        jx = todo.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            if eqn.primitive.name == "shard_map":
                body = None
                for val in eqn.params.values():
                    subs = _sub_jaxprs(val)
                    if subs:
                        body = subs[0]
                        break
                if body is None:
                    continue
                in_names = eqn.params.get("in_names") or ()
                in_facts = [
                    not (in_names[k] if k < len(in_names) else {})
                    for k in range(len(body.invars))
                ]
                _RepInterp(findings, label).run(body, in_facts)
                todo.append(body)  # nested shard_maps still walked
            else:
                for val in eqn.params.values():
                    todo.extend(_sub_jaxprs(val))
    return findings


# --------------------------------------------------------------------- #
# SL502 — group congruence of the compiled collectives (HLO half)       #
# --------------------------------------------------------------------- #
def scan_hlo_congruence(text: str) -> List[Finding]:
    """Rule SL502 over one compiled module's text: every collective
    line's group structure must be congruent — ``source_target_pairs`` a
    permutation of the axis group, ``replica_groups`` a partition of the
    mesh (``num_partitions``). Ring-module and plan-stamped lines
    downgrade to info through the same ``boundaries`` machinery SL101
    uses; everything else is an error — the incongruent collective is a
    hang, not a wrong answer."""
    from ..observability.hlo import _COLLECTIVE_LINE, _shaped_bytes
    from ._groups import (
        parse_replica_groups,
        parse_source_target_pairs,
        partition_defect,
        permutation_defect,
    )
    from .boundaries import planned_reshard_plan_id, ring_schedule_module

    findings: List[Finding] = []
    m_parts = re.search(r"num_partitions=(\d+)", text)
    n_dev = int(m_parts.group(1)) if m_parts else None
    seen: Set[Tuple[str, str, bool]] = set()
    for m in _COLLECTIVE_LINE.finditer(text):
        ssa, result_type, op = m.group(1), m.group(2), m.group(3)
        line_end = text.find("\n", m.end())
        full_line = text[m.start() : len(text) if line_end == -1 else line_end]
        if op == "collective-permute":
            pairs = parse_source_target_pairs(full_line)
            defect = permutation_defect(pairs, n_dev) if pairs else None
        else:
            grps = parse_replica_groups(full_line)
            defect = partition_defect(grps, n_dev) if grps else None
        if defect is None:
            continue
        stamp = planned_reshard_plan_id(full_line)
        blessed = ring_schedule_module(full_line)
        # dedup WITHIN a severity class only — a blessed/stamped line
        # must never mask a later hand-rolled hang with the same defect
        key = (op, defect, bool(stamp or blessed))
        if key in seen:
            continue
        seen.add(key)
        nbytes = _shaped_bytes(result_type)
        if stamp or blessed:
            kind = "plan-stamped schedule" if stamp else "documented ring schedule"
            findings.append(
                Finding(
                    "SL502",
                    "info",
                    f"incongruent-looking {op} in a {kind} "
                    f"({stamp or blessed}): {defect} — the module's own "
                    "block rotation/exchange; verified by its plan "
                    "contract, reported for the audit trail",
                    op=op,
                    nbytes=nbytes,
                )
            )
            continue
        findings.append(
            Finding(
                "SL502",
                "error",
                f"incomplete permute/partition: {op} ({ssa}, ~{nbytes} B) — "
                f"{defect}. On TPU this is a silent hang: the unmatched "
                "device waits forever. Close the ring "
                "(kernels.cmatmul.grouped_ring_perm builds complete grouped "
                "permutations) or make the groups partition the mesh",
                op=op,
                nbytes=nbytes,
            )
        )
    return findings


# --------------------------------------------------------------------- #
# the standalone pass runner (SL501-SL503, IR half)                     #
# --------------------------------------------------------------------- #
def commcheck(fn, *args, mesh=None, **kwargs) -> AnalysisReport:
    """Statically prove the collective congruence of the program
    ``fn(*args, **kwargs)`` compiles to (same argument contract as
    :func:`ht.analysis.check`; compile-only, nothing executes). Runs the
    SL501/SL503 replication-lattice walk over the jaxpr and the SL502
    group-congruence scan over the compiled HLO. The same scans are
    folded into :func:`ht.analysis.check`; this entry point runs pass 5
    alone (cheaper, and the report context carries the pass name the
    MPMD stage-graph annotation will consume)."""
    import numpy as np

    from ..observability.hlo import _count_ops
    from .ircheck import _lower_checked

    findings: List[Finding] = []
    context: Dict[str, Any] = {"pass": "commcheck"}
    if mesh is not None:
        context["mesh_devices"] = int(np.asarray(mesh.devices).size)

    lowered = _lower_checked(fn, args, kwargs, findings)
    if lowered is None:
        return AnalysisReport(findings, context)
    closed, compiled = lowered

    label = getattr(fn, "__name__", "") or ""
    findings += scan_jaxpr_divergence(closed, label=label)
    text = compiled.as_text()
    context["collective_counts"] = {k: v for k, v in _count_ops(text).items() if v}
    findings += scan_hlo_congruence(text)
    findings.sort(key=lambda f: ({"error": 0, "warning": 1, "info": 2}[f.severity], f.rule))
    return AnalysisReport(findings, context)


# --------------------------------------------------------------------- #
# SL504 — unfenced dispatch entry (source half)                         #
# --------------------------------------------------------------------- #
#: the executor/dispatcher layer — modules whose entry points issue
#: collectives on behalf of callers and must therefore carry the PR 13
#: epoch fence (``elastic.check_world``/``check_epoch``) on every entry
#: path: a dispatch racing a world re-resolution fails TYPED instead of
#: hanging on devices that are gone. Scoped, like PLANNER_MODULES — a
#: public library op (``ht.sum``) is not a dispatch entry; the executor
#: fences for it. tests pin the population.
FENCED_DISPATCH_MODULES: Tuple[str, ...] = (
    "redistribution/executor.py",
    "serving/dispatcher.py",
)

#: the fence spellings the rule recognizes (resilience/elastic.py)
_FENCE_NAMES: FrozenSet[str] = frozenset({"check_world", "check_epoch"})

#: lax collective launchers — reaching one of these means the closure
#: issues mesh collectives directly
_LAUNCH_ATTRS: FrozenSet[str] = frozenset(
    {"all_to_all", "ppermute", "psum", "all_gather", "psum_scatter",
     "pmax", "pmin", "reduce_scatter"}
)


def _issues_collectives(fn_node: ast.AST) -> bool:
    """Does a function body contain a collective ISSUE SITE: a lax
    collective launch, a compiled-program invocation (the executor's
    ``_*_program(...)(phys)`` shape), or a program-table dispatch (the
    serving ``self.programs[bucket](...)`` shape)?"""
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _LAUNCH_ATTRS:
            return True
        if isinstance(f, ast.Call) and _call_name(f.func).endswith("_program"):
            return True
        if isinstance(f, ast.Subscript):
            base = f.value
            name = base.attr if isinstance(base, ast.Attribute) else getattr(base, "id", "")
            if name == "programs":
                return True
    return False


def _fences(fn_node: ast.AST) -> bool:
    return any(
        isinstance(node, ast.Call) and _call_name(node.func) in _FENCE_NAMES
        for node in ast.walk(fn_node)
    )


def _closure_nodes(
    root_name: str,
    mod_fns: Dict[str, ast.FunctionDef],
    methods: Optional[Dict[str, ast.FunctionDef]] = None,
) -> List[ast.FunctionDef]:
    """The intra-module call closure of one entry: bare-name calls onto
    module functions plus ``self.m(...)`` edges within the class — the
    same reachability SL402 uses."""
    start = (methods or {}).get(root_name) or mod_fns.get(root_name)
    if start is None:
        return []
    out: List[ast.FunctionDef] = []
    seen: Set[str] = {root_name}
    todo = [start]
    while todo:
        cur = todo.pop()
        out.append(cur)
        for node in ast.walk(cur):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name) and node.func.id in mod_fns:
                callee = mod_fns[node.func.id]
                key = node.func.id
            elif (
                methods
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in methods
            ):
                callee = methods[node.func.attr]
                key = node.func.attr
            if callee is not None and key not in seen:
                seen.add(key)
                todo.append(callee)
    return out


def lint_source(src: str, rel: str) -> List[Finding]:
    """Rule SL504 over one module (only :data:`FENCED_DISPATCH_MODULES`
    are in scope): every ENTRY — a public module-level function, a
    public method, or a worker-thread root — whose intra-module closure
    issues collectives must reach an epoch-fence call in that closure."""
    rel = rel.replace("\\", "/")
    if not any(rel.endswith(sfx) for sfx in FENCED_DISPATCH_MODULES):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("SL201", "error", f"unparseable module: {e}", path=rel, line=e.lineno)]
    pragmas = _pragmas_of(src)
    findings: List[Finding] = []
    mod_fns = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}

    def check_entry(name: str, node: ast.FunctionDef, methods=None, cls=None) -> None:
        closure = _closure_nodes(name, mod_fns, methods)
        if not closure:
            return
        if not any(_issues_collectives(fn) for fn in closure):
            return
        if any(_fences(fn) for fn in closure):
            return
        stack = (cls.name, name) if cls is not None else (name,)
        lines = (cls.lineno, node.lineno) if cls is not None else (node.lineno,)
        scope = _Scope(stack, lines)
        if _suppressed("SL504", node.lineno, scope, pragmas):
            return
        where = ".".join(stack)
        findings.append(
            Finding(
                "SL504",
                "warning",
                f"unfenced dispatch entry {where!r}: this executor/dispatcher "
                "path issues collectives with no WorldChangedError epoch-fence "
                "(elastic.check_world / check_epoch) reachable on entry — work "
                "dispatched across a world re-resolution hangs on devices that "
                "are gone instead of failing typed. Fence the entry (see "
                "redistribution/executor.execute), or declare the design with "
                "`# shardlint: ignore[SL504] -- reason`",
                path=rel,
                line=node.lineno,
            )
        )

    for name, node in mod_fns.items():
        if not name.startswith("_"):
            check_entry(name, node)
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        methods = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
        worker_roots: Set[str] = set()
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and _call_name(node.func) == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                            if (
                                isinstance(kw.value.value, ast.Name)
                                and kw.value.value.id == "self"
                                and kw.value.attr in methods
                            ):
                                worker_roots.add(kw.value.attr)
        for name, node in methods.items():
            public = not name.startswith("_") and name != "__init__"
            if public or name in worker_roots:
                check_entry(name, node, methods=methods, cls=cls)
    findings.sort(key=lambda f: (f.path or "", f.line or 0, f.rule))
    return findings


def lint_paths(paths, root: Optional[str] = None) -> AnalysisReport:
    """Pass 5's source half over every ``.py`` file under ``paths`` (the
    commcheck face of ``scripts/lint.py``)."""
    root = os.path.abspath(root or os.getcwd())
    findings: List[Finding] = []
    n_files = 0
    for path in paths:
        for fp in _iter_py_files(path):
            n_files += 1
            rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
            # only the fenced-dispatch modules are in scope — skipping
            # the rest BEFORE open() keeps `--pass all` from paying a
            # third full-tree read for a two-module rule
            if not any(rel.endswith(sfx) for sfx in FENCED_DISPATCH_MODULES):
                continue
            with open(fp, encoding="utf-8") as f:
                src = f.read()
            findings += lint_source(src, rel)
    return AnalysisReport(findings, context={"files": n_files, "pass": "commcheck"})
