"""heat_tpu — a TPU-native distributed tensor framework.

Capabilities of the reference Heat framework (distributed NumPy/SciPy/
scikit-learn-style computing; /root/reference/heat/__init__.py), re-designed
single-controller on JAX/XLA: the array is a ``jax.Array`` with a GSPMD
``NamedSharding`` derived from its ``split`` axis, communication lowers to
XLA collectives over the ICI/DCN mesh, and one process drives the device
population.

Usage mirrors the reference::

    import heat_tpu as ht
    x = ht.arange(10, split=0)
    print(ht.sum(x))
"""

# 64-bit dtype support is a PLATFORM POLICY, not an import side effect:
# CPU/GPU worlds enable JAX's x64 mode on first backend use (full
# float64/int64 parity with the reference); TPU worlds keep it off and
# degrade 64-bit dtype requests to 32-bit (the chip has no 64-bit
# arithmetic). Override explicitly with ``ht.use_x64(True/False)``.
# See core/devices.py:_apply_x64_policy.
#
# Complex dtypes are the same kind of policy: native on CPU/GPU; on TPU
# plugins (whose XLA backend rejects complex buffers — and poisons the
# process on the first enqueued complex op) complex DNDarrays run in
# PLANAR form — split real/imaginary f32 planes computed by ordinary XLA
# programs (core/complex_planar.py). Ops outside the documented planar
# surface raise an actionable TypeError instead of computing wrong
# results. ``ht.use_complex(True)`` forces native complex (for a TPU
# runtime that implements it), ``ht.use_complex(False)`` restores the
# fail-at-creation refusal. See core/devices.py:complex_mode.

from .core import *
from .core.linalg import *

from . import core
from . import analysis
from . import classification
from . import cluster
from . import graph
from . import kernels
from . import naive_bayes
from . import nn
from . import observability
from . import optim
from . import preprocessing
from . import redistribution
from . import regression
from . import resilience
from . import serving
from . import sparse
from . import spatial
from . import utils
from . import datasets
from .observability import telemetry
from .observability import tracing
from .version import __version__


def __getattr__(name):
    """Lazy ``tpu``/``gpu`` device singletons: platform probing is deferred
    past import so ``init_distributed`` can run first (see core.devices)."""
    if name in ("tpu", "gpu"):
        from .core import devices as _devices

        return getattr(_devices, name)
    raise AttributeError(f"module 'heat_tpu' has no attribute {name!r}")
