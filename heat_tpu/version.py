"""Version information for heat_tpu.

Mirrors the role of ``heat/core/version.py`` in the reference
(/root/reference/heat/core/version.py): single source of the package version.
"""

major: int = 0
"""Major version (API-incompatible changes)."""
minor: int = 1
"""Minor version (backward-compatible features)."""
micro: int = 0
"""Micro version (bug fixes)."""
extension: str = "dev"
"""Pre-release tag."""

if not extension:
    __version__: str = f"{major}.{minor}.{micro}"
else:
    __version__: str = f"{major}.{minor}.{micro}-{extension}"
