"""Optimizer layer of heat_tpu.

Parity with /root/reference/heat/optim/__init__.py: ``DataParallelOptimizer``
and ``DASO`` (dp_optimizer.py:851/:64), ``lr_scheduler`` and plateau
utilities. Local optimizers (SGD/Adam/AdamW) are optax-backed; unknown
attributes fall through to ``optax`` (the analog of the reference's
torch.optim delegation).
"""

from .dp_optimizer import SGD, Adam, AdamW, DataParallelOptimizer, DASO, LocalOptimizer
from .utils import DetectMetricPlateau
from . import lr_scheduler
from . import utils

__all__ = [
    "SGD",
    "Adam",
    "AdamW",
    "LocalOptimizer",
    "DataParallelOptimizer",
    "DASO",
    "DetectMetricPlateau",
    "lr_scheduler",
    "utils",
]


def __getattr__(name):
    import optax as _optax

    try:
        return getattr(_optax, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.optim' has no attribute '{name}'")
