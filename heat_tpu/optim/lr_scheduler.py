"""Learning-rate schedulers.

The reference wraps ``torch.optim.lr_scheduler`` wholesale
(/root/reference/heat/optim/lr_scheduler.py:9: module-level pass-through)
so any torch scheduler drives a ``DataParallelOptimizer``. Here the
optimizers keep their learning rate as a mutable hyperparameter in the
optax state (``inject_hyperparams``), and schedulers mutate it through
``optimizer.set_lr`` — same call pattern (``scheduler.step()`` after each
epoch/batch), TPU-native state.
"""

from __future__ import annotations

from .utils import DetectMetricPlateau

__all__ = ["StepLR", "ExponentialLR", "ReduceLROnPlateau"]


class _Scheduler:
    def __init__(self, optimizer):
        if not hasattr(optimizer, "set_lr") or not hasattr(optimizer, "lr"):
            raise TypeError("optimizer must expose lr/set_lr (DataParallelOptimizer)")
        self.optimizer = optimizer
        self.base_lr = optimizer.lr
        self.last_epoch = 0

    def get_last_lr(self):
        return [self.optimizer.lr]

    def step(self, *args) -> None:
        self.last_epoch += 1
        self._apply(*args)

    def _apply(self, *args) -> None:
        raise NotImplementedError


class StepLR(_Scheduler):
    """Decay lr by ``gamma`` every ``step_size`` steps (torch StepLR)."""

    def __init__(self, optimizer, step_size: int, gamma: float = 0.1):
        super().__init__(optimizer)
        self.step_size = int(step_size)
        self.gamma = float(gamma)

    def _apply(self) -> None:
        self.optimizer.set_lr(self.base_lr * self.gamma ** (self.last_epoch // self.step_size))


class ExponentialLR(_Scheduler):
    """Decay lr by ``gamma`` every step (torch ExponentialLR)."""

    def __init__(self, optimizer, gamma: float):
        super().__init__(optimizer)
        self.gamma = float(gamma)

    def _apply(self) -> None:
        self.optimizer.set_lr(self.base_lr * self.gamma ** self.last_epoch)


class ReduceLROnPlateau(_Scheduler):
    """Reduce lr when a metric plateaus (torch ReduceLROnPlateau; detector
    shared with DASO — reference optim/utils.py:14)."""

    def __init__(self, optimizer, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4,
                 threshold_mode: str = "rel", min_lr: float = 0.0):
        super().__init__(optimizer)
        self.factor = float(factor)
        self.min_lr = float(min_lr)
        self.detector = DetectMetricPlateau(mode, patience, threshold, threshold_mode)

    def _apply(self, metric) -> None:
        if self.detector.test_if_improving(metric):
            self.optimizer.set_lr(max(self.optimizer.lr * self.factor, self.min_lr))
