"""Optimizer utilities.

Parity with /root/reference/heat/optim/utils.py: ``DetectMetricPlateau``
(utils.py:14) — the plateau detector DASO's skip schedule consults, with
``get_state``/``set_state`` capture (utils.py:72/89, the reference's only
optimizer-state checkpoint hooks).
"""

from __future__ import annotations

from typing import Any, Dict

__all__ = ["DetectMetricPlateau"]


class DetectMetricPlateau:
    """Detects whether a tracked metric has stopped improving (reference
    utils.py:14; semantics follow torch's ReduceLROnPlateau detection).

    Parameters
    ----------
    mode : 'min' or 'max'
    patience : int
        Number of checks with no improvement before a plateau is declared.
    threshold : float
        Minimum relative change to count as an improvement.
    threshold_mode : 'rel' or 'abs'
    """

    def __init__(self, mode: str = "min", patience: int = 10,
                 threshold: float = 1e-4, threshold_mode: str = "rel"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode}")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError(f"threshold_mode must be 'rel' or 'abs', got {threshold_mode}")
        self.mode = mode
        self.patience = int(patience)
        self.threshold = float(threshold)
        self.threshold_mode = threshold_mode
        self.reset()

    def reset(self) -> None:
        self.best = float("inf") if self.mode == "min" else -float("inf")
        self.num_bad_epochs = 0

    def is_better(self, a: float, best: float) -> bool:
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < best * (1.0 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1.0 + self.threshold)
        return a > best + self.threshold

    def test_if_improving(self, metric) -> bool:
        """Record ``metric``; return True when a plateau is detected
        (reference utils.py:103: resets the counter on detection)."""
        current = float(metric)
        if self.is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.num_bad_epochs = 0
            return True
        return False

    def get_state(self) -> Dict[str, Any]:
        """Capture detector state (reference utils.py:72)."""
        return {
            "mode": self.mode,
            "patience": self.patience,
            "threshold": self.threshold,
            "threshold_mode": self.threshold_mode,
            "best": self.best,
            "num_bad_epochs": self.num_bad_epochs,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        """Restore detector state (reference utils.py:89)."""
        for k, v in state.items():
            setattr(self, k, v)
