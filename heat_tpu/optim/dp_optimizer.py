"""Data-parallel optimizers.

Replaces /root/reference/heat/optim/dp_optimizer.py:

- ``DataParallelOptimizer`` (reference :851-894): wraps a local optimizer
  for synchronous data parallelism. The reference defers ``step()`` under
  its non-blocking hook scheme; here one jitted train step fuses forward,
  backward, gradient all-reduce (inserted by GSPMD: the batch is sharded
  along axis 0, parameters are replicated, so the gradient of a global-mean
  loss lowers to one fused all-reduce over the mesh) and the optimizer
  update. Blocking vs non-blocking is moot — XLA overlaps the collective
  with compute.
- Quantized-gradient DP (ISSUE 7, opt-in ``wire_quant="int8"/"bf16"``):
  the gradient all-reduce decomposes into the block-quantized wire form
  of ``heat_tpu.kernels.quant`` — quantize the local contribution (plus
  the error-feedback carry), ship int8 blocks through ONE all-to-all
  (the reduce-scatter leg: each device decodes and sums the p partials
  of its block full-width) and ONE all-gather of the re-encoded reduced
  blocks, then dequantize. Wire bytes drop to ``wire_ratio`` (~0.25
  int8 / 0.5 bf16) of the psum's, which on the analytic v5e-64 model
  converts ≥1.5× of step time on ICI-bound layers
  (``kernels.quant.dp_step_model``); the per-device error-feedback
  carry re-injects the compression error next step, so the long-run
  gradient is unbiased (EQuARX, arXiv:2506.17615).
- ``DASO`` (reference :64-850): hierarchical/asynchronous DP. The
  reference runs node-local torch-DDP every batch and staggers global MPI
  syncs across "skip batches" with bf16-compressed buffers and custom MPI
  ops for half types (:21-62). Here the hierarchy is a two-level
  ``Mesh(("node", "local"))``: parameters carry a leading node axis sharded
  over ``"node"`` (each node owns a divergent copy — the single-controller
  representation of per-node model replicas), every step psums gradients
  over ``"local"`` only, and every ``global_skip``-th step additionally
  psum-averages the PARAMETERS over ``"node"``, optionally cast to
  bfloat16 for the wire (the reference's compression, :21-62). The skip
  schedule adapts via ``epoch_loss_logic`` (reference :354).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..core._jax_compat import shard_map

from typing import Optional

from ..core.dndarray import DNDarray
from ..nn.modules import CrossEntropyLoss, scalar_dndarray

__all__ = ["SGD", "Adam", "AdamW", "DataParallelOptimizer", "DASO"]


# --------------------------------------------------------------------- #
# local optimizers (optax-backed; lr lives in state via inject_hyperparams
# so lr_scheduler can mutate it)                                        #
# --------------------------------------------------------------------- #
class LocalOptimizer:
    """A local (per-replica) gradient transformation — the role torch
    optimizers play in the reference (any torch.optim.Optimizer instance,
    dp_optimizer.py:868)."""

    def __init__(self, tx, defaults: dict):
        self.tx = tx
        self.defaults = dict(defaults)


class SGD(LocalOptimizer):
    def __init__(self, lr: float = 0.01, momentum: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False):
        import optax

        # momentum/weight_decay structure is decided statically so plain SGD
        # carries no dead trace accumulator or no-op decay stage
        mom = momentum if momentum else None

        def sgd_part(learning_rate):
            return optax.sgd(learning_rate, momentum=mom, nesterov=nesterov)

        if weight_decay:
            def make(learning_rate, weight_decay):
                return optax.chain(optax.add_decayed_weights(weight_decay),
                                   sgd_part(learning_rate))

            tx = optax.inject_hyperparams(make)(learning_rate=lr, weight_decay=weight_decay)
        else:
            tx = optax.inject_hyperparams(sgd_part)(learning_rate=lr)
        super().__init__(tx, dict(lr=lr, momentum=momentum, weight_decay=weight_decay))


class Adam(LocalOptimizer):
    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0):
        import optax

        b1, b2 = betas

        def adam_part(learning_rate):
            return optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)

        if weight_decay:
            def make(learning_rate, weight_decay):
                return optax.chain(optax.add_decayed_weights(weight_decay),
                                   adam_part(learning_rate))

            tx = optax.inject_hyperparams(make)(learning_rate=lr, weight_decay=weight_decay)
        else:
            tx = optax.inject_hyperparams(adam_part)(learning_rate=lr)
        super().__init__(tx, dict(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay))


class AdamW(LocalOptimizer):
    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 1e-2):
        import optax

        b1, b2 = betas
        tx = optax.inject_hyperparams(
            lambda learning_rate, weight_decay: optax.adamw(
                learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay
            )
        )(learning_rate=lr, weight_decay=weight_decay)
        super().__init__(tx, dict(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay))


_loss_scalar = scalar_dndarray


def _aligned_labels(x: DNDarray, y: DNDarray) -> jax.Array:
    """Physical labels row-aligned with x's physical batch. A replicated
    y against a SHARDED x differs in physical extent whenever the batch
    pads (surfaced by the odd-mesh CI leg: 512 rows over 5 devices pad to
    515 on the sharded side only) — resplitting y to x.split pads it
    identically; the pad rows are masked by the step's validity weight.
    Gated on the EXTENTS, not the splits: when they already match (the
    common evenly-divisible case) the raw buffer passes through free and
    jit reshards it inside the step."""
    if y._phys.shape[0] != x._phys.shape[0]:
        y = y.resplit(x.split)
    return y._phys


class DataParallelOptimizer:
    """Synchronous data-parallel optimizer (reference dp_optimizer.py:851).

    Parameters
    ----------
    local_optimizer : LocalOptimizer
        SGD/Adam/AdamW (or any optax GradientTransformation wrapped in
        LocalOptimizer).
    model : heat_tpu.nn.DataParallel
        The wrapped model whose parameters this optimizer advances.
    loss : loss object with ``raw(output, target, weight)``, optional
        Defaults to CrossEntropyLoss.
    blocking : bool
        Reference API parity; both values run the same fused step (the
        blocking/non-blocking distinction is the reference's hook
        choreography, data_parallel.py:219-295, which XLA makes obsolete).
    wire_quant : {"int8", "bf16"}, optional
        Opt-in quantized-gradient mode: the gradient all-reduce ships
        block-quantized payloads (``heat_tpu.kernels.quant``, scale per
        1024-element tile) with a per-device error-feedback carry. The
        default ``None`` keeps the exact full-width psum — this mode is
        a constructor decision, never an ambient env flip, because it
        changes training numerics (within the codec's pinned tolerance
        per step; EF makes the long-run gradient unbiased).
    """

    def __init__(self, local_optimizer, model, loss=None, blocking: bool = True,
                 wire_quant: Optional[str] = None):
        if not isinstance(local_optimizer, LocalOptimizer):
            raise TypeError(
                f"local_optimizer must be a heat_tpu.optim optimizer, got {type(local_optimizer)}"
            )
        if wire_quant is not None:
            from ..kernels.quant import MODES

            if wire_quant not in MODES:
                raise ValueError(
                    f"wire_quant must be one of {MODES} or None, got {wire_quant!r}"
                )
        self.model = model
        self.tx = local_optimizer.tx
        self.loss = loss if loss is not None else CrossEntropyLoss()
        self.blocking = bool(blocking)
        self.wire_quant = wire_quant
        repl = model.comm.sharding(0, None)
        self.opt_state = jax.device_put(self.tx.init(model.params), repl)
        self._iter = 0
        self._base_key = jax.random.PRNGKey(0)
        self._step_cache = {}
        # per-device error-feedback carry (quantized mode only), built
        # lazily once the flat gradient size is known
        self._ef_carry = None

    # -------------------------------------------------------------- #
    def zero_grad(self) -> None:
        """No-op: gradients are locals of the fused step (reference
        dp_optimizer.py:897 zeroes torch .grad buffers)."""

    @property
    def lr(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    def set_lr(self, lr: float) -> None:
        self.opt_state.hyperparams["learning_rate"] = jnp.asarray(
            lr, dtype=self.opt_state.hyperparams["learning_rate"].dtype
        )

    # -------------------------------------------------------------- #
    def _get_step(self, xshape, xdtype, yshape, ydtype, n_valid: int):
        key = (xshape, xdtype, yshape, ydtype, n_valid)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        module, loss, tx = self.model.module, self.loss, self.tx
        import optax

        padded = xshape[0] != n_valid

        def step(params, opt_state, xb, yb, dropkey):
            weight = None
            if padded:
                weight = (jnp.arange(xb.shape[0]) < n_valid).astype(xb.dtype)

            def lf(p):
                out = module.apply(p, xb, train=True, key=dropkey)
                return loss.raw(out, yb, weight=weight)

            loss_val, grads = jax.value_and_grad(lf)(params)
            updates, new_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state, loss_val

        fn = jax.jit(step, donate_argnums=(0, 1))
        self._step_cache[key] = fn
        return fn

    # -------------------------------------------------------------- #
    # quantized-gradient mode (ISSUE 7)                               #
    # -------------------------------------------------------------- #
    def _flat_param_count(self) -> int:
        from jax.flatten_util import ravel_pytree

        return int(ravel_pytree(self.model.params)[0].size)

    def _init_ef_carry(self):
        """Zero per-device error-feedback residuals: one flat gradient
        vector per device, leading axis sharded over the mesh."""
        comm = self.model.comm
        n = self._flat_param_count()
        self._ef_carry = jax.device_put(
            jnp.zeros((comm.size, n), jnp.float32), comm.sharding(2, 0)
        )

    def _get_quant_step(self, xshape, xdtype, yshape, ydtype, n_valid: int):
        comm = self.model.comm
        # two-tier wire (ISSUE 8): at a tiered topology the quantized
        # all-reduce runs hierarchically — intra-slice reduce-scatter,
        # inter-slice exchange of the reduced+encoded shard, intra-slice
        # all-gather — so only ~1/C of the encoded gradient crosses DCN
        topo_t = comm.topology
        topo = (
            (topo_t.n_slices, topo_t.chips_per_slice)
            if topo_t.tiered and topo_t.chips_per_slice > 1
            else None
        )
        key = (xshape, xdtype, yshape, ydtype, n_valid, self.wire_quant, topo)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        module, loss, tx = self.model.module, self.loss, self.tx
        p, axis = comm.size, comm.axis_name
        mode = self.wire_quant
        import optax

        from jax.flatten_util import ravel_pytree
        from ..kernels import quant as _quant

        blk_rows = xshape[0] // p

        def blk(params, opt_state, carry_blk, xb, yb, dropkey):
            dev = jax.lax.axis_index(axis)
            rows = dev * blk_rows + jnp.arange(blk_rows)
            w = (rows < n_valid).astype(xb.dtype)

            def local_sums(pp):
                out = module.apply(
                    pp, xb, train=True, key=jax.random.fold_in(dropkey, dev)
                )
                # loss contract (see DASO): raw() is the weighted MEAN;
                # x Σw recovers the weighted sum this wire reduces over
                return loss.raw(out, yb, weight=w) * jnp.sum(w)

            sum_loss, g = jax.value_and_grad(local_sums)(params)
            g_flat, unravel = ravel_pytree(g)
            # error feedback: re-inject last step's compression residual,
            # ship the compensated gradient through the quantized wire
            h = g_flat.astype(jnp.float32) + carry_blk[0]
            if topo is not None:
                red, resid = _quant.hierarchical_allreduce_sum(
                    h, axis, topo[0], topo[1], mode
                )
            else:
                red, resid = _quant.quantized_allreduce_sum(h, axis, p, mode)
            wsum = jax.lax.psum(jnp.sum(w), axis)
            gbar = unravel((red / jnp.maximum(wsum, 1.0)).astype(g_flat.dtype))
            updates, o2 = tx.update(gbar, opt_state, params)
            p2 = optax.apply_updates(params, updates)
            gl = jax.lax.psum(sum_loss, axis) / jnp.maximum(wsum, 1.0)
            return p2, o2, resid[None], gl

        mapped = shard_map(
            blk,
            mesh=comm.mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P(), P(axis), P()),
            check_vma=False,
        )
        fn = jax.jit(mapped, donate_argnums=(0, 1, 2))
        self._step_cache[key] = fn
        return fn

    # -------------------------------------------------------------- #
    # checkpointed resume (ISSUE 13)                                  #
    # -------------------------------------------------------------- #
    def checkpoint_state(self) -> dict:
        """Everything a bit-reproducible mid-training resume needs, as
        a flat ``heat_tpu.resilience.checkpoint.save``-able dict:
        parameters and optimizer-state leaves (replicated), the
        per-device error-feedback carry (sharded — streamed as
        split-blocks), the step counter the dropout key folds, and the
        base PRNG key. The pytree STRUCTURES are not serialized — a
        restore adopts the leaves into the structures of the receiving
        optimizer, which must wrap the same architecture."""
        import jax

        p_leaves = jax.tree.leaves(self.model.params)
        o_leaves = jax.tree.leaves(self.opt_state)
        state = {f"param_{i:04d}": l for i, l in enumerate(p_leaves)}
        state.update({f"opt_{i:04d}": l for i, l in enumerate(o_leaves)})
        state["base_key"] = np.asarray(jax.device_get(self._base_key))
        state["iter"] = int(self._iter)
        state["n_params"] = len(p_leaves)
        state["n_opt"] = len(o_leaves)
        state["wire_quant"] = self.wire_quant or ""
        if self._ef_carry is not None:
            state["ef_carry"] = self._ef_carry
        return state

    def load_checkpoint_state(self, state: dict) -> None:
        """Adopt a restored checkpoint ONTO THE CURRENT WORLD: params/
        optimizer leaves re-place replicated over this optimizer's
        mesh, and the error-feedback carry re-shards split-0. On a
        RESIZED world the carry's per-device rows fold as ``row r ->
        r % p_new`` (summed) — the total outstanding residual, which is
        what error feedback re-injects, is preserved exactly; on the
        same-size world the carry restores bit-identically."""
        import jax

        comm = self.model.comm
        repl = comm.sharding(0, None)
        n_p, n_o = int(state["n_params"]), int(state["n_opt"])
        p_leaves = [state[f"param_{i:04d}"] for i in range(n_p)]
        o_leaves = [state[f"opt_{i:04d}"] for i in range(n_o)]
        p_def = jax.tree.structure(self.model.params)
        o_def = jax.tree.structure(self.opt_state)
        if p_def.num_leaves != n_p or o_def.num_leaves != n_o:
            raise ValueError(
                f"checkpoint carries {n_p} param / {n_o} optimizer leaves "
                f"but this optimizer has {p_def.num_leaves} / "
                f"{o_def.num_leaves} — architectures differ"
            )
        # ALL validation precedes mutation: a refused restore must
        # leave the optimizer exactly as it was
        saved_wire = state.get("wire_quant") or None
        if saved_wire != self.wire_quant:
            raise ValueError(
                f"checkpoint was written with wire_quant={saved_wire!r} but "
                f"this optimizer runs {self.wire_quant!r} — the EF carry is "
                "only meaningful under the same codec"
            )
        def _cast(l, c):
            # non-array leaves (plain counters some transforms keep)
            # round-trip as scalars and adopt as-is
            dt = getattr(c, "dtype", None)
            return jnp.asarray(l, dtype=dt) if dt is not None else l

        cur_p = jax.tree.leaves(self.model.params)
        cur_o = jax.tree.leaves(self.opt_state)
        p_leaves = [_cast(l, c) for l, c in zip(p_leaves, cur_p)]
        o_leaves = [_cast(l, c) for l, c in zip(o_leaves, cur_o)]
        self.model.params = jax.device_put(jax.tree.unflatten(p_def, p_leaves), repl)
        self.opt_state = jax.device_put(jax.tree.unflatten(o_def, o_leaves), repl)
        self._iter = int(state["iter"])
        self._base_key = jnp.asarray(state["base_key"])
        carry = state.get("ef_carry")
        if carry is None or self.wire_quant is None:
            self._ef_carry = None
            return
        host = np.asarray(jax.device_get(carry), dtype=np.float32)
        p_new = comm.size
        if host.shape[0] != p_new:
            folded = np.zeros((p_new,) + host.shape[1:], dtype=host.dtype)
            for r in range(host.shape[0]):
                folded[r % p_new] += host[r]
            host = folded
        self._ef_carry = jax.device_put(jnp.asarray(host), comm.sharding(2, 0))

    def step(self, x: DNDarray, y: DNDarray) -> DNDarray:
        """One fused train step on a global batch; returns the global-mean
        loss as a 0-d replicated DNDarray (no host sync)."""
        xb, yb = x._phys, _aligned_labels(x, y)
        self._iter += 1
        dropkey = jax.random.fold_in(self._base_key, self._iter)
        if self.wire_quant is not None and self.model.comm.size > 1:
            if self._ef_carry is None:
                self._init_ef_carry()
            fn = self._get_quant_step(
                tuple(xb.shape), str(xb.dtype), tuple(yb.shape), str(yb.dtype),
                x.shape[0],
            )
            params, self.opt_state, self._ef_carry, loss_val = fn(
                self.model.params, self.opt_state, self._ef_carry, xb, yb, dropkey
            )
            self.model.params = params
            return _loss_scalar(loss_val, self.model.comm, x.device)
        fn = self._get_step(
            tuple(xb.shape), str(xb.dtype), tuple(yb.shape), str(yb.dtype), x.shape[0]
        )
        params, self.opt_state, loss_val = fn(self.model.params, self.opt_state, xb, yb, dropkey)
        self.model.params = params
        return _loss_scalar(loss_val, self.model.comm, x.device)


class DASO:
    """Distributed Asynchronous and Selective Optimization (reference
    dp_optimizer.py:64): hierarchical data parallelism on a two-level mesh.

    Parameters (reference-aligned where the concept survives)
    ----------
    local_optimizer : LocalOptimizer
    model : heat_tpu.nn.DataParallel
    n_nodes : int, optional
        Number of node groups (reference: inferred from MPI topology /
        GPUs per node, dp_optimizer.py:137-160). Default: 2 when the mesh
        size is even, else 1.
    global_skip : int
        Batches between global parameter syncs (reference
        ``max_global_skips``-controlled schedule, :202).
    compression : bool
        Cast parameters to bfloat16 for the global sync wire (reference
        mpi_sum_bfloat custom op, :21-62).
    loss : loss object, optional
    """

    def __init__(self, local_optimizer, model, n_nodes: Optional[int] = None,
                 global_skip: int = 4, compression: bool = True, loss=None,
                 total_epochs: Optional[int] = None, warmup_epochs: int = 4,
                 cooldown_epochs: int = 4, stability_level: float = 0.05,
                 max_global_skips: int = 8, skip_reduction_factor: int = 2,
                 local_skip_factor: int = 4):
        if not isinstance(local_optimizer, LocalOptimizer):
            raise TypeError(
                f"local_optimizer must be a heat_tpu.optim optimizer, got {type(local_optimizer)}"
            )
        self.model = model
        self.comm = model.comm
        self.tx = local_optimizer.tx
        self.loss = loss if loss is not None else CrossEntropyLoss()
        size = self.comm.size
        if n_nodes is None:
            n_nodes = 2 if size % 2 == 0 and size > 1 else 1
        if size % n_nodes != 0:
            raise ValueError(f"mesh size {size} not divisible by n_nodes {n_nodes}")
        self.n_nodes = int(n_nodes)
        self.local_size = size // self.n_nodes
        self.global_skip = int(global_skip)
        self.compression = bool(compression)
        devs = np.array(self.comm.devices).reshape(self.n_nodes, self.local_size)
        self.mesh = Mesh(devs, ("node", "local"))

        # node-stacked parameters: leading axis = node, sharded over "node";
        # the single-controller form of per-node divergent replicas
        node_sharded = NamedSharding(self.mesh, P("node"))
        self.params = jax.tree.map(
            lambda p: jax.device_put(
                jnp.broadcast_to(p[None], (self.n_nodes,) + p.shape), node_sharded
            ),
            model.params,
        )
        self.opt_state = jax.device_put(jax.vmap(self.tx.init)(self.params), node_sharded)
        self._iter = 0
        self._base_key = jax.random.PRNGKey(0)
        self._step_cache = {}
        # epoch_loss_logic state (reference :354-470): the widening/
        # collapsing skip schedule with its stability detector
        from .utils import DetectMetricPlateau

        self.total_epochs = total_epochs
        self.warmup_epochs = int(warmup_epochs)
        self.cooldown_epochs = int(cooldown_epochs)
        self.max_gs = int(max_global_skips)
        self.skip_reduction_factor = int(skip_reduction_factor)
        self.local_skip_factor = int(local_skip_factor)
        self.stability = DetectMetricPlateau(patience=2, threshold=float(stability_level))
        self.epoch = 0
        # local_skip / batches_to_wait are schedule STATE kept for policy
        # parity: the two-level mesh averages within a node in-program
        # every batch (a fused psum over ICI — effectively free, unlike
        # the reference's NCCL hop, so skipping it buys nothing), and a
        # synchronous collective has no recv-delay to wait batches for.
        self.local_skip = 1
        self.batches_to_wait = 1
        # keep the wrapped model's eval path current: forwards read the
        # node-averaged parameters lazily (the reference mutates the torch
        # model in place every step, so eval there is always current)
        self._eval_cache = (-1, None)
        model._param_override = self._eval_params
        model._owner = self

    def _eval_params(self):
        it, cached = self._eval_cache
        if it != self._iter:
            cached = jax.tree.map(lambda a: jnp.mean(a, axis=0), self.params)
            self._eval_cache = (self._iter, cached)
        return cached

    @property
    def lr(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"][0])

    def set_lr(self, lr: float) -> None:
        cur = self.opt_state.hyperparams["learning_rate"]
        self.opt_state.hyperparams["learning_rate"] = jnp.full_like(cur, lr)

    # -------------------------------------------------------------- #
    def _get_step(self, xshape, xdtype, yshape, ydtype, n_valid: int, global_sync: bool):
        key = (xshape, xdtype, yshape, ydtype, n_valid, global_sync)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        module, loss, tx = self.model.module, self.loss, self.tx
        n_nodes, local_size = self.n_nodes, self.local_size
        compression = self.compression
        import optax

        blk_rows = xshape[0] // (n_nodes * local_size)

        def blk(params_blk, opt_blk, xb, yb, dropkey):
            p = jax.tree.map(lambda a: a[0], params_blk)
            o = jax.tree.map(lambda a: a[0], opt_blk)
            dev = jax.lax.axis_index("node") * local_size + jax.lax.axis_index("local")
            rows = dev * blk_rows + jnp.arange(blk_rows)
            w = (rows < n_valid).astype(xb.dtype)

            def local_sums(pp):
                out = module.apply(pp, xb, train=True, key=jax.random.fold_in(dropkey, dev))
                # documented loss contract: raw(output, target, weight) is the
                # weighted MEAN; × Σw recovers the weighted sum this
                # hierarchy reduces over
                return loss.raw(out, yb, weight=w) * jnp.sum(w)

            sum_loss, g = jax.value_and_grad(local_sums)(p)
            wsum = jnp.sum(w)
            node_w = jax.lax.psum(wsum, "local")
            g = jax.tree.map(
                lambda a: jax.lax.psum(a, "local") / jnp.maximum(node_w, 1.0).astype(a.dtype), g
            )
            updates, o2 = tx.update(g, o, p)
            p2 = optax.apply_updates(p, updates)
            if global_sync and n_nodes > 1:
                def gsync(a):
                    wire = a.astype(jnp.bfloat16) if compression else a
                    return (jax.lax.psum(wire, "node") / n_nodes).astype(a.dtype)
                p2 = jax.tree.map(gsync, p2)
            gl = jax.lax.psum(sum_loss, ("node", "local")) / jnp.maximum(
                jax.lax.psum(wsum, ("node", "local")), 1.0
            )
            return (
                jax.tree.map(lambda a: a[None], p2),
                jax.tree.map(lambda a: a[None], o2),
                gl,
            )

        mapped = shard_map(
            blk,
            mesh=self.mesh,
            in_specs=(P("node"), P("node"), P(("node", "local")), P(("node", "local")), P()),
            out_specs=(P("node"), P("node"), P()),
            check_vma=False,
        )
        fn = jax.jit(mapped, donate_argnums=(0, 1))
        self._step_cache[key] = fn
        return fn

    def step(self, x: DNDarray, y: DNDarray) -> DNDarray:
        """One DASO step: node-local sync always, global parameter
        averaging every ``global_skip`` batches (reference :202-350)."""
        xb, yb = x._phys, _aligned_labels(x, y)
        if xb.shape[0] % (self.n_nodes * self.local_size) != 0:
            raise ValueError(
                f"DASO requires the physical batch ({xb.shape[0]}) divisible by the "
                f"mesh ({self.n_nodes}x{self.local_size})"
            )
        self._iter += 1
        global_sync = self.global_skip <= 1 or (self._iter % self.global_skip == 0)
        dropkey = jax.random.fold_in(self._base_key, self._iter)
        fn = self._get_step(
            tuple(xb.shape), str(xb.dtype), tuple(yb.shape), str(yb.dtype),
            x.shape[0], bool(global_sync),
        )
        self.params, self.opt_state, loss_val = fn(self.params, self.opt_state, xb, yb, dropkey)
        return _loss_scalar(loss_val, self.comm, x.device)

    def zero_grad(self) -> None:
        """No-op (see DataParallelOptimizer.zero_grad)."""

    def load_params(self, params) -> None:
        """Adopt externally loaded weights (checkpoint restore): restack
        them per node and reinitialize the optimizer state (momentum is not
        part of the reference's checkpoint either, optim/utils.py:72)."""
        node_sharded = NamedSharding(self.mesh, P("node"))
        self.params = jax.tree.map(
            lambda p: jax.device_put(
                jnp.broadcast_to(jnp.asarray(p)[None], (self.n_nodes,) + jnp.asarray(p).shape),
                node_sharded,
            ),
            params,
        )
        self.opt_state = jax.device_put(jax.vmap(self.tx.init)(self.params), node_sharded)
        self._eval_cache = (-1, None)

    def sync_params(self) -> None:
        """Force a global parameter average and push the result into the
        wrapped model (reference: the end-of-epoch full sync, :700-780)."""
        mean = jax.tree.map(lambda a: jnp.mean(a, axis=0), self.params)
        repl = self.comm.sharding(0, None)
        self.model.params = jax.tree.map(lambda p: jax.device_put(p, repl), mean)
        node_sharded = NamedSharding(self.mesh, P("node"))
        self.params = jax.tree.map(
            lambda p: jax.device_put(jnp.broadcast_to(p[None], (self.n_nodes,) + p.shape),
                                     node_sharded),
            self.model.params,
        )

    def epoch_loss_logic(self, loss, loss_globally_averaged: bool = True) -> None:
        """Adapt the sync schedule from the end-of-epoch loss — the
        reference's policy verbatim (dp_optimizer.py:354-470):

        * warmup epochs: every skip forced to 0 (full sync each batch);
        * end of warmup: ``global_skip=4, local_skip=1, batches_to_wait=1``;
        * cooldown (last ``cooldown_epochs`` of ``total_epochs``): skips 0;
        * plateau detected (``DetectMetricPlateau``, patience 2) while
          ``global_skip > 1``: divide skips by ``skip_reduction_factor``
          and decrement ``batches_to_wait`` (sync more often to escape),
          clamping live skips to ≥ 1;
        * plateau detected at ``global_skip == 1``: widen back to
          ``max_global_skips`` (and ``max_gs // local_skip_factor`` local
          skips / wait batches).

        Call once per epoch with the training loss; the epoch counter
        advances here (the reference advances it on the last batch of its
        DataLoader, which this framework does not see). The loss under a
        single controller is already the global average (``step`` psums
        it), so ``loss_globally_averaged`` defaults True; pass False for a
        per-host value (e.g. a locally computed eval loss) and it is
        averaged across processes first — every host must then make the
        SAME schedule decision or their compiled sync programs diverge
        (the reference's Allreduce at :372 exists for the same reason).
        """
        avg_loss = float(loss)
        if not loss_globally_averaged and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            all_losses = multihost_utils.process_allgather(
                jnp.asarray(avg_loss, dtype=jnp.float32)
            )
            avg_loss = float(jnp.mean(all_losses))
        self.epoch += 1
        epoch = self.epoch - 1  # the epoch this loss belongs to, 0-based

        if epoch < self.warmup_epochs:
            self.global_skip = 0
            self.local_skip = 0
            self.batches_to_wait = 0
            return
        if epoch == self.warmup_epochs:
            self.global_skip = 4
            self.local_skip = 1
            self.batches_to_wait = 1
        if (
            self.total_epochs is not None
            and epoch >= self.total_epochs - self.cooldown_epochs
        ):
            self.global_skip = 0
            self.local_skip = 0
            self.batches_to_wait = 0
            return

        stable = self.stability.test_if_improving(avg_loss)
        if stable and self.global_skip > 1:
            # collapse: sync more often while the loss is on a plateau
            self.global_skip //= self.skip_reduction_factor
            self.local_skip //= self.skip_reduction_factor
            self.batches_to_wait -= 1
            if self.global_skip > 0:
                if self.batches_to_wait == 0:
                    self.batches_to_wait = 1
                if self.local_skip == 0:
                    self.local_skip = 1
        elif stable and self.global_skip == 1:
            # bottomed out: widen back to the maximum
            self.global_skip = self.max_gs
            self.local_skip = self.max_gs // self.local_skip_factor
            self.batches_to_wait = self.max_gs // self.local_skip_factor
