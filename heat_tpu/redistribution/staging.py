"""Out-of-core staging executor — larger-than-HBM operands (ISSUE 11).

Every array in the framework used to have to fit in HBM. Following
"Distributed linear algebra at hundreds of GB on TPUs" (arXiv:2112.09017
— host-resident operands streamed through HBM under compute), this
module opens the scenario class the reference cannot touch: operands
live on the HOST tier of the memory-tier lattice (``core.tiers``) —
pinned host RAM or an HDF5 dataset (``core.io``) — and the
pass-structured algorithms that already think in passes-over-A
(2-pass/1-pass ``hsvd_rank``, streaming ``KMeans.partial_fit``) consume
them window at a time:

- a :class:`HostArray` handle holds the host-resident operand;
- :func:`plan_staged_passes` builds a ``host-staging``
  :class:`~heat_tpu.redistribution.schedule.Schedule` whose
  ``stage_in``/``stage_out`` steps (tier ``"pcie"``) describe the
  (8,128)-tile-aligned windows each pass streams, priced by the lattice
  (``tiers.transfer_time``) and carrying a ``staging`` annotation with
  the depth-2 critical-path model;
- :func:`prove_fits` proves the window schedule's HBM slab peak within
  ``tiers.capacity("hbm")`` via ``Schedule.liveness()`` — the PR-10
  oracle, now gating execution, with ``ht.analysis.verify_plan``
  checking the same invariants symbolically;
- :func:`stream_windows` runs the depth-2 double-buffered loop:
  ``jax.device_put`` of window k+1 is issued BEFORE window k's compute
  consumes the slab, so the PCIe transfer hides under compute exactly
  like the PR-6 chunk pipelines hide copies under wire.

Gate: ``HEAT_TPU_OOC`` — ``0`` disables staging (HostArray operands
are materialized whole when they fit the HBM budget; the exact-bit
escape hatch), ``1`` forces the staged program forms even for fitting
device arrays (the CI leg), ``auto`` (default) stages HostArray
operands and leaves device arrays on their existing in-HBM paths.

BIT-IDENTITY BY CONSTRUCTION: the staged numerics are the in-HBM
numerics. The hsvd sketch passes are expressed as fixed-grain tiled
streams (``svdtools``' ``_pass1_tiles``/``_pass2_tiles``/
``_oneview_tiles`` — 512-wide tiles with explicit carries), window
extents are multiples of the same grain (only the global tail window
is ragged), and every per-tile contraction is therefore the same-shaped
dot on the same data whether the loop runs inside one in-HBM program or
across staged windows. XLA's gemm kernel choice is shape-dependent
(measured: a 128-wide tail gemm reassociates differently than the same
columns inside a 1024-wide gemm), so the shared grain — not luck — is
what the pinned staged-vs-in-HBM bit-identity sweep relies on.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import gates as _gates
from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from .schedule import Schedule, Step
from .spec import RedistSpec

__all__ = [
    "DEFAULT_SLAB_MB",
    "GRAIN",
    "HostArray",
    "OOC_ENV",
    "SLAB_ENV",
    "golden_staged_plans",
    "materialize",
    "ooc_engaged",
    "ooc_mode",
    "plan_staged_passes",
    "prove_fits",
    "slab_bytes",
    "stream_windows",
    "window_extents",
]

OOC_ENV = "HEAT_TPU_OOC"
SLAB_ENV = "HEAT_TPU_OOC_SLAB_MB"

#: default HBM slab for the double-buffered windows (two windows in
#: flight). 256 MiB ≈ 16 ms of PCIe per window at the v5e edge — big
#: enough to amortize dispatch, small next to the 16 GiB budget.
DEFAULT_SLAB_MB = 256

#: window grain per axis: (sublane, lane) = the (8,128) TPU tile, times
#: the 64x/4x factors that make the grain match the 512-wide pass tiles
#: of the hsvd streams (``svdtools._PASS_TILE``). Window extents are
#: multiples of the grain — except the global tail — which is BOTH the
#: (8,128)-tile alignment the HBM slab layout wants AND the shared tile
#: sequence the bit-identity contract needs.
GRAIN = (512, 512)


# --------------------------------------------------------------------- #
# the gate                                                              #
# --------------------------------------------------------------------- #
def ooc_mode() -> str:
    """Resolved ``HEAT_TPU_OOC`` mode (``"0"``/``"1"``/``"auto"``).
    ``0`` disables staging everywhere (HostArray operands materialize
    whole when they fit — the exact-bit escape hatch); ``1`` forces the
    staged window pipeline even for in-HBM device operands on the
    supported paths (the CI leg: every windowed program form executes,
    and the results are pinned bit-identical to the in-HBM forms);
    ``auto`` (default) stages host-resident operands only."""
    v = _gates.get(OOC_ENV, "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "true", "force", "yes"):
        return "1"
    return "auto"


def ooc_engaged(nbytes: int, host_resident: bool = False) -> bool:
    """Does the gate stage an operand of ``nbytes``? Mode ``1`` stages
    every supported operand; ``auto`` stages host-resident operands
    (they cannot run any other way) and leaves device arrays on the
    in-HBM paths; ``0`` never stages."""
    mode = ooc_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return bool(host_resident)


def slab_bytes(override: Optional[int] = None) -> int:
    """HBM slab budget for the double-buffered windows
    (``HEAT_TPU_OOC_SLAB_MB``, default 256 MiB), never more than a
    quarter of ``tiers.capacity("hbm")`` so outputs and workspace keep
    headroom under the liveness proof."""
    from ..core import tiers as _tiers

    if override is not None:
        return max(1, int(override))
    raw = _gates.get(SLAB_ENV, "")
    try:
        mb = int(raw) if raw.strip() else DEFAULT_SLAB_MB
    except ValueError:
        mb = DEFAULT_SLAB_MB
    return max(1 << 20, min(max(1, mb) << 20, _tiers.capacity("hbm") // 4))


# --------------------------------------------------------------------- #
# host-tier operands                                                    #
# --------------------------------------------------------------------- #
class HostArray:
    """A host-tier operand: data resident in (pinned) host RAM or an
    HDF5 dataset, streamed through HBM window by window instead of ever
    being materialized on device.

    Wraps any 2-D array-like with ``shape``/``dtype`` and numpy-style
    slicing — an ``np.ndarray`` (kept C-contiguous so ``stage_in``
    windows are single memcpy-class reads over PCIe) or an ``h5py``
    dataset (windows read straight off disk; ``from_hdf5``). The
    framework's staged paths (``linalg.hsvd_rank``, ``KMeans.fit``/
    ``partial_fit``) accept it wherever a pass-structured stream can
    serve the algorithm.
    """

    def __init__(self, data: Any, dtype=None):
        if isinstance(data, np.ndarray):
            data = np.ascontiguousarray(data if dtype is None else data.astype(dtype, copy=False))
        elif dtype is not None and np.dtype(getattr(data, "dtype", dtype)) != np.dtype(dtype):
            raise TypeError(
                "HostArray: dtype override is only supported for numpy inputs "
                f"(got {type(data).__name__})"
            )
        shape = tuple(int(s) for s in data.shape)
        if len(shape) != 2:
            raise ValueError(f"HostArray serves 2-D operands, got shape {shape}")
        self._data = data
        self.shape = shape
        self.dtype = np.dtype(data.dtype)

    @classmethod
    def from_hdf5(cls, path: str, dataset: str) -> "HostArray":
        """Open an HDF5 dataset as a host-tier operand — windows are
        read lazily, so operands larger than host RAM stream from disk
        (the ``PartialH5Dataset`` scenario of the reference, served by
        the lattice's host tier instead of per-rank reads)."""
        import h5py

        return cls(h5py.File(path, "r")[dataset])

    @property
    def ndim(self) -> int:
        return 2

    @property
    def nbytes(self) -> int:
        return self.shape[0] * self.shape[1] * self.dtype.itemsize

    def window(self, axis: int, start: int, stop: int) -> np.ndarray:
        """One contiguous window along ``axis`` as a host ndarray —
        what ``stage_in`` transfers."""
        sl = (slice(start, stop), slice(None)) if axis == 0 else (slice(None), slice(start, stop))
        return np.asarray(self._data[sl])

    def __repr__(self) -> str:
        return f"HostArray(shape={self.shape}, dtype={self.dtype.name}, tier=host)"


# --------------------------------------------------------------------- #
# window geometry                                                       #
# --------------------------------------------------------------------- #
def window_extents(
    shape: Tuple[int, int],
    itemsize: int,
    axis: int,
    slab: int,
    grain: Optional[int] = None,
) -> List[Tuple[int, int]]:
    """``(start, stop)`` windows along ``axis``: extents are multiples
    of the grain (``GRAIN[axis]``), each window's bytes at most half
    the ``slab`` (two windows in flight at depth 2), and only the
    global tail window is ragged — the alignment contract the
    bit-identity construction and the (8,128) slab layout share. An
    operand whose cross-extent makes even one grain exceed the slab
    still windows at one grain; the liveness proof then rejects the
    schedule rather than silently splitting below the grain."""
    extent = int(shape[axis])
    other = int(shape[1 - axis])
    g = int(GRAIN[axis] if grain is None else grain)
    per_unit = other * int(itemsize)
    per_window = max(1, (int(slab) // 2) // max(per_unit, 1))
    width = max(g, per_window // g * g)
    out: List[Tuple[int, int]] = []
    start = 0
    while start + width <= extent:
        out.append((start, start + width))
        start += width
    if start < extent or not out:
        out.append((start, extent))
    return out


def _win_bytes(shape: Tuple[int, int], itemsize: int, axis: int, win: Tuple[int, int]) -> int:
    other = int(shape[1 - axis])
    return (win[1] - win[0]) * other * int(itemsize)


# --------------------------------------------------------------------- #
# the staged plan                                                       #
# --------------------------------------------------------------------- #
def plan_staged_passes(
    shape,
    dtype,
    passes: Sequence[Dict[str, Any]],
    *,
    slab: Optional[int] = None,
    out_bytes: int = 0,
    mesh_size: int = 1,
    hbm_bytes: Optional[int] = None,
) -> Schedule:
    """Build the ``host-staging`` Schedule for a host-resident operand
    streamed by ``passes`` — each ``{"tag", "axis", "writeback"?}``
    describes one pass over the operand (the hsvd 2-pass schedule is
    ``[{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}]``).

    Steps: per pass, one ``stage_in`` (tier ``"pcie"``) per window —
    ``peak_bytes`` is the slab OCCUPANCY at that step (this window plus
    the depth-2 prefetch of the next) — plus a ``stage_out`` when the
    pass writes per-window results back to host. ``out_bytes`` is the
    HBM-resident working set held ACROSS the loop (sketch factors,
    centroids — the annotation's ``resident_bytes``), so
    ``Schedule.liveness_peak_bytes`` is exactly what :func:`prove_fits`
    holds under ``tiers.capacity("hbm")``.

    The ``staging`` annotation carries the lattice pricing: total pcie
    seconds (``tiers.transfer_time``), the HBM-stream compute model,
    and the depth-2 critical path ``max(pcie, hbm) + min(pcie, hbm)/n``
    (the first/last window's exposed leg) — ``model_speedup`` is the
    sequential/critical-path ratio, same convention as the overlap
    annotation. Deterministic pure Python: the golden staged plans ride
    the ci.sh determinism + verify_plan sweeps."""
    from ..core import tiers as _tiers

    shape = tuple(int(s) for s in shape)
    if len(shape) != 2:
        raise ValueError(f"plan_staged_passes serves 2-D operands, got {shape}")
    dtype = np.dtype(dtype)
    slab_b = slab_bytes(slab)
    # the hbm budget this plan was SIZED against, recorded in the
    # annotation: verify_plan proves fit against the recorded number
    # (well-formedness stays environment-independent — golden dumps pin
    # it explicitly), while prove_fits re-checks the AMBIENT capacity at
    # execution time
    hbm_cap = _tiers.capacity("hbm") if hbm_bytes is None else max(1, int(hbm_bytes))
    spec = RedistSpec.normalize(shape, dtype.name, None, None, int(mesh_size))
    host_bytes = spec.logical_bytes

    steps: List[Step] = []
    pass_meta: List[Dict[str, Any]] = []
    pcie_total = 0
    max_window = 0
    for p in passes:
        axis = int(p["axis"])
        tag = str(p.get("tag", f"pass{len(pass_meta)}"))
        writeback = bool(p.get("writeback", False))
        wins = window_extents(shape, dtype.itemsize, axis, slab_b)
        wb = [_win_bytes(shape, dtype.itemsize, axis, w) for w in wins]
        max_window = max(max_window, max(wb))
        n = len(wins)
        for k, (w, b) in enumerate(zip(wins, wb)):
            occupancy = b + (wb[k + 1] if k + 1 < n else 0)
            steps.append(
                Step(
                    "stage_in",
                    bytes_moved=b,
                    peak_bytes=occupancy,
                    detail=(
                        f"{tag}: window {k}/{n} axis-{axis} "
                        f"[{w[0]}:{w[1]}) host->hbm (depth-2 prefetch)"
                    ),
                    chunk=k,
                    overlap=tag if n > 1 else None,
                    tier="pcie",
                )
            )
            if writeback:
                steps.append(
                    Step(
                        "stage_out",
                        bytes_moved=b,
                        peak_bytes=occupancy,
                        detail=f"{tag}: window {k}/{n} result hbm->host",
                        chunk=k,
                        overlap=tag if n > 1 else None,
                        tier="pcie",
                    )
                )
            pcie_total += b * (2 if writeback else 1)
        pass_meta.append(
            {
                "tag": tag,
                "axis": axis,
                "n_windows": n,
                "window_bytes": max(wb),
                "pcie_bytes": sum(wb) * (2 if writeback else 1),
                "writeback": writeback,
            }
        )

    n_total = sum(pm["n_windows"] for pm in pass_meta)
    # lattice pricing: the streamed bytes cross pcie once per pass and
    # the compute consumes them from HBM once per pass — at depth 2 the
    # slower leg governs, the faster leg is exposed only on the
    # first/last window. Derived from the ROUNDED legs so the verifier's
    # recompute (analysis.planverify, staging invariant) reproduces the
    # numbers bit-for-bit at any plan size.
    pcie_s = round(_tiers.transfer_time(pcie_total, "pcie"), 9)
    hbm_s = round(_tiers.transfer_time(pcie_total, "hbm"), 9)
    seq_s = pcie_s + hbm_s
    cp_s = max(pcie_s, hbm_s) + min(pcie_s, hbm_s) / max(n_total, 1)
    annotation = {
        "depth": 2,
        "grain": [int(GRAIN[0]), int(GRAIN[1])],
        "passes": pass_meta,
        "n_windows": n_total,
        "window_bytes": max_window,
        "slab_bytes": slab_b,
        "resident_bytes": int(out_bytes),
        "host_bytes": host_bytes,
        "hbm_capacity_bytes": hbm_cap,
        "model": {
            "pcie_s": pcie_s,
            "hbm_s": hbm_s,
            "sequential_s": round(seq_s, 9),
            "critical_path_s": round(cp_s, 9),
            "model_speedup": round(seq_s / cp_s, 4) if cp_s else 1.0,
            "bound_gbps": round(pcie_total / cp_s / 1e9, 3) if cp_s else 0.0,
        },
    }
    sched = Schedule(
        spec,
        "host-staging",
        steps,
        slab_b,
        notes=(
            f"out-of-core staging: {len(pass_meta)} pass(es) over a "
            f"{host_bytes} B host-resident operand through a depth-2 "
            f"double-buffered HBM slab (HEAT_TPU_OOC)"
        ),
        staging=annotation,
        # ISSUE 16: the model above was priced through the (possibly
        # profile-calibrated) tiers.transfer_time — record the prices +
        # profile_id so the verifier recomputes from the plan's OWN
        # numbers and a recalibration re-keys the staged plan_ids too.
        # None under the constants: bytes identical to the pre-
        # calibration golden dumps.
        calibration=_tiers.profile_annotation(),
    )
    # staged plans live outside the planner's schedule cache — register
    # for ht.observability.attribution(plan_id) lookup (cheap bounded
    # dict; the module is shadowed by the function in the package
    # namespace, so import the name off the module path)
    from ..observability.attribution import register_plan as _register_plan

    _register_plan(sched)
    if _telemetry._ENABLED:
        _telemetry.inc("redist.staging.planned_windows", n_total)
        _telemetry.inc("redist.staging.planned_bytes", pcie_total)
        _obs_events.emit(
            "staging.plan",
            plan_id=sched.plan_id,
            host_bytes=host_bytes,
            windows=n_total,
            slab_bytes=slab_b,
            model_bound_gbps=annotation["model"]["bound_gbps"],
        )
    return sched


def prove_fits(sched: Schedule, hbm_bytes: Optional[int] = None) -> Schedule:
    """Prove a staged window schedule fits the HBM tier BEFORE running
    it: the ``Schedule.liveness()`` peak (resident working set + the
    depth-2 slab occupancy) must sit within ``tiers.capacity("hbm")``,
    and the host-resident operand within ``tiers.capacity("host")``.
    Raises ``MemoryError`` naming the violating number — the same
    budget arithmetic ``ht.analysis.memcheck`` (SL301) and serving
    admission read, because it IS the same ``capacity()`` call."""
    from ..core import tiers as _tiers

    budget = _tiers.capacity("hbm") if hbm_bytes is None else max(1, int(hbm_bytes))
    live = sched.liveness_peak_bytes
    if live > budget:
        raise MemoryError(
            f"staged plan {sched.plan_id} needs {live} B of HBM (resident "
            f"{sched.resident_bytes} B + slab peak {sched.peak_bytes} B) "
            f"> capacity('hbm') = {budget} B — shrink HEAT_TPU_OOC_SLAB_MB "
            "or the working set"
        )
    if sched.staging and int(sched.staging["host_bytes"]) > _tiers.capacity("host"):
        raise MemoryError(
            f"staged plan {sched.plan_id} keeps {sched.staging['host_bytes']} B "
            f"on the host tier > capacity('host') = {_tiers.capacity('host')} B"
        )
    return sched


def materialize(host: HostArray, what: str = "operand"):
    """Whole-operand device materialization of a :class:`HostArray` —
    the shared ``HEAT_TPU_OOC=0`` escape hatch (and the fallback for
    algorithms staging cannot serve, e.g. a full-SVD rank budget).
    Returns a replicated DNDarray; raises ``MemoryError`` naming the
    numbers when the operand cannot fit the hbm tier — the whole reason
    staging exists."""
    from ..core import factories, tiers as _tiers

    if host.nbytes > _tiers.capacity("hbm"):
        raise MemoryError(
            f"{what}: host-resident operand is {host.nbytes} B > "
            f"tiers.capacity('hbm') = {_tiers.capacity('hbm')} B and staging "
            f"is not engaged ({OOC_ENV}={ooc_mode()!r}) — the staged window "
            "stream is the only way to run it"
        )
    return factories.array(host.window(0, 0, host.shape[0]), split=None)


# --------------------------------------------------------------------- #
# the executor                                                          #
# --------------------------------------------------------------------- #
def stream_windows(
    host: HostArray,
    axis: int,
    windows: Sequence[Tuple[int, int]],
    consume: Callable[[int, Any, Tuple[int, int]], None],
    device_put: Optional[Callable[[np.ndarray], Any]] = None,
    plan_id: Optional[str] = None,
) -> None:
    """Depth-2 double-buffered window loop: the ``jax.device_put`` of
    window ``k+1`` is ISSUED before window ``k``'s compute consumes the
    slab, so the PCIe (host->HBM) transfer of the next window rides
    under the current window's compute — the staging analog of the
    PR-6 prefetch-issue-then-consume chunk pipelines. ``consume(k,
    slab_array, (start, stop))`` runs the per-window compute.

    Under ``HEAT_TPU_TRACE`` each window gets a ``staging.stage_in``
    span (real host wall around the ``device_put`` — the PCIe leg
    attribution measures) and a ``staging.compute`` span around its
    consume call, tagged with ``plan_id`` (the staged plan this stream
    executes) when the caller provides it. The probes wrap the
    callables, never the loop: issue order and numerics are identical
    with the gate on or off."""
    import jax

    put = device_put or jax.device_put
    windows = list(windows)
    if not windows:
        return
    if _tracing._ENABLED:
        put, consume = _tracing.window_probes(put, consume, plan_id)
    live = _telemetry._ENABLED
    nxt = put(host.window(axis, *windows[0]))
    for k, win in enumerate(windows):
        cur = nxt
        if k + 1 < len(windows):
            # depth-2: next window's stage_in goes on the wire now
            nxt = put(host.window(axis, *windows[k + 1]))
        if live:
            _telemetry.inc("redist.staging.windows")
            _telemetry.inc(
                "redist.staging.bytes_in",
                _win_bytes(host.shape, host.dtype.itemsize, axis, win),
            )
        consume(k, cur, win)


# --------------------------------------------------------------------- #
# golden staged plans — pinned by the determinism + verify sweeps       #
# --------------------------------------------------------------------- #
def golden_staged_plans() -> List[Tuple[str, Schedule]]:
    """The (name, staged plan) matrix the ci.sh determinism leg dumps
    and ``scripts/verify_plans.py`` proves well-formed. Slab and
    working-set bytes are pinned explicitly so an ambient
    ``HEAT_TPU_OOC_SLAB_MB``/``HEAT_TPU_HBM_BYTES`` cannot make two CI
    runs diverge. The 20 GB hsvd shape is the ROADMAP scenario (an
    operand larger than one v5e chip's HBM); the 2 GB twins match the
    measured bench rows."""
    from ..core import tiers as _tiers

    slab = DEFAULT_SLAB_MB << 20
    cap = _tiers.DEFAULT_HBM_BYTES  # pinned, NOT the ambient env
    hsvd2 = [{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}]
    return [
        (
            "staged_hsvd_20gb_2pass",
            plan_staged_passes(
                (65536, 81920), "float32", hsvd2, slab=slab,
                out_bytes=128 << 20, hbm_bytes=cap,
            ),
        ),
        (
            "staged_hsvd_2gb_2pass",
            plan_staged_passes(
                (65536, 8192), "float32", hsvd2, slab=slab,
                out_bytes=32 << 20, hbm_bytes=cap,
            ),
        ),
        (
            "staged_hsvd_2gb_1pass",
            plan_staged_passes(
                (65536, 8192),
                "float32",
                [{"tag": "dual-sketch", "axis": 1}],
                slab=slab,
                out_bytes=32 << 20,
                hbm_bytes=cap,
            ),
        ),
        (
            "staged_kmeans_2gb_stream",
            plan_staged_passes(
                (8_388_608, 64), "float32", [{"tag": "partial-fit", "axis": 0}],
                slab=slab, out_bytes=1 << 20, hbm_bytes=cap,
            ),
        ),
        # a transform-shaped pass that writes its windows back to host
        # (the stage_out leg of the verifier templates)
        (
            "staged_transform_4gb_writeback",
            plan_staged_passes(
                (131072, 8192),
                "float32",
                [{"tag": "transform", "axis": 0, "writeback": True}],
                slab=slab,
                out_bytes=0,
                hbm_bytes=cap,
            ),
        ),
    ]
