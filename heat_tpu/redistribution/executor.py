"""Schedule execution — lowering plans to jitted ``shard_map`` programs.

The planner's :class:`~heat_tpu.redistribution.schedule.Schedule` is the
contract; this module compiles it to exactly the collectives it lists
(tier-1 pins ``ht.observability.collective_counts`` == the plan's census
for the golden specs). One program per ``(comm, spec, budget)``, cached
and registered with ``communication.register_mesh_cache`` so world
rebuilds drop programs baked onto a defunct mesh.

Every program body runs under ``jax.named_scope("redist_plan_<id>")``:
the plan id lands in the HLO ``op_name`` metadata of every collective
the program launches, which is how shardlint (``analysis/ircheck``)
recognizes planner-issued reshards and reports them at info severity
with the plan attached instead of flagging the subsystem's own programs
(see ``analysis/boundaries.PLANNER_MODULES``).

Padding discipline (see ``core/_padding``): programs take the physical
(src-split-padded) array and return the physical dst-split-padded array;
pads along the exchanged axes are added/dropped with LOCAL copies inside
the same program, so the zero-pad invariant holds on the way out.

Software pipelining (ISSUE 6): the chunk/hop loops come in two issue
orders — the sequential oracle (lap k's collective, then lap k's
relayout copy: exactly the PR 5 program form, which is what the
``HEAT_TPU_REDIST_OVERLAP=0`` escape hatch restores) and the depth-2
pipelined form (prefetch-issue lap k+1's collective, THEN consume lap
k), selected per-execution by the plan's overlap annotation under the
gate (``_overlap_active``) and baked into the program cache key. Both
forms launch identical collectives and write identical (disjoint)
regions: census and numerics are bit-identical, pinned by
``tests/test_overlap.py``.

Wire quantization (ISSUE 7): when the plan carries ``quantize``/
``dequantize`` codec steps (``HEAT_TPU_WIRE_QUANT``), the same
chunk/hop loops ship encoded int8/bf16 payloads
(``heat_tpu.kernels.quant``): ``issue`` encodes the lap's
per-destination blocks and launches the SAME collective on the wire
buffer, ``consume`` decodes and scatters — so in the pipelined form
the dequantize copy rides under the next chunk's wire exactly like the
reassembly copy it replaces. The codec choice is part of every program
cache key (a gate flip rebuilds, never reuses), the census is
unchanged by construction, and with no codec the code paths are
byte-for-byte the PR 6 forms (the ``=0`` escape hatch is exact-bit).

Two-tier topology (ISSUE 8): a ``hierarchical-a2a`` plan's chunk laps
run the decomposed exchange — an intra-slice all-to-all over the
topology's chip subgroups (``axis_index_groups``; the cheap tier
carries the volume), then an inter-slice all-to-all over the slice
subgroups shipping only the pre-packed per-slice rows that must cross
DCN. The received blocks are placed EXACTLY where the flat all-to-all
would place them, so the output is bit-identical to the flat program
for any input; the codec (when the plan carries codec steps) engages
on the inter-slice hop only, the plan's first-target group. The
topology is part of every program cache key, and with a flat plan
(``HEAT_TPU_TOPOLOGY`` unset/1xN) the code paths are byte-for-byte
the PR 7 forms.
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from typing import Optional, Tuple

from ..core._jax_compat import shard_map
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing
from . import planner as _planner
from .schedule import Schedule
from .spec import RedistSpec

__all__ = ["execute", "resplit_phys", "reshape_phys", "clear_program_cache"]


def _pad_extent(n: int, p: int) -> int:
    from ..core import _padding

    return _padding.pad_extent(int(n), int(p))


def _plan_scope(plan_id: str):
    """The ``redist_plan_<id>`` named scope every program body runs
    under — IFF this module is registered in
    ``analysis/boundaries.PLANNER_MODULES``. The registration is the
    live switch: deregistering the executor stops the stamping, and
    shardlint's SL101/SL102 findings on its collectives revert from
    info+plan_id back to warning/error severity."""
    from ..analysis import boundaries as _boundaries

    if "redistribution/executor.py" in _boundaries.PLANNER_MODULES:
        return jax.named_scope(f"redist_plan_{plan_id}")
    return contextlib.nullcontext()


def _axis_spec(axis_name: str, ndim: int, split: Optional[int]) -> P:
    if split is None:
        return P(*(None,) * ndim)
    return P(*(axis_name if k == split else None for k in range(ndim)))


def _a2a_chunks(sched: Schedule) -> Tuple[int, int]:
    """(before, after) all_to_all LAP counts around the plan's
    ``reshape`` step — the chunk counts of the pivot's two collective
    groups, both structural (a move plan has no reshape step: everything
    lands in ``before``). The executor re-derives C from the schedule
    itself so program and plan cannot disagree, and from step KINDS, not
    the human-readable detail text. A hierarchical lap (ISSUE 8) emits
    an ici + dcn all_to_all PAIR: counting the non-``"ici"`` steps
    counts each lap once for flat (tier None / ``"dcn"``) and
    hierarchical plans alike."""
    before = after = 0
    seen_reshape = False
    for st in sched.steps:
        if st.kind == "reshape":
            seen_reshape = True
        elif st.kind == "all_to_all" and st.tier != "ici":
            if seen_reshape:
                after += 1
            else:
                before += 1
    return before, after


def _run_laps(indices, issue, consume, state, pipelined: bool, span_attrs=None):
    """The depth-2 double-buffer skeleton every chunk/hop loop shares.
    ``issue(k)`` launches lap k's collective (laps are independent —
    each slices from the source), ``consume(state, result, k)`` folds
    lap k's received buffer into the output. Sequential: issue lap k,
    consume lap k — exactly the PR 5 program form the
    ``HEAT_TPU_REDIST_OVERLAP=0`` escape hatch restores. Pipelined:
    prefetch-issue lap k+1 BEFORE consuming lap k, so the reassembly
    copy runs while the next collective is on the wire. Same
    collectives, disjoint writes: bit-identical either way.
    (``kernels.cmatmul.ring_all_gather`` keeps its own loop — its hops
    are CHAINED through the travelling block, a different dependence
    structure.)

    Under ``HEAT_TPU_TRACE`` the (issue, consume) pair is wrapped with
    one span per lap call (``span_attrs``: step kind + tier from the
    call site; plan_id rides the executor's ambient tracing context).
    The wrappers decorate the CALLABLES, never the loop: the issue
    order, the traced computation, and the compiled program bytes are
    identical with the gate on or off."""
    if _tracing._ENABLED:
        issue, consume = _tracing.lap_probes(issue, consume, span_attrs)
    idx = list(indices)
    if not pipelined or len(idx) < 2:
        for k in idx:
            state = consume(state, issue(k), k)
        return state
    prev = issue(idx[0])
    for i in range(1, len(idx)):
        nxt = issue(idx[i])  # lap i on the wire ...
        state = consume(state, prev, idx[i - 1])  # ... while i-1 relayouts
        prev = nxt
    return consume(state, prev, idx[-1])


def _quant_flags(sched: Schedule) -> Tuple[Optional[str], bool, bool]:
    """(mode, quant_in, quant_out): which collective groups of the plan
    run on encoded wire payloads, re-derived from step KINDS around the
    plan's ``reshape`` step (the executor/plan-cannot-disagree rule the
    chunk counts and packed flags already follow). A move/ring plan has
    no reshape step: its codec steps all land in ``quant_in``."""
    mode = sched.quant["mode"] if sched.quant else None
    seen_reshape = False
    qin = qout = False
    for st in sched.steps:
        if st.kind == "reshape":
            seen_reshape = True
        elif st.kind == "quantize":
            if seen_reshape:
                qout = True
            else:
                qin = True
    return mode, qin, qout


def _wire_a2a_blocks(chunk, axis_name: str, p: int, s_ax: int, codec: str):
    """The codec form of one tiled all-to-all lap: split ``chunk`` into
    its p per-destination blocks along ``s_ax``, encode each block as
    one wire row, and launch the SAME single all-to-all on the int8
    buffer. Returns the raw received wire rows — the caller decodes in
    ``consume`` so the full-width write rides under the next lap's
    collective in the pipelined form."""
    from ..kernels import quant as _quant

    m = jnp.moveaxis(chunk, s_ax, 0)
    blocks = m.reshape(p, -1)
    wire = _quant.encode_blocks(blocks, codec)
    return lax.all_to_all(wire, axis_name, 0, 0, tiled=True)


def _hier_groups(topo: Tuple[int, int]) -> Tuple[list, list]:
    """(chip_groups, slice_groups) ``axis_index_groups`` of a slice-major
    two-tier mesh — delegated to ``core.communication.Topology`` so the
    executor's subgroup structure can never drift from the planner's
    tier classification."""
    from ..core.communication import Topology

    t = Topology(*topo)
    return t.chip_axis_groups(), t.slice_axis_groups()


def _chunked_all_to_all(
    x, axis_name: str, p: int, split_axis: int, concat_axis: int, C: int,
    pipelined: bool = False, codec: Optional[str] = None,
    topo: Optional[Tuple[int, int]] = None,
):
    """Tiled all-to-all in C equal chunks along the concat axis, chunk
    results scattered (in place) into the destination-layout buffer.
    C == 1 is the direct single-collective form.

    ``pipelined`` switches the lap loop between the two issue orders of
    the SAME collectives (bit-identical output — the scatters write
    disjoint regions):

    - sequential (the oracle/floor, ``HEAT_TPU_REDIST_OVERLAP=0``):
      issue lap c, scatter lap c — EXACTLY the PR 5 program form, so the
      escape hatch restores the previously shipped schedule (no added
      barriers; XLA keeps whatever freedom it already had);
    - pipelined (depth 2): prefetch-issue lap c+1's all-to-all, THEN
      scatter lap c — the received chunk's relayout copy runs while the
      next chunk is on the wire (the ``nn/attention.py`` ring trick
      applied to the chunk pipeline; XLA's async collective pair
      brackets the independent copy work).

    ``codec`` (ISSUE 7) switches every lap onto the encoded wire:
    ``issue`` packs the lap's p destination blocks through
    ``kernels.quant.encode_blocks`` and launches ONE all-to-all on the
    int8 buffer (census unchanged); ``consume`` decodes and scatters,
    so the full-width dequantize write sits in the consume slot and
    rides under the next lap's wire when pipelined. ``codec=None`` is
    byte-for-byte the PR 6 program form.

    ``topo=(S, C)`` (ISSUE 8) runs each lap HIERARCHICALLY: an
    intra-slice all-to-all over the chip subgroups redistributes by
    destination chip (ICI carries the volume), then an inter-slice
    all-to-all over the slice subgroups ships the pre-packed per-slice
    rows (minimum DCN bytes; the codec — when given — encodes exactly
    this hop). The received per-source blocks are placed where the flat
    all-to-all would place them: bit-identical output by construction."""
    if topo is None and codec is None:
        if C <= 1:
            return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)
    from ..kernels import quant as _quant  # noqa: F401 (codec path only)

    x2 = jnp.moveaxis(x, concat_axis, 0)
    s_ax = split_axis + 1 if split_axis < concat_axis else split_axis
    Bc = x2.shape[0]
    C = max(C, 1)
    step = Bc // C
    out_shape = (Bc * p,) + tuple(
        d // p if k + 1 == s_ax else d for k, d in enumerate(x2.shape[1:])
    )

    if topo is not None:
        S_t, C_t = topo
        g_chip, g_slice = _hier_groups(topo)
        chunk_shape = (step,) + tuple(x2.shape[1:])
        B = chunk_shape[s_ax] // p
        vshape = chunk_shape[:s_ax] + (S_t, C_t, B) + chunk_shape[s_ax + 1 :]
        # the phase-2 buffer with the S axis moved to front (the wire rows)
        rest = vshape[:s_ax] + vshape[s_ax + 1 :]
        n_loc = 1
        for d in rest:
            n_loc *= d

        def _phase1(chunk):
            # destination-flat order (s'·C_t + c') factored as (S, C, B);
            # phase 1 (ICI): within each slice, destination-chip block c'
            # goes to chip c'; index c on that axis becomes SOURCE chip
            return lax.all_to_all(
                chunk.reshape(vshape), axis_name, s_ax + 1, s_ax + 1,
                tiled=True, axis_index_groups=g_chip,
            )

        def _place(out, r, c):
            # r: (..., p*B at s_ax, ...) in (s_src, c_src)-major order ==
            # the flat source-device order; place each source block where
            # the flat all-to-all's scatter puts it
            for q in range(p):
                piece = lax.slice_in_dim(r, q * B, (q + 1) * B, axis=s_ax)
                out = lax.dynamic_update_slice_in_dim(
                    out, piece, q * Bc + c * step, axis=0
                )
            return out

        if codec is None:

            def issue(c):
                chunk = lax.slice_in_dim(x2, c * step, (c + 1) * step, axis=0)
                v = _phase1(chunk)
                # phase 2 (DCN): same-chip peers across slices exchange
                # the destination-slice rows — already packed per slice,
                # so only the genuinely crossing bytes travel
                v = lax.all_to_all(
                    v, axis_name, s_ax, s_ax, tiled=True,
                    axis_index_groups=g_slice,
                )
                return v.reshape(
                    chunk_shape[:s_ax] + (p * B,) + chunk_shape[s_ax + 1 :]
                )

            consume = _place

        else:

            def issue(c):
                chunk = lax.slice_in_dim(x2, c * step, (c + 1) * step, axis=0)
                m = jnp.moveaxis(_phase1(chunk), s_ax, 0)
                wire = _quant.encode_blocks(m.reshape(S_t, n_loc), codec)
                # the encoded DCN hop; the decode sits in consume so the
                # full-width dequantize write rides under the next lap's
                # wire at depth 2, exactly like the flat codec form
                return lax.all_to_all(
                    wire, axis_name, 0, 0, tiled=True, axis_index_groups=g_slice
                )

            def consume(out, w, c):
                dec = _quant.decode_blocks(w, n_loc, codec).astype(x.dtype)
                v = jnp.moveaxis(dec.reshape((S_t,) + rest), 0, s_ax)
                r = v.reshape(
                    chunk_shape[:s_ax] + (p * B,) + chunk_shape[s_ax + 1 :]
                )
                return _place(out, r, c)

    elif codec is None:

        def issue(c):
            chunk = lax.slice_in_dim(x2, c * step, (c + 1) * step, axis=0)
            return lax.all_to_all(chunk, axis_name, s_ax, 0, tiled=True)  # (p*step, ...)

        def consume(out, r, c):
            for s in range(p):
                piece = lax.slice_in_dim(r, s * step, (s + 1) * step, axis=0)
                out = lax.dynamic_update_slice_in_dim(
                    out, piece, s * Bc + c * step, axis=0
                )
            return out

    else:
        S = x2.shape[s_ax]
        rest = tuple(x2.shape[1:s_ax]) + tuple(x2.shape[s_ax + 1 :])
        part_m_shape = (S // p, step) + rest
        n_loc = (S // p) * step
        for d in rest:
            n_loc *= d

        def issue(c):
            chunk = lax.slice_in_dim(x2, c * step, (c + 1) * step, axis=0)
            return _wire_a2a_blocks(chunk, axis_name, p, s_ax, codec)

        def consume(out, w, c):
            dec = _quant.decode_blocks(w, n_loc, codec).astype(x.dtype)
            for q in range(p):
                part = jnp.moveaxis(dec[q].reshape(part_m_shape), 0, s_ax)
                out = lax.dynamic_update_slice_in_dim(
                    out, part, q * Bc + c * step, axis=0
                )
            return out

    out = _run_laps(
        range(C), issue, consume, jnp.zeros(out_shape, x.dtype), pipelined,
        {"step": "all_to_all", "tier": "ici+dcn" if topo is not None else "ici"},
    )
    return jnp.moveaxis(out, 0, concat_axis)


def _packed_flags(sched: Schedule) -> Tuple[bool, bool]:
    """(packed_in, packed_out) — which pivot stages the plan runs on
    lane-packed buffers, re-derived from step KINDS around the plan's
    ``reshape`` step so program and plan cannot disagree."""
    seen_reshape = False
    packed_in = packed_out = False
    for st in sched.steps:
        if st.kind == "reshape":
            seen_reshape = True
        elif st.kind == "unpack" and not seen_reshape:
            packed_in = True
        elif st.kind == "pack" and seen_reshape:
            packed_out = True
    return packed_in, packed_out


def _chunked_a2a_flat(
    x, axis_name: str, p: int, C: int, pipelined: bool = False,
    codec: Optional[str] = None, topo: Optional[Tuple[int, int]] = None,
):
    """Tiled all-to-all of a ``(p, M)`` column-grouped FLAT buffer
    (``kernels.relayout.pack_rows`` layout): row d is the block bound
    for device d; the result's row q is the block received from device
    q. Both faces are lane-full wide buffers — the packed pivot's
    collective form. ``C > 1`` chunks equal column laps (C | M);
    ``pipelined`` prefetch-issues lap c+1 before placing lap c (same
    issue-order contract as :func:`_chunked_all_to_all`). ``codec``
    ships each lap's rows encoded (the buffer is already
    destination-major, so the wire rows ARE its rows); the decode sits
    in the consume slot. ``topo`` runs each lap hierarchically (ISSUE
    8): the row axis factors as (S, C) destination blocks — intra-slice
    exchange on the chip factor, inter-slice on the slice factor
    (codec-encoded when given) — and the received rows land in the same
    source-major order as the flat form: bit-identical."""
    if topo is None and codec is None:
        if C <= 1:
            return lax.all_to_all(x, axis_name, 0, 0, tiled=True)
    from ..kernels import quant as _quant  # noqa: F401 (codec path only)

    M = x.shape[1]
    C = max(C, 1)
    step = M // C

    if topo is not None:
        S_t, C_t = topo
        g_chip, g_slice = _hier_groups(topo)

        def _phase1(chunk):
            # rows (p, step) factored (S, C, step); intra-slice a2a on
            # the destination-chip factor
            return lax.all_to_all(
                chunk.reshape(S_t, C_t, step), axis_name, 1, 1, tiled=True,
                axis_index_groups=g_chip,
            )

        if codec is None:

            def issue(c):
                chunk = lax.slice_in_dim(x, c * step, (c + 1) * step, axis=1)
                v = lax.all_to_all(
                    _phase1(chunk), axis_name, 0, 0, tiled=True,
                    axis_index_groups=g_slice,
                )
                return v.reshape(p, step)

            def consume(out, r, c):
                return lax.dynamic_update_slice_in_dim(out, r, c * step, axis=1)

        else:

            def issue(c):
                chunk = lax.slice_in_dim(x, c * step, (c + 1) * step, axis=1)
                wire = _quant.encode_blocks(
                    _phase1(chunk).reshape(S_t, C_t * step), codec
                )
                # encoded DCN hop; decode sits in consume so the
                # full-width write rides under the next lap's wire
                return lax.all_to_all(
                    wire, axis_name, 0, 0, tiled=True, axis_index_groups=g_slice
                )

            def consume(out, w, c):
                dec = _quant.decode_blocks(w, C_t * step, codec).astype(x.dtype)
                return lax.dynamic_update_slice_in_dim(
                    out, dec.reshape(p, step), c * step, axis=1
                )

    elif codec is None:

        def issue(c):
            chunk = lax.slice_in_dim(x, c * step, (c + 1) * step, axis=1)
            return lax.all_to_all(chunk, axis_name, 0, 0, tiled=True)

        def consume(out, r, c):
            return lax.dynamic_update_slice_in_dim(out, r, c * step, axis=1)

    else:

        def issue(c):
            chunk = lax.slice_in_dim(x, c * step, (c + 1) * step, axis=1)
            wire = _quant.encode_blocks(chunk, codec)
            return lax.all_to_all(wire, axis_name, 0, 0, tiled=True)

        def consume(out, w, c):
            dec = _quant.decode_blocks(w, step, codec).astype(x.dtype)
            return lax.dynamic_update_slice_in_dim(out, dec, c * step, axis=1)

    return _run_laps(
        range(C), issue, consume, jnp.zeros_like(x), pipelined,
        {"step": "all_to_all", "tier": "ici+dcn" if topo is not None else "ici"},
    )


def _ring_exchange(
    x, axis_name: str, p: int, split_axis: int, concat_axis: int,
    pipelined: bool = False, codec: Optional[str] = None,
):
    """The same split i->j move as p-1 ppermute hops: at distance d every
    device ships ONE neighbor block, so only 2·(local/p) bytes are in
    flight per step — the minimal-footprint schedule. ``pipelined``
    prefetch-issues hop d+1's ppermute before scattering hop d's
    received block (hops slice independently from ``x``, so the rotation
    is a pure reorder: same hops, bit-identical output). ``codec``
    encodes each hop's neighbor block before the ppermute and decodes
    in the place slot — same hops, quarter the wire."""
    from ..kernels import quant as _quant  # noqa: F401 (codec path only)

    r = lax.axis_index(axis_name)
    S = x.shape[split_axis]
    Bs = S // p
    Bc = x.shape[concat_axis]
    out_shape = tuple(
        d * p if k == concat_axis else (Bs if k == split_axis else d)
        for k, d in enumerate(x.shape)
    )
    blk_shape = tuple(Bs if k == split_axis else d for k, d in enumerate(x.shape))
    blk_elems = 1
    for d in blk_shape:
        blk_elems *= d

    def hop(d):
        blk = lax.dynamic_slice_in_dim(x, ((r + d) % p) * Bs, Bs, axis=split_axis)
        if codec is not None:
            blk = _quant.encode_blocks(blk.reshape(1, blk_elems), codec)
        return lax.ppermute(blk, axis_name, [(s, (s + d) % p) for s in range(p)])

    def place(out, recv, d):
        if codec is not None:
            recv = (
                _quant.decode_blocks(recv, blk_elems, codec)
                .astype(x.dtype)
                .reshape(blk_shape)
            )
        return lax.dynamic_update_slice_in_dim(
            out, recv, ((r - d) % p) * Bc, axis=concat_axis
        )

    out = jnp.zeros(out_shape, x.dtype)
    own = lax.dynamic_slice_in_dim(x, r * Bs, Bs, axis=split_axis)
    out = lax.dynamic_update_slice_in_dim(out, own, r * Bc, axis=concat_axis)
    return _run_laps(
        range(1, p), hop, place, out, pipelined, {"step": "ppermute", "tier": "ici"}
    )


# --------------------------------------------------------------------- #
# program builders (one compiled program per (comm, spec, budget))      #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=512)
def _move_program(
    comm, spec: RedistSpec, budget: int, pipelined: bool = False,
    wire: Optional[str] = None, topo: Optional[Tuple[int, int]] = None,
):
    """split i -> split j (all-to-all / chunked / ring / hierarchical)
    on the physical array: pad dst axis (local) -> shard_map exchange ->
    drop src-axis pad (local). ``pipelined`` selects the depth-2
    prefetch-issue form of the chunk/hop loops (same collectives,
    bit-identical output) and is part of the program cache key —
    flipping the ``HEAT_TPU_REDIST_OVERLAP`` gate rebuilds the program.
    ``wire`` (the plan's codec mode, cache-keyed the same way) compiles
    the encoded-payload loop forms when the plan carries codec steps.
    ``topo`` (the plan's topology key, ISSUE 8) compiles the
    hierarchical exchange when the plan's strategy decomposed across
    tiers — and pins the internal re-plan to the same topology, so the
    stamped plan_id always matches the plan the caller executes."""
    sched = _planner.plan(
        spec, budget, quant=wire or "0", topology=topo if topo else "flat"
    )
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    i, j = spec.src_split, spec.dst_split
    ndim = len(spec.gshape)
    Ni, Nj = spec.gshape[i], spec.gshape[j]
    Nip, Njp = _pad_extent(Ni, p), _pad_extent(Nj, p)
    C = max(_a2a_chunks(sched)[0], 1)
    ring = sched.strategy == "ring"
    hier = sched.topo_key if sched.strategy == "hierarchical-a2a" else None
    codec, qin, _ = _quant_flags(sched)
    codec = codec if qin else None

    def body(xl):
        if ring:
            return _ring_exchange(
                xl, axis_name, p, split_axis=j, concat_axis=i,
                pipelined=pipelined, codec=codec,
            )
        return _chunked_all_to_all(
            xl, axis_name, p, split_axis=j, concat_axis=i, C=C,
            pipelined=pipelined, codec=codec, topo=hier,
        )

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, ndim, i),),
        out_specs=_axis_spec(axis_name, ndim, j),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            x = phys
            if Njp != Nj:  # local: axis j is unsharded in the src layout
                widths = [(0, 0)] * ndim
                widths[j] = (0, Njp - Nj)
                x = jnp.pad(x, widths)
            y = mapped(x)
            if Nip != Ni:  # local: axis i is unsharded in the dst layout
                y = lax.slice_in_dim(y, 0, Ni, axis=i)
            return y

    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _pivot_program(
    comm, spec: RedistSpec, budget: int, pipelined: bool = False,
    wire: Optional[str] = None, topo: Optional[Tuple[int, int]] = None,
):
    """Reshape-with-repartition through the split-0 pivot: all-to-all to
    the flat-contiguous split-0 layout, LOCAL row-major reshape (the
    minor-dim packing copy runs at full width), all-to-all out. Both
    chunk groups run ``pipelined`` as decorated prefetch-issue loops;
    each engages the wire codec independently per the plan's codec
    steps (``wire`` keys the cache); ``topo`` compiles both stage
    exchanges hierarchically when the plan decomposed across tiers."""
    sched = _planner.plan(
        spec, budget, quant=wire or "0", topology=topo if topo else "flat"
    )
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    in_shape, out_shape = spec.gshape, spec.out_shape
    ndim_in, ndim_out = len(in_shape), len(out_shape)
    n_in, n_out = _a2a_chunks(sched)
    C1, C2 = max(n_in, 1), max(n_out, 1)
    hier = sched.topo_key if sched.strategy == "hierarchical-a2a" else None
    codec, qin, qout = _quant_flags(sched)

    def body(xl):
        y = xl
        if s is not None and s != 0:
            y = _chunked_all_to_all(
                y, axis_name, p, split_axis=0, concat_axis=s, C=C1,
                pipelined=pipelined, codec=codec if qin else None, topo=hier,
            )
            in_s, in_sp = in_shape[s], _pad_extent(in_shape[s], p)
            if in_sp != in_s:
                y = lax.slice_in_dim(y, 0, in_s, axis=s)
        local_rows = out_shape[0] // p
        y = y.reshape((local_rows,) + tuple(out_shape[1:]))
        if t is not None and t != 0:
            out_t, out_tp = out_shape[t], _pad_extent(out_shape[t], p)
            if out_tp != out_t:
                widths = [(0, 0)] * ndim_out
                widths[t] = (0, out_tp - out_t)
                y = jnp.pad(y, widths)
            y = _chunked_all_to_all(
                y, axis_name, p, split_axis=t, concat_axis=0, C=C2,
                pipelined=pipelined, codec=codec if qout else None, topo=hier,
            )
        return y

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, ndim_in, s),),
        out_specs=_axis_spec(axis_name, ndim_out, t),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            return mapped(phys)

    return jax.jit(fn)


def _relayout_impls(
    spec: RedistSpec, sched: Schedule, concrete: bool = True
) -> Tuple[Optional[str], Optional[str]]:
    """The (unpack-in, pack-out) kernel implementations serving a
    packed-pivot plan, decided EAGERLY at program-build time and baked
    into the program cache key: flipping ``HEAT_TPU_RELAYOUT_KERNEL``
    rebuilds the program. ``concrete=False`` (the executor is itself
    being traced, e.g. a reshape under ``ht.jit``) forbids the blocking
    autotune — the decision falls back to a cached winner or the XLA
    floor, honoring the ``relayout-autotune-sync`` boundary's
    never-inside-a-trace contract."""
    from ..kernels import relayout as _relayout

    packed_in, packed_out = _packed_flags(sched)
    p = spec.mesh_size
    (r0, c0), (r1, c1) = spec.gshape, spec.out_shape
    c0p, c1p = _pad_extent(c0, p), _pad_extent(c1, p)
    impl_in = (
        _relayout.decide("unpack", r0 // p, c0p, c0, p, spec.dtype, concrete=concrete)
        if packed_in
        else None
    )
    impl_out = (
        _relayout.decide("pack", r1 // p, c1, c1p, p, spec.dtype, concrete=concrete)
        if packed_out
        else None
    )
    return impl_in, impl_out


@functools.lru_cache(maxsize=512)
def _packed_pivot_program(
    comm, spec: RedistSpec, budget: int, impl_in, impl_out,
    pipelined: bool = False, wire: Optional[str] = None,
    topo: Optional[Tuple[int, int]] = None,
):
    """The lane-packing pivot (``packed-pivot``): narrow-minor stages
    run on (p, rows·cols/p) column-grouped FLAT buffers so the chunked
    all-to-alls stream full VREGs; the pack/unpack tile-transposing
    copies are served by ``heat_tpu.kernels.relayout`` (XLA formulation
    or the Pallas tiled-copy kernel per ``impl_*``), and the only
    lane-amplified write left is the final dst-shard materialization.
    Same collective census as the direct pivot."""
    from ..kernels import relayout as _relayout

    sched = _planner.plan(
        spec, budget, quant=wire or "0", topology=topo if topo else "flat"
    )
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    (r0, c0), (r1, c1) = spec.gshape, spec.out_shape
    c0p, c1p = _pad_extent(c0, p), _pad_extent(c1, p)
    R0, R1 = r0 // p, r1 // p
    cs0, cs1 = c0p // p, c1p // p
    n_in, n_out = _a2a_chunks(sched)
    C1, C2 = max(n_in, 1), max(n_out, 1)
    packed_in, packed_out = _packed_flags(sched)
    hier = sched.topo_key if sched.strategy == "hierarchical-a2a" else None
    codec, qin, qout = _quant_flags(sched)
    codec_in = codec if qin else None
    codec_out = codec if qout else None

    def body(xl):
        if s == 1:
            if packed_in:
                grouped = xl.reshape(p, R0 * cs0)  # free row-block grouping
                recv = _chunked_a2a_flat(
                    grouped, axis_name, p, C1, pipelined=pipelined,
                    codec=codec_in, topo=hier,
                )
                flat = _relayout.unpack_rows(recv, R0, c0p, c0, p, impl=impl_in)
            else:
                y = _chunked_all_to_all(
                    xl, axis_name, p, split_axis=0, concat_axis=1, C=C1,
                    pipelined=pipelined, codec=codec_in, topo=hier,
                )
                if c0p != c0:
                    y = lax.slice_in_dim(y, 0, c0, axis=1)
                flat = y.reshape(R0 * c0)
        else:  # s == 0: the shard already is a contiguous flat block
            flat = xl.reshape(-1)
        if t == 1:
            if packed_out:
                grouped = _relayout.pack_rows(flat, R1, c1, c1p, p, impl=impl_out)
                recv = _chunked_a2a_flat(
                    grouped, axis_name, p, C2, pipelined=pipelined,
                    codec=codec_out, topo=hier,
                )
                # rows arrive in global order: the reshape IS the single
                # lane-amplified materialization of the requested layout
                return recv.reshape(r1, cs1)
            y = flat.reshape(R1, c1)
            if c1p != c1:
                y = jnp.pad(y, ((0, 0), (0, c1p - c1)))
            return _chunked_all_to_all(
                y, axis_name, p, split_axis=1, concat_axis=0, C=C2,
                pipelined=pipelined, codec=codec_out, topo=hier,
            )
        return flat.reshape(R1, c1)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, 2, s),),
        out_specs=_axis_spec(axis_name, 2, t),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            return mapped(phys)

    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _gather_reshape_program(
    comm, spec: RedistSpec, budget: int, topo: Optional[Tuple[int, int]] = None
):
    """The explicit fallback: replicate the physical operand (ONE
    all-gather), drop pads, reshape, re-pad and slice out the dst shard.
    Also serves the replicated-source reshape (no gather: the constraint
    on an already-replicated operand is a no-op). ``topo`` only pins the
    internal re-plan (the tier annotation changes the stamped plan_id,
    never the program form — a full gather spans slices either way)."""
    from ..core import _padding

    sched = _planner.plan(spec, budget, topology=topo if topo else "flat")
    mesh, axis_name = comm.mesh, comm.axis_name
    s, t = spec.src_split, spec.dst_split
    out_shape = spec.out_shape
    ndim_out = max(len(out_shape), 1)

    def fn(phys):
        with _plan_scope(sched.plan_id):
            full = lax.with_sharding_constraint(
                phys, comm.sharding(max(phys.ndim, 1), None)
            )
            logical = _padding.unpad(full, spec.gshape, s)
            r = jnp.reshape(logical, out_shape) if spec.is_reshape else logical
            rp = _padding.pad_logical(r, t, comm.size)
            return lax.with_sharding_constraint(rp, comm.sharding(ndim_out, t))

    return comm.jit_sharded(fn, ndim_out, t)


@functools.lru_cache(maxsize=512)
def _local_reshape_program(comm, spec: RedistSpec, budget: int):
    """Zero-collective reshape paths: 1-device meshes and replicated
    sources (the dst distribution is a local slice). No topo key: a
    collective-free plan carries no tier annotation, so its plan_id is
    topology-independent by construction."""
    from ..core import _padding

    sched = _planner.plan(spec, budget)
    s, t = spec.src_split, spec.dst_split
    out_shape = spec.out_shape
    ndim_out = max(len(out_shape), 1)

    def fn(phys):
        with _plan_scope(sched.plan_id):
            logical = _padding.unpad(phys, spec.gshape, s)
            r = jnp.reshape(logical, out_shape)
            rp = _padding.pad_logical(r, t, comm.size)
            return lax.with_sharding_constraint(rp, comm.sharding(ndim_out, t))

    return comm.jit_sharded(fn, ndim_out, t)


def clear_program_cache() -> None:
    _move_program.cache_clear()
    _pivot_program.cache_clear()
    _packed_pivot_program.cache_clear()
    _gather_reshape_program.cache_clear()
    _local_reshape_program.cache_clear()


# a world rebuild (init_distributed) invalidates every program: the
# mesh (and the comm identity in the cache key) baked into them is gone
from ..core.communication import register_mesh_cache as _register_mesh_cache

_register_mesh_cache(_move_program)
_register_mesh_cache(_pivot_program)
_register_mesh_cache(_packed_pivot_program)
_register_mesh_cache(_gather_reshape_program)
_register_mesh_cache(_local_reshape_program)


# --------------------------------------------------------------------- #
# execution                                                             #
# --------------------------------------------------------------------- #
def _overlap_active(sched: Schedule) -> bool:
    """Does this execution run the software-pipelined program form?
    ``HEAT_TPU_REDIST_OVERLAP=0`` forces the sequential oracle, ``=1``
    forces pipelining, and the default ``auto`` follows the plan's own
    overlap annotation (the planner's modeled depth decision). Either
    way the plan — and therefore the collective census — is the same;
    only the issue order inside the chunk loops changes."""
    mode = _planner.overlap_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return sched.overlap is not None


def _reshard_direct(comm, phys, gshape, src, dst):
    """The legacy relayout (unpad -> repad -> placement): still the
    lowering for the no-collective strategies, where GSPMD's local
    slice IS the schedule."""
    from ..core import _padding

    logical = _padding.unpad(phys, tuple(gshape), src)
    return comm.shard(logical, dst)


def execute(comm, phys, spec: RedistSpec, sched: Optional[Schedule] = None):
    """Run the planned redistribution of ``phys`` (a physical array laid
    out per ``spec.src_split``) and return the dst-layout physical
    array. Trace-safe: under a trace the cached jitted programs inline
    and the eager placements lower to sharding constraints."""
    # world-epoch fence (ISSUE 13): an in-flight collective entering on
    # a communicator the elastic runtime stamped for a world that has
    # since re-resolved raises the typed WorldChangedError instead of
    # hanging on devices that are gone. Zero-cost by construction when
    # no communicator was ever stamped (the default and the
    # HEAT_TPU_RESILIENCE=0 escape hatch: one empty-dict truthiness
    # check), so the pre-resilience dispatch path is untouched.
    from ..resilience import elastic as _elastic

    _elastic.check_world(comm)
    if sched is None:
        sched = _planner.plan(spec)
    else:
        # the program builders compile the PLANNER's schedule for
        # (spec, budget, codec, topology) — a hand-built/modified
        # Schedule would be silently ignored, so refuse it instead (a
        # caller-provided sched pins ITS codec AND topology: passing a
        # quantized or tiered plan executes that program regardless of
        # the ambient gates)
        planned = _planner.plan(
            spec, sched.budget_bytes,
            quant=sched.quant["mode"] if sched.quant else "0",
            topology=sched.topo_key if sched.topo_key else "flat",
        )
        if planned.plan_id != sched.plan_id:
            raise ValueError(
                f"execute: schedule {sched.plan_id} is not the planner's "
                f"plan for {spec!r} under budget {sched.budget_bytes} B "
                f"(expected {planned.plan_id}); executor programs compile "
                "from the plan cache, not from caller-provided schedules"
            )
    if _telemetry._ENABLED:
        _telemetry.inc("redist.execute.calls")
    strategy = sched.strategy
    budget = sched.budget_bytes
    wire = sched.quant["mode"] if sched.quant else None
    topo = sched.topo_key
    # a program only HAS a pipelined issue order when the plan carries
    # tagged laps (chunk groups / ring hops): single-collective plans and
    # the barrier strategies (replicate/gather-reshape/local-reshape)
    # must neither count as pipelined executions nor compile a second,
    # identical program under the pipelined cache key
    pipeable = any(st.overlap for st in sched.steps)
    pipelined = _overlap_active(sched) and pipeable
    if _telemetry._ENABLED and strategy not in ("noop", "local", "slice"):
        _telemetry.inc(
            "redist.overlap.pipelined" if pipelined else "redist.overlap.sequential"
        )
        if sched.n_collectives:
            # bytes-on-wire accounting (ISSUE 7): raw = full-width
            # payload of the plan's collectives, sent = what actually
            # crosses the mesh (the encoded bytes under the codec)
            raw, sent = sched.wire_bytes_raw, sched.wire_bytes_sent
            _telemetry.inc("redist.wire.bytes_raw", raw)
            _telemetry.inc("redist.wire.bytes_sent", sent)
            _telemetry.inc("redist.wire.saved", raw - sent)
        if topo is not None and sched.n_collectives:
            # per-tier wire accounting (ISSUE 8)
            tb = sched.tier_bytes()
            _telemetry.inc("redist.tier.ici_bytes", tb["ici"])
            _telemetry.inc("redist.tier.dcn_bytes", tb["dcn"])
    def _dispatch():
        if strategy == "noop":
            return phys
        if strategy in ("slice",) or (strategy == "local" and not spec.is_reshape):
            # no-collective placements: GSPMD's local slice IS the schedule,
            # and with no collective there is nothing for shardlint to flag
            return _reshard_direct(comm, phys, spec.gshape, spec.src_split, spec.dst_split)
        if strategy == "replicate":
            # the explicit full all-gather runs as a stamped program too, so
            # its SL102 finding reports as info with the plan id attached
            return _gather_reshape_program(comm, spec, budget, topo)(phys)
        if strategy in ("all-to-all", "chunked-all-to-all", "ring"):
            return _move_program(comm, spec, budget, pipelined, wire, topo)(phys)
        if strategy == "hierarchical-a2a":
            # the tiered decomposition (ISSUE 8): pivot-family when the plan
            # carries a reshape step, plain move otherwise; packed when the
            # plan carries pack/unpack steps — all re-derived from step
            # KINDS so program and plan cannot disagree
            if spec.is_reshape:
                if any(st.kind in ("pack", "unpack") for st in sched.steps):
                    if _telemetry._ENABLED:
                        _telemetry.inc("redist.relayout.packed")
                    impl_in, impl_out = _relayout_impls(
                        spec, sched, concrete=not isinstance(phys, jax.core.Tracer)
                    )
                    return _packed_pivot_program(
                        comm, spec, budget, impl_in, impl_out, pipelined, wire, topo
                    )(phys)
                if _telemetry._ENABLED:
                    _telemetry.inc("redist.relayout.direct")
                return _pivot_program(comm, spec, budget, pipelined, wire, topo)(phys)
            return _move_program(comm, spec, budget, pipelined, wire, topo)(phys)
        if strategy == "split0-pivot":
            if _telemetry._ENABLED:
                _telemetry.inc("redist.relayout.direct")
            return _pivot_program(comm, spec, budget, pipelined, wire, topo)(phys)
        if strategy == "packed-pivot":
            if _telemetry._ENABLED:
                _telemetry.inc("redist.relayout.packed")
            impl_in, impl_out = _relayout_impls(
                spec, sched, concrete=not isinstance(phys, jax.core.Tracer)
            )
            return _packed_pivot_program(
                comm, spec, budget, impl_in, impl_out, pipelined, wire, topo
            )(phys)
        if strategy == "gather-reshape":
            return _gather_reshape_program(comm, spec, budget, topo)(phys)
        if strategy in ("local-reshape", "local"):
            if spec.src_split == 0 and spec.dst_split == 0 and spec.mesh_size > 1:
                # divisible split-0 <-> split-0: device blocks stay put
                return _pivot_program(comm, spec, budget, pipelined, wire, topo)(phys)
            return _local_reshape_program(comm, spec, budget)(phys)
        raise ValueError(f"unknown strategy {strategy!r} (plan {sched.plan_id})")

    if not _tracing._ENABLED:
        return _dispatch()
    # span tracing (ISSUE 15): one host-side `redist.execute` span per
    # plan execution, with the plan_id as ambient context so the
    # per-lap probes inside the (possibly now-tracing) program body
    # inherit it. On a program-cache hit the body never re-traces, so
    # the lap spans fire once per compile — span census == plan
    # structure, pinned in tier-1.
    # the MODULE, not the `attribution` function that shadows it in the
    # observability package namespace (the core.jit gotcha)
    from ..observability.attribution import register_plan as _register_plan

    _register_plan(sched)
    with _tracing.span(
        "redist.execute",
        plan_id=sched.plan_id,
        strategy=strategy,
        step="execute",
        pipelined=pipelined,
        n_steps=sched.n_steps,
        n_collectives=sched.n_collectives,
    ):
        with _tracing.context(plan_id=sched.plan_id):
            return _dispatch()


def resplit_phys(comm, phys, gshape, src: Optional[int], dst: Optional[int]):
    """Planner-routed split change of a physical array — the engine
    under ``DNDarray.resplit``/``resplit_`` and
    ``MeshCommunication.reshard_phys``."""
    gshape = tuple(int(v) for v in gshape)
    if (
        not _planner.planner_enabled()
        or phys.ndim != len(gshape)  # planar-complex plane pairs: legacy path
        or any(v == 0 for v in gshape)
    ):
        return _reshard_direct(comm, phys, gshape, src, dst)
    spec = RedistSpec.normalize(gshape, np.dtype(phys.dtype).name, src, dst, comm.size)
    return execute(comm, phys, spec)


def reshape_phys(comm, phys, in_gshape, in_split, out_shape, out_split):
    """Planner-routed reshape-with-repartition of a physical array — the
    engine under ``ht.reshape(..., new_split=...)``."""
    in_gshape = tuple(int(v) for v in in_gshape)
    out_shape = tuple(int(v) for v in out_shape)
    spec = RedistSpec.normalize(
        in_gshape,
        np.dtype(phys.dtype).name,
        in_split,
        out_split,
        comm.size,
        reshape_to=out_shape,
    )
    return execute(comm, phys, spec)
