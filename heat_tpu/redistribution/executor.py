"""Schedule execution — lowering plans to jitted ``shard_map`` programs.

The planner's :class:`~heat_tpu.redistribution.schedule.Schedule` is the
contract; this module compiles it to exactly the collectives it lists
(tier-1 pins ``ht.observability.collective_counts`` == the plan's census
for the golden specs). One program per ``(comm, spec, budget)``, cached
and registered with ``communication.register_mesh_cache`` so world
rebuilds drop programs baked onto a defunct mesh.

Every program body runs under ``jax.named_scope("redist_plan_<id>")``:
the plan id lands in the HLO ``op_name`` metadata of every collective
the program launches, which is how shardlint (``analysis/ircheck``)
recognizes planner-issued reshards and reports them at info severity
with the plan attached instead of flagging the subsystem's own programs
(see ``analysis/boundaries.PLANNER_MODULES``).

Padding discipline (see ``core/_padding``): programs take the physical
(src-split-padded) array and return the physical dst-split-padded array;
pads along the exchanged axes are added/dropped with LOCAL copies inside
the same program, so the zero-pad invariant holds on the way out.
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from typing import Optional, Tuple

from ..core._jax_compat import shard_map
from ..observability import telemetry as _telemetry
from . import planner as _planner
from .schedule import Schedule
from .spec import RedistSpec

__all__ = ["execute", "resplit_phys", "reshape_phys", "clear_program_cache"]


def _pad_extent(n: int, p: int) -> int:
    from ..core import _padding

    return _padding.pad_extent(int(n), int(p))


def _plan_scope(plan_id: str):
    """The ``redist_plan_<id>`` named scope every program body runs
    under — IFF this module is registered in
    ``analysis/boundaries.PLANNER_MODULES``. The registration is the
    live switch: deregistering the executor stops the stamping, and
    shardlint's SL101/SL102 findings on its collectives revert from
    info+plan_id back to warning/error severity."""
    from ..analysis import boundaries as _boundaries

    if "redistribution/executor.py" in _boundaries.PLANNER_MODULES:
        return jax.named_scope(f"redist_plan_{plan_id}")
    return contextlib.nullcontext()


def _axis_spec(axis_name: str, ndim: int, split: Optional[int]) -> P:
    if split is None:
        return P(*(None,) * ndim)
    return P(*(axis_name if k == split else None for k in range(ndim)))


def _a2a_chunks(sched: Schedule) -> Tuple[int, int]:
    """(before, after) all_to_all counts around the plan's ``reshape``
    step — the chunk counts of the pivot's two collective groups, both
    structural (a move plan has no reshape step: everything lands in
    ``before``). The executor re-derives C from the schedule itself so
    program and plan cannot disagree, and from step KINDS, not the
    human-readable detail text."""
    before = after = 0
    seen_reshape = False
    for st in sched.steps:
        if st.kind == "reshape":
            seen_reshape = True
        elif st.kind == "all_to_all":
            if seen_reshape:
                after += 1
            else:
                before += 1
    return before, after


def _chunked_all_to_all(x, axis_name: str, p: int, split_axis: int, concat_axis: int, C: int):
    """Tiled all-to-all in C equal chunks along the concat axis, chunk
    results scattered (in place) into the destination-layout buffer.
    C == 1 is the direct single-collective form."""
    if C <= 1:
        return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)
    x2 = jnp.moveaxis(x, concat_axis, 0)
    s_ax = split_axis + 1 if split_axis < concat_axis else split_axis
    Bc = x2.shape[0]
    step = Bc // C
    out_shape = (Bc * p,) + tuple(
        d // p if k + 1 == s_ax else d for k, d in enumerate(x2.shape[1:])
    )
    out = jnp.zeros(out_shape, x.dtype)
    for c in range(C):
        chunk = lax.slice_in_dim(x2, c * step, (c + 1) * step, axis=0)
        r = lax.all_to_all(chunk, axis_name, s_ax, 0, tiled=True)  # (p*step, ...)
        for s in range(p):
            piece = lax.slice_in_dim(r, s * step, (s + 1) * step, axis=0)
            out = lax.dynamic_update_slice_in_dim(out, piece, s * Bc + c * step, axis=0)
    return jnp.moveaxis(out, 0, concat_axis)


def _packed_flags(sched: Schedule) -> Tuple[bool, bool]:
    """(packed_in, packed_out) — which pivot stages the plan runs on
    lane-packed buffers, re-derived from step KINDS around the plan's
    ``reshape`` step so program and plan cannot disagree."""
    seen_reshape = False
    packed_in = packed_out = False
    for st in sched.steps:
        if st.kind == "reshape":
            seen_reshape = True
        elif st.kind == "unpack" and not seen_reshape:
            packed_in = True
        elif st.kind == "pack" and seen_reshape:
            packed_out = True
    return packed_in, packed_out


def _chunked_a2a_flat(x, axis_name: str, p: int, C: int):
    """Tiled all-to-all of a ``(p, M)`` column-grouped FLAT buffer
    (``kernels.relayout.pack_rows`` layout): row d is the block bound
    for device d; the result's row q is the block received from device
    q. Both faces are lane-full wide buffers — the packed pivot's
    collective form. ``C > 1`` pipelines equal column chunks (C | M)."""
    if C <= 1:
        return lax.all_to_all(x, axis_name, 0, 0, tiled=True)
    M = x.shape[1]
    step = M // C
    out = jnp.zeros_like(x)
    for c in range(C):
        chunk = lax.slice_in_dim(x, c * step, (c + 1) * step, axis=1)
        r = lax.all_to_all(chunk, axis_name, 0, 0, tiled=True)
        out = lax.dynamic_update_slice_in_dim(out, r, c * step, axis=1)
    return out


def _ring_exchange(x, axis_name: str, p: int, split_axis: int, concat_axis: int):
    """The same split i->j move as p-1 ppermute hops: at distance d every
    device ships ONE neighbor block, so only 2·(local/p) bytes are in
    flight per step — the minimal-footprint schedule."""
    r = lax.axis_index(axis_name)
    S = x.shape[split_axis]
    Bs = S // p
    Bc = x.shape[concat_axis]
    out_shape = tuple(
        d * p if k == concat_axis else (Bs if k == split_axis else d)
        for k, d in enumerate(x.shape)
    )
    out = jnp.zeros(out_shape, x.dtype)
    own = lax.dynamic_slice_in_dim(x, r * Bs, Bs, axis=split_axis)
    out = lax.dynamic_update_slice_in_dim(out, own, r * Bc, axis=concat_axis)
    for d in range(1, p):
        blk = lax.dynamic_slice_in_dim(x, ((r + d) % p) * Bs, Bs, axis=split_axis)
        recv = lax.ppermute(blk, axis_name, [(s, (s + d) % p) for s in range(p)])
        out = lax.dynamic_update_slice_in_dim(out, recv, ((r - d) % p) * Bc, axis=concat_axis)
    return out


# --------------------------------------------------------------------- #
# program builders (one compiled program per (comm, spec, budget))      #
# --------------------------------------------------------------------- #
@functools.lru_cache(maxsize=512)
def _move_program(comm, spec: RedistSpec, budget: int):
    """split i -> split j (all-to-all / chunked / ring) on the physical
    array: pad dst axis (local) -> shard_map exchange -> drop src-axis
    pad (local)."""
    sched = _planner.plan(spec, budget)
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    i, j = spec.src_split, spec.dst_split
    ndim = len(spec.gshape)
    Ni, Nj = spec.gshape[i], spec.gshape[j]
    Nip, Njp = _pad_extent(Ni, p), _pad_extent(Nj, p)
    C = max(_a2a_chunks(sched)[0], 1)
    ring = sched.strategy == "ring"

    def body(xl):
        if ring:
            return _ring_exchange(xl, axis_name, p, split_axis=j, concat_axis=i)
        return _chunked_all_to_all(xl, axis_name, p, split_axis=j, concat_axis=i, C=C)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, ndim, i),),
        out_specs=_axis_spec(axis_name, ndim, j),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            x = phys
            if Njp != Nj:  # local: axis j is unsharded in the src layout
                widths = [(0, 0)] * ndim
                widths[j] = (0, Njp - Nj)
                x = jnp.pad(x, widths)
            y = mapped(x)
            if Nip != Ni:  # local: axis i is unsharded in the dst layout
                y = lax.slice_in_dim(y, 0, Ni, axis=i)
            return y

    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _pivot_program(comm, spec: RedistSpec, budget: int):
    """Reshape-with-repartition through the split-0 pivot: all-to-all to
    the flat-contiguous split-0 layout, LOCAL row-major reshape (the
    minor-dim packing copy runs at full width), all-to-all out."""
    sched = _planner.plan(spec, budget)
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    in_shape, out_shape = spec.gshape, spec.out_shape
    ndim_in, ndim_out = len(in_shape), len(out_shape)
    n_in, n_out = _a2a_chunks(sched)
    C1, C2 = max(n_in, 1), max(n_out, 1)

    def body(xl):
        y = xl
        if s is not None and s != 0:
            y = _chunked_all_to_all(y, axis_name, p, split_axis=0, concat_axis=s, C=C1)
            in_s, in_sp = in_shape[s], _pad_extent(in_shape[s], p)
            if in_sp != in_s:
                y = lax.slice_in_dim(y, 0, in_s, axis=s)
        local_rows = out_shape[0] // p
        y = y.reshape((local_rows,) + tuple(out_shape[1:]))
        if t is not None and t != 0:
            out_t, out_tp = out_shape[t], _pad_extent(out_shape[t], p)
            if out_tp != out_t:
                widths = [(0, 0)] * ndim_out
                widths[t] = (0, out_tp - out_t)
                y = jnp.pad(y, widths)
            y = _chunked_all_to_all(y, axis_name, p, split_axis=t, concat_axis=0, C=C2)
        return y

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, ndim_in, s),),
        out_specs=_axis_spec(axis_name, ndim_out, t),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            return mapped(phys)

    return jax.jit(fn)


def _relayout_impls(
    spec: RedistSpec, sched: Schedule, concrete: bool = True
) -> Tuple[Optional[str], Optional[str]]:
    """The (unpack-in, pack-out) kernel implementations serving a
    packed-pivot plan, decided EAGERLY at program-build time and baked
    into the program cache key: flipping ``HEAT_TPU_RELAYOUT_KERNEL``
    rebuilds the program. ``concrete=False`` (the executor is itself
    being traced, e.g. a reshape under ``ht.jit``) forbids the blocking
    autotune — the decision falls back to a cached winner or the XLA
    floor, honoring the ``relayout-autotune-sync`` boundary's
    never-inside-a-trace contract."""
    from ..kernels import relayout as _relayout

    packed_in, packed_out = _packed_flags(sched)
    p = spec.mesh_size
    (r0, c0), (r1, c1) = spec.gshape, spec.out_shape
    c0p, c1p = _pad_extent(c0, p), _pad_extent(c1, p)
    impl_in = (
        _relayout.decide("unpack", r0 // p, c0p, c0, p, spec.dtype, concrete=concrete)
        if packed_in
        else None
    )
    impl_out = (
        _relayout.decide("pack", r1 // p, c1, c1p, p, spec.dtype, concrete=concrete)
        if packed_out
        else None
    )
    return impl_in, impl_out


@functools.lru_cache(maxsize=512)
def _packed_pivot_program(comm, spec: RedistSpec, budget: int, impl_in, impl_out):
    """The lane-packing pivot (``packed-pivot``): narrow-minor stages
    run on (p, rows·cols/p) column-grouped FLAT buffers so the chunked
    all-to-alls stream full VREGs; the pack/unpack tile-transposing
    copies are served by ``heat_tpu.kernels.relayout`` (XLA formulation
    or the Pallas tiled-copy kernel per ``impl_*``), and the only
    lane-amplified write left is the final dst-shard materialization.
    Same collective census as the direct pivot."""
    from ..kernels import relayout as _relayout

    sched = _planner.plan(spec, budget)
    mesh, axis_name = comm.mesh, comm.axis_name
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    (r0, c0), (r1, c1) = spec.gshape, spec.out_shape
    c0p, c1p = _pad_extent(c0, p), _pad_extent(c1, p)
    R0, R1 = r0 // p, r1 // p
    cs0, cs1 = c0p // p, c1p // p
    n_in, n_out = _a2a_chunks(sched)
    C1, C2 = max(n_in, 1), max(n_out, 1)
    packed_in, packed_out = _packed_flags(sched)

    def body(xl):
        if s == 1:
            if packed_in:
                grouped = xl.reshape(p, R0 * cs0)  # free row-block grouping
                recv = _chunked_a2a_flat(grouped, axis_name, p, C1)
                flat = _relayout.unpack_rows(recv, R0, c0p, c0, p, impl=impl_in)
            else:
                y = _chunked_all_to_all(xl, axis_name, p, split_axis=0, concat_axis=1, C=C1)
                if c0p != c0:
                    y = lax.slice_in_dim(y, 0, c0, axis=1)
                flat = y.reshape(R0 * c0)
        else:  # s == 0: the shard already is a contiguous flat block
            flat = xl.reshape(-1)
        if t == 1:
            if packed_out:
                grouped = _relayout.pack_rows(flat, R1, c1, c1p, p, impl=impl_out)
                recv = _chunked_a2a_flat(grouped, axis_name, p, C2)
                # rows arrive in global order: the reshape IS the single
                # lane-amplified materialization of the requested layout
                return recv.reshape(r1, cs1)
            y = flat.reshape(R1, c1)
            if c1p != c1:
                y = jnp.pad(y, ((0, 0), (0, c1p - c1)))
            return _chunked_all_to_all(y, axis_name, p, split_axis=1, concat_axis=0, C=C2)
        return flat.reshape(R1, c1)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(_axis_spec(axis_name, 2, s),),
        out_specs=_axis_spec(axis_name, 2, t),
        check_vma=False,
    )

    def fn(phys):
        with _plan_scope(sched.plan_id):
            return mapped(phys)

    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _gather_reshape_program(comm, spec: RedistSpec, budget: int):
    """The explicit fallback: replicate the physical operand (ONE
    all-gather), drop pads, reshape, re-pad and slice out the dst shard.
    Also serves the replicated-source reshape (no gather: the constraint
    on an already-replicated operand is a no-op)."""
    from ..core import _padding

    sched = _planner.plan(spec, budget)
    mesh, axis_name = comm.mesh, comm.axis_name
    s, t = spec.src_split, spec.dst_split
    out_shape = spec.out_shape
    ndim_out = max(len(out_shape), 1)

    def fn(phys):
        with _plan_scope(sched.plan_id):
            full = lax.with_sharding_constraint(
                phys, comm.sharding(max(phys.ndim, 1), None)
            )
            logical = _padding.unpad(full, spec.gshape, s)
            r = jnp.reshape(logical, out_shape) if spec.is_reshape else logical
            rp = _padding.pad_logical(r, t, comm.size)
            return lax.with_sharding_constraint(rp, comm.sharding(ndim_out, t))

    return comm.jit_sharded(fn, ndim_out, t)


@functools.lru_cache(maxsize=512)
def _local_reshape_program(comm, spec: RedistSpec, budget: int):
    """Zero-collective reshape paths: 1-device meshes and replicated
    sources (the dst distribution is a local slice)."""
    from ..core import _padding

    sched = _planner.plan(spec, budget)
    s, t = spec.src_split, spec.dst_split
    out_shape = spec.out_shape
    ndim_out = max(len(out_shape), 1)

    def fn(phys):
        with _plan_scope(sched.plan_id):
            logical = _padding.unpad(phys, spec.gshape, s)
            r = jnp.reshape(logical, out_shape)
            rp = _padding.pad_logical(r, t, comm.size)
            return lax.with_sharding_constraint(rp, comm.sharding(ndim_out, t))

    return comm.jit_sharded(fn, ndim_out, t)


def clear_program_cache() -> None:
    _move_program.cache_clear()
    _pivot_program.cache_clear()
    _packed_pivot_program.cache_clear()
    _gather_reshape_program.cache_clear()
    _local_reshape_program.cache_clear()


# a world rebuild (init_distributed) invalidates every program: the
# mesh (and the comm identity in the cache key) baked into them is gone
from ..core.communication import register_mesh_cache as _register_mesh_cache

_register_mesh_cache(_move_program)
_register_mesh_cache(_pivot_program)
_register_mesh_cache(_packed_pivot_program)
_register_mesh_cache(_gather_reshape_program)
_register_mesh_cache(_local_reshape_program)


# --------------------------------------------------------------------- #
# execution                                                             #
# --------------------------------------------------------------------- #
def _reshard_direct(comm, phys, gshape, src, dst):
    """The legacy relayout (unpad -> repad -> placement): still the
    lowering for the no-collective strategies, where GSPMD's local
    slice IS the schedule."""
    from ..core import _padding

    logical = _padding.unpad(phys, tuple(gshape), src)
    return comm.shard(logical, dst)


def execute(comm, phys, spec: RedistSpec, sched: Optional[Schedule] = None):
    """Run the planned redistribution of ``phys`` (a physical array laid
    out per ``spec.src_split``) and return the dst-layout physical
    array. Trace-safe: under a trace the cached jitted programs inline
    and the eager placements lower to sharding constraints."""
    if sched is None:
        sched = _planner.plan(spec)
    else:
        # the program builders compile the PLANNER's schedule for
        # (spec, budget) — a hand-built/modified Schedule would be
        # silently ignored, so refuse it instead
        planned = _planner.plan(spec, sched.budget_bytes)
        if planned.plan_id != sched.plan_id:
            raise ValueError(
                f"execute: schedule {sched.plan_id} is not the planner's "
                f"plan for {spec!r} under budget {sched.budget_bytes} B "
                f"(expected {planned.plan_id}); executor programs compile "
                "from the plan cache, not from caller-provided schedules"
            )
    if _telemetry._ENABLED:
        _telemetry.inc("redist.execute.calls")
    strategy = sched.strategy
    budget = sched.budget_bytes
    if strategy == "noop":
        return phys
    if strategy in ("slice",) or (strategy == "local" and not spec.is_reshape):
        # no-collective placements: GSPMD's local slice IS the schedule,
        # and with no collective there is nothing for shardlint to flag
        return _reshard_direct(comm, phys, spec.gshape, spec.src_split, spec.dst_split)
    if strategy == "replicate":
        # the explicit full all-gather runs as a stamped program too, so
        # its SL102 finding reports as info with the plan id attached
        return _gather_reshape_program(comm, spec, budget)(phys)
    if strategy in ("all-to-all", "chunked-all-to-all", "ring"):
        return _move_program(comm, spec, budget)(phys)
    if strategy == "split0-pivot":
        if _telemetry._ENABLED:
            _telemetry.inc("redist.relayout.direct")
        return _pivot_program(comm, spec, budget)(phys)
    if strategy == "packed-pivot":
        if _telemetry._ENABLED:
            _telemetry.inc("redist.relayout.packed")
        impl_in, impl_out = _relayout_impls(
            spec, sched, concrete=not isinstance(phys, jax.core.Tracer)
        )
        return _packed_pivot_program(comm, spec, budget, impl_in, impl_out)(phys)
    if strategy == "gather-reshape":
        return _gather_reshape_program(comm, spec, budget)(phys)
    if strategy in ("local-reshape", "local"):
        if spec.src_split == 0 and spec.dst_split == 0 and spec.mesh_size > 1:
            # divisible split-0 <-> split-0: device blocks stay put
            return _pivot_program(comm, spec, budget)(phys)
        return _local_reshape_program(comm, spec, budget)(phys)
    raise ValueError(f"unknown strategy {strategy!r} (plan {sched.plan_id})")


def resplit_phys(comm, phys, gshape, src: Optional[int], dst: Optional[int]):
    """Planner-routed split change of a physical array — the engine
    under ``DNDarray.resplit``/``resplit_`` and
    ``MeshCommunication.reshard_phys``."""
    gshape = tuple(int(v) for v in gshape)
    if (
        not _planner.planner_enabled()
        or phys.ndim != len(gshape)  # planar-complex plane pairs: legacy path
        or any(v == 0 for v in gshape)
    ):
        return _reshard_direct(comm, phys, gshape, src, dst)
    spec = RedistSpec.normalize(gshape, np.dtype(phys.dtype).name, src, dst, comm.size)
    return execute(comm, phys, spec)


def reshape_phys(comm, phys, in_gshape, in_split, out_shape, out_split):
    """Planner-routed reshape-with-repartition of a physical array — the
    engine under ``ht.reshape(..., new_split=...)``."""
    in_gshape = tuple(int(v) for v in in_gshape)
    out_shape = tuple(int(v) for v in out_shape)
    spec = RedistSpec.normalize(
        in_gshape,
        np.dtype(phys.dtype).name,
        in_split,
        out_split,
        comm.size,
        reshape_to=out_shape,
    )
    return execute(comm, phys, spec)
