"""Normalized redistribution problem statement — the planner's cache key.

Every split change in the framework (``resplit``/``resplit_``, the
``reshape(..., new_split=)`` repartition, ``communication.reshard_phys``)
is first normalized to one :class:`RedistSpec`: global shape, dtype,
source/destination split, mesh size, and — for the reshape repartition —
the target shape. Two call sites asking for the same movement produce
the SAME spec, so plans (``planner.plan``) and compiled executor
programs (``executor``) cache per spec, not per call site.

The spec is deliberately value-free: no arrays, no mesh object, no
device identities. Mesh geometry enters only as ``mesh_size`` (what the
chunk math depends on); the executor binds a concrete mesh at program
build time and registers its cache with
``communication.register_mesh_cache`` for world rebuilds.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from typing import Optional, Tuple

__all__ = ["RedistSpec"]


def _prod(shape: Tuple[int, ...]) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


@dataclasses.dataclass(frozen=True)
class RedistSpec:
    """One redistribution problem, normalized and hashable.

    Attributes
    ----------
    gshape : global (logical) shape of the source array.
    dtype : canonical numpy dtype name of the physical array.
    src_split / dst_split : heat split axes (already modded into range),
        ``None`` for replicated.
    mesh_size : number of shards on the 1-D mesh axis.
    reshape_to : target global shape when the movement is a
        reshape-with-repartition (``dst_split`` then indexes this shape);
        ``None`` for a pure resplit.
    """

    gshape: Tuple[int, ...]
    dtype: str
    src_split: Optional[int]
    dst_split: Optional[int]
    mesh_size: int
    reshape_to: Optional[Tuple[int, ...]] = None

    # ------------------------------------------------------------------ #
    # construction                                                       #
    # ------------------------------------------------------------------ #
    @classmethod
    def normalize(
        cls,
        gshape,
        dtype,
        src_split: Optional[int],
        dst_split: Optional[int],
        mesh_size: int,
        reshape_to=None,
    ) -> "RedistSpec":
        """Build a spec with axes modded into range and types canonical."""
        gshape = tuple(int(s) for s in gshape)
        out_shape = None if reshape_to is None else tuple(int(s) for s in reshape_to)
        if out_shape is not None and _prod(out_shape) != _prod(gshape):
            raise ValueError(
                f"cannot redistribute-reshape {gshape} into {out_shape}: sizes differ"
            )
        ndim_src = max(len(gshape), 1)
        ndim_dst = max(len(out_shape if out_shape is not None else gshape), 1)
        if src_split is not None:
            src_split = int(src_split) % ndim_src
        if dst_split is not None:
            dst_split = int(dst_split) % ndim_dst
        return cls(
            gshape=gshape,
            dtype=np.dtype(dtype).name,
            src_split=src_split,
            dst_split=dst_split,
            mesh_size=int(mesh_size),
            reshape_to=out_shape,
        )

    # ------------------------------------------------------------------ #
    # derived geometry                                                   #
    # ------------------------------------------------------------------ #
    @property
    def out_shape(self) -> Tuple[int, ...]:
        return self.reshape_to if self.reshape_to is not None else self.gshape

    @property
    def is_reshape(self) -> bool:
        return self.reshape_to is not None

    @property
    def itemsize(self) -> int:
        return np.dtype(self.dtype).itemsize

    @property
    def size(self) -> int:
        return _prod(self.gshape)

    @property
    def logical_bytes(self) -> int:
        """Bytes of the whole logical array."""
        return self.size * self.itemsize

    @property
    def dst_shard_bytes(self) -> int:
        """Per-device bytes of one (padded) shard of the destination."""
        from ..core import _padding

        if self.dst_split is None or self.mesh_size <= 1:
            return self.logical_bytes
        phys = _padding.phys_shape(self.out_shape, self.dst_split, self.mesh_size)
        return _prod(phys) * self.itemsize // self.mesh_size

    @property
    def src_shard_bytes(self) -> int:
        """Per-device bytes of one (padded) shard of the SOURCE — with
        :attr:`dst_shard_bytes` the resident baseline a redistribution
        holds live on top of every step's transient (the liveness
        account ``Schedule.liveness`` exposes)."""
        from ..core import _padding

        if self.src_split is None or self.mesh_size <= 1:
            return self.logical_bytes
        phys = _padding.phys_shape(self.gshape, self.src_split, self.mesh_size)
        return _prod(phys) * self.itemsize // self.mesh_size

    def as_dict(self) -> dict:
        return {
            "gshape": list(self.gshape),
            "dtype": self.dtype,
            "src_split": self.src_split,
            "dst_split": self.dst_split,
            "mesh_size": self.mesh_size,
            "reshape_to": None if self.reshape_to is None else list(self.reshape_to),
        }

    def __repr__(self) -> str:
        move = f"split {self.src_split}->{self.dst_split}"
        shape = f"{self.gshape}"
        if self.is_reshape:
            shape += f"->{self.reshape_to}"
        return f"RedistSpec({shape} {self.dtype}, {move}, p={self.mesh_size})"
