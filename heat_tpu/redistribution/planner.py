"""Cost-modeled redistribution planning.

Every nontrivial relayout used to be ONE monolithic collective chosen
implicitly by GSPMD (``resplit(None)`` = one full all-gather; the
split-1 reshape repartition = one full all-gather at ~0.09x HBM).
Following "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075), the planner instead
*decomposes* each :class:`~heat_tpu.redistribution.spec.RedistSpec`
into a bounded-footprint :class:`~heat_tpu.redistribution.schedule.Schedule`
chosen by an explicit cost model over candidate strategies:

==================  ====================================================
strategy            when / what
==================  ====================================================
``noop``            same split, same shape — nothing moves
``local``           1-device mesh (and zero-size arrays): local copy
``slice``           replicated → split: every device slices its shard,
                    no collective
``replicate``       split → replicated: the one FULL all-gather left in
                    the system, and only as this explicit strategy
``all-to-all``      split i → j whose send+recv transient fits the
                    budget: one tiled all-to-all (the pinned easy case)
``chunked-all-to-all``  the same move pipelined in C budget-sized
                    chunks: slice → all-to-all → scatter per chunk
``ring``            minimal-footprint fallback: p-1 ``ppermute`` hops,
                    one neighbor block in flight per step — chosen when
                    chunking would need more than p-1 laps
``split0-pivot``    reshape-with-repartition via a split-0 intermediate
                    (the minor-dim packing relayout): all-to-all in,
                    LOCAL row-major reshape at full lane width,
                    all-to-all out — replaces the full all-gather the
                    split-1 reshape used to compile to
``local-reshape``   reshape whose device blocks stay put (split-0 ↔
                    split-0 divisible, or replicated source): 0
                    collectives
``gather-reshape``  fallback when divisibility rules out the pivot:
                    gather → reshape → slice (the old behavior, now
                    explicit and accounted)
==================  ====================================================

Cost model: a collective step costs ``ALPHA_BYTES + bytes_moved``
(latency expressed in byte-equivalents, so step count and volume share
one unit). Among candidates whose per-step transient peak fits the
``HEAT_TPU_REDIST_BUDGET_MB`` budget the cheapest wins; when nothing
fits, the smallest peak wins (ring is that floor for split moves).
Local copy steps (pad/slice/reshape) are bounded by one shard and are
accounted but not chunkable — the budget must be at least one
destination shard.

Plans are cached per ``(spec, budget)`` and feed the PR-1 telemetry
registry: ``redist.plan_cache.{hit,miss}``, ``redist.planned_bytes``,
``redist.steps``, ``redist.peak_bytes``.
"""

from __future__ import annotations

import os
import threading

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry
from .schedule import Schedule, Step
from .spec import RedistSpec

__all__ = [
    "ALPHA_BYTES",
    "DEFAULT_BUDGET_MB",
    "budget_bytes",
    "clear_plan_cache",
    "explain",
    "golden_specs",
    "plan",
    "planner_enabled",
]

#: per-collective launch latency expressed in byte-equivalents (~1 MiB
#: of ICI time per collective dispatch): makes step count and byte
#: volume comparable in one scalar cost.
ALPHA_BYTES = 1 << 20

DEFAULT_BUDGET_MB = 256
_BUDGET_ENV = "HEAT_TPU_REDIST_BUDGET_MB"
_ENABLE_ENV = "HEAT_TPU_REDIST_PLANNER"

_plan_lock = threading.Lock()
_plan_cache: Dict[Tuple[RedistSpec, int], Schedule] = {}
#: bounded like the executor's program caches (lru_cache(512)); planning
#: is cheap pure Python, so FIFO eviction on overflow is plenty
_PLAN_CACHE_MAX = 4096


def planner_enabled() -> bool:
    """Planner routing switch (``HEAT_TPU_REDIST_PLANNER=0`` restores
    the legacy single-device_put relayout paths)."""
    val = os.environ.get(_ENABLE_ENV, "1").strip().lower()
    return val not in ("0", "false", "off", "no")


def budget_bytes() -> int:
    """Per-device peak-memory budget for redistribution transients
    (``HEAT_TPU_REDIST_BUDGET_MB``, default 256 MiB)."""
    raw = os.environ.get(_BUDGET_ENV, "")
    try:
        mb = int(raw) if raw.strip() else DEFAULT_BUDGET_MB
    except ValueError:
        mb = DEFAULT_BUDGET_MB
    return max(1, mb) << 20


def clear_plan_cache() -> None:
    with _plan_lock:
        _plan_cache.clear()


# --------------------------------------------------------------------- #
# geometry helpers                                                      #
# --------------------------------------------------------------------- #
def _prod(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _pad_extent(n: int, p: int) -> int:
    from ..core import _padding

    return _padding.pad_extent(int(n), int(p))


def _divisor_chunks(extent: int, needed: int) -> int:
    """Smallest chunk count >= ``needed`` that divides ``extent`` (chunks
    must be equal-sized for the scatter reassembly to be static)."""
    extent = max(int(extent), 1)
    needed = min(max(1, int(needed)), extent)
    for c in range(needed, extent + 1):
        if extent % c == 0:
            return c
    return extent


def _local_move_bytes(spec: RedistSpec) -> int:
    """Per-device bytes of the doubly-padded shard a split i->j move
    exchanges (source split axis padded for the source layout, dest
    split axis padded so the tiled all-to-all divides evenly)."""
    p = spec.mesh_size
    shape = list(spec.gshape)
    shape[spec.src_split] = _pad_extent(shape[spec.src_split], p)
    shape[spec.dst_split] = _pad_extent(shape[spec.dst_split], p)
    return _prod(shape) // p * spec.itemsize


# --------------------------------------------------------------------- #
# candidate builders                                                    #
# --------------------------------------------------------------------- #
def _a2a_chunk_steps(
    L: int, p: int, C: int, what: str, pad_step: Optional[Step], tail_slice: Optional[Step]
) -> List[Step]:
    """C laps of slice -> all-to-all, then a scatter reassembly (written
    in place into the destination buffer: no transient)."""
    steps: List[Step] = []
    if pad_step is not None:
        steps.append(pad_step)
    crossing = L * (p - 1) // p  # the diagonal block stays home
    if C <= 1:
        steps.append(
            Step("all_to_all", bytes_moved=crossing, peak_bytes=2 * L, detail=what)
        )
    else:
        for c in range(C):
            steps.append(
                Step("slice", peak_bytes=L // C, detail=f"chunk {c}/{C} of {what}", chunk=c)
            )
            steps.append(
                Step(
                    "all_to_all",
                    bytes_moved=crossing // C,
                    peak_bytes=2 * L // C,
                    detail=what,
                    chunk=c,
                )
            )
        steps.append(Step("pack", peak_bytes=0, detail="scatter chunks into dst shard"))
    if tail_slice is not None:
        steps.append(tail_slice)
    return steps


def _resplit_candidates(spec: RedistSpec, budget: int) -> List[Schedule]:
    """split i -> split j candidates: (chunked) all-to-all and the ring."""
    p = spec.mesh_size
    i, j = spec.src_split, spec.dst_split
    L = _local_move_bytes(spec)
    Nj, Njp = spec.gshape[j], _pad_extent(spec.gshape[j], p)
    Ni, Nip = spec.gshape[i], _pad_extent(spec.gshape[i], p)
    pad_step = (
        Step("pad", peak_bytes=L, detail=f"pad axis {j} {Nj}->{Njp} (local)")
        if Njp != Nj
        else None
    )
    tail = (
        Step("slice", peak_bytes=L, detail=f"drop axis {i} pad {Nip}->{Ni} (local)")
        if Nip != Ni
        else None
    )
    # concat axis is the source split axis: its local extent is what the
    # chunk laps tile over
    concat_extent = Nip // p
    needed = -(-2 * L // budget)
    C = _divisor_chunks(concat_extent, needed)

    what = f"split {i}->{j}"
    a2a = Schedule(
        spec,
        "all-to-all" if C <= 1 else "chunked-all-to-all",
        _a2a_chunk_steps(L, p, C, what, pad_step, tail),
        budget,
        notes=f"C={C} chunks over local axis-{i} extent {concat_extent}" if C > 1 else "",
    )

    ring_steps: List[Step] = []
    if pad_step is not None:
        ring_steps.append(pad_step)
    blk = L // p
    for d in range(1, p):
        ring_steps.append(
            Step(
                "ppermute",
                bytes_moved=blk,
                peak_bytes=2 * blk,
                detail=f"hop distance {d}: neighbor block of {what}",
            )
        )
    if tail is not None:
        ring_steps.append(tail)
    ring = Schedule(
        spec,
        "ring",
        ring_steps,
        budget,
        notes="p-1 ppermute hops, one neighbor block in flight per step",
    )
    return [a2a, ring]


def _pivot_valid(spec: RedistSpec) -> bool:
    """The split-0 pivot needs the leading extents to divide the mesh on
    both sides (device blocks are then contiguous runs of the row-major
    element order, so the middle reshape is LOCAL)."""
    p = spec.mesh_size
    in0 = spec.gshape[0] if spec.gshape else 0
    out0 = spec.out_shape[0] if spec.out_shape else 0
    return (
        len(spec.gshape) >= 1
        and len(spec.out_shape) >= 1
        and in0 > 0
        and out0 > 0
        and in0 % p == 0
        and out0 % p == 0
    )


def _pivot_schedule(spec: RedistSpec, budget: int) -> Schedule:
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    item = spec.itemsize
    steps: List[Step] = []
    shard = spec.size // p * item  # logical bytes per device block

    n_coll = 0
    if s is not None and s != 0:
        L1 = _prod(
            [_pad_extent(d, p) if ax == s else d for ax, d in enumerate(spec.gshape)]
        ) // p * item
        C1 = _divisor_chunks(
            _pad_extent(spec.gshape[s], p) // p, -(-2 * L1 // budget)
        )
        steps += _a2a_chunk_steps(L1, p, C1, f"split {s}->0 (pivot in)", None, None)
        n_coll += C1
        if _pad_extent(spec.gshape[s], p) != spec.gshape[s]:
            steps.append(
                Step("slice", peak_bytes=shard, detail=f"drop axis {s} pad (local)")
            )
    steps.append(
        Step(
            "reshape",
            peak_bytes=shard,
            detail="local row-major reshape at full minor-dim width",
        )
    )
    if t is not None and t != 0:
        out_t, out_tp = spec.out_shape[t], _pad_extent(spec.out_shape[t], p)
        L2 = _prod(
            [_pad_extent(d, p) if ax == t else d for ax, d in enumerate(spec.out_shape)]
        ) // p * item
        if out_tp != out_t:
            steps.append(
                Step(
                    "pad",
                    peak_bytes=L2,
                    detail=f"pad axis {t} {out_t}->{out_tp} (local)",
                )
            )
        C2 = _divisor_chunks(spec.out_shape[0] // p, -(-2 * L2 // budget))
        steps += _a2a_chunk_steps(L2, p, C2, f"split 0->{t} (pivot out)", None, None)
        n_coll += C2
    strategy = "split0-pivot" if n_coll else "local-reshape"
    return Schedule(
        spec,
        strategy,
        steps,
        budget,
        notes="minor-dim packing: heavy copies run on the split-0 layout",
    )


def _gather_reshape_schedule(spec: RedistSpec, budget: int) -> Schedule:
    p = spec.mesh_size
    logical = spec.logical_bytes
    steps = [
        Step(
            "all_gather",
            bytes_moved=logical * (p - 1) // p,
            peak_bytes=logical,
            detail="replicate the full operand (fallback: pivot divisibility failed)"
            if spec.is_reshape
            else "explicit replicate",
        )
    ]
    if spec.is_reshape:
        steps.append(Step("reshape", peak_bytes=logical, detail="replicated reshape"))
    if spec.dst_split is not None:
        steps.append(
            Step(
                "slice",
                peak_bytes=spec.dst_shard_bytes,
                detail=f"slice dst shard (split {spec.dst_split})",
            )
        )
    return Schedule(
        spec,
        "gather-reshape" if spec.is_reshape else "replicate",
        steps,
        budget,
        notes="full all-gather — the only strategy that materializes the logical array",
    )


def _cost(s: Schedule) -> int:
    return sum(ALPHA_BYTES + st.bytes_moved for st in s.steps if st.is_collective)


def _select(candidates: List[Schedule]) -> Schedule:
    feasible = [c for c in candidates if c.within_budget]
    if feasible:
        return min(feasible, key=_cost)
    # nothing fits: degrade to the smallest footprint and say so —
    # rebuilt (not mutated) so plan_id stays the sha1 of the canonical
    # serialization, notes included
    best = min(candidates, key=lambda c: c.peak_bytes)
    notes = (best.notes + "; " if best.notes else "") + (
        f"over budget: peak {best.peak_bytes} B > {best.budget_bytes} B "
        "(smallest-footprint candidate chosen)"
    )
    return Schedule(best.spec, best.strategy, best.steps, best.budget_bytes, notes=notes)


# --------------------------------------------------------------------- #
# the planner                                                           #
# --------------------------------------------------------------------- #
def _build(spec: RedistSpec, budget: int) -> Schedule:
    p = spec.mesh_size

    if spec.is_reshape:
        if spec.gshape == spec.reshape_to and spec.src_split == spec.dst_split:
            return Schedule(spec, "noop", [], budget)
        if p <= 1 or spec.size == 0:
            return Schedule(
                spec,
                "local",
                [Step("reshape", peak_bytes=spec.logical_bytes, detail="single-shard reshape")],
                budget,
            )
        if spec.src_split is None:
            steps = [
                Step("reshape", peak_bytes=spec.logical_bytes, detail="replicated reshape")
            ]
            if spec.dst_split is not None:
                steps.append(
                    Step(
                        "slice",
                        peak_bytes=spec.dst_shard_bytes,
                        detail=f"slice dst shard (split {spec.dst_split})",
                    )
                )
            return Schedule(spec, "local-reshape", steps, budget)
        if spec.dst_split is None:
            return _gather_reshape_schedule(spec, budget)
        candidates = []
        if _pivot_valid(spec):
            candidates.append(_pivot_schedule(spec, budget))
        candidates.append(_gather_reshape_schedule(spec, budget))
        return _select(candidates)

    # pure resplit
    if spec.src_split == spec.dst_split:
        return Schedule(spec, "noop", [], budget)
    if p <= 1 or spec.size == 0:
        return Schedule(spec, "local", [], budget)
    if spec.src_split is None:
        return Schedule(
            spec,
            "slice",
            [
                Step(
                    "slice",
                    peak_bytes=spec.dst_shard_bytes,
                    detail=f"local shard slice (split {spec.dst_split})",
                )
            ],
            budget,
        )
    if spec.dst_split is None:
        return _gather_reshape_schedule(spec, budget)
    return _select(_resplit_candidates(spec, budget))


def plan(spec: RedistSpec, budget: Optional[int] = None) -> Schedule:
    """Plan ``spec`` under ``budget`` bytes (default: the env knob).
    Cached per (spec, budget); cache hits/misses and the planned
    byte/step/peak totals feed the telemetry registry."""
    b = budget_bytes() if budget is None else int(budget)
    key = (spec, b)
    with _plan_lock:
        cached = _plan_cache.get(key)
    if cached is not None:
        if _telemetry._ENABLED:
            _telemetry.inc("redist.plan_cache.hit")
        return cached
    sched = _build(spec, b)
    with _plan_lock:
        if len(_plan_cache) >= _PLAN_CACHE_MAX:
            _plan_cache.pop(next(iter(_plan_cache)))
        _plan_cache[key] = sched
    if _telemetry._ENABLED:
        _telemetry.inc("redist.plan_cache.miss")
        _telemetry.inc("redist.planned_bytes", sched.bytes_moved)
        _telemetry.inc("redist.steps", sched.n_steps)
        _telemetry.inc("redist.peak_bytes", sched.peak_bytes)
        _obs_events.emit(
            "redist.plan",
            plan_id=sched.plan_id,
            strategy=sched.strategy,
            spec=repr(sched.spec),
            steps=sched.n_steps,
            collectives=sched.collective_counts(),
            peak_bytes=sched.peak_bytes,
            budget_bytes=b,
        )
    return sched


def explain(arr, axis=None, *, reshape=None, new_split=None) -> Schedule:
    """The chosen redistribution plan for ``arr`` — without executing it.

    ``explain(arr, axis)`` plans the resplit to ``axis``;
    ``explain(arr, reshape=shape, new_split=...)`` plans the
    reshape-with-repartition (``new_split`` defaults the same way
    ``ht.reshape`` defaults it). Returns the
    :class:`~heat_tpu.redistribution.schedule.Schedule` the executor
    would compile — strategy, steps, per-step peak-memory accounting,
    plan id.
    """
    from ..core.dndarray import DNDarray
    from ..core.stride_tricks import sanitize_axis

    if not planner_enabled():
        raise RuntimeError(
            "explain: the redistribution planner is disabled "
            f"({_ENABLE_ENV}=0) — resplit/reshape run the legacy "
            "one-collective paths, so there is no plan to show. Unset "
            f"{_ENABLE_ENV} to re-enable planner routing."
        )
    if not isinstance(arr, DNDarray):
        raise TypeError(f"explain expects a DNDarray, got {type(arr)}")
    if arr._is_planar:
        raise TypeError(
            "explain: planar-complex arrays take the legacy relayout path "
            "(the planner routes real/physical layouts only)"
        )
    if reshape is not None:
        # THE resolver the public call uses — explain must build its
        # spec from exactly the (shape, new_split) ht.reshape executes
        from ..core.manipulations import _normalize_reshape_args

        shape, new_split = _normalize_reshape_args(arr, (tuple(reshape),) if isinstance(
            reshape, (tuple, list)
        ) else (reshape,), new_split)
        spec = RedistSpec.normalize(
            arr.gshape,
            np.dtype(arr._phys.dtype).name,
            arr.split,
            new_split,
            arr.comm.size,
            reshape_to=shape,
        )
    else:
        axis = sanitize_axis(arr.gshape, axis)
        spec = RedistSpec.normalize(
            arr.gshape, np.dtype(arr._phys.dtype).name, arr.split, axis, arr.comm.size
        )
    return plan(spec)


# --------------------------------------------------------------------- #
# golden matrix — pinned by tier-1 and the ci.sh determinism leg        #
# --------------------------------------------------------------------- #
def golden_specs() -> List[Tuple[str, RedistSpec]]:
    """The (name, spec) matrix whose plans are golden: strategies and
    step counts are pinned in ``tests/test_redistribution.py`` and the
    serialized plans must be byte-identical run-to-run (ci.sh diffs two
    runs of ``scripts/redist_plans.py``)."""
    S = RedistSpec.normalize
    return [
        ("noop_same_split", S((64, 48), "float32", 1, 1, 8)),
        ("resplit_0_to_1_p8", S((64, 48), "float32", 0, 1, 8)),
        ("resplit_1_to_0_p8", S((64, 48), "float32", 1, 0, 8)),
        ("resplit_0_to_1_int32_p4", S((64, 48), "int32", 0, 1, 4)),
        ("resplit_uneven_p8", S((63, 48), "float32", 0, 1, 8)),
        ("resplit_3d_1_to_2_p8", S((16, 24, 40), "float32", 1, 2, 8)),
        ("replicate_p8", S((64, 48), "float32", 0, None, 8)),
        ("slice_from_replicated_p8", S((64, 48), "float32", None, 1, 8)),
        ("mesh1_resplit", S((64, 48), "float32", 0, 1, 1)),
        ("resplit_chunked_2gb_p8", S((32768, 16384), "float32", 0, 1, 8)),
        ("resplit_ring_8gb_p8", S((131072, 16384), "float32", 0, 1, 8)),
        ("reshape_pivot_p8", S((40960, 40), "float32", 1, 1, 8, reshape_to=(20480, 80))),
        ("reshape_split0_local_p8", S((64, 48), "float32", 0, 0, 8, reshape_to=(32, 96))),
        (
            "reshape_gather_fallback_p8",
            S((1000, 26), "float32", 1, 1, 8, reshape_to=(26, 1000)),
        ),
        (
            "reshape_split1_1gb_p8",
            S((1000, 250000), "float32", 1, 1, 8, reshape_to=(10_000_000, 25)),
        ),
    ]
