"""Cost-modeled redistribution planning.

Every nontrivial relayout used to be ONE monolithic collective chosen
implicitly by GSPMD (``resplit(None)`` = one full all-gather; the
split-1 reshape repartition = one full all-gather at ~0.09x HBM).
Following "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075), the planner instead
*decomposes* each :class:`~heat_tpu.redistribution.spec.RedistSpec`
into a bounded-footprint :class:`~heat_tpu.redistribution.schedule.Schedule`
chosen by an explicit cost model over candidate strategies:

==================  ====================================================
strategy            when / what
==================  ====================================================
``noop``            same split, same shape — nothing moves
``local``           1-device mesh (and zero-size arrays): local copy
``slice``           replicated → split: every device slices its shard,
                    no collective
``replicate``       split → replicated: the one FULL all-gather left in
                    the system, and only as this explicit strategy
``all-to-all``      split i → j whose send+recv transient fits the
                    budget: one tiled all-to-all (the pinned easy case)
``chunked-all-to-all``  the same move pipelined in C budget-sized
                    chunks: slice → all-to-all → scatter per chunk
``ring``            minimal-footprint fallback: p-1 ``ppermute`` hops,
                    one neighbor block in flight per step — chosen when
                    chunking would need more than p-1 laps
``split0-pivot``    reshape-with-repartition via a split-0 intermediate
                    (the minor-dim packing relayout): all-to-all in,
                    LOCAL row-major reshape at full lane width,
                    all-to-all out — replaces the full all-gather the
                    split-1 reshape used to compile to
``packed-pivot``    the same pivot with narrow-minor-dim stages run on
                    LANE-PACKED buffers (``heat_tpu.kernels.relayout``):
                    a tile-transposing pack folds rows into the lane
                    axis so the chunked all-to-alls and relayout copies
                    stream full VREGs; ONE unpack materializes the
                    destination's narrow layout (the single
                    lane-amplified write the requested layout makes
                    unavoidable)
``local-reshape``   reshape whose device blocks stay put (split-0 ↔
                    split-0 divisible, or replicated source): 0
                    collectives
``gather-reshape``  fallback when divisibility rules out the pivot:
                    gather → reshape → slice (the old behavior, now
                    explicit and accounted)
==================  ====================================================

Cost model: a collective step costs ``ALPHA_BYTES + bytes_moved``
(latency expressed in byte-equivalents, so step count and volume share
one unit), a local relayout copy costs its ``bytes_copied``, and BOTH
are divided by the step's ``lane_fill`` — the fraction of VREG lanes
the step's buffer layout fills (``kernels.relayout.lane_fill``,
``minor_dim/128`` below one tile). 1/lane_fill is the HBM amplification
a copy through a narrow tiled layout pays on TPU; the term is what
makes ``packed-pivot`` (one amplified write) beat ``split0-pivot``
(every stage amplified) exactly on the narrow-minor-dim specs. Among
candidates whose per-step transient peak fits the
``HEAT_TPU_REDIST_BUDGET_MB`` budget the cheapest wins; when nothing
fits, the smallest peak wins (ring is that floor for split moves).
Local copy steps (pad/slice/reshape/pack/unpack) are bounded by one
shard and are accounted but not chunkable — the budget must be at
least one destination shard.

Overlap (ISSUE 6): exchanges big enough to amortize per-lap launch
latency are chunked to the ``OVERLAP_GRAIN_BYTES`` grain even when the
budget alone would not require it, and every chunk group (and the
ppermute ring) carries a depth-2 **overlap annotation** — the modeled
critical path prices a pipelined stage pair at ``max(wire, copy)``
instead of ``wire + copy`` (arXiv:2112.09017's latency-hiding
schedules). The lap structure is gate-INDEPENDENT, so the collective
census is identical overlap-on vs overlap-off; ``HEAT_TPU_REDIST_OVERLAP``
only switches the executor between the sequential oracle and the
prefetch-issue-then-consume program form. The annotation folds into the
canonical serialization and ``plan_id``.

Wire quantization (ISSUE 7): after selection, the winning plan's
admissible collective groups are wrapped in ``quantize``/``dequantize``
codec steps (``heat_tpu.kernels.quant`` — int8 payloads with one f32
scale per 1024-element tile, ~0.251×, or the bf16 cast at 0.5×) under
the ``HEAT_TPU_WIRE_QUANT`` gate. Running the codec pass AFTER
``_select`` is what makes the census gate-invariant by construction:
the gate can change how many bytes each collective carries, never which
strategy wins or how many collectives launch. Admissibility is the
numerics-tolerance policy: float32 transient exchanges of at least
``QUANT_MIN_WIRE_BYTES`` full-width — everything else (ints, f64,
small moves, the materializing replicate/gather strategies) ships
exact-bit under every gate value.

Two-tier topology (ISSUE 8): at a tiered topology
(``HEAT_TPU_TOPOLOGY``, ``core.communication.Topology`` — ``auto``
reads ``slice_index`` off the resolved world, ``SxC`` forces a
simulated factorization) every candidate is priced per tier: a flat
collective whose replica groups span slices rides DCN (its steps carry
``tier="dcn"`` and cost ``DCN_PENALTY`` ≈ 8× per byte — the slowest
edge in the group governs the collective), and a new
``hierarchical-a2a`` strategy decomposes each cross-slice all-to-all
into an intra-slice pivot (the cheap tier carries the volume,
``L·(C-1)/C`` on ICI) plus an inter-slice exchange of pre-packed
per-slice rows (the expensive tier ships only the bytes that must
cross, ``L·(S-1)/S`` — the portable-redistribution factorization of
arXiv:2112.01075 applied across tiers). The DCN group is the first
group the wire codec targets: in hierarchical plans the admissibility
policy quantizes ONLY the ``tier="dcn"`` exchanges (the ICI hop is
wire-cheap and stays exact, halving the codec error for free). Tier
annotations and the schedule-level ``topology`` annotation fold into
the canonical serialization and ``plan_id``; with the topology unset or
``1xN`` no annotation exists and every plan is byte-identical to the
pre-topology era.

Plans are cached per ``(spec, budget, codec, topology)`` and feed the
PR-1 telemetry registry: ``redist.plan_cache.{hit,miss}``,
``redist.planned_bytes``, ``redist.steps``, ``redist.peak_bytes``.
"""

from __future__ import annotations

import threading

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import gates as _gates
from ..observability import events as _obs_events
from ..observability import telemetry as _telemetry
from .schedule import Schedule, Step
from .spec import RedistSpec

__all__ = [
    "ALPHA_BYTES",
    "DEFAULT_BUDGET_MB",
    "OVERLAP_ENV",
    "QUANT_MIN_WIRE_BYTES",
    "WIRE_QUANT_ENV",
    "budget_bytes",
    "clear_plan_cache",
    "explain",
    "golden_specs",
    "overlap_mode",
    "plan",
    "planner_enabled",
    "quant_tolerance",
    "resolve_topology",
    "tier_time_model",
    "wire_quant_gate",
    "wire_quant_mode",
]

#: per-collective launch latency expressed in byte-equivalents (~1 MiB
#: of ICI time per collective dispatch): makes step count and byte
#: volume comparable in one scalar cost.
ALPHA_BYTES = 1 << 20

DEFAULT_BUDGET_MB = 256
_BUDGET_ENV = "HEAT_TPU_REDIST_BUDGET_MB"
_ENABLE_ENV = "HEAT_TPU_REDIST_PLANNER"
OVERLAP_ENV = "HEAT_TPU_REDIST_OVERLAP"

#: pipelinable exchanges are chunked into laps of roughly this size even
#: when the peak-memory budget alone would not require chunking — laps
#: are what the depth-2 pipeline overlaps (chunk k's relayout copy under
#: chunk k+1's wire). Gate-INDEPENDENT: the lap structure (and therefore
#: the collective census) is identical overlap-on and overlap-off; the
#: HEAT_TPU_REDIST_OVERLAP gate only controls the executor's issue order.
OVERLAP_GRAIN_BYTES = 32 << 20
_OVERLAP_MAX_LAPS = 4

WIRE_QUANT_ENV = "HEAT_TPU_WIRE_QUANT"

#: a collective GROUP (one chunk pipeline / ring / standalone exchange)
#: engages the wire codec only when its full-width payload reaches this
#: size — smaller exchanges are latency-bound (ALPHA, not bytes), and
#: keeping them exact-bit is what lets every small-array contract in
#: the suite (executor equivalence, pinned censuses, escape-hatch
#: parity) hold verbatim even under the forced HEAT_TPU_WIRE_QUANT=1
#: CI leg.
QUANT_MIN_WIRE_BYTES = 2 << 20

#: strategies whose collectives ship TRANSIENT exchange payloads — the
#: codec's domain. ``replicate``/``gather-reshape`` materialize the
#: array values compute then consumes, so they stay exact-bit always.
_QUANT_STRATEGIES = (
    "all-to-all", "chunked-all-to-all", "ring", "split0-pivot", "packed-pivot",
    "hierarchical-a2a",
)

_plan_lock = threading.Lock()
_plan_cache: Dict[Tuple[RedistSpec, int, str], Schedule] = {}
#: bounded like the executor's program caches (lru_cache(512)); planning
#: is cheap pure Python, so FIFO eviction on overflow is plenty
_PLAN_CACHE_MAX = 4096


def planner_enabled() -> bool:
    """Planner routing switch (``HEAT_TPU_REDIST_PLANNER=0`` restores
    the legacy single-device_put relayout paths)."""
    val = _gates.get(_ENABLE_ENV, "1").strip().lower()
    return val not in ("0", "false", "off", "no")


def overlap_mode() -> str:
    """Resolved ``HEAT_TPU_REDIST_OVERLAP`` mode (``"0"``/``"1"``/
    ``"auto"``). ``0`` forces every executor program (and the linalg
    collective-matmul forms) into the sequential oracle, ``1`` forces
    the software-pipelined forms everywhere they exist, and the default
    ``auto`` follows the plan's overlap annotation for redistribution
    programs (pipelining is a free reordering — bit-identical, census
    unchanged) while the linalg ring decompositions, which trade an
    all-gather/all-reduce for a byte-equivalent ppermute ring, engage
    only on the TPU backend where the latency hiding pays."""
    v = _gates.get(OVERLAP_ENV, "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "true", "force", "yes"):
        return "1"
    return "auto"


def wire_quant_mode() -> str:
    """Parsed ``HEAT_TPU_WIRE_QUANT`` (``"0"``/``"1"``/``"bf16"``/
    ``"auto"``). ``0`` is the escape hatch (every wire stays full-width
    exact-bit — the PR 6 program forms verbatim); ``1`` forces the int8
    codec on every admissible exchange on any backend (the CI leg);
    ``bf16`` forces the cast codec the same way; the default ``auto``
    engages the lossy int8 codec only on the TPU backend — where the
    ICI wire is the modeled binding term and the pinned tolerance is
    the documented trade — and keeps every other backend exact-bit, so
    the CPU tier-1 contracts hold untouched by default."""
    v = _gates.get(WIRE_QUANT_ENV, "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "true", "force", "yes", "int8"):
        return "1"
    if v == "bf16":
        return "bf16"
    return "auto"


def wire_quant_gate() -> Optional[str]:
    """The codec mode the current gate resolves to (``"int8"``/
    ``"bf16"``) or ``None`` when every wire stays full-width. Per-spec
    admissibility (dtype/strategy/size — the numerics-tolerance policy)
    is decided separately at planning time."""
    m = wire_quant_mode()
    if m == "0":
        return None
    if m == "1":
        return "int8"
    if m == "bf16":
        return "bf16"
    import jax

    return "int8" if jax.default_backend() == "tpu" else None


def quant_tolerance(mode: Optional[str]) -> float:
    """The per-crossing error bound the planner declares for plans it
    quantizes under ``mode`` (the ``quant.tol`` annotation value) —
    the codec's pinned tolerance, 0.0 for ``None`` (exact-bit wires).
    Read-only delegation to :func:`heat_tpu.kernels.quant.tolerance`:
    the planner annotates exactly what the codec guarantees, and the
    ``tolerance`` plan invariant (ht.analysis.check_tolerance) proves
    the dumped annotation still equals this recomputation."""
    if mode is None:
        return 0.0
    from ..kernels import quant as _quant_mod

    return float(_quant_mod.tolerance(mode))


def _dcn_penalty() -> int:
    from ..core import tiers as _tiers

    return _tiers.penalty("dcn")


def resolve_topology(mesh_size: int, override=None) -> Optional[Tuple[int, int]]:
    """``(n_slices, chips_per_slice)`` of the TIERED topology governing
    a ``mesh_size`` mesh, or ``None`` when flat (one ICI domain — every
    pre-ISSUE-8 plan). ``override``: ``None`` resolves the ambient
    ``HEAT_TPU_TOPOLOGY`` (``auto`` on the resolved world's
    ``slice_index``), ``"flat"`` forces flat, an ``"SxC"`` string /
    ``Topology`` / ``(S, C)`` tuple forces that factorization (falling
    back to flat when the product does not equal ``mesh_size``)."""
    if isinstance(override, tuple):
        S, C = int(override[0]), int(override[1])
        return (S, C) if S > 1 and S * C == int(mesh_size) else None
    from ..core import communication as _comm

    t = _comm.topology_for(mesh_size, override)
    return (t.n_slices, t.chips_per_slice) if t.tiered else None


def _topo_annotation(topo: Tuple[int, int]) -> dict:
    return {
        "n_slices": int(topo[0]),
        "chips_per_slice": int(topo[1]),
        "dcn_penalty": _dcn_penalty(),
    }


def tier_time_model(sched: Schedule, edges: Optional[dict] = None) -> dict:
    """Analytic per-device wall-time split of a plan's payload over the
    lattice edges it rides (``core.tiers.transfer_time``: the v5e
    constants, or the measured profile when ``HEAT_TPU_LATTICE_PROFILE``
    is active) — the checkable model the ``*_2x8_dcn`` and
    ``*_hostram`` bench rows report (no DCN/PCIe hardware is driven on
    the CPU container; this is the MULTICHIP methodology). Flat plans
    price everything at ICI; staged plans (ISSUE 11) additionally carry
    the ``pcie`` staging traffic.

    ``edges`` (ISSUE 16) overrides the per-edge bytes/s explicitly —
    ``{edge: bps}`` or profile-style ``{edge: {"bps": ...}}`` records;
    missing edges fall through to the ambient price. Attribution uses
    this to build the CALIBRATED model column from a plan's recorded
    ``calibration`` annotation without touching the process gate."""
    from ..core import tiers as _tiers

    def _time(nbytes: int, edge: str) -> float:
        if edges and edge in edges:
            rec = edges[edge]
            bps = float(rec["bps"] if isinstance(rec, dict) else rec)
            if bps > 0:
                return max(int(nbytes), 0) / bps
        return _tiers.transfer_time(nbytes, edge)

    tb = sched.tier_bytes()
    ici_s = _time(tb["ici"], "ici")
    dcn_s = _time(tb["dcn"], "dcn")
    out = {
        "ici_bytes": tb["ici"],
        "dcn_bytes": tb["dcn"],
        "ici_s": ici_s,
        "dcn_s": dcn_s,
        "total_s": ici_s + dcn_s,
    }
    if tb.get("pcie"):
        pcie_s = _time(tb["pcie"], "pcie")
        out["pcie_bytes"] = tb["pcie"]
        out["pcie_s"] = pcie_s
        out["total_s"] = ici_s + dcn_s + pcie_s
    return out


def budget_bytes() -> int:
    """Per-device peak-memory budget for redistribution transients
    (``HEAT_TPU_REDIST_BUDGET_MB``, default 256 MiB)."""
    raw = _gates.get(_BUDGET_ENV, "")
    try:
        mb = int(raw) if raw.strip() else DEFAULT_BUDGET_MB
    except ValueError:
        mb = DEFAULT_BUDGET_MB
    return max(1, mb) << 20


def clear_plan_cache() -> int:
    """Drop every cached schedule; returns the eviction count. Plans
    are pure metadata keyed on (spec, budget, codec, topology) — a
    world change can never serve a WRONG one — but a resized world
    leaves the dead world's entries unreachable, and the elastic
    runtime's eviction sweep (``heat_tpu.resilience.elastic.
    invalidate_caches``, ISSUE 13) reclaims them here."""
    with _plan_lock:
        n = len(_plan_cache)
        _plan_cache.clear()
    return n


# --------------------------------------------------------------------- #
# geometry helpers                                                      #
# --------------------------------------------------------------------- #
def _prod(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _pad_extent(n: int, p: int) -> int:
    from ..core import _padding

    return _padding.pad_extent(int(n), int(p))


def _divisor_chunks(extent: int, needed: int) -> int:
    """Smallest chunk count >= ``needed`` that divides ``extent`` (chunks
    must be equal-sized for the scatter reassembly to be static)."""
    extent = max(int(extent), 1)
    needed = min(max(1, int(needed)), extent)
    for c in range(needed, extent + 1):
        if extent % c == 0:
            return c
    return extent


def _local_move_bytes(spec: RedistSpec) -> int:
    """Per-device bytes of the doubly-padded shard a split i->j move
    exchanges (source split axis padded for the source layout, dest
    split axis padded so the tiled all-to-all divides evenly)."""
    p = spec.mesh_size
    shape = list(spec.gshape)
    shape[spec.src_split] = _pad_extent(shape[spec.src_split], p)
    shape[spec.dst_split] = _pad_extent(shape[spec.dst_split], p)
    return _prod(shape) // p * spec.itemsize


# --------------------------------------------------------------------- #
# lane geometry (the kernels.relayout cost term)                         #
# --------------------------------------------------------------------- #
def _fill(minor: int) -> float:
    from ..kernels import relayout as _relayout

    return _relayout.lane_fill(minor)


def _pack_threshold() -> float:
    from ..kernels import relayout as _relayout

    return _relayout.PACK_FILL_THRESHOLD


def _shard_minor(shape, split: Optional[int], p: int) -> int:
    """Minor-dim extent of the local shard of (shape, split)."""
    if not shape:
        return 1
    loc = [int(v) for v in shape]
    if split is not None:
        loc[split] = _pad_extent(loc[split], p) // p
    return max(loc[-1], 1)


def _exchange_fill(shape, i: int, j: int, p: int) -> float:
    """Worst lane fill among the buffers a split i<->j exchange of
    ``shape`` touches (pre-exchange: split i, axis j padded;
    post-exchange: split j, axis i still padded)."""

    def minor_of(split):
        loc = [int(v) for v in shape]
        loc[i] = _pad_extent(loc[i], p)
        loc[j] = _pad_extent(loc[j], p)
        loc[split] //= p
        return max(loc[-1], 1)

    return min(_fill(minor_of(i)), _fill(minor_of(j)))


# --------------------------------------------------------------------- #
# overlap (software-pipelining) model                                   #
# --------------------------------------------------------------------- #
def _overlap_laps(L: int) -> int:
    """Lap count the pipeline wants for an exchange of ``L`` local
    bytes: ~OVERLAP_GRAIN_BYTES laps (capped) once the buffer is big
    enough that per-lap ALPHA overhead is noise, else 1 (no pipelining —
    small moves stay one collective and the pinned censuses hold)."""
    L = int(L)
    if L < 2 * OVERLAP_GRAIN_BYTES:
        return 1
    return min(_OVERLAP_MAX_LAPS, L // OVERLAP_GRAIN_BYTES)


def _lap_count(extent: int, L: int, budget: int) -> int:
    """Chunk count for a pipelinable exchange over ``extent``: the
    larger of the budget requirement and the overlap grain, rounded to a
    divisor of ``extent``. Overlap-motivated chunking is BEST-EFFORT:
    equal laps need a divisor, and an extent with no small one (a prime
    extent rounds all the way up to ``extent`` itself) must not explode
    into a million-step schedule for a move the budget was happy to run
    in one collective — past 4x the grain cap the overlap ask is
    dropped and only the budget requirement stands."""
    need_budget = -(-2 * L // budget)
    c_budget = _divisor_chunks(extent, need_budget)
    want = max(need_budget, _overlap_laps(L))
    if want <= need_budget:
        return c_budget
    c = _divisor_chunks(extent, want)
    if c > 4 * _OVERLAP_MAX_LAPS:
        return c_budget
    return c


def _overlap_group(tag: str, laps: int, wire_bytes: int, copy_bytes: int) -> Optional[dict]:
    """Critical-path model of one pipelined chunk group at depth 2.
    Sequentially each lap pays ``wire + copy`` (the collective, then the
    reassembly copy of its result); double-buffered, lap k's copy runs
    under lap k+1's wire, so the steady state costs ``max(wire, copy)``
    per stage pair and only the first wire / last copy are exposed:

        critical_path = w + (laps - 1) * max(w, c) + c
        (w = wire_bytes / laps, c = copy_bytes / laps)

    Returns ``None`` when there is nothing to pipeline (laps < 2) or the
    model shows no gain."""
    laps = int(laps)
    wire_bytes, copy_bytes = int(wire_bytes), int(copy_bytes)
    if laps < 2:
        return None
    w, c = wire_bytes // laps, copy_bytes // laps
    cp = w + (laps - 1) * max(w, c) + c
    seq = wire_bytes + copy_bytes
    if cp >= seq:
        return None
    return {
        "tag": tag,
        "laps": laps,
        "wire_bytes": wire_bytes,
        "copy_bytes": copy_bytes,
        "sequential_bytes": seq,
        "critical_path_bytes": int(cp),
    }


def _overlap_annotation(groups: List[Optional[dict]]) -> Optional[dict]:
    """Fold per-group critical-path models into the Schedule-level
    annotation (None when no group pipelines — the plan is sequential
    and serializes without the key's contents)."""
    groups = [g for g in groups if g]
    if not groups:
        return None
    seq = sum(g["sequential_bytes"] for g in groups)
    cp = sum(g["critical_path_bytes"] for g in groups)
    return {
        "depth": 2,
        "groups": groups,
        "sequential_bytes": int(seq),
        "critical_path_bytes": int(cp),
        "model_speedup": round(seq / cp, 4),
    }


# --------------------------------------------------------------------- #
# candidate builders                                                    #
# --------------------------------------------------------------------- #
def _a2a_chunk_steps(
    L: int,
    p: int,
    C: int,
    what: str,
    pad_step: Optional[Step],
    tail_slice: Optional[Step],
    lane_fill: float = 1.0,
    pipe: Optional[str] = None,
) -> List[Step]:
    """C laps of slice -> all-to-all, then a scatter reassembly (written
    in place into the destination buffer: no transient). ``lane_fill``
    annotates the collective steps with the VREG fill of the buffers
    they stream (1.0 = full lanes, the packed forms). ``pipe`` tags the
    lap steps as one software-pipelined group (C >= 2 only): the
    executor may then overlap chunk k's scatter with chunk k+1's
    collective."""
    steps: List[Step] = []
    if pad_step is not None:
        steps.append(pad_step)
    crossing = L * (p - 1) // p  # the diagonal block stays home
    if C <= 1:
        steps.append(
            Step(
                "all_to_all",
                bytes_moved=crossing,
                peak_bytes=2 * L,
                detail=what,
                lane_fill=lane_fill,
            )
        )
    else:
        for c in range(C):
            steps.append(
                Step(
                    "slice",
                    peak_bytes=L // C,
                    detail=f"chunk {c}/{C} of {what}",
                    chunk=c,
                    overlap=pipe,
                )
            )
            steps.append(
                Step(
                    "all_to_all",
                    bytes_moved=crossing // C,
                    peak_bytes=2 * L // C,
                    detail=what,
                    chunk=c,
                    lane_fill=lane_fill,
                    overlap=pipe,
                )
            )
        steps.append(
            Step(
                "concat",
                peak_bytes=0,
                detail="scatter chunks into dst shard",
                overlap=pipe,
            )
        )
    if tail_slice is not None:
        steps.append(tail_slice)
    return steps


def _a2a_group(tag: str, L: int, p: int, C: int, lane_fill: float) -> Optional[dict]:
    """Overlap group for a C-lap chunked all-to-all of ``L`` local
    bytes: wire = the crossing payload, copy = the scatter reassembly
    write of the received laps, both lane-amplified like the cost
    model's step accounting."""
    fill = max(float(lane_fill), 1e-9)
    crossing = L * (p - 1) // p
    return _overlap_group(tag, C, int(crossing / fill), int(L / fill))


# --------------------------------------------------------------------- #
# two-tier topology (ISSUE 8): tier classification + hierarchical a2a   #
# --------------------------------------------------------------------- #
def _tier_group(
    tag: str, laps: int, ici_bytes: int, dcn_bytes: int, copy_bytes: int
) -> Optional[dict]:
    """Critical-path model of one pipelined chunk group at a TIERED
    topology: a lap's ICI hop, its (penalty-priced) DCN hop, and the
    reassembly copy each occupy a different engine, so the depth-2
    steady state prices a lap at ``max(ici, dcn·penalty, copy)`` with
    the first wire legs and last copy exposed. ``wire_bytes`` is kept in
    ICI byte-equivalents (``ici + dcn·penalty``) so the schedule-level
    ``sequential_model_bytes``/``critical_path_bytes`` arithmetic is
    unit-consistent with the flat groups."""
    laps = int(laps)
    if laps < 2:
        return None
    pen = _dcn_penalty()
    ici_bytes, dcn_bytes, copy_bytes = int(ici_bytes), int(dcn_bytes), int(copy_bytes)
    wi, wd, c = ici_bytes // laps, dcn_bytes * pen // laps, copy_bytes // laps
    cp = wi + wd + c + (laps - 1) * max(wi, wd, c)
    wire_eq = ici_bytes + dcn_bytes * pen
    seq = wire_eq + copy_bytes
    if cp >= seq:
        return None
    return {
        "tag": tag,
        "laps": laps,
        "wire_bytes": int(wire_eq),
        "copy_bytes": copy_bytes,
        "ici_bytes": ici_bytes,
        "dcn_bytes": dcn_bytes,
        "dcn_penalty": pen,
        "sequential_bytes": int(seq),
        "critical_path_bytes": int(cp),
    }


def _hier_a2a_group(
    tag: str, L: int, topo: Tuple[int, int], laps: int, lane_fill: float
) -> Optional[dict]:
    """Tier group for a ``laps``-lap hierarchical all-to-all of ``L``
    local bytes at topology ``(S, C)``: the intra-slice pivot carries
    ``L·(C-1)/C`` on ICI, the inter-slice exchange ``L·(S-1)/S`` on
    DCN, and the scatter reassembly writes ``L``."""
    S, C = topo
    fill = max(float(lane_fill), 1e-9)
    return _tier_group(
        tag,
        laps,
        int(L * (C - 1) // C / fill),
        int(L * (S - 1) // S / fill),
        int(L / fill),
    )


def _with_tier(st: Step, tier: str) -> Step:
    return Step(
        st.kind,
        bytes_moved=st.bytes_moved,
        peak_bytes=st.peak_bytes,
        detail=st.detail,
        chunk=st.chunk,
        bytes_copied=st.bytes_copied,
        lane_fill=st.lane_fill,
        overlap=st.overlap,
        tier=tier,
    )


def _tier_flat(sched: Schedule, topo: Optional[Tuple[int, int]]) -> Schedule:
    """Classify a FLAT-structure candidate at a tiered topology: its
    replica groups span the whole mesh, so every collective rides DCN —
    each collective step gains ``tier="dcn"`` (the cost model then
    prices its bytes at the penalty) and the schedule carries the
    topology annotation. Structure, census, and executor program form
    are unchanged — only the price and the serialization."""
    if topo is None or not any(st.is_collective for st in sched.steps):
        return sched
    steps = [_with_tier(st, "dcn") if st.is_collective else st for st in sched.steps]
    overlap = sched.overlap
    if overlap:
        rebuilt = [
            _tier_group(g["tag"], g["laps"], 0, g["wire_bytes"], g["copy_bytes"])
            for g in overlap["groups"]
        ]
        overlap = _overlap_annotation(rebuilt)
    return Schedule(
        sched.spec,
        sched.strategy,
        steps,
        sched.budget_bytes,
        notes=sched.notes,
        overlap=overlap,
        quant=sched.quant,
        topology=_topo_annotation(topo),
    )


def _hier_chunk_steps(
    L: int,
    topo: Tuple[int, int],
    K: int,
    what: str,
    pad_step: Optional[Step],
    tail_slice: Optional[Step],
    lane_fill: float = 1.0,
    pipe: Optional[str] = None,
) -> List[Step]:
    """The hierarchical counterpart of :func:`_a2a_chunk_steps`: K laps
    of slice → intra-slice all-to-all (chip subgroups, the cheap tier
    carries the volume) → inter-slice all-to-all of pre-packed per-slice
    rows (the expensive tier ships only the bytes that must cross) →
    scatter reassembly. Census: 2·K all-to-alls, tiers ici/dcn."""
    S, C = topo
    steps: List[Step] = []
    if pad_step is not None:
        steps.append(pad_step)
    ici_cross = L * (C - 1) // C
    dcn_cross = L * (S - 1) // S
    pipe = pipe if K > 1 else None  # single-lap: nothing to pipeline

    def lap(chunk: Optional[int], l_bytes: int):
        out = []
        if chunk is not None:
            out.append(
                Step(
                    "slice",
                    peak_bytes=l_bytes,
                    detail=f"chunk {chunk}/{K} of {what}",
                    chunk=chunk,
                    overlap=pipe,
                )
            )
        out.append(
            Step(
                "all_to_all",
                bytes_moved=ici_cross // max(K, 1),
                peak_bytes=2 * l_bytes,
                detail=f"intra-slice pivot of {what} (chip subgroups)",
                chunk=chunk,
                lane_fill=lane_fill,
                overlap=pipe,
                tier="ici",
            )
        )
        out.append(
            Step(
                "all_to_all",
                bytes_moved=dcn_cross // max(K, 1),
                peak_bytes=2 * l_bytes,
                detail=(
                    f"inter-slice exchange of {what} (pre-packed per-slice "
                    "rows — minimum DCN bytes)"
                ),
                chunk=chunk,
                lane_fill=lane_fill,
                overlap=pipe,
                tier="dcn",
            )
        )
        return out

    if K <= 1:
        steps += lap(None, L)
    else:
        for c in range(K):
            steps += lap(c, L // K)
        steps.append(
            Step(
                "concat",
                peak_bytes=0,
                detail="scatter chunks into dst shard",
                overlap=pipe,
            )
        )
    if tail_slice is not None:
        steps.append(tail_slice)
    return steps


def _resplit_candidates(
    spec: RedistSpec, budget: int, topo: Optional[Tuple[int, int]] = None
) -> List[Schedule]:
    """split i -> split j candidates: (chunked) all-to-all and the ring
    — plus, at a tiered topology, the ``hierarchical-a2a`` decomposition
    (and the flat forms DCN-classified, since their replica groups span
    slices)."""
    p = spec.mesh_size
    i, j = spec.src_split, spec.dst_split
    L = _local_move_bytes(spec)
    Nj, Njp = spec.gshape[j], _pad_extent(spec.gshape[j], p)
    Ni, Nip = spec.gshape[i], _pad_extent(spec.gshape[i], p)
    pad_step = (
        Step("pad", peak_bytes=L, detail=f"pad axis {j} {Nj}->{Njp} (local)")
        if Njp != Nj
        else None
    )
    tail = (
        Step("slice", peak_bytes=L, detail=f"drop axis {i} pad {Nip}->{Ni} (local)")
        if Nip != Ni
        else None
    )
    # concat axis is the source split axis: its local extent is what the
    # chunk laps tile over. Laps come from the tighter of the budget
    # requirement and the overlap grain (pipelinable buffers chunk even
    # under a roomy budget so the executor has stages to double-buffer).
    concat_extent = Nip // p
    C = _lap_count(concat_extent, L, budget)

    what = f"split {i}->{j}"
    fill = _exchange_fill(spec.gshape, i, j, p)
    a2a = Schedule(
        spec,
        "all-to-all" if C <= 1 else "chunked-all-to-all",
        _a2a_chunk_steps(L, p, C, what, pad_step, tail, lane_fill=fill, pipe="pipe0"),
        budget,
        notes=f"C={C} chunks over local axis-{i} extent {concat_extent}" if C > 1 else "",
        overlap=_overlap_annotation([_a2a_group("pipe0", L, p, C, fill)]) if C > 1 else None,
    )

    ring_steps: List[Step] = []
    if pad_step is not None:
        ring_steps.append(pad_step)
    blk = L // p
    for d in range(1, p):
        ring_steps.append(
            Step(
                "ppermute",
                bytes_moved=blk,
                peak_bytes=2 * blk,
                detail=f"hop distance {d}: neighbor block of {what}",
                lane_fill=fill,
                overlap="ring0" if p > 2 else None,
            )
        )
    if tail is not None:
        ring_steps.append(tail)
    # ring overlap: hop d+1's ppermute flies while hop d's received
    # block is scattered into the destination (wire = copy = one
    # neighbor block per hop)
    ring_group = (
        _overlap_group(
            "ring0", p - 1, int(blk * (p - 1) / max(fill, 1e-9)),
            int(blk * (p - 1) / max(fill, 1e-9)),
        )
        if p > 2
        else None
    )
    ring = Schedule(
        spec,
        "ring",
        ring_steps,
        budget,
        notes="p-1 ppermute hops, one neighbor block in flight per step",
        overlap=_overlap_annotation([ring_group]),
    )
    if topo is None:
        return [a2a, ring]
    # tiered topology: the flat forms span slices (every collective —
    # including each +d ring hop, whose wraparound neighbors cross the
    # slice boundary — rides DCN at the penalty price), and the
    # hierarchical decomposition competes
    hier_steps = _hier_chunk_steps(
        L, topo, C, what, pad_step, tail, lane_fill=fill, pipe="pipe0"
    )
    hier = Schedule(
        spec,
        "hierarchical-a2a",
        hier_steps,
        budget,
        notes=(
            f"two-tier decomposition at {topo[0]}x{topo[1]}: intra-slice "
            "pivot (ICI carries the volume) + inter-slice exchange of "
            "pre-packed per-slice rows (minimum DCN bytes)"
            + (f"; C={C} chunks" if C > 1 else "")
        ),
        overlap=_overlap_annotation([_hier_a2a_group("pipe0", L, topo, C, fill)]),
        topology=_topo_annotation(topo),
    )
    return [_tier_flat(a2a, topo), _tier_flat(ring, topo), hier]


def _pivot_valid(spec: RedistSpec) -> bool:
    """The split-0 pivot needs the leading extents to divide the mesh on
    both sides (device blocks are then contiguous runs of the row-major
    element order, so the middle reshape is LOCAL)."""
    p = spec.mesh_size
    in0 = spec.gshape[0] if spec.gshape else 0
    out0 = spec.out_shape[0] if spec.out_shape else 0
    return (
        len(spec.gshape) >= 1
        and len(spec.out_shape) >= 1
        and in0 > 0
        and out0 > 0
        and in0 % p == 0
        and out0 % p == 0
    )


def _pivot_schedule(
    spec: RedistSpec, budget: int, topo: Optional[Tuple[int, int]] = None
) -> Schedule:
    """The split-0 pivot. ``topo`` builds the HIERARCHICAL variant
    (ISSUE 8): each stage exchange decomposes into the intra-slice +
    inter-slice pair, the strategy is named ``hierarchical-a2a``, and
    the overlap groups price laps at ``max(ici, dcn·penalty, copy)``."""
    p = spec.mesh_size
    s, t = spec.src_split, spec.dst_split
    item = spec.itemsize
    steps: List[Step] = []
    groups: List[Optional[dict]] = []
    shard = spec.size // p * item  # logical bytes per device block

    def stage(L, C, what, fill, pipe):
        if topo is None:
            groups.append(_a2a_group(pipe, L, p, C, fill) if C > 1 else None)
            return _a2a_chunk_steps(
                L, p, C, what, None, None, lane_fill=fill, pipe=pipe
            )
        groups.append(_hier_a2a_group(pipe, L, topo, C, fill))
        return _hier_chunk_steps(L, topo, C, what, None, None, lane_fill=fill, pipe=pipe)

    n_coll = 0
    if s is not None and s != 0:
        L1 = _prod(
            [_pad_extent(d, p) if ax == s else d for ax, d in enumerate(spec.gshape)]
        ) // p * item
        C1 = _lap_count(_pad_extent(spec.gshape[s], p) // p, L1, budget)
        fill_in = _exchange_fill(spec.gshape, s, 0, p)
        steps += stage(L1, C1, f"split {s}->0 (pivot in)", fill_in, "pipe0")
        n_coll += C1
        if _pad_extent(spec.gshape[s], p) != spec.gshape[s]:
            steps.append(
                Step("slice", peak_bytes=shard, detail=f"drop axis {s} pad (local)")
            )
    steps.append(
        Step(
            "reshape",
            peak_bytes=shard,
            bytes_copied=shard,
            lane_fill=min(
                _fill(spec.gshape[-1] if spec.gshape else 1),
                _fill(spec.out_shape[-1] if spec.out_shape else 1),
            ),
            detail="local row-major reshape at full minor-dim width",
        )
    )
    if t is not None and t != 0:
        out_t, out_tp = spec.out_shape[t], _pad_extent(spec.out_shape[t], p)
        L2 = _prod(
            [_pad_extent(d, p) if ax == t else d for ax, d in enumerate(spec.out_shape)]
        ) // p * item
        if out_tp != out_t:
            pad_minor = out_tp if t == len(spec.out_shape) - 1 else spec.out_shape[-1]
            steps.append(
                Step(
                    "pad",
                    peak_bytes=L2,
                    bytes_copied=L2,
                    lane_fill=_fill(pad_minor),
                    detail=f"pad axis {t} {out_t}->{out_tp} (local)",
                )
            )
        C2 = _lap_count(spec.out_shape[0] // p, L2, budget)
        fill_out = _exchange_fill(spec.out_shape, 0, t, p)
        steps += stage(L2, C2, f"split 0->{t} (pivot out)", fill_out, "pipe1")
        n_coll += C2
    if n_coll:
        strategy = "hierarchical-a2a" if topo is not None else "split0-pivot"
    else:
        strategy = "local-reshape"
    return Schedule(
        spec,
        strategy,
        steps,
        budget,
        notes="minor-dim packing: heavy copies run on the split-0 layout"
        + (
            f"; two-tier pivot stages at {topo[0]}x{topo[1]}"
            if topo is not None and n_coll
            else ""
        ),
        overlap=_overlap_annotation(groups),
        topology=_topo_annotation(topo) if topo is not None and n_coll else None,
    )


def _packed_sides(spec: RedistSpec) -> Tuple[bool, bool]:
    """(packed_in, packed_out): which pivot stages engage the
    lane-packed form — 2-D pivots whose shard minor dim fills less than
    ``kernels.relayout.PACK_FILL_THRESHOLD`` of the lane axis."""
    p = spec.mesh_size
    if (
        not spec.is_reshape
        or len(spec.gshape) != 2
        or len(spec.out_shape) != 2
        or not _pivot_valid(spec)
    ):
        return False, False
    thr = _pack_threshold()
    s, t = spec.src_split, spec.dst_split
    packed_in = s == 1 and _fill(_pad_extent(spec.gshape[1], p) // p) < thr
    packed_out = t == 1 and _fill(_pad_extent(spec.out_shape[1], p) // p) < thr
    return packed_in, packed_out


def _packed_pivot_schedule(
    spec: RedistSpec, budget: int, topo: Optional[Tuple[int, int]] = None
) -> Schedule:
    """The split-0 pivot with its narrow-minor stages rewritten on
    lane-packed buffers (``heat_tpu.kernels.relayout``): the chunked
    all-to-alls stream (p, rows·cols/p) column-grouped FLAT buffers
    (full VREGs), and the only lane-amplified copy left is the single
    unpack that materializes the destination's requested narrow layout.
    Same collective census as the direct pivot — the packing changes
    layouts, never movement. ``topo`` builds the hierarchical variant
    (strategy ``hierarchical-a2a``): the packed flat buffers decompose
    across tiers exactly like the direct ones."""
    p = spec.mesh_size
    item = spec.itemsize
    s, t = spec.src_split, spec.dst_split
    (r0, c0), (r1, c1) = spec.gshape, spec.out_shape
    c0p, c1p = _pad_extent(c0, p), _pad_extent(c1, p)
    R0, R1 = r0 // p, r1 // p
    shard = spec.size // p * item
    packed_in, packed_out = _packed_sides(spec)
    steps: List[Step] = []
    groups: List[Optional[dict]] = []

    def stage(L, C, what, fill, pipe):
        if topo is None:
            groups.append(_a2a_group(pipe, L, p, C, fill) if C > 1 else None)
            return _a2a_chunk_steps(
                L, p, C, what, None, None, lane_fill=fill, pipe=pipe
            )
        groups.append(_hier_a2a_group(pipe, L, topo, C, fill))
        return _hier_chunk_steps(L, topo, C, what, None, None, lane_fill=fill, pipe=pipe)

    if s == 1:
        L1 = r0 * c0p // p * item
        C1 = _lap_count(c0p // p, L1, budget)
        if packed_in:
            steps += stage(L1, C1, "split 1->0 (packed pivot in)", 1.0, "pipe0")
            steps.append(
                Step(
                    "unpack",
                    bytes_copied=R0 * c0 * item,
                    peak_bytes=R0 * c0p * item,
                    lane_fill=1.0,
                    detail=(
                        f"lane-unpack: ungroup {p} col-blocks, drop row pad "
                        f"{c0p}->{c0} (kernel-served flat copy)"
                    ),
                )
            )
        else:
            fill_in = _exchange_fill(spec.gshape, 1, 0, p)
            steps += stage(L1, C1, f"split {s}->0 (pivot in)", fill_in, "pipe0")
            if c0p != c0:
                steps.append(
                    Step("slice", peak_bytes=shard, detail="drop axis 1 pad (local)")
                )
    steps.append(
        Step(
            "reshape",
            peak_bytes=shard,
            lane_fill=1.0,
            detail="flat row-major view of the contiguous split-0 block (no narrow materialization)",
        )
    )
    if t == 1:
        L2 = r1 * c1p // p * item
        C2 = _lap_count(R1, L2, budget)
        if packed_out:
            steps.append(
                Step(
                    "pack",
                    bytes_copied=R1 * c1p * item,
                    peak_bytes=R1 * c1p * item,
                    lane_fill=1.0,
                    detail=(
                        f"lane-pack rows {c1}->{c1p} + group {p} col-blocks for "
                        "all-to-all (kernel-served flat copy)"
                    ),
                )
            )
            steps += stage(L2, C2, "split 0->1 (packed pivot out)", 1.0, "pipe1")
            steps.append(
                Step(
                    "unpack",
                    bytes_copied=R1 * c1p * item,
                    peak_bytes=R1 * c1p * item,
                    lane_fill=_fill(c1p // p),
                    detail=(
                        f"materialize dst shard ({r1}, {c1p // p}) — the single "
                        "lane-amplified write the requested layout costs"
                    ),
                )
            )
        else:
            if c1p != c1:
                steps.append(
                    Step(
                        "pad",
                        peak_bytes=L2,
                        bytes_copied=L2,
                        lane_fill=_fill(c1p),
                        detail=f"pad axis 1 {c1}->{c1p} (local)",
                    )
                )
            fill_out = _exchange_fill(spec.out_shape, 0, 1, p)
            steps += stage(L2, C2, f"split 0->{t} (pivot out)", fill_out, "pipe1")
    return Schedule(
        spec,
        "hierarchical-a2a" if topo is not None else "packed-pivot",
        steps,
        budget,
        notes=(
            "lane-packing pivot: collectives and heavy copies run on packed "
            "full-lane buffers (HEAT_TPU_RELAYOUT_KERNEL gates the tiled-copy kernel)"
        )
        + (
            f"; two-tier pivot stages at {topo[0]}x{topo[1]}"
            if topo is not None
            else ""
        ),
        overlap=_overlap_annotation(groups),
        topology=_topo_annotation(topo) if topo is not None else None,
    )


def _gather_reshape_schedule(spec: RedistSpec, budget: int) -> Schedule:
    p = spec.mesh_size
    logical = spec.logical_bytes
    steps = [
        Step(
            "all_gather",
            bytes_moved=logical * (p - 1) // p,
            peak_bytes=logical,
            lane_fill=_fill(_shard_minor(spec.gshape, spec.src_split, p)),
            detail="replicate the full operand (fallback: pivot divisibility failed)"
            if spec.is_reshape
            else "explicit replicate",
        )
    ]
    if spec.is_reshape:
        steps.append(
            Step(
                "reshape",
                peak_bytes=logical,
                bytes_copied=logical,
                lane_fill=min(
                    _fill(spec.gshape[-1] if spec.gshape else 1),
                    _fill(spec.out_shape[-1] if spec.out_shape else 1),
                ),
                detail="replicated reshape",
            )
        )
    if spec.dst_split is not None:
        steps.append(
            Step(
                "slice",
                peak_bytes=spec.dst_shard_bytes,
                bytes_copied=spec.dst_shard_bytes,
                lane_fill=_fill(_shard_minor(spec.out_shape, spec.dst_split, p)),
                detail=f"slice dst shard (split {spec.dst_split})",
            )
        )
    return Schedule(
        spec,
        "gather-reshape" if spec.is_reshape else "replicate",
        steps,
        budget,
        notes="full all-gather — the only strategy that materializes the logical array",
    )


def _cost(s: Schedule) -> int:
    """Byte-equivalent cost: ALPHA per collective launch, plus every
    step's lane-amplified HBM traffic (payload + local relayout copy
    writes, divided by the step's VREG lane fill). A ``tier="dcn"``
    collective's bytes are priced at ``DCN_PENALTY`` (≈ 8×, the
    ICI/DCN bandwidth ratio) — the tier term that makes
    ``hierarchical-a2a`` beat the slice-spanning flat forms exactly on
    the big cross-slice moves (ISSUE 8)."""
    pen = _dcn_penalty() if s.topology else 1
    total = 0
    for st in s.steps:
        eff = st.effective_bytes
        if st.tier == "dcn":
            eff *= pen
        total += (ALPHA_BYTES if st.is_collective else 0) + eff
    return total


def _select(candidates: List[Schedule]) -> Schedule:
    feasible = [c for c in candidates if c.within_budget]
    if feasible:
        return min(feasible, key=_cost)
    # nothing fits: degrade to the smallest footprint and say so —
    # rebuilt (not mutated) so plan_id stays the sha1 of the canonical
    # serialization, notes included
    best = min(candidates, key=lambda c: c.peak_bytes)
    notes = (best.notes + "; " if best.notes else "") + (
        f"over budget: peak {best.peak_bytes} B > {best.budget_bytes} B "
        "(smallest-footprint candidate chosen)"
    )
    return Schedule(
        best.spec, best.strategy, best.steps, best.budget_bytes,
        notes=notes, overlap=best.overlap, topology=best.topology,
    )


# --------------------------------------------------------------------- #
# wire quantization (ISSUE 7): the codec pass over a selected plan      #
# --------------------------------------------------------------------- #
def _quantize_schedule(sched: Schedule, mode: Optional[str]) -> Schedule:
    """Wrap the admissible collective groups of a SELECTED plan in
    ``quantize``/``dequantize`` codec steps (``heat_tpu.kernels.quant``)
    and scale their ``bytes_moved`` to the encoded wire size.

    Runs AFTER strategy selection, on the winner only: the gate can
    therefore never flip which strategy (or how many collectives) a
    spec plans to — censuses and lap structure are identical gate-on vs
    gate-off by construction, which is the invariant every golden pin
    relies on. The numerics-tolerance policy lives here: float32
    payloads only (ints/bools/f64 are never lossy on the wire — they
    ship exact-bit), transient-exchange strategies only (replicate/
    gather-reshape materialize consumed values), and only groups
    shipping at least ``QUANT_MIN_WIRE_BYTES`` full-width (smaller
    exchanges are latency-bound and stay exact). The overlap groups'
    critical-path models are rebuilt on the encoded wire bytes — the
    codec shrinks the ``wire`` leg of ``max(wire, copy)``, which is
    exactly the ICI-bound rows' binding term.

    Tiered plans (ISSUE 8): in a ``hierarchical-a2a`` plan only the
    ``tier="dcn"`` exchanges are codec-eligible — the inter-slice hop
    is the wire-bound leg the decomposition isolated, and it is the
    FIRST group the codec targets; the intra-slice pivot is wire-cheap
    and stays exact (half the codec error for free). Slice-spanning
    FLAT plans quantize all their collectives exactly as before — every
    byte of theirs rides DCN anyway."""
    if mode is None:
        return sched
    spec = sched.spec
    if spec.dtype != "float32" or sched.strategy not in _QUANT_STRATEGIES:
        return sched
    from ..kernels import quant as _quant

    p = spec.mesh_size
    item = spec.itemsize
    hier = sched.strategy == "hierarchical-a2a"
    # the number of independently encoded wire rows per exchange: the
    # destination count of the collective's replica groups — the S
    # slices for the hierarchical DCN hop, the p devices otherwise
    n_dest = int(sched.topology["n_slices"]) if hier else p
    groups: Dict[str, List[int]] = {}
    for idx, st in enumerate(sched.steps):
        if not st.is_collective:
            continue
        if hier and st.tier != "dcn":
            continue  # the ICI pivot ships exact (see docstring)
        key = st.overlap if st.overlap is not None else f"_solo{idx}"
        groups.setdefault(key, []).append(idx)
    sent_of: Dict[int, int] = {}
    for key, idxs in groups.items():
        if sum(sched.steps[i].bytes_moved for i in idxs) < QUANT_MIN_WIRE_BYTES:
            continue
        for i in idxs:
            st = sched.steps[i]
            if st.kind == "ppermute":
                # one neighbor block per hop
                sent_of[i] = _quant.wire_bytes(st.bytes_moved // item, mode)
            else:
                # crossing payload = (n_dest-1) per-destination blocks,
                # each encoded independently (the executor's wire rows)
                blk_elems = st.bytes_moved // (n_dest - 1) // item
                sent_of[i] = (n_dest - 1) * _quant.wire_bytes(blk_elems, mode)
    if not sent_of:
        return sched

    raw_total = sched.bytes_moved
    new_steps: List[Step] = []
    for i, st in enumerate(sched.steps):
        if i not in sent_of:
            new_steps.append(st)
            continue
        sent = sent_of[i]
        raw = st.bytes_moved
        if st.kind == "ppermute":
            full_local = raw
            enc_write = sent
        else:
            # incl. the resident diagonal block
            full_local = raw * n_dest // (n_dest - 1)
            enc_write = sent * n_dest // (n_dest - 1)
        new_steps.append(
            Step(
                "quantize",
                bytes_copied=enc_write,
                peak_bytes=enc_write,
                detail=(
                    f"{mode}-encode wire blocks ({_quant.TILE}-elem tile "
                    f"scales): {raw} B -> {sent} B on the wire "
                    f"(saved {raw - sent} B)"
                ),
                chunk=st.chunk,
                overlap=st.overlap,
            )
        )
        new_steps.append(
            Step(
                st.kind,
                bytes_moved=sent,
                peak_bytes=st.peak_bytes,
                detail=st.detail + f" [{mode} wire]",
                chunk=st.chunk,
                lane_fill=1.0,  # encoded payloads are dense flat byte streams
                overlap=st.overlap,
                tier=st.tier,
            )
        )
        new_steps.append(
            Step(
                "dequantize",
                bytes_copied=0 if st.overlap else full_local,
                peak_bytes=0 if st.overlap else full_local,
                detail=(
                    f"{mode}-decode received blocks"
                    + (
                        " (full-width write rides the group's reassembly copy)"
                        if st.overlap
                        else f" ({full_local} B full-width write)"
                    )
                ),
                chunk=st.chunk,
                overlap=st.overlap,
            )
        )

    new_overlap = sched.overlap
    if sched.overlap:
        rebuilt = []
        for g in sched.overlap["groups"]:
            idxs = [i for i in groups.get(g["tag"], []) if i in sent_of]
            if not idxs:
                rebuilt.append(g)
                continue
            wire_new = sum(sent_of[i] for i in idxs)
            if "ici_bytes" in g:
                # tiered group: the codec shrinks only the DCN leg (the
                # ICI pivot ships exact in hierarchical plans; in
                # slice-spanning flat plans the ICI leg is 0)
                rebuilt.append(
                    _tier_group(
                        g["tag"], g["laps"], g["ici_bytes"], wire_new,
                        g["copy_bytes"],
                    )
                )
            else:
                rebuilt.append(
                    _overlap_group(g["tag"], g["laps"], wire_new, g["copy_bytes"])
                )
        new_overlap = _overlap_annotation(rebuilt)

    sent_total = raw_total - sum(
        sched.steps[i].bytes_moved for i in sent_of
    ) + sum(sent_of.values())
    ann = {
        "mode": mode,
        "tol": _quant.tolerance(mode),
        "bytes_raw": int(raw_total),
        "bytes_sent": int(sent_total),
        "ratio": round(sent_total / raw_total, 4) if raw_total else 1.0,
        "min_group_bytes": QUANT_MIN_WIRE_BYTES,
    }
    notes = sched.notes + ("; " if sched.notes else "") + (
        f"{mode} wire codec on {len(sent_of)} collective step(s) "
        f"(kernels.quant, tol {ann['tol']})"
    )
    return Schedule(
        spec,
        sched.strategy,
        new_steps,
        sched.budget_bytes,
        notes=notes,
        overlap=new_overlap,
        quant=ann,
        topology=sched.topology,
    )


# --------------------------------------------------------------------- #
# the planner                                                           #
# --------------------------------------------------------------------- #
def _build(
    spec: RedistSpec, budget: int, topo: Optional[Tuple[int, int]] = None
) -> Schedule:
    p = spec.mesh_size

    if spec.is_reshape:
        if spec.gshape == spec.reshape_to and spec.src_split == spec.dst_split:
            return Schedule(spec, "noop", [], budget)
        if p <= 1 or spec.size == 0:
            return Schedule(
                spec,
                "local",
                [Step("reshape", peak_bytes=spec.logical_bytes, detail="single-shard reshape")],
                budget,
            )
        if spec.src_split is None:
            steps = [
                Step("reshape", peak_bytes=spec.logical_bytes, detail="replicated reshape")
            ]
            if spec.dst_split is not None:
                steps.append(
                    Step(
                        "slice",
                        peak_bytes=spec.dst_shard_bytes,
                        detail=f"slice dst shard (split {spec.dst_split})",
                    )
                )
            return Schedule(spec, "local-reshape", steps, budget)
        if spec.dst_split is None:
            return _tier_flat(_gather_reshape_schedule(spec, budget), topo)
        candidates = []
        if _pivot_valid(spec):
            candidates.append(_tier_flat(_pivot_schedule(spec, budget), topo))
            if any(_packed_sides(spec)):
                candidates.append(
                    _tier_flat(_packed_pivot_schedule(spec, budget), topo)
                )
            if topo is not None:
                # the hierarchical pivot variants (ISSUE 8): every stage
                # exchange decomposed across tiers
                candidates.append(_pivot_schedule(spec, budget, topo=topo))
                if any(_packed_sides(spec)):
                    candidates.append(_packed_pivot_schedule(spec, budget, topo=topo))
        candidates.append(_tier_flat(_gather_reshape_schedule(spec, budget), topo))
        return _select(candidates)

    # pure resplit
    if spec.src_split == spec.dst_split:
        return Schedule(spec, "noop", [], budget)
    if p <= 1 or spec.size == 0:
        return Schedule(spec, "local", [], budget)
    if spec.src_split is None:
        return Schedule(
            spec,
            "slice",
            [
                Step(
                    "slice",
                    peak_bytes=spec.dst_shard_bytes,
                    detail=f"local shard slice (split {spec.dst_split})",
                )
            ],
            budget,
        )
    if spec.dst_split is None:
        return _tier_flat(_gather_reshape_schedule(spec, budget), topo)
    return _select(_resplit_candidates(spec, budget, topo))


def plan(
    spec: RedistSpec,
    budget: Optional[int] = None,
    quant: Optional[str] = None,
    topology=None,
) -> Schedule:
    """Plan ``spec`` under ``budget`` bytes (default: the env knob).

    ``quant`` pins the wire codec explicitly — ``"0"`` plans the
    full-width exact-bit schedule, ``"int8"``/``"bf16"`` force that
    codec through the admissibility policy, and the default ``None``
    resolves the ``HEAT_TPU_WIRE_QUANT`` gate (:func:`wire_quant_gate`).
    ``topology`` pins the two-tier topology the same way (ISSUE 8):
    ``None`` resolves the ambient ``HEAT_TPU_TOPOLOGY``, ``"flat"``
    forces one ICI domain (the pre-topology plans, byte-identical), an
    ``"SxC"`` string / ``(S, C)`` tuple forces a simulated
    factorization. Plans are cached per (spec, budget, resolved codec,
    resolved topology, active lattice profile_id) — all five are part
    of the canonical serialization and plan_id, so a gate flip (or a
    recalibration, ISSUE 16) can never serve a stale plan. Cache
    hits/misses and the planned byte/step/peak totals feed the
    telemetry registry."""
    b = budget_bytes() if budget is None else int(budget)
    if quant is None:
        qmode = wire_quant_gate()
    elif quant in ("0", "off", None):
        qmode = None
    else:
        from ..kernels.quant import MODES as _MODES

        if quant not in _MODES:
            raise ValueError(f"plan: unknown wire codec {quant!r}")
        qmode = quant
    topo = resolve_topology(spec.mesh_size, topology)
    # ISSUE 16: the active lattice profile (HEAT_TPU_LATTICE_PROFILE)
    # re-prices candidate selection (_cost's dcn penalty, the tier
    # annotations' recorded prices), so it is plan-cache key material —
    # and the chosen plan is rebuilt with the calibration annotation so
    # the profile_id lands in the canonical serialization and plan_id
    # (recalibration = visible invalidation). Unset resolves to None:
    # key and plan bytes are identical to the pre-calibration era.
    from ..core import tiers as _tiers

    cal = _tiers.profile_annotation()
    key = (spec, b, qmode or "0", topo, cal["profile_id"] if cal else None)
    with _plan_lock:
        cached = _plan_cache.get(key)
    if cached is not None:
        if _telemetry._ENABLED:
            _telemetry.inc("redist.plan_cache.hit")
        return cached
    sched = _quantize_schedule(_build(spec, b, topo), qmode)
    if cal is not None:
        sched = Schedule(
            sched.spec, sched.strategy, sched.steps, sched.budget_bytes,
            notes=sched.notes, overlap=sched.overlap, quant=sched.quant,
            topology=sched.topology, staging=sched.staging, calibration=cal,
        )
    with _plan_lock:
        if len(_plan_cache) >= _PLAN_CACHE_MAX:
            _plan_cache.pop(next(iter(_plan_cache)))
        _plan_cache[key] = sched
    if _telemetry._ENABLED:
        _telemetry.inc("redist.plan_cache.miss")
        _telemetry.inc("redist.planned_bytes", sched.bytes_moved)
        _telemetry.inc("redist.steps", sched.n_steps)
        _telemetry.inc("redist.peak_bytes", sched.peak_bytes)
        _obs_events.emit(
            "redist.plan",
            plan_id=sched.plan_id,
            strategy=sched.strategy,
            spec=repr(sched.spec),
            steps=sched.n_steps,
            collectives=sched.collective_counts(),
            peak_bytes=sched.peak_bytes,
            budget_bytes=b,
            overlap_depth=sched.overlap_depth,
            critical_path_model=(
                sched.overlap["model_speedup"] if sched.overlap else None
            ),
            quant=sched.quant["mode"] if sched.quant else None,
            wire_bytes_saved=sched.wire_bytes_raw - sched.wire_bytes_sent,
            topology=f"{topo[0]}x{topo[1]}" if topo else None,
            dcn_bytes=sched.tier_bytes()["dcn"] if topo else 0,
        )
    return sched


def explain(arr, axis=None, *, reshape=None, new_split=None, topology=None) -> Schedule:
    """The chosen redistribution plan for ``arr`` — without executing it.

    ``explain(arr, axis)`` plans the resplit to ``axis``;
    ``explain(arr, reshape=shape, new_split=...)`` plans the
    reshape-with-repartition (``new_split`` defaults the same way
    ``ht.reshape`` defaults it). ``topology`` overrides the ambient
    ``HEAT_TPU_TOPOLOGY`` (``"flat"``, ``"SxC"``, a ``Topology``, or an
    ``(S, C)`` tuple) — what-if planning for a mesh factorization this
    process is not running on. Returns the
    :class:`~heat_tpu.redistribution.schedule.Schedule` the executor
    would compile — strategy, steps, per-step peak-memory accounting,
    plan id.
    """
    from ..core.dndarray import DNDarray
    from ..core.stride_tricks import sanitize_axis

    if not planner_enabled():
        raise RuntimeError(
            "explain: the redistribution planner is disabled "
            f"({_ENABLE_ENV}=0) — resplit/reshape run the legacy "
            "one-collective paths, so there is no plan to show. Unset "
            f"{_ENABLE_ENV} to re-enable planner routing."
        )
    if not isinstance(arr, DNDarray):
        raise TypeError(f"explain expects a DNDarray, got {type(arr)}")
    if arr._is_planar:
        raise TypeError(
            "explain: planar-complex arrays take the legacy relayout path "
            "(the planner routes real/physical layouts only)"
        )
    if reshape is not None:
        # THE resolver the public call uses — explain must build its
        # spec from exactly the (shape, new_split) ht.reshape executes
        from ..core.manipulations import _normalize_reshape_args

        shape, new_split = _normalize_reshape_args(arr, (tuple(reshape),) if isinstance(
            reshape, (tuple, list)
        ) else (reshape,), new_split)
        spec = RedistSpec.normalize(
            arr.gshape,
            np.dtype(arr._phys.dtype).name,
            arr.split,
            new_split,
            arr.comm.size,
            reshape_to=shape,
        )
    else:
        axis = sanitize_axis(arr.gshape, axis)
        spec = RedistSpec.normalize(
            arr.gshape, np.dtype(arr._phys.dtype).name, arr.split, axis, arr.comm.size
        )
    return plan(spec, topology=topology)


# --------------------------------------------------------------------- #
# golden matrix — pinned by tier-1 and the ci.sh determinism leg        #
# --------------------------------------------------------------------- #
def golden_specs() -> List[Tuple[str, RedistSpec]]:
    """The (name, spec) matrix whose plans are golden: strategies and
    step counts are pinned in ``tests/test_redistribution.py`` and the
    serialized plans must be byte-identical run-to-run (ci.sh diffs two
    runs of ``scripts/redist_plans.py``)."""
    S = RedistSpec.normalize
    return [
        ("noop_same_split", S((64, 48), "float32", 1, 1, 8)),
        ("resplit_0_to_1_p8", S((64, 48), "float32", 0, 1, 8)),
        ("resplit_1_to_0_p8", S((64, 48), "float32", 1, 0, 8)),
        ("resplit_0_to_1_int32_p4", S((64, 48), "int32", 0, 1, 4)),
        ("resplit_uneven_p8", S((63, 48), "float32", 0, 1, 8)),
        ("resplit_3d_1_to_2_p8", S((16, 24, 40), "float32", 1, 2, 8)),
        ("replicate_p8", S((64, 48), "float32", 0, None, 8)),
        ("slice_from_replicated_p8", S((64, 48), "float32", None, 1, 8)),
        ("mesh1_resplit", S((64, 48), "float32", 0, 1, 1)),
        ("resplit_chunked_2gb_p8", S((32768, 16384), "float32", 0, 1, 8)),
        ("resplit_ring_8gb_p8", S((131072, 16384), "float32", 0, 1, 8)),
        ("reshape_pivot_p8", S((40960, 40), "float32", 1, 1, 8, reshape_to=(20480, 80))),
        ("reshape_split0_local_p8", S((64, 48), "float32", 0, 0, 8, reshape_to=(32, 96))),
        (
            "reshape_gather_fallback_p8",
            S((1000, 26), "float32", 1, 1, 8, reshape_to=(26, 1000)),
        ),
        (
            "reshape_split1_1gb_p8",
            S((1000, 250000), "float32", 1, 1, 8, reshape_to=(10_000_000, 25)),
        ),
        # the reverse of the 1 GB bench move: narrow minor on the SOURCE
        # side, so the packed pivot engages its lane-unpack stage
        (
            "reshape_packed_rev_p8",
            S((10_000_000, 25), "float32", 1, 1, 8, reshape_to=(1000, 250000)),
        ),
        # lane-friendly companion (minor dims >= 128 end to end): the
        # cost model must keep the DIRECT pivot — packing gains nothing
        (
            "reshape_lane_1gb_p8",
            S((65536, 4096), "float32", 1, 1, 8, reshape_to=(131072, 2048)),
        ),
        # ISSUE 8: the 2x8-acceptance pair — mesh-16 variants of the two
        # 1 GB rows, covered flat here and tiered by the --topology 2x8
        # determinism dump + tests/test_topology.py. The reshape uses the
        # flat-order-preserving 16-divisible view of the 1 GB payload
        # (1000 % 16 != 0 rules the bench shape's pivot out at p=16;
        # (16000, 15625) is the same row-major element order).
        ("resplit_1gb_p16", S((1000, 250000), "float32", 0, 1, 16)),
        (
            "reshape_split1_1gb_p16",
            S((16000, 15625), "float32", 1, 1, 16, reshape_to=(10_000_000, 25)),
        ),
    ]
