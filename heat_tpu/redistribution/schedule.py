"""Schedule IR — the inspectable, golden-testable plan representation.

A :class:`Schedule` is a strategy name plus an ordered list of
:class:`Step`\\ s (slice → collective → concat), each carrying

- ``bytes_moved`` — the per-device payload the step ships across the
  mesh (0 for local copy steps), and
- ``peak_bytes`` — the per-device TRANSIENT buffer the step needs on
  top of the resident source/destination shards (send+recv buffers for
  collectives, the output buffer for local relayout copies).

``Schedule.peak_bytes`` (max over steps) is what the planner holds
under the ``HEAT_TPU_REDIST_BUDGET_MB`` budget by chunking collectives;
``Schedule.collective_counts()`` is the exact HLO collective census the
executor's compiled program must match — tier-1 pins that equality for
the golden specs (arXiv:2112.01075's "the schedule is checkable before
it runs").

Plans serialize canonically (``canonical_json``): byte-identical
run-to-run for the same spec + budget, since the ``plan_id`` derived
from that serialization keys the executor's program cache.
"""

from __future__ import annotations

import hashlib
import json

from typing import Any, Dict, List, Optional, Tuple

from .spec import RedistSpec

__all__ = ["Step", "Schedule", "COLLECTIVE_STEP_KINDS"]

# step kind -> HLO collective op it must compile to (1:1). Every other
# kind is a local copy/view and must emit NO collective.
COLLECTIVE_STEP_KINDS: Dict[str, str] = {
    "all_to_all": "all-to-all",
    "all_gather": "all-gather",
    "ppermute": "collective-permute",
}

# ``pack``/``unpack`` are the lane-packing relayout copies
# (heat_tpu.kernels.relayout): pack folds narrow rows into the lane
# axis so the collective steps run on full-VREG buffers; unpack
# materializes the destination's narrow layout in ONE copy.
_LOCAL_STEP_KINDS = ("slice", "pad", "reshape", "concat", "pack", "unpack")


class Step:
    """One schedule step.

    Attributes
    ----------
    kind : ``all_to_all`` | ``all_gather`` | ``ppermute`` | ``slice`` |
        ``pad`` | ``reshape`` | ``concat`` | ``pack`` | ``unpack``.
    bytes_moved : per-device payload crossing the mesh (collectives;
        0 for local steps).
    bytes_copied : per-device HBM bytes a LOCAL relayout copy writes
        (0 for views, collectives, and steps whose copy rides another
        step's accounting).
    peak_bytes : per-device transient buffer bytes of this step.
    lane_fill : fraction of VREG lanes the step's dominant buffer
        layout fills (``kernels.relayout.lane_fill`` of its minor dim);
        1/lane_fill is the HBM amplification the cost model charges.
    detail : short human-readable description of what the step does.
    chunk : chunk index when the step is one lap of a chunked pipeline.
    """

    __slots__ = (
        "kind", "bytes_moved", "bytes_copied", "peak_bytes", "lane_fill",
        "detail", "chunk",
    )

    def __init__(
        self,
        kind: str,
        bytes_moved: int = 0,
        peak_bytes: int = 0,
        detail: str = "",
        chunk: Optional[int] = None,
        bytes_copied: int = 0,
        lane_fill: float = 1.0,
    ):
        if kind not in COLLECTIVE_STEP_KINDS and kind not in _LOCAL_STEP_KINDS:
            raise ValueError(f"unknown step kind {kind!r}")
        self.kind = kind
        self.bytes_moved = int(bytes_moved)
        self.bytes_copied = int(bytes_copied)
        self.peak_bytes = int(peak_bytes)
        self.lane_fill = float(lane_fill)
        self.detail = detail
        self.chunk = chunk

    @property
    def is_collective(self) -> bool:
        return self.kind in COLLECTIVE_STEP_KINDS

    @property
    def effective_bytes(self) -> int:
        """Lane-amplified HBM traffic the cost model charges this step:
        (payload + local copy writes) / lane_fill."""
        return int((self.bytes_moved + self.bytes_copied) / max(self.lane_fill, 1e-9))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "bytes_moved": self.bytes_moved,
            "bytes_copied": self.bytes_copied,
            "peak_bytes": self.peak_bytes,
            "lane_fill": self.lane_fill,
            "detail": self.detail,
            "chunk": self.chunk,
        }

    def __repr__(self) -> str:
        c = f"[{self.chunk}]" if self.chunk is not None else ""
        return f"Step({self.kind}{c}, moved={self.bytes_moved}, peak={self.peak_bytes})"


class Schedule:
    """An ordered redistribution plan for one :class:`RedistSpec`."""

    def __init__(
        self,
        spec: RedistSpec,
        strategy: str,
        steps: List[Step],
        budget_bytes: int,
        notes: str = "",
    ):
        self.spec = spec
        self.strategy = strategy
        self.steps: List[Step] = list(steps)
        self.budget_bytes = int(budget_bytes)
        self.notes = notes
        self.plan_id = hashlib.sha1(
            self.canonical_json(with_plan_id=False).encode()
        ).hexdigest()[:12]

    # ------------------------------------------------------------------ #
    # accounting                                                         #
    # ------------------------------------------------------------------ #
    @property
    def peak_bytes(self) -> int:
        """Max per-device transient footprint over all steps."""
        return max((s.peak_bytes for s in self.steps), default=0)

    @property
    def bytes_moved(self) -> int:
        """Total per-device payload shipped across the mesh."""
        return sum(s.bytes_moved for s in self.steps)

    @property
    def bytes_copied(self) -> int:
        """Total per-device local relayout copy writes."""
        return sum(s.bytes_copied for s in self.steps)

    @property
    def effective_bytes(self) -> int:
        """Lane-amplified HBM traffic of the whole plan — the volume
        term of the planner's cost model."""
        return sum(s.effective_bytes for s in self.steps)

    @property
    def n_steps(self) -> int:
        return len(self.steps)

    @property
    def n_collectives(self) -> int:
        return sum(1 for s in self.steps if s.is_collective)

    @property
    def within_budget(self) -> bool:
        return self.peak_bytes <= self.budget_bytes

    def collective_counts(self) -> Dict[str, int]:
        """{HLO op name: count} the executed program must launch —
        directly comparable with
        ``ht.observability.collective_counts(...).counts``."""
        out: Dict[str, int] = {}
        for s in self.steps:
            if s.is_collective:
                op = COLLECTIVE_STEP_KINDS[s.kind]
                out[op] = out.get(op, 0) + 1
        return out

    # ------------------------------------------------------------------ #
    # serialization                                                      #
    # ------------------------------------------------------------------ #
    def as_dict(self, with_plan_id: bool = True) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "spec": self.spec.as_dict(),
            "strategy": self.strategy,
            "budget_bytes": self.budget_bytes,
            "steps": [s.as_dict() for s in self.steps],
            "peak_bytes": self.peak_bytes,
            "bytes_moved": self.bytes_moved,
            "bytes_copied": self.bytes_copied,
            "collective_counts": self.collective_counts(),
            "within_budget": self.within_budget,
            "notes": self.notes,
        }
        if with_plan_id:
            d["plan_id"] = self.plan_id
        return d

    def canonical_json(self, with_plan_id: bool = True) -> str:
        """Deterministic serialization — byte-identical run-to-run for
        the same (spec, budget); ci.sh diffs two runs of the golden
        matrix against each other."""
        return json.dumps(
            self.as_dict(with_plan_id=with_plan_id),
            sort_keys=True,
            separators=(",", ":"),
        )

    def __repr__(self) -> str:
        kinds = [
            s.kind + (f"[{s.chunk}]" if s.chunk is not None else "") for s in self.steps
        ]
        return (
            f"Schedule({self.strategy}, plan={self.plan_id}, {self.spec!r}, "
            f"steps={kinds}, peak={self.peak_bytes}B/{self.budget_bytes}B)"
        )
