"""Schedule IR — the inspectable, golden-testable plan representation.

A :class:`Schedule` is a strategy name plus an ordered list of
:class:`Step`\\ s (slice → collective → concat), each carrying

- ``bytes_moved`` — the per-device payload the step ships across the
  mesh (0 for local copy steps), and
- ``peak_bytes`` — the per-device TRANSIENT buffer the step needs on
  top of the resident source/destination shards (send+recv buffers for
  collectives, the output buffer for local relayout copies).

``Schedule.peak_bytes`` (max over steps) is what the planner holds
under the ``HEAT_TPU_REDIST_BUDGET_MB`` budget by chunking collectives;
``Schedule.collective_counts()`` is the exact HLO collective census the
executor's compiled program must match — tier-1 pins that equality for
the golden specs (arXiv:2112.01075's "the schedule is checkable before
it runs").

Plans serialize canonically (``canonical_json``): byte-identical
run-to-run for the same spec + budget, since the ``plan_id`` derived
from that serialization keys the executor's program cache.

ISSUE 6 adds the **overlap annotation**: steps that belong to a
software-pipelined chunk group carry an ``overlap`` tag, and the
schedule carries a modeled critical-path account per group — at pipeline
depth 2 a stage pair costs ``max(wire, copy)`` instead of ``wire +
copy``, because chunk k's relayout copy runs while chunk k+1's
collective is on the wire (arXiv:2112.09017's latency-hiding schedules
applied to the chunk pipelines of arXiv:2112.01075). The annotation is
part of the canonical serialization (and therefore of ``plan_id``); the
executor consults it (plus the ``HEAT_TPU_REDIST_OVERLAP`` gate) to
decide whether to emit the prefetch-issue-then-consume program form.
Pipelining never changes WHAT moves — census and numerics are
bit-identical overlap-on vs overlap-off by construction.

ISSUE 7 adds the **wire-codec steps**: under ``HEAT_TPU_WIRE_QUANT``
the planner wraps admissible collective groups in ``quantize``/
``dequantize`` steps (``heat_tpu.kernels.quant`` — int8/bf16 payloads,
scale per (8,128) tile), scales the collectives' ``bytes_moved`` to
the encoded wire bytes, and attaches a schedule-level ``quant``
annotation ({mode, tol, bytes_raw, bytes_sent, ratio}). The codec
changes HOW MANY BYTES each collective carries, never how many
collectives launch: the census (and the lap/pipe structure) is
identical gate-on vs gate-off by construction, while the canonical
serialization — and therefore the ``plan_id`` and every program cache
key derived from it — distinguishes the quantized plan.

ISSUE 8 adds the **tier annotations**: at a two-tier topology
(``HEAT_TPU_TOPOLOGY``, ``core.communication.Topology``) every
collective step carries a ``tier`` — ``"ici"`` when its replica groups
stay within one slice, ``"dcn"`` when they span slices — and the
schedule carries a ``topology`` annotation ({n_slices,
chips_per_slice, dcn_penalty}; the per-tier byte split is derived from
the steps via :meth:`Schedule.tier_bytes`, so the codec pass can
re-scale ``bytes_moved`` without staling the annotation). The cost
model prices a DCN byte at ``dcn_penalty`` (= ICI/DCN bandwidth ≈ 8)
ICI bytes, ``describe()`` renders the per-tier byte/time split, and
both annotations fold into the canonical serialization and
``plan_id``.
CRITICALLY, both are *conditional* keys: a flat-topology plan
serializes without them, byte-identical to the pre-ISSUE-8 plans — the
``HEAT_TPU_TOPOLOGY`` unset/1xN escape hatch is exact by construction.

ISSUE 16 adds the **calibration annotation** under the same contract:
a plan priced under a measured lattice profile
(``HEAT_TPU_LATTICE_PROFILE``, ``observability.calibration``) carries
``calibration`` = {profile_id, edges: {edge -> bytes/s}} in its
canonical serialization, so recalibrating a deployment changes every
plan_id it re-prices — a VISIBLE invalidation the program caches key
on — while the unset default serializes without the key,
byte-identical to the constants era.
"""

from __future__ import annotations

import hashlib
import json

from typing import Any, Dict, List, Optional, Tuple

from .spec import RedistSpec

__all__ = ["Step", "Schedule", "COLLECTIVE_STEP_KINDS", "STAGING_STEP_KINDS"]

# step kind -> HLO collective op it must compile to (1:1). Every other
# kind is a local copy/view and must emit NO collective.
COLLECTIVE_STEP_KINDS: Dict[str, str] = {
    "all_to_all": "all-to-all",
    "all_gather": "all-gather",
    "ppermute": "collective-permute",
}

# ``pack``/``unpack`` are the lane-packing relayout copies
# (heat_tpu.kernels.relayout): pack folds narrow rows into the lane
# axis so the collective steps run on full-VREG buffers; unpack
# materializes the destination's narrow layout in ONE copy.
# ``quantize``/``dequantize`` are the wire-codec copies
# (heat_tpu.kernels.quant): quantize encodes the collective's
# per-destination blocks to the int8/bf16 wire format, dequantize
# restores full width on the receive side (riding the group's
# reassembly copy in the pipelined forms).
_LOCAL_STEP_KINDS = (
    "slice", "pad", "reshape", "concat", "pack", "unpack",
    "quantize", "dequantize",
)

# ``stage_in``/``stage_out`` (ISSUE 11) are the out-of-core staging
# transfers (``redistribution.staging``): one (8,128)-tile-aligned
# window of a host-resident operand device_put into / fetched out of
# the double-buffered HBM slab. They MOVE bytes — across the host<->HBM
# PCIe edge of the memory-tier lattice (``core.tiers``), carried as
# ``tier="pcie"`` — but launch NO mesh collective, so the HLO collective
# census is untouched by staging.
STAGING_STEP_KINDS = ("stage_in", "stage_out")


class Step:
    """One schedule step.

    Attributes
    ----------
    kind : ``all_to_all`` | ``all_gather`` | ``ppermute`` | ``slice`` |
        ``pad`` | ``reshape`` | ``concat`` | ``pack`` | ``unpack`` |
        ``quantize`` | ``dequantize`` | ``stage_in`` | ``stage_out``.
    bytes_moved : per-device payload crossing the mesh (collectives) or
        the host<->HBM PCIe edge (``stage_in``/``stage_out``; 0 for
        local steps).
    bytes_copied : per-device HBM bytes a LOCAL relayout copy writes
        (0 for views, collectives, and steps whose copy rides another
        step's accounting).
    peak_bytes : per-device transient buffer bytes of this step.
    lane_fill : fraction of VREG lanes the step's dominant buffer
        layout fills (``kernels.relayout.lane_fill`` of its minor dim);
        1/lane_fill is the HBM amplification the cost model charges.
    detail : short human-readable description of what the step does.
    chunk : chunk index when the step is one lap of a chunked pipeline.
    overlap : pipeline-group tag (e.g. ``"pipe0"``) when the step is one
        lap of a software-pipelined chunk group — chunk k's local work
        overlaps chunk k+1's collective inside the group; ``None`` for
        steps the executor issues sequentially.
    tier : ``"ici"`` / ``"dcn"`` at a two-tier topology (ISSUE 8):
        which wire a collective step's replica groups ride — ``"ici"``
        for intra-slice subgroups, ``"dcn"`` when the groups span
        slices; ``"pcie"`` on the staging steps (ISSUE 11), the
        host<->HBM edge of the memory-tier lattice. ``None`` for local
        steps and every flat-topology plan (the key is then omitted
        from the serialization, keeping flat plans byte-identical to
        the pre-topology era).
    """

    __slots__ = (
        "kind", "bytes_moved", "bytes_copied", "peak_bytes", "lane_fill",
        "detail", "chunk", "overlap", "tier",
    )

    def __init__(
        self,
        kind: str,
        bytes_moved: int = 0,
        peak_bytes: int = 0,
        detail: str = "",
        chunk: Optional[int] = None,
        bytes_copied: int = 0,
        lane_fill: float = 1.0,
        overlap: Optional[str] = None,
        tier: Optional[str] = None,
    ):
        if (
            kind not in COLLECTIVE_STEP_KINDS
            and kind not in _LOCAL_STEP_KINDS
            and kind not in STAGING_STEP_KINDS
        ):
            raise ValueError(f"unknown step kind {kind!r}")
        if tier not in (None, "ici", "dcn", "pcie"):
            raise ValueError(f"unknown tier {tier!r} (expected 'ici'/'dcn'/'pcie'/None)")
        if kind in STAGING_STEP_KINDS and tier != "pcie":
            raise ValueError(
                f"staging step {kind!r} must ride the pcie edge (got tier={tier!r})"
            )
        if tier == "pcie" and kind not in STAGING_STEP_KINDS:
            raise ValueError(f"tier 'pcie' is reserved for staging steps (got {kind!r})")
        self.kind = kind
        self.bytes_moved = int(bytes_moved)
        self.bytes_copied = int(bytes_copied)
        self.peak_bytes = int(peak_bytes)
        self.lane_fill = float(lane_fill)
        self.detail = detail
        self.chunk = chunk
        self.overlap = overlap
        self.tier = tier

    @property
    def is_collective(self) -> bool:
        return self.kind in COLLECTIVE_STEP_KINDS

    @property
    def effective_bytes(self) -> int:
        """Lane-amplified HBM traffic the cost model charges this step:
        (payload + local copy writes) / lane_fill."""
        return int((self.bytes_moved + self.bytes_copied) / max(self.lane_fill, 1e-9))

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "kind": self.kind,
            "bytes_moved": self.bytes_moved,
            "bytes_copied": self.bytes_copied,
            "peak_bytes": self.peak_bytes,
            "lane_fill": self.lane_fill,
            "detail": self.detail,
            "chunk": self.chunk,
            "overlap": self.overlap,
        }
        # conditional: a flat-topology plan must serialize byte-identically
        # to the pre-ISSUE-8 era, so untier'd steps carry no key at all
        if self.tier is not None:
            d["tier"] = self.tier
        return d

    def __repr__(self) -> str:
        c = f"[{self.chunk}]" if self.chunk is not None else ""
        t = f", tier={self.tier}" if self.tier else ""
        return f"Step({self.kind}{c}, moved={self.bytes_moved}, peak={self.peak_bytes}{t})"


class Schedule:
    """An ordered redistribution plan for one :class:`RedistSpec`.

    ``overlap`` (optional) is the software-pipelining annotation the
    planner attaches when the plan's chunk groups can hide local copy
    work under collective wire time::

        {
          "depth": 2,                      # pipeline depth (double-buffer)
          "groups": [{"tag": "pipe0", "laps": C,
                      "wire_bytes": ..., "copy_bytes": ...,
                      "sequential_bytes": wire + copy,
                      "critical_path_bytes": w + (C-1)*max(w, c) + c}, ...],
          "sequential_bytes":   sum of group sequential models,
          "critical_path_bytes": sum of group critical paths,
          "model_speedup":      sequential / critical-path  (the bench
                                ``critical_path_model`` field),
        }

    The annotation is cost MODEL, not movement: an overlapped program
    launches exactly the same collectives in the same order, so census
    and numerics are identical to the sequential form.
    """

    def __init__(
        self,
        spec: RedistSpec,
        strategy: str,
        steps: List[Step],
        budget_bytes: int,
        notes: str = "",
        overlap: Optional[Dict[str, Any]] = None,
        quant: Optional[Dict[str, Any]] = None,
        topology: Optional[Dict[str, Any]] = None,
        staging: Optional[Dict[str, Any]] = None,
        calibration: Optional[Dict[str, Any]] = None,
    ):
        self.spec = spec
        self.strategy = strategy
        self.steps: List[Step] = list(steps)
        self.budget_bytes = int(budget_bytes)
        self.notes = notes
        self.overlap = overlap
        self.quant = quant
        self.topology = topology
        # ISSUE 11: the out-of-core staging annotation
        # (redistribution.staging) — {depth, axis, window_bytes,
        # n_windows, slab_bytes, resident_bytes, host_bytes, grain}.
        # Conditional like quant/topology: non-staged plans serialize
        # without the key, byte-identical to the pre-staging era.
        self.staging = staging
        # ISSUE 16: the calibration annotation — {profile_id, edges:
        # {edge -> measured bytes/s}} recorded when the plan was priced
        # under a lattice profile (HEAT_TPU_LATTICE_PROFILE). Part of
        # the canonical serialization, so a recalibration CHANGES the
        # plan_id — a visible invalidation, never silent drift — and
        # verify_plan can recompute the recorded prices. Conditional
        # like the others: constants-priced plans (the default)
        # serialize without the key, byte-identical to the
        # pre-calibration era.
        self.calibration = calibration
        self.plan_id = hashlib.sha1(
            self.canonical_json(with_plan_id=False).encode()
        ).hexdigest()[:12]

    # ------------------------------------------------------------------ #
    # accounting                                                         #
    # ------------------------------------------------------------------ #
    @property
    def peak_bytes(self) -> int:
        """Max per-device transient footprint over all steps."""
        return max((s.peak_bytes for s in self.steps), default=0)

    @property
    def bytes_moved(self) -> int:
        """Total per-device payload shipped across the mesh."""
        return sum(s.bytes_moved for s in self.steps)

    @property
    def bytes_copied(self) -> int:
        """Total per-device local relayout copy writes."""
        return sum(s.bytes_copied for s in self.steps)

    @property
    def effective_bytes(self) -> int:
        """Lane-amplified HBM traffic of the whole plan — the volume
        term of the planner's cost model."""
        return sum(s.effective_bytes for s in self.steps)

    @property
    def n_steps(self) -> int:
        return len(self.steps)

    @property
    def n_collectives(self) -> int:
        return sum(1 for s in self.steps if s.is_collective)

    @property
    def within_budget(self) -> bool:
        return self.peak_bytes <= self.budget_bytes

    @property
    def wire_bytes_sent(self) -> int:
        """Per-device bytes that actually cross the mesh: the current
        steps' payload sum — the encoded wire bytes when the plan
        carries a ``quant`` annotation, else :attr:`bytes_moved`."""
        return self.bytes_moved

    @property
    def wire_bytes_raw(self) -> int:
        """Per-device full-width payload the same movement would ship
        without the wire codec (== :attr:`wire_bytes_sent` for
        unquantized plans)."""
        return int(self.quant["bytes_raw"]) if self.quant else self.bytes_moved

    @property
    def overlap_depth(self) -> int:
        """Pipeline depth the executor runs the chunk groups at: 2
        (double-buffered) when the plan carries an overlap annotation,
        1 (sequential) otherwise."""
        return int(self.overlap["depth"]) if self.overlap else 1

    @property
    def critical_path_bytes(self) -> int:
        """Modeled byte-equivalent time of the plan's movement under
        depth-2 pipelining: the non-pipelined steps at face value plus
        each overlap group's ``max(wire, copy)``-per-stage-pair critical
        path (equals :attr:`sequential_model_bytes` when nothing
        pipelines)."""
        base = self.sequential_model_bytes
        if not self.overlap:
            return base
        return base - int(self.overlap["sequential_bytes"]) + int(
            self.overlap["critical_path_bytes"]
        )

    @property
    def sequential_model_bytes(self) -> int:
        """Modeled byte-equivalent time with every stage serialized —
        the lane-amplified traffic (:attr:`effective_bytes`) plus the
        overlap groups' reassembly-copy terms the per-step accounting
        folds into the group model rather than ``bytes_copied``."""
        extra = 0
        if self.overlap:
            group_wire = sum(int(g["wire_bytes"]) for g in self.overlap["groups"])
            extra = int(self.overlap["sequential_bytes"]) - group_wire
        return self.effective_bytes + extra

    @property
    def topo_key(self) -> Optional[Tuple[int, int]]:
        """``(n_slices, chips_per_slice)`` of a tiered plan, ``None``
        for flat — the hashable form the executor's program cache keys
        carry."""
        if not self.topology:
            return None
        return (int(self.topology["n_slices"]), int(self.topology["chips_per_slice"]))

    # ------------------------------------------------------------------ #
    # liveness (ISSUE 10): the per-step live-byte account memcheck and   #
    # the plan verifier reason over                                      #
    # ------------------------------------------------------------------ #
    @property
    def resident_bytes(self) -> int:
        """Per-device bytes RESIDENT for the whole redistribution: the
        source shard being consumed plus the destination shard being
        built. ``peak_bytes`` deliberately excludes them (it budgets the
        chunkable transients); the liveness view adds them back so the
        number is comparable with a whole-program peak-HBM estimate
        (``ht.analysis.memcheck``).

        STAGED plans (ISSUE 11) override this with the annotation's
        ``resident_bytes``: the operand itself lives on the HOST tier,
        so only the outputs held across the window loop are
        HBM-resident — the slab transients ride ``peak_bytes`` like any
        other transient, and ``liveness_peak_bytes`` is exactly the
        number the staging executor proves under
        ``tiers.capacity("hbm")`` before running."""
        if self.staging is not None:
            return int(self.staging["resident_bytes"])
        return int(self.spec.src_shard_bytes) + int(self.spec.dst_shard_bytes)

    def liveness(self) -> List[Dict[str, int]]:
        """Per-step live-byte account: ``{"kind", "transient_bytes",
        "live_bytes"}`` per step, where ``live_bytes`` = resident source
        + destination shards + this step's transient. The recomputed
        ``max(transient_bytes)`` must equal :attr:`peak_bytes` — one of
        the invariants ``ht.analysis.verify_plan`` proves."""
        resident = self.resident_bytes
        return [
            {
                "kind": s.kind,
                "transient_bytes": int(s.peak_bytes),
                "live_bytes": resident + int(s.peak_bytes),
            }
            for s in self.steps
        ]

    @property
    def liveness_peak_bytes(self) -> int:
        """Max ``live_bytes`` over the steps (``resident_bytes`` for an
        empty plan) — the schedule-level analog of memcheck's static
        peak estimate."""
        return self.resident_bytes + self.peak_bytes

    def tier_bytes(self) -> Dict[str, int]:
        """Per-tier payload split: ``{"ici": B, "dcn": B}`` over the
        collectives (flat plans — every pre-topology schedule — report
        all movement as ``"ici"``: one ICI domain is tier 0 by
        definition), plus a ``"pcie"`` entry when the plan stages
        windows across the host edge (ISSUE 11; the key is present only
        on staged plans, so established ``{"ici", "dcn"}`` consumers
        are unchanged)."""
        out = {"ici": 0, "dcn": 0}
        for s in self.steps:
            if s.is_collective:
                out[s.tier or "ici"] += s.bytes_moved
            elif s.kind in STAGING_STEP_KINDS:
                out["pcie"] = out.get("pcie", 0) + s.bytes_moved
        return out

    # ------------------------------------------------------------------ #
    # congruence hooks (ISSUE 14): the per-step group structure the     #
    # progress replay (ht.analysis.check_progress / verify_plan's       #
    # ``progress`` invariant) reasons over. Properties/methods only —   #
    # like the liveness hooks, they never touch the canonical           #
    # serialization, so plan bytes and plan_ids are unchanged.          #
    # ------------------------------------------------------------------ #
    def collective_group_structure(self) -> List[Dict[str, Any]]:
        """Per-collective-step symbolic group structure: ``{"kind",
        "tier", "chunk", "n_groups", "group_size"}`` — the subgroup
        shape each collective's participants must agree on. Flat plans
        ride ONE group of ``mesh_size``; at a hierarchical topology the
        ``ici`` halves ride ``n_slices`` groups of ``chips_per_slice``
        and the ``dcn`` halves ``chips_per_slice`` groups of
        ``n_slices`` — both partitions of the mesh by construction
        (``S·C == p``), which is exactly what the progress replay
        re-proves on dumped plans (and what the MPMD stage-graph
        verifier will consume per stage)."""
        p = int(self.spec.mesh_size)
        S = C = None
        if self.topology:
            S = int(self.topology["n_slices"])
            C = int(self.topology["chips_per_slice"])
        out: List[Dict[str, Any]] = []
        for s in self.steps:
            if not s.is_collective:
                continue
            if s.tier == "ici" and S is not None:
                n_groups, group_size = S, C
            elif s.tier == "dcn" and S is not None and self.strategy == "hierarchical-a2a":
                n_groups, group_size = C, S
            else:
                n_groups, group_size = 1, p
            out.append(
                {
                    "kind": s.kind,
                    "tier": s.tier,
                    "chunk": s.chunk,
                    "n_groups": n_groups,
                    "group_size": group_size,
                }
            )
        return out

    def overlap_lap_chunks(self, tag: str) -> List[Optional[int]]:
        """The chunk indices of one overlap group's collective laps, in
        issue order (a hierarchical lap's ici/dcn pair contributes one
        entry). The depth-2 double buffer consumes lap k-1 at issue of
        lap k, so a well-formed group reads ``[0, 1, ..., laps-1]`` (or
        all ``None`` for the ring's positional hops) — the invariant
        the progress replay checks on every golden dump."""
        lap_mult = 2 if self.strategy == "hierarchical-a2a" else 1
        tagged = [s for s in self.steps if s.is_collective and s.overlap == tag]
        return [
            tagged[i * lap_mult].chunk for i in range(len(tagged) // lap_mult)
        ]

    # ------------------------------------------------------------------ #
    # tolerance hooks (ISSUE 17): the per-step error bounds the         #
    # ``tolerance`` invariant (ht.analysis.check_tolerance /            #
    # verify_plan) composes end-to-end. Properties/methods only — like  #
    # the congruence hooks above, they never touch the canonical        #
    # serialization, so plan bytes and plan_ids are unchanged.          #
    # ------------------------------------------------------------------ #
    @property
    def quant_tolerance(self) -> float:
        """The schedule-level declared error bound: the wire codec's
        pinned tolerance when the plan carries a quant annotation
        (``2^-7`` int8, ``2^-8`` bf16 — kernels/quant.py), 0.0 for an
        unquantized plan (every step exact-bit)."""
        return float(self.quant["tol"]) if self.quant else 0.0

    def step_tolerances(self) -> List[float]:
        """Per-step relative error contribution, step-aligned with
        ``self.steps``: ``tolerance(mode)`` on each quantize step (the
        lossy rounding happens at encode; the wire and the dequantize
        are exact given the encoded blocks), 0.0 everywhere else —
        collectives move bits verbatim, staging/relayout/overlap steps
        are exact-bit copies. ``compose_tolerance`` over the steps one
        payload element traverses recovers the end-to-end bound the
        ``tolerance`` invariant proves equal to ``quant_tolerance``."""
        mode = self.quant.get("mode") if self.quant else None
        if mode is None:
            return [0.0] * len(self.steps)
        from ..kernels import quant as _quant

        tol = _quant.tolerance(mode)
        return [
            tol if s.kind == "quantize" else 0.0 for s in self.steps
        ]

    def collective_counts(self) -> Dict[str, int]:
        """{HLO op name: count} the executed program must launch —
        directly comparable with
        ``ht.observability.collective_counts(...).counts``."""
        out: Dict[str, int] = {}
        for s in self.steps:
            if s.is_collective:
                op = COLLECTIVE_STEP_KINDS[s.kind]
                out[op] = out.get(op, 0) + 1
        return out

    # ------------------------------------------------------------------ #
    # serialization                                                      #
    # ------------------------------------------------------------------ #
    def as_dict(self, with_plan_id: bool = True) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "spec": self.spec.as_dict(),
            "strategy": self.strategy,
            "budget_bytes": self.budget_bytes,
            "steps": [s.as_dict() for s in self.steps],
            "peak_bytes": self.peak_bytes,
            "bytes_moved": self.bytes_moved,
            "bytes_copied": self.bytes_copied,
            "collective_counts": self.collective_counts(),
            "within_budget": self.within_budget,
            "notes": self.notes,
            "overlap": self.overlap,
            "quant": self.quant,
        }
        # conditional (ISSUE 8): flat plans serialize without the key so
        # their bytes — and plan_ids — match the pre-topology era exactly
        if self.topology is not None:
            d["topology"] = self.topology
        # conditional (ISSUE 11): same contract for the staging
        # annotation — non-staged plans stay byte-identical
        if self.staging is not None:
            d["staging"] = self.staging
        # conditional (ISSUE 16): same contract for the calibration
        # annotation — constants-priced plans stay byte-identical
        if self.calibration is not None:
            d["calibration"] = self.calibration
        if with_plan_id:
            d["plan_id"] = self.plan_id
        return d

    def canonical_json(self, with_plan_id: bool = True) -> str:
        """Deterministic serialization — byte-identical run-to-run for
        the same (spec, budget); ci.sh diffs two runs of the golden
        matrix against each other."""
        return json.dumps(
            self.as_dict(with_plan_id=with_plan_id),
            sort_keys=True,
            separators=(",", ":"),
        )

    def describe(self) -> str:
        """Human-readable rendering of the plan: one line per step with
        its movement/copy accounting and pipeline tag, plus the overlap
        annotation's modeled critical-path arithmetic — what
        ``ht.redistribution.explain(...)`` shows when printed."""
        groups = {g["tag"]: g for g in (self.overlap or {}).get("groups", [])}
        lines = [
            f"plan {self.plan_id}  strategy={self.strategy}  "
            f"depth={self.overlap_depth}  {self.spec!r}"
        ]
        for k, s in enumerate(self.steps):
            chunk = f"[{s.chunk}]" if s.chunk is not None else ""
            pipe = f"  pipe={s.overlap}" if s.overlap else ""
            tier = f"  tier={s.tier}" if s.tier else ""
            g = groups.get(s.overlap)
            if g and s.is_collective and "ici_bytes" in g:
                # tiered group (ISSUE 8): a pipelined lap is priced at
                # max(ici wire, penalty-scaled dcn wire, copy)
                wi = g["ici_bytes"] // g["laps"]
                wd = g["dcn_bytes"] * g["dcn_penalty"] // g["laps"]
                c = g["copy_bytes"] // g["laps"]
                model = (
                    f"  model=max(ici {wi}, dcn {wd}, copy {c})={max(wi, wd, c)} B-eq"
                )
            elif g and s.is_collective:
                # per-step modeled time under depth-2 pipelining: this
                # lap's wire overlaps the previous lap's reassembly copy
                w = g["wire_bytes"] // g["laps"]
                c = g["copy_bytes"] // g["laps"]
                model = f"  model=max(wire {w}, copy {c})={max(w, c)} B"
            else:
                model = f"  model={s.effective_bytes} B"
            lines.append(
                f"  [{k:2d}] {s.kind}{chunk}  moved={s.bytes_moved}  "
                f"copied={s.bytes_copied}  peak={s.peak_bytes}{tier}{pipe}{model}"
                + (f"  -- {s.detail}" if s.detail else "")
            )
        if self.overlap:
            o = self.overlap
            lines.append(
                f"  overlap: depth={o['depth']} groups={len(o['groups'])} "
                f"critical_path={o['critical_path_bytes']} B vs "
                f"sequential={o['sequential_bytes']} B "
                f"(model_speedup={o['model_speedup']}x)"
            )
        else:
            lines.append("  overlap: none (sequential schedule)")
        if self.quant:
            q = self.quant
            lines.append(
                f"  quant: {q['mode']} wire codec  "
                f"raw={q['bytes_raw']} B -> sent={q['bytes_sent']} B "
                f"(saved {q['bytes_raw'] - q['bytes_sent']} B, "
                f"ratio {q['ratio']}, tol {q['tol']})"
            )
        else:
            lines.append("  quant: none (full-width wire)")
        if self.topology:
            t = self.topology
            tb = self.tier_bytes()
            lines.append(
                f"  topology: {t['n_slices']}x{t['chips_per_slice']} two-tier  "
                f"ici={tb['ici']} B  dcn={tb['dcn']} B "
                f"(dcn priced {t['dcn_penalty']}x — "
                f"time-eq {tb['ici'] + tb['dcn'] * t['dcn_penalty']} B)"
            )
        if self.staging:
            sg = self.staging
            passes = ", ".join(
                f"{p['tag']}(axis {p['axis']}: {p['n_windows']}w"
                + ("+wb" if p.get("writeback") else "")
                + ")"
                for p in sg["passes"]
            )
            model = sg["model"]
            lines.append(
                f"  staging: depth={sg['depth']} [{passes}]  "
                f"{sg['n_windows']} window(s) x <= {sg['window_bytes']} B "
                f"over pcie  slab={sg['slab_bytes']} B  "
                f"hbm-resident={sg['resident_bytes']} B  "
                f"host-resident={sg['host_bytes']} B  "
                f"model: pcie {model['pcie_s']}s / critical path "
                f"{model['critical_path_s']}s ({model['bound_gbps']} GB/s)"
            )
        if self.calibration:
            c = self.calibration
            edges = "  ".join(
                f"{e}={c['edges'][e] / 1e9:.2f}GB/s" for e in sorted(c["edges"])
            )
            lines.append(
                f"  calibration: profile {c['profile_id']}  {edges}"
            )
        if self.notes:
            lines.append(f"  notes: {self.notes}")
        return "\n".join(lines)

    def __repr__(self) -> str:
        kinds = [
            s.kind + (f"[{s.chunk}]" if s.chunk is not None else "") for s in self.steps
        ]
        ov = f", overlap=depth{self.overlap_depth}" if self.overlap else ""
        qt = f", quant={self.quant['mode']}" if self.quant else ""
        tp = (
            f", topo={self.topology['n_slices']}x{self.topology['chips_per_slice']}"
            if self.topology
            else ""
        )
        return (
            f"Schedule({self.strategy}, plan={self.plan_id}, {self.spec!r}, "
            f"steps={kinds}, peak={self.peak_bytes}B/{self.budget_bytes}B{ov}{qt}{tp})"
        )
