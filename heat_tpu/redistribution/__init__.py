"""Cost-modeled redistribution planning (``ht.redistribution``).

The reference treats resplit as a first-class algorithm
(Allgatherv / tiled Isend-Irecv chains chosen per case,
heat dndarray.py:1406); the seed of this repo collapsed every relayout
into one implicit GSPMD collective. This subsystem restores the
algorithmic treatment, TPU-native (arXiv:2112.01075): split changes and
reshape repartitions are *planned* —

- :mod:`~heat_tpu.redistribution.spec` — :class:`RedistSpec`, the
  normalized problem statement and cache key;
- :mod:`~heat_tpu.redistribution.planner` — the byte/step/peak-memory
  cost model (with a VREG lane-fill term, ``kernels.relayout``)
  choosing among direct all-to-all, budget-chunked all-to-all
  pipelines, the ppermute ring, the split-0-pivot reshape, its
  lane-packed variant (``packed-pivot`` — narrow-minor stages run on
  packed full-lane buffers), and the explicit full-all-gather
  replicate;
- :mod:`~heat_tpu.redistribution.schedule` — the inspectable,
  golden-testable schedule IR with per-step peak-memory accounting;
- :mod:`~heat_tpu.redistribution.executor` — lowers schedules to jitted
  ``shard_map`` programs (per-spec program cache); the compiled HLO's
  collective census must equal the plan's, and tier-1 pins it.

``ht.redistribution.explain(arr, axis)`` (or ``reshape=...``) returns
the plan the public ``resplit``/``reshape`` APIs will execute —
``.describe()`` renders the steps with their overlap pipe tags and the
modeled max(wire, copy) critical-path account. The peak-memory budget
is the ``HEAT_TPU_REDIST_BUDGET_MB`` env knob;
``HEAT_TPU_REDIST_PLANNER=0`` restores the legacy one-collective paths;
``HEAT_TPU_REDIST_OVERLAP=0/1/auto`` switches the executor between the
sequential oracle and the software-pipelined program forms (same plans,
same census, bit-identical results);
``HEAT_TPU_WIRE_QUANT=0/1/bf16/auto`` gates the block-quantized wire
codec (``heat_tpu.kernels.quant``) — admissible collective groups ship
int8/bf16 payloads as ``quantize``/``dequantize`` plan steps at a
pinned numerics tolerance, same census, wire bytes ~quartered (int8) or
halved (bf16); ``=0`` (and every non-admissible path) is exact-bit;
``HEAT_TPU_TOPOLOGY=auto/SxC/flat`` declares the two-tier topology
(ISSUE 8) — at a tiered mesh the planner prices each collective's
bytes per tier (DCN ≈ 8× ICI), decomposes cross-slice all-to-alls into
the ``hierarchical-a2a`` intra-slice pivot + inter-slice exchange, and
the codec targets the DCN hop first; unset/flat is byte-identical to
the pre-topology plans;
``HEAT_TPU_OOC=0/1/auto`` gates the out-of-core staging executor
(ISSUE 11, :mod:`~heat_tpu.redistribution.staging`) — HOST-tier
operands (:class:`HostArray`: pinned host RAM or HDF5) stream
(8,128)-aligned windows through a depth-2 double-buffered HBM slab as
``host-staging`` plans whose ``stage_in``/``stage_out`` steps ride the
``pcie`` edge of the memory-tier lattice (``ht.core.tiers``), proven
to fit ``capacity("hbm")`` by ``Schedule.liveness()`` before running;
``0`` is the exact-bit escape hatch, ``1`` forces the staged window
forms (bit-identical by construction — the hsvd sketch passes share a
fixed tile grain with the in-HBM programs).
"""

from . import executor
from . import planner
from . import schedule as schedule_ir
from . import spec as spec_mod
from . import staging

from .executor import execute, reshape_phys, resplit_phys
from .staging import HostArray, ooc_mode, plan_staged_passes, prove_fits
from .planner import (
    budget_bytes,
    clear_plan_cache,
    explain,
    golden_specs,
    overlap_mode,
    plan,
    planner_enabled,
    resolve_topology,
    tier_time_model,
    wire_quant_gate,
    wire_quant_mode,
)
from .schedule import Schedule, Step
from .spec import RedistSpec

__all__ = [
    "HostArray",
    "RedistSpec",
    "Schedule",
    "Step",
    "budget_bytes",
    "clear_plan_cache",
    "execute",
    "explain",
    "golden_specs",
    "ooc_mode",
    "overlap_mode",
    "plan",
    "plan_staged_passes",
    "planner_enabled",
    "prove_fits",
    "reshape_phys",
    "resolve_topology",
    "resplit_phys",
    "tier_time_model",
    "wire_quant_gate",
    "wire_quant_mode",
]
