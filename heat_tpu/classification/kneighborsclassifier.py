"""K-nearest-neighbors classifier.

API parity with /root/reference/heat/classification/kneighborsclassifier.py
(``KNeighborsClassifier`` :18: fit stores the data; predict = cdist + topk
+ one-hot vote, :45-131). The vote here is one fused expression on the
sharded distance matrix.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..spatial import distance

__all__ = ["KNeighborsClassifier"]


class KNeighborsClassifier(BaseEstimator, ClassificationMixin):
    """Classification by majority vote of the k nearest neighbors
    (reference: kneighborsclassifier.py:18)."""

    def __init__(self, n_neighbors: int = 5, effective_metric_: Optional[Callable] = None):
        self.n_neighbors = n_neighbors
        self.effective_metric_ = effective_metric_ if effective_metric_ is not None else distance.cdist
        self.x = None
        self.y = None
        self._classes = None

    @staticmethod
    def one_hot_encoding(x: DNDarray) -> DNDarray:
        """One-hot-encode an integer label vector (reference:
        kneighborsclassifier.py:45 — class count = max(x)+1)."""
        sanitize_in(x)
        n_features = int(jnp.max(x.larray)) + 1
        onehot = (
            x.larray.reshape(-1)[:, None] == jnp.arange(n_features)[None, :]
        ).astype(jnp.float32)
        split = x.split if x.split in (None, 0) else 0
        phys = x.comm.shard(onehot, split) if split is not None else onehot
        return DNDarray(
            phys,
            tuple(int(s) for s in onehot.shape),
            types.float32,
            split,
            x.device,
            x.comm,
        )

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store training data and labels (reference:
        kneighborsclassifier.py fit). ``y`` may be 1-D labels or one-hot."""
        sanitize_in(x)
        sanitize_in(y)
        if y.ndim == 1:
            classes = jnp.unique(y.larray)
            self._classes = classes
            onehot = (y.larray[:, None] == classes[None, :]).astype(jnp.float32)
            self.y = DNDarray(
                x.comm.shard(onehot, y.split) if y.split is not None else onehot,
                tuple(int(s) for s in onehot.shape),
                types.float32,
                y.split,
                y.device,
                y.comm,
            )
        elif y.ndim == 2:
            self._classes = jnp.arange(y.shape[1])
            self.y = y
        else:
            raise ValueError(f"labels must be 1- or 2-dimensional, got {y.ndim}")
        self.x = x
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Majority vote over the k nearest training points (reference:
        kneighborsclassifier.py predict)."""
        sanitize_in(x)
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        dist = self.effective_metric_(x, self.x)
        neg = -dist.larray
        _, idx = jax.lax.top_k(neg, self.n_neighbors)  # (n_query, k)
        votes = jnp.take(self.y.larray, idx, axis=0)  # (n_query, k, n_classes)
        counts = jnp.sum(votes, axis=1)
        winners = jnp.argmax(counts, axis=1)
        labels = jnp.take(self._classes, winners)
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            labels = x.comm.shard(labels, split)
        return DNDarray(
            labels, gshape, types.canonical_heat_type(labels.dtype), split, x.device, x.comm
        )
