"""K-nearest-neighbors classifier.

API parity with /root/reference/heat/classification/kneighborsclassifier.py
(``KNeighborsClassifier`` :18: fit stores the data; predict = cdist + topk
+ one-hot vote, :45-131). The vote here is one fused expression on the
sharded distance matrix.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from typing import Callable, Optional

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..spatial import distance

__all__ = ["KNeighborsClassifier"]

import functools


@functools.lru_cache(maxsize=64)
def _knn_predict_program(n_neighbors: int):
    """The fused KNN vote ``(xq, xt, y_onehot, classes) -> labels`` as
    ONE program: pairwise distances (the same direct formula as the
    default ``spatial.distance.cdist`` path), top-k, one-hot vote,
    winner lookup. Shared by eager ``predict`` and the serving
    endpoints (ISSUE 9) so served results are bit-identical to eager
    ones by construction."""

    def run(xq, xt, y_onehot, classes):
        diff = xq[:, None, :] - xt[None, :, :]
        dist = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        _, idx = jax.lax.top_k(-dist, n_neighbors)  # (n_query, k)
        votes = jnp.take(y_onehot, idx, axis=0)  # (n_query, k, n_classes)
        counts = jnp.sum(votes, axis=1)
        winners = jnp.argmax(counts, axis=1)
        return jnp.take(classes, winners)

    return jax.jit(run)


def serving_spec(n_neighbors: int, xt: jax.Array, y_onehot: jax.Array,
                 classes: jax.Array, comm=None) -> dict:
    """The serving-endpoint description of a KNN predict program
    (consumed by ``ht.serving.estimator_endpoint`` and the warmup CLI's
    declared set — both must derive identical AOT cache keys)."""
    d = int(xt.shape[1])
    return {
        "build": lambda: _knn_predict_program(int(n_neighbors)),
        "args": (xt, y_onehot, classes),
        "key": (
            "knn-predict", int(n_neighbors), int(xt.shape[0]), d,
            int(y_onehot.shape[1]), str(np.dtype(xt.dtype)),
        ),
        "feature_shape": (d,),
        "dtype": np.dtype(xt.dtype),
        "comm": comm,
        "name": "knn-predict",
    }


class KNeighborsClassifier(BaseEstimator, ClassificationMixin):
    """Classification by majority vote of the k nearest neighbors
    (reference: kneighborsclassifier.py:18)."""

    def __init__(self, n_neighbors: int = 5, effective_metric_: Optional[Callable] = None):
        self.n_neighbors = n_neighbors
        self.effective_metric_ = effective_metric_ if effective_metric_ is not None else distance.cdist
        self.x = None
        self.y = None
        self._classes = None

    @staticmethod
    def one_hot_encoding(x: DNDarray) -> DNDarray:
        """One-hot-encode an integer label vector (reference:
        kneighborsclassifier.py:45 — class count = max(x)+1)."""
        sanitize_in(x)
        n_features = int(jnp.max(x.larray)) + 1
        onehot = (
            x.larray.reshape(-1)[:, None] == jnp.arange(n_features)[None, :]
        ).astype(jnp.float32)
        split = x.split if x.split in (None, 0) else 0
        phys = x.comm.shard(onehot, split) if split is not None else onehot
        return DNDarray(
            phys,
            tuple(int(s) for s in onehot.shape),
            types.float32,
            split,
            x.device,
            x.comm,
        )

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store training data and labels (reference:
        kneighborsclassifier.py fit). ``y`` may be 1-D labels or one-hot."""
        sanitize_in(x)
        sanitize_in(y)
        if y.ndim == 1:
            classes = jnp.unique(y.larray)
            self._classes = classes
            onehot = (y.larray[:, None] == classes[None, :]).astype(jnp.float32)
            self.y = DNDarray(
                x.comm.shard(onehot, y.split) if y.split is not None else onehot,
                tuple(int(s) for s in onehot.shape),
                types.float32,
                y.split,
                y.device,
                y.comm,
            )
        elif y.ndim == 2:
            self._classes = jnp.arange(y.shape[1])
            self.y = y
        else:
            raise ValueError(f"labels must be 1- or 2-dimensional, got {y.ndim}")
        self.x = x
        return self

    def _compute_dtype(self, query_dtype=None):
        """The fused program's compute dtype: EXACTLY the promotion
        ``spatial.distance._prepare`` applies on the composite path —
        float32 unless the promotion lands on float64 (so f16/bf16
        operands compute in f32 there and here alike)."""
        promoted = (
            self.x.dtype if types.heat_type_is_inexact(self.x.dtype) else types.float32
        )
        if query_dtype is not None and types.heat_type_is_inexact(query_dtype):
            promoted = types.promote_types(promoted, query_dtype)
        if promoted is not types.float64:
            promoted = types.float32
        return promoted

    def _serving_inputs(self, dtype=None):
        """(xt, y_onehot, classes) in the fused program's compute dtype."""
        jt = (dtype or self._compute_dtype()).jax_type()
        return self.x.larray.astype(jt), self.y.larray, self._classes

    def predict(self, x: DNDarray) -> DNDarray:
        """Majority vote over the k nearest training points (reference:
        kneighborsclassifier.py predict). The default-metric path runs
        as ONE fused program (``_knn_predict_program``, shared with the
        serving endpoints); a custom ``effective_metric_`` keeps the
        composite path."""
        sanitize_in(x)
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        if self.effective_metric_ is distance.cdist:
            dtype = self._compute_dtype(x.dtype)
            xt, y_onehot, classes = self._serving_inputs(dtype)
            xq = x.larray.astype(dtype.jax_type())
            labels = _knn_predict_program(self.n_neighbors)(xq, xt, y_onehot, classes)
        else:
            dist = self.effective_metric_(x, self.x)
            neg = -dist.larray
            _, idx = jax.lax.top_k(neg, self.n_neighbors)  # (n_query, k)
            votes = jnp.take(self.y.larray, idx, axis=0)  # (n_query, k, n_classes)
            counts = jnp.sum(votes, axis=1)
            winners = jnp.argmax(counts, axis=1)
            labels = jnp.take(self._classes, winners)
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            labels = x.comm.shard(labels, split)
        return DNDarray(
            labels, gshape, types.canonical_heat_type(labels.dtype), split, x.device, x.comm
        )

    def serving_program(self) -> dict:
        """The endpoint description ``ht.serving.estimator_endpoint``
        consumes: the fitted KNN vote program, its replicated model
        state (training set, one-hot labels, classes), and the
        persistent AOT cache key parts. Custom metrics have no fused
        program and cannot be served through an endpoint."""
        if self.x is None:
            raise RuntimeError("fit needs to be called before serving")
        if self.effective_metric_ is not distance.cdist:
            raise ValueError(
                "serving_program supports the default euclidean metric only "
                "(a custom effective_metric_ has no fused serving program)"
            )
        xt, y_onehot, classes = self._serving_inputs()
        return serving_spec(self.n_neighbors, xt, y_onehot, classes, comm=self.x.comm)
