"""Distributed classification (reference: /root/reference/heat/classification/)."""

from .kneighborsclassifier import *
