"""World re-resolution — elastic response to slice loss and resize.

The plan/program/AOT cache keys have carried topology since PR 8, so a
RESOLVED world change can never serve a wrong-world program — but
nothing before this module ever resolved one: a lost slice was a hang,
and caches keyed for the dead world lingered forever. This module adds
the runtime half (ISSUE 13):

- a **world epoch**: every re-resolution bumps one monotonic counter;
  communicators the elastic runtime has stamped are fenced against it,
  and an in-flight collective entering the redistribution executor
  under a stale-epoch communicator raises the typed
  :class:`WorldChangedError` instead of hanging on devices that are
  gone (zero-cost when no communicator was ever stamped — the default
  and the ``HEAT_TPU_RESILIENCE=0`` escape hatch);
- an **eviction sweep** (:func:`invalidate_caches`): the executor's
  registered mesh-keyed program caches, the planner's schedule cache,
  and every live ``ht.jit`` wrapper cache are dropped in one call — the
  epoch bump makes stale entries unreachable, the sweep frees them;
- a pluggable :class:`WorldWatcher` with a CPU-mesh
  :class:`SimulatedWorldWatcher` (the chaos harness's instrument): a
  declared slice loss shrinks the simulated world at a declared stream
  step, deterministically;
- :func:`resolve_world`: build + install the communicator over the
  surviving devices (``Topology`` re-resolves on the new size through
  the PR 8 machinery) and stamp it with the current epoch;
- :func:`elastic_fit`: the detect → checkpoint-restore → re-resolve →
  resume driver for streaming fits, and
  :func:`drain_and_rewarm` for the serving side (dispatcher drain with
  ``ServingOverloaded(reason="resize")``, endpoint re-warm from the AOT
  store against the new world).
"""

from __future__ import annotations

import time

from typing import Callable, Dict, List, Optional

from . import checkpoint as _ckpt
from ..core import communication as _comm_mod
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing

__all__ = [
    "CollectivePoisoned",
    "SimulatedWorldWatcher",
    "WorldChangedError",
    "WorldEvent",
    "WorldWatcher",
    "capture_epoch",
    "check_epoch",
    "check_world",
    "drain_and_rewarm",
    "elastic_fit",
    "invalidate_caches",
    "resolve_world",
    "stamp",
    "world_epoch",
]


class WorldChangedError(RuntimeError):
    """Typed world-change signal: the device world this work was bound
    to is gone (slice loss, resize). Carries what a supervisor needs to
    act — the reason, the epoch the work was stamped with, and the old/
    new world sizes. In-flight collectives surface it instead of
    hanging; the elastic driver catches it, re-resolves, and resumes
    from the last committed checkpoint.

    Carries the flight-recorder tail (ISSUE 15, ``flight_tail``): the
    last N things the process did before the world change — the
    post-mortem starts inside the exception object instead of a log
    archaeology dig."""

    def __init__(self, reason: str, old_size: Optional[int] = None,
                 new_size: Optional[int] = None, epoch: Optional[int] = None):
        self.reason = reason
        self.old_size = old_size
        self.new_size = new_size
        self.epoch = epoch
        _tracing.flight_record("world.changed", reason, new_size)
        self.flight_tail = _tracing.flight_tail()
        msg = f"world changed ({reason})"
        if old_size is not None or new_size is not None:
            msg += f": {old_size} -> {new_size} devices"
        if epoch is not None:
            msg += f" (epoch {epoch})"
        super().__init__(msg)


class CollectivePoisoned(RuntimeError):
    """A window update produced non-finite state — the signature of a
    poisoned collective / corrupted exchange. The elastic driver treats
    it like a failure: restore from the last committed checkpoint and
    re-run the poisoned window."""


class WorldEvent:
    """One observed world change: ``kind`` (``"slice-lost"`` /
    ``"resize"``), the surviving device list, and free-form detail."""

    __slots__ = ("kind", "devices", "detail")

    def __init__(self, kind: str, devices: list, detail: Optional[dict] = None):
        self.kind = kind
        self.devices = list(devices)
        self.detail = dict(detail or {})

    def __repr__(self) -> str:
        return f"WorldEvent({self.kind!r}, {len(self.devices)} devices, {self.detail})"


class WorldWatcher:
    """The pluggable failure detector. ``poll(step)`` returns a
    :class:`WorldEvent` when the world changed since the last poll (or
    ``None``); ``devices()`` is the current surviving world. The base
    class watches nothing — real deployments plug the fleet's health
    endpoint in; tests and the chaos harness use
    :class:`SimulatedWorldWatcher`."""

    def poll(self, step: Optional[int] = None) -> Optional[WorldEvent]:
        return None

    def devices(self) -> list:
        return _comm_mod.get_comm().devices


class SimulatedWorldWatcher(WorldWatcher):
    """Deterministic CPU-mesh watcher: slice losses / resizes are
    DECLARED at stream steps and fire exactly there — the instrument
    the chaos harness and the CI leg drive. Slices follow the PR 8
    slice-major layout: slice ``s`` of an ``SxC`` topology owns the
    contiguous device positions ``[s*C, (s+1)*C)``."""

    def __init__(self, comm=None, topology=None):
        comm = comm or _comm_mod.get_comm()
        self._all = list(comm.devices)
        self._devices = list(self._all)
        self._topology = topology if topology is not None else comm.topology
        if isinstance(self._topology, str):
            self._topology = _comm_mod.topology_for(len(self._all), self._topology)
        self._pending: Dict[int, tuple] = {}
        self.events: List[WorldEvent] = []

    def kill_slice_at(self, step: int, slice_index: int = 0) -> "SimulatedWorldWatcher":
        """Declare: at stream step ``step`` the ``slice_index``-th slice
        of the watcher's topology is preempted."""
        self._pending[int(step)] = ("slice-lost", int(slice_index))
        return self

    def resize_at(self, step: int, n_devices: int) -> "SimulatedWorldWatcher":
        """Declare: at stream step ``step`` the world becomes its first
        ``n_devices`` devices (a planned shrink/grow-back)."""
        self._pending[int(step)] = ("resize", int(n_devices))
        return self

    def poll(self, step: Optional[int] = None) -> Optional[WorldEvent]:
        evt = self._pending.pop(int(step or 0), None)
        if evt is None:
            return None
        kind, arg = evt
        old_size = len(self._devices)
        if kind == "slice-lost":
            topo = self._topology
            c = topo.chips_per_slice if topo.tiered else max(1, len(self._devices) // 2)
            lost = set(range(arg * c, (arg + 1) * c))
            survivors = [
                d for i, d in enumerate(self._all) if i not in lost and d in self._devices
            ]
            detail = {"slice_index": arg, "chips_lost": len(lost), "old_size": old_size}
        else:
            survivors = self._all[:arg]
            detail = {"resize_to": arg, "old_size": old_size}
        if not survivors:
            raise ValueError("SimulatedWorldWatcher: a declared event left zero devices")
        self._devices = survivors
        # fire-time breadcrumb: an injected/observed kill must be IN the
        # flight tail the resulting WorldChangedError carries
        _tracing.flight_record(f"chaos.{kind}", kind, int(step or 0))
        event = WorldEvent(kind, survivors, detail)
        self.events.append(event)
        return event

    def devices(self) -> list:
        return list(self._devices)


# --------------------------------------------------------------------- #
# world epoch + the collective fence
# --------------------------------------------------------------------- #
_EPOCH = 0
#: flipped once the elastic runtime ever stamps a communicator — the
#: default path's zero-cost gate (one module-global truthiness check)
_ANY_STAMPED = False


def world_epoch() -> int:
    """The monotonic world epoch (bumped by every
    :func:`invalidate_caches`)."""
    return _EPOCH


def stamp(comm) -> None:
    """Bind ``comm`` to the current epoch: once a later re-resolution
    bumps the epoch, work entering the redistribution executor under
    this communicator raises :class:`WorldChangedError`. The stamp
    lives ON the communicator (a dedicated slot), never in an id-keyed
    side table — a recycled object id can therefore never inherit a
    dead communicator's stamp."""
    global _ANY_STAMPED
    comm._ht_epoch = _EPOCH
    _ANY_STAMPED = True


def _clear_stamps() -> None:
    """Disarm the fence (test hook / process-level reset)."""
    global _ANY_STAMPED
    _ANY_STAMPED = False


def check_world(comm) -> None:
    """The in-flight fence the executor calls: zero-cost (one module
    flag check) until the elastic runtime stamps a communicator, a
    no-op under ``HEAT_TPU_RESILIENCE=0``."""
    if not _ANY_STAMPED:
        return
    e = getattr(comm, "_ht_epoch", None)
    if e is None or e == _EPOCH:
        return
    if not _ckpt.resilience_enabled(explicit=True):
        return
    raise WorldChangedError(
        "stale-epoch communicator", old_size=getattr(comm, "size", None),
        new_size=len(_comm_mod.get_comm().devices), epoch=e,
    )


def capture_epoch() -> int:
    """The current world epoch as an opaque token for OBJECT-level
    fencing (ISSUE 14): a dispatch-side artifact built against the
    current world (a serving ``Endpoint``'s bucket programs, a future
    MPMD stage program) records this at construction and hands it back
    to :func:`check_epoch` on every issue. The communicator-level
    :func:`stamp`/:func:`check_world` pair fences the redistribution
    executor; this pair fences entry points that hold compiled programs
    rather than a communicator."""
    return _EPOCH


def check_epoch(token: Optional[int], what: str = "dispatch") -> None:
    """The entry fence for epoch-token holders (commcheck rule SL504's
    sanctioned shape next to ``check_world``): zero-cost — one module
    flag check — until the elastic runtime stamps a communicator, a
    no-op under ``HEAT_TPU_RESILIENCE=0``; on a stale token it raises
    the typed :class:`WorldChangedError` instead of letting the held
    programs hang on devices that are gone."""
    if not _ANY_STAMPED or token is None or token == _EPOCH:
        return
    if not _ckpt.resilience_enabled(explicit=True):
        return
    raise WorldChangedError(
        f"stale-epoch {what}",
        new_size=len(_comm_mod.get_comm().devices), epoch=token,
    )


def invalidate_caches(reason: str = "resize") -> Dict[str, int]:
    """The epoch bump + eviction sweep: drop every cache whose entries
    were built for the dead world — the executor's registered mesh-keyed
    program caches, the planner's schedule cache, and every live
    ``ht.jit`` wrapper cache. The keys already carry topology/comm
    identity (PR 8), so staleness was never a correctness risk; the
    sweep reclaims the memory and the bump arms the in-flight fence.
    Returns eviction counts per cache family."""
    global _EPOCH
    _EPOCH += 1
    _tracing.flight_record("world.invalidate", reason, _EPOCH)
    _sp = _tracing.start_span(
        "elastic.invalidate", reason=reason, epoch=_EPOCH
    ) if _tracing._ENABLED else None
    try:
        import importlib

        from ..redistribution import executor as _executor, planner as _planner

        # heat_tpu.core.jit the MODULE is shadowed by the jit FUNCTION in
        # the core package namespace — importlib resolves the module
        jit_mod = importlib.import_module("heat_tpu.core.jit")
        plans = _planner.clear_plan_cache()
        programs = 0
        for fn in _comm_mod._MESH_KEYED_CACHES:
            programs += fn.cache_info().currsize
        _comm_mod._clear_mesh_caches()
        _executor.clear_program_cache()  # idempotent with the sweep above
        wrappers = jit_mod.clear_wrapper_caches()
        # order-independence with resolve_world: a communicator stamped as
        # THE CURRENT WORLD moves forward with the bump — only dead worlds'
        # comms stay behind and trip the fence (resolve-then-invalidate and
        # invalidate-then-resolve both leave the installed world live)
        cur = _comm_mod.get_comm()
        if getattr(cur, "_ht_epoch", None) is not None:
            cur._ht_epoch = _EPOCH
    except BaseException:
        # a mid-sweep failure must not strand the open span on the
        # thread's active stack (every later span would parent to it)
        _tracing.end_span(_sp, error=True)
        raise
    counts = {"plans": plans, "programs": programs, "jit_entries": wrappers}
    _tracing.end_span(_sp, **counts)
    if _telemetry._ENABLED:
        from ..observability import events as _obs_events

        _telemetry.inc("resilience.world.invalidate")
        _obs_events.emit(
            "resilience.world.invalidate", reason=reason, epoch=_EPOCH, **counts
        )
    return counts


def resolve_world(devices: Optional[list] = None) -> "_comm_mod.MeshCommunication":
    """Build the communicator over the SURVIVING world, install it as
    the global default, and stamp it with the current epoch. The
    ``Topology`` re-resolves through the PR 8 machinery on the new size
    (``HEAT_TPU_TOPOLOGY`` semantics unchanged: a forced factorization
    that no longer divides the shrunk world resolves flat)."""
    if devices is None:
        devices = _comm_mod.MPI_WORLD.devices
    _tracing.flight_record("world.resolve", "", len(devices))
    with _tracing.span("elastic.resolve", step="resolve", world=len(devices)):
        comm = _comm_mod.MeshCommunication(list(devices))
        _comm_mod.use_comm(comm)
        stamp(comm)
    if _telemetry._ENABLED:
        _telemetry.inc("resilience.world.resolve")
    return comm


# --------------------------------------------------------------------- #
# the elastic training driver
# --------------------------------------------------------------------- #
def _finite_state(model) -> bool:
    """Host check that the model's streaming state is finite — the
    poisoned-collective detector (declared host boundary
    ``resilience-state-validate``: the centers are a (k, d) scalar-class
    array, and the read IS the detection)."""
    import jax
    import numpy as np

    centers = model._cluster_centers
    if centers is None:
        return True
    host = np.asarray(jax.device_get(centers.larray))
    return bool(np.isfinite(host).all())


def elastic_fit(model, host, *, ckpt: "_ckpt.CheckpointConfig",
                watcher: Optional[WorldWatcher] = None,
                chaos=None, max_failures: int = 4):
    """Fault-tolerant streaming fit: run ``model.fit(host, ckpt=ckpt)``
    under a :class:`WorldWatcher` (and optionally a chaos harness);
    on :class:`WorldChangedError` / :class:`CollectivePoisoned`,
    re-resolve the world onto the survivors, bump the epoch + sweep the
    caches, and resume from the newest committed checkpoint — the
    resumed run replays the remaining windows and reproduces the
    uninterrupted run's bits (the chaos CI leg's pin).

    With ``HEAT_TPU_RESILIENCE=0`` this is exactly ``model.fit(host)``:
    no checkpoints, no fences, no watcher polls."""
    if not _ckpt.resilience_enabled(explicit=True):
        return model.fit(host)
    failures = 0
    while True:
        try:
            return model.fit(host, ckpt=ckpt, _watcher=watcher, _chaos=chaos)
        except (WorldChangedError, CollectivePoisoned) as e:
            failures += 1
            _tracing.flight_record(
                "elastic.failover", getattr(e, "reason", "poisoned"), failures
            )
            if _telemetry._ENABLED:
                _telemetry.inc("resilience.fit.failover")
            if failures > max_failures:
                raise
            if isinstance(e, WorldChangedError) and watcher is not None:
                resolve_world(watcher.devices())
            invalidate_caches(reason=getattr(e, "reason", "poisoned"))
            # the resumed attempt restores from the newest committed
            # checkpoint inside fit(ckpt=) — nothing else to carry over


# --------------------------------------------------------------------- #
# serving failover
# --------------------------------------------------------------------- #
def drain_and_rewarm(dispatcher, rebuild_endpoint: Callable[[], object],
                     reason: str = "resize", timeout: float = 30.0):
    """The serving half of a world change: fence the dispatcher's
    in-flight batches and shed its queue as
    ``ServingOverloaded(reason="resize")`` (load balancers FAIL OVER on
    that reason — the PR 9 shutdown contract extended), then rebuild
    the endpoint against the CURRENT world — ``rebuild_endpoint()``
    resolves its bucket programs through ``serving.aot_cache.
    ensure_program``, so a store warmed for this world serves them
    without compiling — and resume. Returns the new endpoint.

    A drain that cannot confirm within ``timeout`` raises: swapping the
    endpoint under a live (un-parked) worker would hand batches
    collected against the old endpoint to the new one's programs, and
    clearing the pause early would serve requests the resize contract
    promised to shed — a wedged in-flight batch means this REPLICA is
    lost, and the caller must escalate, not pretend the failover
    happened."""
    with _tracing.span("serving.drain_confirm", reason=reason):
        confirmed = dispatcher.drain(reason=reason, timeout=timeout)
    if not confirmed:
        raise TimeoutError(
            f"dispatcher drain ({reason}) did not confirm within "
            f"{timeout}s — the in-flight batch is wedged; escalate "
            "(replace the replica) instead of rewarming under a live worker"
        )
    t0 = time.perf_counter()
    with _tracing.span("serving.rewarm", reason=reason):
        endpoint = rebuild_endpoint()
    dispatcher.resume(endpoint=endpoint)
    if _telemetry._ENABLED:
        _telemetry.observe("resilience.serving.rewarm", time.perf_counter() - t0)
        _telemetry.inc("resilience.serving.failover")
    return endpoint
