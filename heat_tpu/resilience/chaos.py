"""Deterministic fault injection — the chaos harness (ISSUE 13).

A resilience mechanism that was never exercised is a mechanism that
does not work; a chaos harness that fires nondeterministically is a CI
flake. This module injects exactly three failure classes, each at a
DECLARED stream step, each reproducible from a seed:

- **kill a simulated slice** mid-``fit``: wires a
  :class:`~heat_tpu.resilience.elastic.SimulatedWorldWatcher` slice
  loss at the declared step (the watcher's poll raises the world
  change into the stream loop);
- **poison a collective**: the staged window buffer of the declared
  step is overwritten with NaNs before the update consumes it — the
  observable signature of a corrupted exchange — which the stream
  loop's finite-state validation converts into the typed
  :class:`~heat_tpu.resilience.elastic.CollectivePoisoned`;
- **truncate a checkpoint**: after the declared step's envelope
  commits, its largest entry file is cut short — restore must detect
  the mutilation (sha256/length mismatch → ``CheckpointCorrupt``) and
  fall back to the previous committed step.

The seed drives every UNDECLARED choice (which slice dies, how many
bytes survive a truncation) through one ``random.Random(seed)`` stream,
so two monkeys with the same seed and the same declarations produce
byte-identical injection schedules — the chaos CI leg's determinism
contract. ``scripts/chaos_drill.py`` is the end-to-end consumer.
"""

from __future__ import annotations

import os
import random

from typing import Dict, List, Optional

from . import elastic as _elastic
from ..observability import tracing as _tracing

__all__ = ["ChaosMonkey"]


class ChaosMonkey:
    """Seeded, declarative fault injector for the streaming-fit loop.

    Usage::

        monkey = (ChaosMonkey(seed=7)
                  .kill_slice(step=5)            # slice chosen by seed
                  .poison_collective(step=9)
                  .truncate_checkpoint(step=12))
        watcher = monkey.watcher(topology="2x4")
        ht.resilience.elastic_fit(model, host, ckpt=cfg,
                                  watcher=watcher, chaos=monkey)

    Every event fires AT MOST ONCE (a resumed stream does not re-kill
    the slice it already killed — preemption is modeled as an event,
    not a state).
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._kills: Dict[int, Optional[int]] = {}
        self._poisons: Dict[int, bool] = {}
        self._truncations: Dict[int, Optional[int]] = {}
        self._next_window = 0
        self.log: List[dict] = []

    # ------------------------------------------------------------------ #
    # declarations
    # ------------------------------------------------------------------ #
    def kill_slice(self, step: int, slice_index: Optional[int] = None) -> "ChaosMonkey":
        """At stream step ``step``, preempt one slice (``slice_index``
        or a seed-drawn one)."""
        self._kills[int(step)] = slice_index
        return self

    def poison_collective(self, step: int) -> "ChaosMonkey":
        """At stream step ``step``, corrupt the staged exchange buffer
        (NaN payload) before the update consumes it."""
        self._poisons[int(step)] = True
        return self

    def truncate_checkpoint(self, step: int, keep_bytes: Optional[int] = None) -> "ChaosMonkey":
        """After the checkpoint covering stream step ``step`` commits,
        truncate its largest entry to ``keep_bytes`` (or a seed-drawn
        fraction)."""
        self._truncations[int(step)] = keep_bytes
        return self

    # ------------------------------------------------------------------ #
    # wiring
    # ------------------------------------------------------------------ #
    def watcher(self, comm=None, topology=None) -> _elastic.SimulatedWorldWatcher:
        """A :class:`SimulatedWorldWatcher` with every declared slice
        kill scheduled (seed resolves unspecified slice indices)."""
        w = _elastic.SimulatedWorldWatcher(comm=comm, topology=topology)
        topo = w._topology
        n_slices = topo.n_slices if topo.tiered else 2
        for step, idx in sorted(self._kills.items()):
            if idx is None:
                idx = self._rng.randrange(n_slices)
                self._kills[step] = idx
            w.kill_slice_at(step, idx)
            self.log.append({"step": step, "kind": "kill-slice", "slice": idx})
        return w

    def poison_put(self, base_put=None):
        """A ``device_put`` replacement for ``staging.stream_windows``:
        the declared steps' windows are staged as NaNs. The step counter
        is the WINDOW INDEX the stream reports via :meth:`bind_offset`
        (a resumed stream re-binds so global window numbering holds)."""
        import jax
        import numpy as np

        put = base_put or jax.device_put
        monkey = self

        def chaos_put(host_block):
            step = monkey._next_window
            monkey._next_window += 1
            if monkey._poisons.pop(step, None):
                monkey.log.append({"step": step, "kind": "poison-collective"})
                # fire-time breadcrumb: the flight tail a post-mortem
                # reads MUST contain the injected fault at its step
                _tracing.flight_record("chaos.poison", "poison-collective", step)
                poisoned = np.full_like(np.asarray(host_block), np.nan)
                return put(poisoned)
            return put(host_block)

        return chaos_put

    def bind_offset(self, window: int) -> None:
        """Tell the poison counter which GLOBAL window the stream will
        stage next (stream restarts re-bind here)."""
        self._next_window = int(window)

    def after_checkpoint(self, path: str, step: int) -> None:
        """Post-commit hook the checkpointing stream calls: apply any
        declared truncation to the just-committed envelope."""
        keep = self._truncations.pop(int(step), "absent")
        if keep == "absent":
            return
        victim, size = None, -1
        for name in os.listdir(path):
            if name.endswith(".bin"):
                s = os.path.getsize(os.path.join(path, name))
                if s > size:
                    victim, size = name, s
        if victim is None:
            return
        if keep is None:
            keep = self._rng.randrange(max(1, size // 2))
        with open(os.path.join(path, victim), "r+b") as f:
            f.truncate(int(keep))
        _tracing.flight_record("chaos.truncate", victim, int(step))
        self.log.append(
            {"step": int(step), "kind": "truncate-ckpt", "entry": victim,
             "kept_bytes": int(keep), "was_bytes": size}
        )

    def schedule(self) -> List[dict]:
        """The declared schedule (before firing) — what two same-seed
        monkeys must agree on byte-for-byte."""
        out = []
        for step, idx in sorted(self._kills.items()):
            out.append({"step": step, "kind": "kill-slice", "slice": idx})
        for step in sorted(self._poisons):
            out.append({"step": step, "kind": "poison-collective"})
        for step, keep in sorted(self._truncations.items()):
            out.append({"step": step, "kind": "truncate-ckpt", "keep_bytes": keep})
        return out
