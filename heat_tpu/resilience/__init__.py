"""heat_tpu.resilience — elastic, fault-tolerant runtime (ISSUE 13).

Heavy traffic runs on preemptible TPU fleets, where a lost slice is the
common case, not the exception — yet until this package a preemption
was a hang or a crash, and a resized world left every topology-keyed
cache holding entries for devices that no longer exist. Four
coordinated pieces close that gap:

- :mod:`~heat_tpu.resilience.checkpoint` — deterministic slab-streamed
  checkpointing: a versioned sha256-keyed envelope (gate roster +
  topology stamped, atomic rename commit, host memory O(slab) and
  RECORDED) capturing estimator/optimizer state mid-``fit`` — cluster
  centers/streaming counts, ``DataParallelOptimizer`` params +
  error-feedback carry, and the explicit RNG stream state. Restore
  re-shards onto the CURRENT world and the resumed ``fit(ckpt=)`` /
  ``partial_fit`` stream is bit-reproducible.
- :mod:`~heat_tpu.resilience.elastic` — world re-resolution: a
  pluggable :class:`WorldWatcher` (simulated on CPU meshes), the
  world-epoch bump + cache eviction sweep (plan / program / ``ht.jit``
  caches), the typed :class:`WorldChangedError` fence for in-flight
  collectives, and the :func:`elastic_fit` detect→restore→resume
  driver.
- serving failover — ``Dispatcher.drain(reason="resize")`` fences
  in-flight batches and resolves queued futures as
  ``ServingOverloaded(reason="resize")`` (load balancers fail over
  instead of backing off — the PR 9 shutdown contract extended), then
  :func:`drain_and_rewarm` re-warms endpoint programs against the new
  world from the AOT store.
- :mod:`~heat_tpu.resilience.chaos` — a deterministic, seedable fault
  harness (kill a simulated slice / poison a collective / truncate a
  checkpoint, each at a declared step) driving the chaos CI leg: a
  slice dies mid-``fit`` and the checkpoint-resumed run is pinned
  bit-identical to an uninterrupted one.

Gates: ``HEAT_TPU_RESILIENCE=0/1/auto`` (``0`` = exact pre-resilience
paths, the bit-for-bit escape hatch) and ``HEAT_TPU_CKPT_DIR`` (the
checkpoint store root — a trust boundary like the AOT store), both
declared in ``core/gates.py``.
"""

from __future__ import annotations

from .checkpoint import (
    CheckpointConfig,
    CheckpointCorrupt,
    ckpt_dir,
    latest_step,
    list_steps,
    load,
    resilience_enabled,
    resilience_mode,
    restore_latest,
    save,
)
from .elastic import (
    CollectivePoisoned,
    SimulatedWorldWatcher,
    WorldChangedError,
    WorldEvent,
    WorldWatcher,
    check_world,
    drain_and_rewarm,
    elastic_fit,
    invalidate_caches,
    resolve_world,
    world_epoch,
)
from .chaos import ChaosMonkey

from . import checkpoint
from . import chaos
from . import elastic

__all__ = [
    "ChaosMonkey",
    "CheckpointConfig",
    "CheckpointCorrupt",
    "CollectivePoisoned",
    "SimulatedWorldWatcher",
    "WorldChangedError",
    "WorldEvent",
    "WorldWatcher",
    "chaos",
    "check_world",
    "checkpoint",
    "ckpt_dir",
    "drain_and_rewarm",
    "elastic",
    "elastic_fit",
    "invalidate_caches",
    "latest_step",
    "list_steps",
    "load",
    "resilience_enabled",
    "resilience_mode",
    "resolve_world",
    "restore_latest",
    "save",
    "world_epoch",
]
