"""Deterministic slab-streamed checkpointing (ISSUE 13).

A preemptible fleet loses slices as a matter of course; the only state
that survives is what reached a persistent store before the preemption.
This module is the durable half of ``heat_tpu.resilience``: a versioned
on-disk envelope capturing estimator/optimizer state mid-``fit`` —
cluster centers and streaming counts, ``DataParallelOptimizer`` params,
optimizer state and the error-feedback carry, and the EXPLICIT RNG
stream state — with three hard properties:

- **O(slab) host memory** — arrays are written as bounded split-block
  slabs through the same per-device-block machinery ``core/io.py``
  streams saves with: a sharded operand contributes one device block at
  a time, an unsharded one is chunked at :data:`SLAB_BYTES`. Nothing
  ever materializes a second full copy on the host; the observed
  high-water mark is RECORDED in the envelope (``max_slab_bytes``) so
  tests assert the bound instead of eyeballing it.
- **Integrity + provenance** — every entry carries a sha256 computed
  while its slabs stream out (the AOT-cache keying discipline applied
  to training state), and the envelope meta stamps the PR 12 gate
  roster (``gates.program_gate_roster``), the resolved topology, the
  world size and the jax/heat_tpu versions. A truncated or bit-flipped
  entry fails verification as :class:`CheckpointCorrupt` — restore then
  falls back to the previous committed step, never resumes from garbage.
- **Atomic commit** — a checkpoint is written under
  ``step_<N>.tmp-<pid>`` (data files fsynced, then the meta, which is
  written LAST) and becomes visible via one ``os.rename``. A crash at
  any byte leaves either the previous committed step or an ignorable
  ``.tmp-*`` orphan; there is no torn-but-visible state.

``restore_latest`` re-shards every saved array onto the CURRENT world
(a restored split-0 operand lands on however many devices survive), so
the ``fit(ckpt=)`` / ``partial_fit`` resume contract holds across a
world resize — the resumed stream replays the remaining windows on the
new mesh and, because the streaming updates are replicated-window
programs, reproduces the uninterrupted run's bits exactly (pinned by
the chaos CI leg at 8 AND 5 virtual devices).

Trust boundary: like the AOT store, ``HEAT_TPU_CKPT_DIR`` must carry
the same write permissions as the deployment's code. Restore parses
JSON and raw little-endian buffers only — no pickle — but training
state is still an input an attacker who owns the directory controls.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil

import numpy as np

from typing import Any, Dict, Optional, Tuple

from ..core import gates as _gates
from ..observability import telemetry as _telemetry
from ..observability import tracing as _tracing

__all__ = [
    "CKPT_DIR_ENV",
    "CheckpointConfig",
    "CheckpointCorrupt",
    "FORMAT",
    "RESILIENCE_ENV",
    "SLAB_BYTES",
    "ckpt_dir",
    "latest_step",
    "list_steps",
    "load",
    "resilience_enabled",
    "resilience_mode",
    "restore_latest",
    "save",
    "step_path",
]

RESILIENCE_ENV = "HEAT_TPU_RESILIENCE"
CKPT_DIR_ENV = "HEAT_TPU_CKPT_DIR"

#: envelope format version — bumped on layout changes; a mismatch is
#: :class:`CheckpointCorrupt` (never a best-effort parse).
FORMAT = 1

#: slab granularity for UNSHARDED entries (numpy / replicated jax
#: arrays): 64 MiB keeps host staging far below any operand of
#: interest while amortizing syscall overhead; sharded entries stream
#: at their natural split-block size instead (the io.py unit).
SLAB_BYTES = 64 << 20

_STEP_RE = re.compile(r"^step_(\d{8})$")


# --------------------------------------------------------------------- #
# the gate
# --------------------------------------------------------------------- #
def resilience_mode() -> str:
    """Resolved ``HEAT_TPU_RESILIENCE`` mode (``"0"``/``"1"``/``"auto"``).
    ``0`` disables the elastic runtime everywhere — no checkpoint hooks,
    no world-epoch guards, no drain fences: the exact pre-resilience
    code paths (the escape hatch every gated subsystem ships). ``1``
    forces it (the chaos CI leg); ``auto`` (default) engages where the
    caller explicitly hands the runtime a checkpoint config or a world
    watcher."""
    v = _gates.get(RESILIENCE_ENV, "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "true", "force", "yes"):
        return "1"
    return "auto"


def resilience_enabled(explicit: bool = False) -> bool:
    """Does the elastic runtime engage? ``explicit`` = the caller handed
    it a checkpoint config / watcher (the ``auto`` trigger)."""
    mode = resilience_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return bool(explicit)


def ckpt_dir(override: Optional[str] = None) -> str:
    """The checkpoint store root: ``override``, else
    ``HEAT_TPU_CKPT_DIR``, else the user default."""
    if override:
        return os.path.expanduser(override)
    return os.path.expanduser(
        _gates.get(
            CKPT_DIR_ENV,
            os.path.join("~", ".cache", "heat_tpu", "ckpt"),
        )
    )


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed verification: truncated/bit-flipped entry
    (sha256 mismatch), malformed meta, or a format-version mismatch.
    ``restore_latest`` treats it as "this step never committed" and
    falls back to the previous one."""


class CheckpointConfig:
    """How a resumable ``fit`` checkpoints.

    Parameters
    ----------
    directory : store root (default: :func:`ckpt_dir`).
    tag : the envelope family one training run writes under.
    every : checkpoint every N stream windows (``fit(ckpt=)``).
    keep : committed steps retained per tag (older ones are pruned
        after each successful commit; >= 2 so a truncated newest step
        always has a committed predecessor to fall back to).
    """

    def __init__(self, directory: Optional[str] = None, tag: str = "fit",
                 every: int = 1, keep: int = 2):
        if every < 1:
            raise ValueError(f"ckpt.every must be >= 1, got {every}")
        if keep < 2:
            raise ValueError(f"ckpt.keep must be >= 2 (fallback needs a predecessor), got {keep}")
        self.directory = ckpt_dir(directory)
        self.tag = str(tag)
        self.every = int(every)
        self.keep = int(keep)

    def __repr__(self) -> str:
        return (
            f"CheckpointConfig(directory={self.directory!r}, tag={self.tag!r}, "
            f"every={self.every}, keep={self.keep})"
        )


# --------------------------------------------------------------------- #
# envelope layout helpers
# --------------------------------------------------------------------- #
def step_path(directory: str, tag: str, step: int) -> str:
    return os.path.join(directory, tag, f"step_{int(step):08d}")


def list_steps(directory: str, tag: str) -> list:
    """Committed step numbers for ``tag``, ascending (``.tmp-*`` write
    orphans are invisible by construction)."""
    root = os.path.join(directory, tag)
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for n in names:
        m = _STEP_RE.match(n)
        if m and os.path.isfile(os.path.join(root, n, "meta.json")):
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(directory: str, tag: str) -> Optional[int]:
    steps = list_steps(directory, tag)
    return steps[-1] if steps else None


def _stamps() -> Dict[str, Any]:
    """Provenance stamps: versions, world geometry, the resolved
    topology, and the PR 12 program-affecting gate ROSTER — so an
    operator can always answer "what produced this checkpoint"."""
    import jax

    from ..core import communication as _comm
    from ..version import __version__

    world = _comm.get_comm()
    try:
        size = int(world.size)
        topo = str(world.topology)
    except Exception:
        size, topo = -1, "flat"
    return {
        "heat_tpu": __version__,
        "jax": jax.__version__,
        "platform": jax.default_backend(),
        "world_size": size,
        "topology": topo,
        "gate_roster": _gates.program_gate_roster(),
    }


class _SlabWriter:
    """Streams one entry's bytes to disk while hashing them — the
    single funnel every entry kind writes through, so the sha256 and
    the O(slab) high-water mark are computed in the same pass.

    The durable commit is pipelined so it runs at the DISK edge, not
    the hash edge: sha256 rides a background hasher thread (a bounded
    queue of the slab views — still O(slab) host memory), and after
    each slab the kernel is nudged to start writeback early
    (``sync_file_range``-style via a background fsync), so the final
    close-time fsync flushes a mostly-clean file instead of paying the
    whole flush serially after the whole write. Measured on the dev
    box: inline hashing + one trailing fsync commits a 2.1 GB entry at
    ~0.36 GB/s; pipelined it tracks the raw durable-write figure
    (~0.47 GB/s) — the ``ckpt_write_2gb`` bench row pins the floor."""

    def __init__(self, path: str):
        import queue
        import threading

        self._f = open(path, "wb")
        # hasher-thread-owned; close() JOINS the thread before reading
        # the digest — the join is the fence
        self._sha = hashlib.sha256()  # racecheck: guarded-by(hasher join in close())
        self.nbytes = 0
        self.max_slab = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=4)
        self._done = threading.Event()
        # worker-thread-only; close() joins the flusher before reading
        self._flush_error = None  # racecheck: guarded-by(flusher join in close())
        self._hasher = threading.Thread(target=self._hash_loop, daemon=True)
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._hasher.start()
        self._flusher.start()

    def _hash_loop(self) -> None:
        while True:
            block = self._q.get()
            if block is None:
                return
            self._sha.update(block)

    def _flush_loop(self) -> None:
        # early writeback: flush the dirty pages accumulated so far
        # while the main thread keeps writing/hashing — fsync from a
        # second thread on the same fd is the portable
        # sync_file_range. A writeback error here is RECORDED and
        # fails the commit at close(): on Linux >= 4.13 the first
        # fsync to observe an EIO marks it seen for this struct file,
        # so close()'s own fsync could otherwise falsely succeed and
        # commit an envelope that never durably reached the disk.
        fd = self._f.fileno()
        while not self._done.wait(0.05):
            try:
                os.fsync(fd)
            except OSError as e:
                self._flush_error = e
                return

    def write(self, host_block: np.ndarray) -> None:
        arr = np.ascontiguousarray(host_block)
        view = memoryview(arr).cast("B")
        self.max_slab = max(self.max_slab, view.nbytes)
        self._q.put(view)  # the ndarray ref keeps the bytes alive
        self._f.write(view)
        self.nbytes += view.nbytes

    def record_staging(self, nbytes: int) -> None:
        """Fold an out-of-band host staging cost (e.g. the one-shot
        ``device_get`` of a replicated device entry) into the recorded
        high-water mark — ``max_slab_bytes`` must reflect the TRUE
        host footprint or the O(slab) assertion certifies a lie."""
        self.max_slab = max(self.max_slab, int(nbytes))

    def close(self) -> Tuple[str, int, int]:
        self._q.put(None)
        self._hasher.join()
        self._f.flush()
        self._done.set()
        self._flusher.join()
        if self._flush_error is not None:
            self._f.close()
            raise self._flush_error
        os.fsync(self._f.fileno())
        self._f.close()
        return self._sha.hexdigest(), self.nbytes, self.max_slab

    def abort(self) -> None:
        """Tear down without committing (the save() error path): both
        threads joined, fd closed — a failed save must not leak a
        20 Hz flusher, a parked hasher, or an open fd per retry."""
        self._done.set()
        try:
            self._q.put_nowait(None)
        except Exception:
            # queue full: the hasher is alive and draining — a blocking
            # put is bounded by one block's hash time
            self._q.put(None)
        self._hasher.join()
        self._flusher.join()
        try:
            self._f.close()
        except OSError:
            pass


def _iter_np_slabs(arr: np.ndarray, slab: int):
    """Fixed-size slabs of an unsharded host array (flat byte view)."""
    flat = arr.reshape(-1)
    per = max(1, slab // max(arr.dtype.itemsize, 1))
    for off in range(0, flat.size, per):
        yield flat[off:off + per]


def _write_dnd(writer: _SlabWriter, data) -> Dict[str, Any]:
    """One DNDarray entry, streamed block-by-block through the io.py
    per-device-slab machinery (``_write_shards``): the host never holds
    more than one device's logical block. Split None/0 only — row-major
    file layout keeps those blocks contiguous; other splits resplit at
    the caller."""
    from ..core import io as _io

    if data.split not in (None, 0):
        raise NotImplementedError(
            f"checkpoint: DNDarray entries support split None/0, got "
            f"split={data.split} — resplit(0) before checkpointing"
        )
    _io._write_shards(data, lambda _sl, host: writer.write(host))
    return {
        "kind": "dnd",
        "shape": list(data.shape),
        "dtype": data.dtype.__name__,
        "split": data.split,
    }


def _write_jax(writer: _SlabWriter, arr) -> Dict[str, Any]:
    """One jax.Array entry. A split-0-sharded array streams its
    addressable shards in mesh order (one device block on the host at a
    time — the EF-carry case); a replicated/single-device array is
    fetched once and chunked at :data:`SLAB_BYTES`."""
    import jax

    shards = getattr(arr, "addressable_shards", None)
    sharded = bool(shards) and len(shards) > 1 and not _replicated(arr)
    if sharded:
        blocks = sorted(shards, key=lambda s: (s.index[0].start or 0))
        starts = [(s.index[0].start or 0) for s in blocks]
        if len(set(starts)) != len(starts):
            sharded = False  # partial replication: fall back to one fetch
    if sharded:
        for s in blocks:
            writer.write(np.asarray(jax.device_get(s.data)))
    else:
        # a replicated/single-device entry stages WHOLE on the host for
        # the duration of its write — that one-shot fetch IS the true
        # high-water mark for this entry, and it is recorded as such
        # (the O(slab) contract holds for the split-block and numpy
        # paths; big state should ride those — this records, not hides)
        host = np.asarray(jax.device_get(arr))
        writer.record_staging(host.nbytes)
        for slab in _iter_np_slabs(host, SLAB_BYTES):
            writer.write(slab)
    return {
        "kind": "jax",
        "shape": list(arr.shape),
        "dtype": str(np.dtype(arr.dtype)),
        "split": 0 if sharded else None,
    }


def _replicated(arr) -> bool:
    try:
        return bool(arr.sharding.is_fully_replicated)
    except Exception:
        return True


def _write_np(writer: _SlabWriter, arr: np.ndarray) -> Dict[str, Any]:
    for slab in _iter_np_slabs(arr, SLAB_BYTES):
        writer.write(slab)
    return {
        "kind": "np",
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "split": None,
    }


_SCALAR_TYPES = (bool, int, float, str, type(None))


def _is_scalarish(v) -> bool:
    if isinstance(v, _SCALAR_TYPES):
        return True
    if isinstance(v, (tuple, list)):
        return all(_is_scalarish(x) for x in v)
    return False


# --------------------------------------------------------------------- #
# save
# --------------------------------------------------------------------- #
def save(state: Dict[str, Any], *, tag: str, step: int,
         directory: Optional[str] = None) -> str:
    """Commit one checkpoint envelope atomically. ``state`` maps entry
    names to DNDarrays, jax arrays, numpy arrays, or plain scalars/
    tuples (the RNG stream tuple rides here). Returns the committed
    step directory. Host memory stays O(slab) throughout; the observed
    high-water mark lands in ``meta["max_slab_bytes"]``."""
    from ..core.dndarray import DNDarray
    from ..observability import events as _obs_events

    directory = ckpt_dir(directory)
    final = step_path(directory, tag, step)
    tmp = f"{final}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    entries: Dict[str, Dict[str, Any]] = {}
    scalars: Dict[str, Any] = {}
    max_slab = 0
    total = 0
    writer = None
    save_sp = _tracing.start_span(
        "ckpt.save", tag=tag, step=int(step)
    ) if _tracing._ENABLED else None
    try:
        for name in sorted(state):
            value = state[name]
            if _is_scalarish(value):
                scalars[name] = (
                    list(value) if isinstance(value, tuple) else value
                )
                continue
            # one span per entry around the slab write stream, one
            # around close() — the hasher join + trailing fsync, the
            # durable edge the ckpt_write_2gb bench row prices
            # detached: a mid-write failure (ENOSPC) must not strand an
            # open span on the thread's parent stack
            entry_sp = _tracing.start_span(
                "ckpt.write", entry=name, detached=True,
                parent_id=None if save_sp is None else save_sp.id,
            ) if _tracing._ENABLED else None
            writer = _SlabWriter(os.path.join(tmp, f"{name}.bin"))
            if isinstance(value, DNDarray):
                desc = _write_dnd(writer, value)
            elif isinstance(value, np.ndarray):
                desc = _write_np(writer, value)
            else:
                desc = _write_jax(writer, value)
            with _tracing.span(
                "ckpt.hash_commit", entry=name,
                parent_id=None if entry_sp is None else entry_sp.id,
            ):
                sha, nbytes, slab_hi = writer.close()
            writer = None
            _tracing.end_span(entry_sp, bytes=nbytes)
            desc.update({"sha256": sha, "nbytes": nbytes})
            entries[name] = desc
            max_slab = max(max_slab, slab_hi)
            total += nbytes
        meta = {
            "format": FORMAT,
            "tag": tag,
            "step": int(step),
            "stamps": _stamps(),
            "entries": entries,
            "scalars": scalars,
            "total_bytes": total,
            "max_slab_bytes": max_slab,
        }
        # the meta carries the RESUME-CRITICAL cursor (window_index,
        # slab, RNG tuple) — it gets the same integrity treatment the
        # entry files do: a digest over its canonical serialization,
        # verified at every load
        meta["meta_sha256"] = _meta_digest(meta)
        with _tracing.span(
            "ckpt.commit", tag=tag, step=int(step), bytes=total,
            parent_id=None if save_sp is None else save_sp.id,
        ):
            meta_path = os.path.join(tmp, "meta.json")
            with open(meta_path, "w") as f:
                json.dump(meta, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            if os.path.isdir(final):
                # re-saving an already-committed step is an explicit
                # overwrite (not a crash-path concern): drop the old one
                shutil.rmtree(final)
            os.rename(tmp, final)  # THE commit point
            _fsync_dir(os.path.dirname(final))
    except BaseException:
        if writer is not None:
            # a mid-entry failure (ENOSPC is the routine one) must not
            # leak the writer's threads/fd on every retry
            writer.abort()
        shutil.rmtree(tmp, ignore_errors=True)
        _tracing.end_span(save_sp, status="error")
        raise
    _tracing.end_span(save_sp, bytes=total)
    _tracing.flight_record("ckpt.commit", tag, int(step))
    if _telemetry._ENABLED:
        _telemetry.inc("resilience.ckpt.save")
        _telemetry.inc("resilience.ckpt.bytes", total)
        _obs_events.emit(
            "resilience.ckpt.save", tag=tag, step=int(step),
            bytes=total, max_slab_bytes=max_slab,
        )
    return final


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # platforms without directory fsync


def prune(directory: str, tag: str, keep: int) -> list:
    """Drop all but the newest ``keep`` committed steps; returns the
    pruned step numbers."""
    steps = list_steps(directory, tag)
    drop = steps[:-keep] if keep > 0 else []
    for s in drop:
        shutil.rmtree(step_path(directory, tag, s), ignore_errors=True)
    return drop


# --------------------------------------------------------------------- #
# load / restore
# --------------------------------------------------------------------- #
def _meta_digest(meta: Dict[str, Any]) -> str:
    """sha256 over the meta's canonical serialization (sort_keys JSON,
    the digest field excluded)."""
    body = {k: v for k, v in meta.items() if k != "meta_sha256"}
    return hashlib.sha256(json.dumps(body, sort_keys=True).encode()).hexdigest()


def _read_meta(path: str) -> Dict[str, Any]:
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable meta.json ({e})") from None
    if not isinstance(meta, dict) or meta.get("format") != FORMAT:
        raise CheckpointCorrupt(
            f"{path}: format {meta.get('format') if isinstance(meta, dict) else '?'} "
            f"!= {FORMAT}"
        )
    if meta.get("meta_sha256") != _meta_digest(meta):
        raise CheckpointCorrupt(
            f"{path}: meta.json digest mismatch — the envelope's cursor/"
            "scalar state does not match what was committed"
        )
    return meta


def _verify_entry(path: str, name: str, desc: Dict[str, Any]) -> None:
    """Streaming sha256 re-hash of one entry file (O(slab) memory)."""
    fp = os.path.join(path, f"{name}.bin")
    sha = hashlib.sha256()
    nbytes = 0
    try:
        with open(fp, "rb") as f:
            while True:
                chunk = f.read(SLAB_BYTES)
                if not chunk:
                    break
                sha.update(chunk)
                nbytes += len(chunk)
    except OSError as e:
        raise CheckpointCorrupt(f"{path}: entry {name!r} unreadable ({e})") from None
    if nbytes != int(desc["nbytes"]):
        raise CheckpointCorrupt(
            f"{path}: entry {name!r} truncated — {nbytes} B on disk, "
            f"{desc['nbytes']} B committed"
        )
    if sha.hexdigest() != desc["sha256"]:
        raise CheckpointCorrupt(
            f"{path}: entry {name!r} sha256 mismatch — bytes on disk do "
            "not match what was committed"
        )


def _restore_flat_entry(path: str, name: str, desc: Dict[str, Any], verify: bool):
    """One-pass restore of an ``np``/``jax`` entry: the bytes are read
    ONCE into the destination buffer and hashed from there — recovery
    reads each byte a single time (a second full read of a multi-GB
    envelope at the disk edge would double exactly the ``recovery_s``
    wall-clock the bench gates)."""
    import jax.numpy as jnp

    fp = os.path.join(path, f"{name}.bin")
    shape = tuple(int(s) for s in desc["shape"])
    host = np.empty(shape, dtype=np.dtype(desc["dtype"]))
    view = memoryview(host).cast("B")
    try:
        with open(fp, "rb") as f:
            n = f.readinto(view)
            extra = f.read(1)
    except OSError as e:
        raise CheckpointCorrupt(f"{path}: entry {name!r} unreadable ({e})") from None
    if n != int(desc["nbytes"]) or extra:
        raise CheckpointCorrupt(
            f"{path}: entry {name!r} is {n}{'+' if extra else ''} B on disk, "
            f"{desc['nbytes']} B committed"
        )
    if verify:
        sha = hashlib.sha256()
        for off in range(0, n, SLAB_BYTES):
            sha.update(view[off:off + SLAB_BYTES])
        if sha.hexdigest() != desc["sha256"]:
            raise CheckpointCorrupt(
                f"{path}: entry {name!r} sha256 mismatch — bytes on disk do "
                "not match what was committed"
            )
    if desc["kind"] == "jax":
        if desc.get("split") == 0:
            from ..core import communication as _comm

            return _comm.get_comm().shard(jnp.asarray(host), 0)
        return jnp.asarray(host)
    return host


def _restore_entry(path: str, name: str, desc: Dict[str, Any]):
    """Rebuild one ``dnd`` entry ONTO THE CURRENT WORLD: a DNDarray
    re-sharded over however many devices the resolved world has now
    (the io.py per-device assembly — no global host array). Flat
    ``np``/``jax`` entries restore through :func:`_restore_flat_entry`
    instead."""
    from ..core import io as _io, types as _types

    fp = os.path.join(path, f"{name}.bin")
    shape = tuple(int(s) for s in desc["shape"])
    dtype = getattr(_types, desc["dtype"])
    np_dtype = _io._np_storage_dtype(dtype)

    def read_slab(sl):
        return _read_block(fp, shape, np_dtype, sl)

    return _io._assemble_sharded(
        read_slab, shape, dtype, desc["split"], None, None
    )


def _read_block(fp: str, shape, np_dtype, sl) -> np.ndarray:
    """One contiguous row-block of a row-major entry file (split 0 /
    replicated reads only — the write-side restriction's mirror)."""
    row_elems = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 else 1
    start = sl[0].start or 0
    stop = sl[0].stop if sl[0].stop is not None else shape[0]
    count = (stop - start) * row_elems
    block = np.fromfile(
        fp, dtype=np_dtype, count=count, offset=start * row_elems * np_dtype.itemsize
    )
    block = block.reshape((stop - start,) + tuple(shape[1:]))
    rest = tuple(sl[1:])
    return block[(slice(None),) + rest] if rest else block


def load(path: str, verify: bool = True) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load one committed envelope: ``(state, meta)``. ``state`` holds
    the restored arrays (re-sharded onto the current world) plus the
    scalar entries; tuples round-trip as tuples. ``verify`` re-hashes
    every entry first (:class:`CheckpointCorrupt` on any mismatch)."""
    meta = _read_meta(path)
    state: Dict[str, Any] = {}
    for name, desc in meta["entries"].items():
        if desc["kind"] in ("np", "jax"):
            # flat entries verify AND restore in one read
            state[name] = _restore_flat_entry(path, name, desc, verify)
        else:
            if verify:
                _verify_entry(path, name, desc)
            state[name] = _restore_entry(path, name, desc)
    for name, value in meta["scalars"].items():
        state[name] = tuple(value) if isinstance(value, list) else value
    if _telemetry._ENABLED:
        _telemetry.inc("resilience.ckpt.load")
    return state, meta


def restore_latest(directory: Optional[str] = None, *, tag: str
                   ) -> Optional[Tuple[int, Dict[str, Any], Dict[str, Any]]]:
    """The newest VALID committed checkpoint for ``tag``:
    ``(step, state, meta)``, or ``None`` when no step verifies. A
    truncated/corrupt newest step (the chaos harness's injection) falls
    back to its committed predecessor — corruption costs recency, never
    correctness."""
    directory = ckpt_dir(directory)
    for step in reversed(list_steps(directory, tag)):
        path = step_path(directory, tag, step)
        try:
            state, meta = load(path, verify=True)
        except CheckpointCorrupt:
            if _telemetry._ENABLED:
                _telemetry.inc("resilience.ckpt.corrupt")
            continue
        return step, state, meta
    return None
