"""Distributed regression (reference: /root/reference/heat/regression/)."""

from .lasso import *
