"""Lasso regression.

API parity with /root/reference/heat/regression/lasso.py (``Lasso`` :15:
coordinate-descent soft-threshold fit :121-172 using ``ht.matmul`` per
feature). Same cyclic coordinate descent here; each coordinate update is a
sharded matvec (one all-reduce when the sample axis is split).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional

from ..core import types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["Lasso"]


@functools.lru_cache(maxsize=64)
def _cd_program(m: int, max_iter: int):
    """Whole coordinate-descent fit as ONE compiled program: per-fit
    closures would recompile on every ``fit`` call, and baking lam/tol in
    as constants would recompile per regularization value — they are
    TRACED scalars, so a regularization-path sweep reuses one executable
    (jit retraces per operand shape/dtype, so neither needs a key).
    Sweeps run as a fori_loop over coordinates; convergence is a
    while_loop with the tol test on device (a host check per sweep costs
    a ~90 ms tunnel round trip)."""

    def sweep(X, yarr, col_msq, lam, th):
        def body(j, th):
            resid = yarr - X @ th + X[:, j] * th[j]
            rho = jnp.mean(X[:, j] * resid)
            denom = jnp.maximum(col_msq[j], 1e-30)
            unpenalized = rho / denom
            penalized = jnp.where(
                rho < -lam,
                (rho + lam) / denom,
                jnp.where(rho > lam, (rho - lam) / denom, 0.0),
            )
            new_j = jnp.where(j == 0, unpenalized, penalized)
            return th.at[j].set(new_j)

        return jax.lax.fori_loop(0, m, body, th)

    def run(X, yarr, col_msq, lam, tol, theta0):
        def cond(state):
            it, th, diff = state
            return (it < max_iter) & (diff >= tol)

        def body(state):
            it, th, _ = state
            nt = sweep(X, yarr, col_msq, lam, th)
            return (it + 1, nt, jnp.max(jnp.abs(nt - th)))

        return jax.lax.while_loop(
            cond, body, (0, theta0, jnp.asarray(jnp.inf, theta0.dtype))
        )

    return jax.jit(run)


class Lasso(BaseEstimator, RegressionMixin):
    """L1-regularized least squares via cyclic coordinate descent
    (reference: lasso.py:15). ``theta`` includes the intercept (feature 0,
    unpenalized), matching the reference."""

    def __init__(self, lam: Optional[float] = 0.1, max_iter: Optional[int] = 100, tol: Optional[float] = 1e-6):
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def lam(self) -> float:
        return self.__lam

    @lam.setter
    def lam(self, arg: float):
        self.__lam = arg

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    def soft_threshold(self, rho):
        """Soft-threshold operator (reference: lasso.py soft_threshold)."""
        if isinstance(rho, DNDarray):
            val = rho.larray
            out = jnp.where(val < -self.__lam, val + self.__lam, jnp.where(val > self.__lam, val - self.__lam, 0.0))
            return DNDarray(out, rho.shape, rho.dtype, rho.split, rho.device, rho.comm)
        if rho < -self.__lam:
            return rho + self.__lam
        if rho > self.__lam:
            return rho - self.__lam
        return 0.0

    def rmse(self, gt: DNDarray, yest: DNDarray) -> float:
        """Root mean squared error (reference: lasso.py rmse)."""
        diff = gt.larray.ravel() - yest.larray.ravel()
        return float(jnp.sqrt(jnp.mean(diff**2)))

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate-descent fit (reference: lasso.py:121-172)."""
        sanitize_in(x)
        sanitize_in(y)
        if x.ndim != 2:
            raise ValueError(f"x needs to be 2-dimensional, got {x.ndim}")
        if y.ndim > 2 or (y.ndim == 2 and y.shape[1] != 1):
            raise ValueError(f"y needs to be 1-D or (n, 1), got {y.shape}")

        arr = x.larray.astype(jnp.float32 if x.dtype is not types.float64 else jnp.float64)
        yarr = y.larray.reshape(-1).astype(arr.dtype)
        n, f = arr.shape
        # prepend intercept column
        X = jnp.concatenate([jnp.ones((n, 1), dtype=arr.dtype), arr], axis=1)
        m = f + 1
        theta = jnp.zeros((m,), dtype=arr.dtype)
        # mean-scale statistics: the reference thresholds the per-sample
        # mean correlation against lam (reference lasso.py:121-172), so lam
        # is sample-size independent
        col_msq = jnp.mean(X * X, axis=0)
        prog = _cd_program(m, int(self.max_iter))
        n_iter_dev, theta, _ = prog(
            X, yarr, col_msq,
            jnp.asarray(self.__lam, arr.dtype), jnp.asarray(self.tol, arr.dtype),
            theta,
        )
        self.n_iter = int(n_iter_dev)

        from ..core import factories

        self.__theta = factories.array(
            np.asarray(theta).reshape(-1, 1), comm=x.comm, device=x.device
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction with intercept (reference: lasso.py predict)."""
        sanitize_in(x)
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        theta = self.__theta.larray.reshape(-1)
        arr = x.larray.astype(theta.dtype)
        yest = arr @ theta[1:] + theta[0]
        gshape = (x.shape[0],)
        split = 0 if x.split is not None else None
        if split is not None:
            yest = x.comm.shard(yest, split)
        return DNDarray(
            yest, gshape, types.canonical_heat_type(yest.dtype), split, x.device, x.comm
        )
