"""Deep-learning layer of heat_tpu.

Parity with /root/reference/heat/nn/__init__.py: ``DataParallel`` /
``DataParallelMultiGPU`` plus a layer namespace. The reference delegates
unknown attributes to ``torch.nn`` (nn/__init__.py:19-47); here unknown
attributes resolve to ``flax.linen`` — the JAX ecosystem's layer zoo —
so e.g. ``ht.nn.Conv`` works without this package re-wrapping every layer.
"""

from .modules import (
    Module,
    Linear,
    MultiheadAttention,
    ReLU,
    GELU,
    Tanh,
    Sigmoid,
    LogSoftmax,
    Softmax,
    Flatten,
    Dropout,
    Dropout2d,
    Conv2d,
    MaxPool2d,
    AvgPool2d,
    LayerNorm,
    Embedding,
    Sequential,
    MSELoss,
    NLLLoss,
    CrossEntropyLoss,
)
from .data_parallel import DataParallel, DataParallelMultiGPU
from . import functional
from . import functional as F

__all__ = [
    "Module",
    "Linear",
    "MultiheadAttention",
    "ReLU",
    "GELU",
    "Tanh",
    "Sigmoid",
    "LogSoftmax",
    "Softmax",
    "Flatten",
    "Dropout",
    "Dropout2d",
    "Conv2d",
    "MaxPool2d",
    "AvgPool2d",
    "LayerNorm",
    "Embedding",
    "Sequential",
    "MSELoss",
    "NLLLoss",
    "CrossEntropyLoss",
    "DataParallel",
    "DataParallelMultiGPU",
    "functional",
    "F",
]


def __getattr__(name):
    """Delegate unknown layer names to flax.linen (the analog of the
    reference's torch.nn fallback, nn/__init__.py:19-47)."""
    import flax.linen as _linen

    try:
        return getattr(_linen, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn' has no attribute '{name}'")
from . import attention
from .attention import ring_attention, ring_self_attention
