"""Ring attention: sequence-parallel exact attention for long contexts.

The reference has NO attention stack; SURVEY §5 notes its long-context
mechanisms are exactly the ring-circulation pattern of
``spatial/distance._dist`` (distance.py:262-359). This module is the
TPU-native realization of that pattern for attention (Liu et al., Ring
Attention; the flash-attention online-softmax rescaling makes each ring
step exact): the SEQUENCE axis is sharded over the mesh, each device
keeps its Q block stationary, and K/V blocks circulate with
``lax.ppermute`` over ICI — per step the rotating block is consumed in
(Bq × chunk) attention tiles on the MXU while the next K/V block is in
flight. Memory per device is O(S·d / p + Bq·chunk) with chunk ≤ 1024
(``_RING_INNER_CHUNK``): no device ever holds the full S×S score matrix,
the full K/V, or even a full (Bq × Bk) block product, so sequence length
scales with the mesh without the score buffer growing as (S/p)².

Differentiable (scan + ppermute have transpose rules), causal-maskable,
and pad-safe: logical sequence lengths propagate through the masks so
uneven shards never contribute.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from ..core._jax_compat import pcast, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from typing import Optional

from ..core.dndarray import DNDarray
from ..core.communication import register_mesh_cache
from ..core import types

__all__ = ["ring_attention", "ring_self_attention"]


def _online_softmax_update(q, k_c, v_c, o, m, l, valid, scale, neg):
    """One flash-attention accumulation step, shared by the ring program
    (distributed) and the blocked program (single device) so the two paths
    cannot numerically diverge: masked scores → running-max rescale →
    (o, m, l) update."""
    s = jnp.einsum("...qd,...kd->...qk", q, k_c) * jnp.asarray(scale, q.dtype)
    s = jnp.where(valid, s, neg)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    pexp = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(pexp, axis=-1, keepdims=True)
    o = o * corr + jnp.einsum("...qk,...kd->...qd", pexp, v_c)
    return o, m_new, l


# upper bound on the K/V sub-chunk each inner attention tile works on:
# the per-ring-step score buffer is (..., bq, min(bk, CHUNK)) instead of
# (..., bq, bk) — the einsum materializes scores over ALL leading
# batch/head dims at once, so at the 1M-token/64-chip north star
# (B=1, H=8, bk=16384, bf16) the naive block product is a 4 GB live
# buffer per step (16 GB in f32); chunked it is 256 MB.
_RING_INNER_CHUNK = 1024


def _ring_attention_program(
    mesh: Mesh,
    axis_name: str,
    ndim: int,
    seq_axis: int,
    n_q: int,
    n_kv: int,
    causal: bool,
    scale: float,
    jdtype: str,
    inner_chunk: Optional[int] = None,
):
    """Normalizing entry point for the cached blocked-ring builder: the
    lru_cache keys on the positional signature, so a defaulted call and
    an explicit-same-value call would otherwise compile the identical
    program twice (ADVICE r4). All callers go through here."""
    return _ring_attention_program_cached(
        mesh, axis_name, int(ndim), int(seq_axis), int(n_q), int(n_kv),
        bool(causal), float(scale), str(jdtype),
        _RING_INNER_CHUNK if inner_chunk is None else int(inner_chunk),
    )


@functools.lru_cache(maxsize=64)
def _ring_attention_program_cached(
    mesh: Mesh,
    axis_name: str,
    ndim: int,
    seq_axis: int,
    n_q: int,
    n_kv: int,
    causal: bool,
    scale: float,
    jdtype: str,
    inner_chunk: int,
):
    """One jitted shard_map program: stationary Q block, K/V rotating the
    ring, online-softmax (m, l, o) accumulation per step; within a step
    the rotating block is consumed in ``inner_chunk``-sized tiles (same
    blocked schedule as the single-device program) so live memory is
    O(bq·chunk), independent of the per-device block size."""
    p = mesh.devices.size
    spec = P(*(axis_name if i == seq_axis else None for i in range(ndim)))
    neg = jnp.finfo(jnp.dtype(jdtype)).min

    def body(q, k, v):
        r = lax.axis_index(axis_name)
        bq = q.shape[seq_axis]
        bk = k.shape[seq_axis]
        chunk = max(1, min(int(inner_chunk), bk))
        n_inner = -(-bk // chunk)
        pad_inner = n_inner * chunk - bk
        if pad_inner:
            # pad ONCE before the ring; rotations carry the padded block
            # (bounded overhead: < chunk/bk extra ICI bytes) and the
            # lidx < bk mask below keeps pad rows out of the softmax
            widths = [(0, 0)] * ndim
            widths[-2] = (0, pad_inner)
            k = jnp.pad(k, widths)
            v = jnp.pad(v, widths)
        # canonical layout (..., B, D): seq axis at -2 already by caller
        q_pos = (r * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)).astype(jnp.int32)

        # constant-initialized carry entries must be marked device-varying:
        # they mix with the rotating (varying) K/V blocks inside the scan.
        # o accumulates into V's head dim (which may differ from q's)
        o0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), dtype=q.dtype)
        m0 = jnp.full(q.shape[:-1] + (1,), neg, dtype=q.dtype)
        l0 = jnp.zeros(q.shape[:-1] + (1,), dtype=q.dtype)
        if p > 1:
            o0 = pcast(o0, axis_name, to="varying")
            m0 = pcast(m0, axis_name, to="varying")
            l0 = pcast(l0, axis_name, to="varying")
        k0, v0 = k, v

        def step(carry, t):
            k_cur, v_cur, o, m, l = carry
            src = (r + t) % p

            def tile(c2, j):
                o, m, l = c2
                k_c = lax.dynamic_slice_in_dim(k_cur, j * chunk, chunk, axis=-2)
                v_c = lax.dynamic_slice_in_dim(v_cur, j * chunk, chunk, axis=-2)
                lidx = j * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
                k_pos = (src * bk + lidx).astype(jnp.int32)
                # lidx < bk masks the inner-chunk pad; k_pos < n_kv the
                # global sequence pad
                valid = (lidx < bk) & (k_pos < n_kv)
                if causal:
                    valid = valid & (k_pos <= q_pos)
                o, m, l = _online_softmax_update(q, k_c, v_c, o, m, l, valid, scale, neg)
                return (o, m, l), None

            if n_inner == 1:
                (o, m, l), _ = tile((o, m, l), 0)
            else:
                (o, m, l), _ = lax.scan(tile, (o, m, l), jnp.arange(n_inner))
            perm = [((i + 1) % p, i) for i in range(p)]
            k_nxt = lax.ppermute(k_cur, axis_name, perm) if p > 1 else k_cur
            v_nxt = lax.ppermute(v_cur, axis_name, perm) if p > 1 else v_cur
            return (k_nxt, v_nxt, o, m, l), None

        (_, _, o, m, l), _ = lax.scan(step, (k0, v0, o0, m0, l0), jnp.arange(p))
        # normalize; zero q pad rows explicitly (they attend to valid keys
        # and would otherwise carry garbage into the pad region)
        keep = (q_pos < n_q) & (l > 0)  # (..., bq, 1): broadcasts over D
        o = jnp.where(keep, o / jnp.where(l > 0, l, 1.0), 0.0)
        return o

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _blocked_attention_program(
    q_shape, k_shape, v_shape, causal: bool, scale: float, jdtype: str
):
    """Single-device flash-style attention: ``lax.scan`` over K/V chunks
    with the same online-softmax accumulation the ring uses — one
    (S, chunk) tile live at a time instead of the full (S, S) scores."""
    S_kv = k_shape[-2]
    chunk = max(1, min(1024, S_kv))
    n_chunks = max(1, -(-S_kv // chunk))
    pad = n_chunks * chunk - S_kv
    neg = jnp.finfo(jnp.dtype(jdtype)).min

    def run(q, k, v):
        if pad:
            widths_k = [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)]
            k = jnp.pad(k, widths_k)
            v = jnp.pad(v, widths_k)
        S_q = q.shape[-2]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (S_q, 1), 0)
        # (chunks, ..., chunk, d) leading scan axis
        ks = jnp.moveaxis(
            k.reshape(k.shape[:-2] + (n_chunks, chunk, k.shape[-1])), -3, 0
        )
        vs = jnp.moveaxis(
            v.reshape(v.shape[:-2] + (n_chunks, chunk, v.shape[-1])), -3, 0
        )

        o0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), dtype=q.dtype)
        m0 = jnp.full(q.shape[:-1] + (1,), neg, dtype=q.dtype)
        l0 = jnp.zeros(q.shape[:-1] + (1,), dtype=q.dtype)

        def step(carry, blk):
            o, m, l, idx = carry
            k_c, v_c = blk
            k_pos = idx * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
            valid = k_pos < S_kv
            if causal:
                valid = valid & (k_pos <= q_pos)
            o, m, l = _online_softmax_update(q, k_c, v_c, o, m, l, valid, scale, neg)
            return (o, m, l, idx + 1), None

        (o, _, l, _), _ = lax.scan(step, (o0, m0, l0, jnp.int32(0)), (ks, vs))
        return jnp.where(l > 0, o / jnp.where(l > 0, l, 1.0), 0.0)

    return jax.jit(run)


# set only on import-level failure (kernel module unavailable); a shape
# whose kernel cannot compile is cached as None per-signature instead
_PALLAS_ATTENTION_UNAVAILABLE = False
_SPLASH_ATTENTION_UNAVAILABLE = False

# tests force Mosaic interpret mode so the kernel ring path runs (slowly)
# on CPU meshes; production leaves this False and the path is TPU-gated
_RING_KERNEL_INTERPRET = False

# tests force the scan-with-carry ring body (the f32/flash hardware
# composition) on CPU meshes, where the unrolled body would otherwise be
# the only one CI ever compiles; build-time flag — clear the builder
# caches after flipping it
_RING_KERNEL_FORCE_SCAN = False


def _pick_block(n: int, candidates) -> Optional[int]:
    """Largest candidate block size that divides n, else None."""
    return next((c for c in candidates if n % c == 0), None)


@functools.lru_cache(maxsize=64)
def _ring_step_kernels(
    b: int, h: int, bq: int, bk: int, d: int,
    scale: float, jdtype: str, interpret: bool,
):
    """Per-ring-step Pallas kernel pair ``(full_fn, diag_fn)`` for one
    block signature, or None when no kernel serves it.

    Each fn maps raw (B, H, bq|bk, D) blocks to ``(out, lse)`` where
    ``out`` is the NORMALIZED attention output of q against that K/V
    block alone and ``lse`` is its float32 logsumexp — the save-residuals
    form that lets the ring combine per-step results exactly
    (o = Σ_i exp(lse_i − LSE)·out_i). ``diag_fn`` applies the causal mask
    for the block on the ring diagonal (src == r, requires bq == bk);
    ``full_fn`` is unmasked for blocks strictly behind the query block.

    bf16 → splash kernel (the 0.684-MFU single-device carrier, which
    computes in bf16 anyway); f32 → the flash kernel via its residual
    form (keeps f32 exactness, no interpret mode). Build failures are
    cached as None and the blocked XLA ring stays the fallback/oracle.
    """
    jt = jnp.dtype(jdtype)
    if jt == jnp.bfloat16 or (interpret and jt == jnp.float32):
        if _SPLASH_ATTENTION_UNAVAILABLE:
            return None
        bq_blk = _pick_block(bq, (1024, 512, 256, 128))
        bkv_blk = _pick_block(bk, (2048, 1024, 512, 256, 128))
        if bq_blk is None or bkv_blk is None or d % 64 != 0:
            return None
        try:
            full_fn = _build_splash_mha(
                h, bq, bk, False, scale, bq_blk, bkv_blk, True, interpret
            )
            diag_fn = (
                _build_splash_mha(
                    h, bq, bq, True, scale, bq_blk, bq_blk, True, interpret
                )
                if bq == bk
                else None
            )
        except Exception:
            return None
        return (full_fn, diag_fn)

    if jt == jnp.float32 and not interpret:
        if _PALLAS_ATTENTION_UNAVAILABLE:
            return None
        try:
            import jax.experimental.pallas.ops.tpu.flash_attention as _fa
        except Exception:
            return None
        bq_blk = _pick_block(bq, (1024, 512, 256, 128))
        bkm = _pick_block(bk, (2048, 1024, 512, 256, 128))
        bk_blk = _pick_block(bk, (1024, 512, 256, 128))
        if None in (bq_blk, bkm, bk_blk) or d % 64 != 0:
            return None

        def build(causal_blk: bool):
            def run(qa, ka, va):
                # keyword-bind everything after the arrays: the impl is
                # underscore-private, and a signature drift must fail
                # loudly (TypeError → cached None) rather than bind
                # positionally and compute wrong residuals
                o, l, m = _fa._flash_attention_impl(
                    qa, ka, va, None, None,
                    save_residuals=True, causal=causal_blk,
                    sm_scale=float(scale), block_b=1, block_q=bq_blk,
                    block_k_major=bkm, block_k=bk_blk, debug=False,
                )
                # full/diag blocks always have ≥1 valid key per row, l > 0
                return o, (m + jnp.log(l)).astype(jnp.float32)

            return run

        return (build(False), build(True) if bq == bk else None)

    return None


@functools.lru_cache(maxsize=64)
def _ring_attention_kernel_callable(
    mesh: Mesh,
    axis_name: str,
    n_q: int,
    n_kv: int,
    b: int,
    h: int,
    d: int,
    causal: bool,
    scale: float,
    jdtype: str,
    interpret: bool,
):
    """TRACEABLE shard_map form of the kernel-backed ring attention: the
    same stationary-Q / rotating-K,V ppermute schedule as
    ``_ring_attention_program``, but each ring step runs a fused Pallas
    kernel (splash for bf16, flash for f32) instead of the blocked XLA
    online-softmax — so sharded-sequence attention keeps kernel-level
    MFU. The per-step results combine exactly via their logsumexp
    residuals (f32 accumulator); for causal masks a 3-way ``lax.switch``
    schedules each step as skip (block strictly ahead of the queries),
    diagonal (causal-masked kernel), or full (unmasked).

    Returns None when the signature has no serving kernel (odd blocks,
    non-divisible shards, unavailable kernel module). Dispatch goes
    through the AOT ``_ring_attention_kernel_program``; bench loops this
    traceable form inside a fori_loop for the device-rate ring row.
    """
    p = mesh.devices.size
    if n_q % p or n_kv % p:
        return None  # physical pad rows would need masks the kernels lack
    bq, bk = n_q // p, n_kv // p
    if causal and bq != bk:
        return None
    kernels = _ring_step_kernels(b, h, bq, bk, d, float(scale), jdtype, interpret)
    if kernels is None:
        return None
    full_fn, diag_fn = kernels
    if causal and diag_fn is None:
        return None
    spec = P(None, None, axis_name, None)
    jt = jnp.dtype(jdtype)
    neg_inf = jnp.float32(-jnp.inf)
    # Composition is gated by kernel family (empirical Mosaic constraint
    # on this toolchain): the splash kernel compiles under shard_map in
    # ANY composition, so bf16 takes the faster UNROLLED body; the flash
    # kernel under shard_map only compiles inside a scan-with-carry
    # region (direct call, 2/3-branch switch without scan, and scan
    # without array carry all crash the TPU compile helper), so f32
    # keeps the scan+switch body.
    unrolled = (
        jt == jnp.bfloat16 or (interpret and jt == jnp.float32)
    ) and not _RING_KERNEL_FORCE_SCAN

    def body_unrolled(q, k, v):
        # UNROLLED over the (static) ring length: t=0 ASSIGNS the first
        # kernel result instead of combining against a -inf carry (one
        # whole output pass saved — measured ~0.4 ms at 16k/p=1, the
        # bulk of the wrapper overhead vs the bare kernel), the causal
        # diagonal kernel is chosen statically at t=0 (src == r exactly
        # when t == 0), and the final wasted K/V rotation is skipped
        # (p-1 hops, not p). XLA can also pipeline hop t+1 against
        # kernel t — the overlap the ring schedule exists for.
        r = lax.axis_index(axis_name)
        perm = [((i + 1) % p, i) for i in range(p)]
        k_cur, v_cur = k, v
        o = lse = None
        for t in range(p):
            if t == 0:
                out_i, lse_i = (diag_fn if causal else full_fn)(q, k_cur, v_cur)
                o, lse = out_i.astype(jnp.float32), lse_i
            else:
                if causal:
                    # src = (r+t) % p != r here: only full (src strictly
                    # behind the queries) or skip (strictly ahead)
                    def run_skip(qa, ka, va):
                        return (
                            jnp.zeros((b, h, bq, d), dtype=jt),
                            jnp.full((b, h, bq), neg_inf, dtype=jnp.float32),
                        )

                    src = (r + t) % p
                    out_i, lse_i = lax.switch(
                        jnp.where(src < r, 1, 0).astype(jnp.int32),
                        (run_skip, lambda qa, ka, va: full_fn(qa, ka, va)),
                        q, k_cur, v_cur,
                    )
                else:
                    out_i, lse_i = full_fn(q, k_cur, v_cur)
                lse_new = jnp.logaddexp(lse, lse_i)
                # skip steps carry lse_i = -inf; lse is finite from t=0
                # (causal t=0 is the diagonal), so lse_new stays finite
                # and exp(lse_i - lse_new) cleanly gives beta = 0
                alpha = jnp.exp(lse - lse_new)
                beta = jnp.exp(lse_i - lse_new)
                o = o * alpha[..., None] + out_i.astype(jnp.float32) * beta[..., None]
                lse = lse_new
            if t < p - 1:
                k_cur = lax.ppermute(k_cur, axis_name, perm)
                v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o.astype(jt)

    def body_scan(q, k, v):
        r = lax.axis_index(axis_name)
        o0 = jnp.zeros((b, h, bq, d), dtype=jnp.float32)
        lse0 = jnp.full((b, h, bq), neg_inf, dtype=jnp.float32)

        def step(carry, t):
            k_cur, v_cur, o, lse = carry
            src = (r + t) % p
            if causal:
                def run_skip(qa, ka, va):
                    return (
                        jnp.zeros((b, h, bq, d), dtype=jt),
                        jnp.full((b, h, bq), neg_inf, dtype=jnp.float32),
                    )

                idx = jnp.where(src == r, 1, jnp.where(src < r, 2, 0))
                out_i, lse_i = lax.switch(
                    idx, (run_skip, diag_fn, full_fn), q, k_cur, v_cur
                )
            else:
                out_i, lse_i = full_fn(q, k_cur, v_cur)
            lse_new = jnp.logaddexp(lse, lse_i)
            # both-(-inf) cannot happen causally (t=0 is the diagonal),
            # but keep the combine total: exp(-inf − -inf) would be NaN
            dead = jnp.isneginf(lse_new)
            alpha = jnp.where(dead, 0.0, jnp.exp(lse - lse_new))
            beta = jnp.where(dead, 0.0, jnp.exp(lse_i - lse_new))
            o = o * alpha[..., None] + out_i.astype(jnp.float32) * beta[..., None]
            k_nxt = lax.ppermute(k_cur, axis_name, perm_all) if p > 1 else k_cur
            v_nxt = lax.ppermute(v_cur, axis_name, perm_all) if p > 1 else v_cur
            return (k_nxt, v_nxt, o, lse_new), None

        perm_all = [((i + 1) % p, i) for i in range(p)]
        (_, _, o, _), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(p))
        return o.astype(jt)

    body = body_unrolled if unrolled else body_scan

    # check_vma=False: pallas_call outputs carry no varying-mesh-axes
    # annotation, which the vma checker rejects inside shard_map
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )


@functools.lru_cache(maxsize=64)
def _ring_attention_kernel_program(
    mesh: Mesh,
    axis_name: str,
    n_q: int,
    n_kv: int,
    b: int,
    h: int,
    d: int,
    causal: bool,
    scale: float,
    jdtype: str,
    interpret: bool,
):
    """AOT-compiled executable of ``_ring_attention_kernel_callable``,
    lowered against the exact shardings dispatch guarantees (the DNDarray
    physical layout) — same rationale as ``_pallas_attention_program``: a
    per-signature Mosaic failure surfaces here, once, and is cached as
    None; it can never be re-paid at every ring_attention call."""
    fn = _ring_attention_kernel_callable(
        mesh, axis_name, n_q, n_kv, b, h, d, causal, scale, jdtype, interpret
    )
    if fn is None:
        return None
    seq_axis = 2
    spec = P(*(axis_name if i == seq_axis else None for i in range(4)))
    jt = jnp.dtype(jdtype)
    sh = NamedSharding(mesh, spec)
    try:
        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct((b, h, n_q, d), jt, sharding=sh),
            jax.ShapeDtypeStruct((b, h, n_kv, d), jt, sharding=sh),
            jax.ShapeDtypeStruct((b, h, n_kv, d), jt, sharding=sh),
        ).compile()
    except Exception:
        return None


def _ring_kernel_eligible(qp, kp, vp, ndim: int, seq_axis: int, jt) -> bool:
    """Dispatch gate for the kernel ring: concrete 4-D (B, H, S, D)
    self-attention-shaped operands on the TPU backend (or interpret mode
    for tests), matching head dims, x64 off. Shape/divisibility gates
    live in the program builder, which caches None per signature."""
    if not (_RING_KERNEL_INTERPRET or jax.default_backend() == "tpu"):
        return False
    if jax.config.jax_enable_x64 and not _RING_KERNEL_INTERPRET:
        # hardware kernels mis-trace under forced x64 (same gate as
        # _pallas_attention); interpret mode traces cleanly regardless
        return False
    if any(isinstance(t, jax.core.Tracer) for t in (qp, kp, vp)):
        # user jit/grad trace: only the blocked ring is guaranteed
        # differentiable (the save-residuals combine is forward-only)
        return False
    if ndim != 4 or seq_axis != 2:
        return False
    if qp.shape[-1] != vp.shape[-1]:
        return False
    return jnp.dtype(jt) in (jnp.bfloat16, jnp.float32)


def _build_splash_mha(
    h: int, sq: int, skv: int, causal: bool, scale: float,
    block_q: int, block_kv: int, save_residuals: bool, interpret: bool,
):
    """Shared splash-kernel assembly (mask, BlockSizes, pre-scaled-q vmap
    wrapper) behind both the single-device callable and the ring step
    kernels — the splash configuration lives in exactly one place.
    Splash takes a PRE-SCALED q (no sm_scale parameter). Raises on
    import/shape failure; callers cache None."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as _sk,
        splash_attention_mask as _sm,
    )

    kv_comp = min(1024, block_kv)
    bs = _sk.BlockSizes(
        block_q=block_q, block_kv=block_kv, block_kv_compute=kv_comp,
        block_q_dkv=block_q, block_kv_dkv=block_kv,
        block_kv_dkv_compute=kv_comp,
        block_q_dq=block_q, block_kv_dq=block_kv,
    )
    mask = _sm.MultiHeadMask(
        [
            _sm.CausalMask((sq, skv)) if causal else _sm.FullMask((sq, skv))
            for _ in range(h)
        ]
    )
    kern = _sk.make_splash_mha_single_device(
        mask=mask, block_sizes=bs, save_residuals=save_residuals,
        interpret=interpret,
    )

    def run(qa, ka, va):
        qs = (qa * qa.dtype.type(scale)).astype(qa.dtype)
        out = jax.vmap(kern)(qs, ka, va)
        if not save_residuals:
            return out
        o, res = out
        lse = res[0] if isinstance(res, tuple) else res
        return o, lse.astype(jnp.float32)

    return run


@functools.lru_cache(maxsize=64)
def _splash_callable(q_shape, kv_shape, causal: bool, scale: float, jdtype: str):
    """TRACEABLE splash-attention callable (the newer production TPU
    kernel family), or None when it cannot serve the signature. Measured
    on v5e at S=16k/D=128/causal bf16: ~0.68-0.70 MFU vs the flash
    kernel's ~0.60-0.67 across a block sweep (docs/PERF.md records the
    sweep) — splash is preferred, flash is the fallback, the blocked XLA
    program stays the oracle. Splash takes a PRE-SCALED q (no sm_scale
    parameter), applied inside the compiled program. bench.py loops this
    callable inside a fori_loop for the stable device-rate row; dispatch
    uses the AOT ``_splash_attention_program``."""
    global _SPLASH_ATTENTION_UNAVAILABLE
    if _SPLASH_ATTENTION_UNAVAILABLE:
        return None

    if jnp.dtype(jdtype) != jnp.bfloat16:
        # splash runs its matmuls in bf16 regardless of input dtype
        # (measured f32 rel-err ~3e-3 vs the blocked oracle, where the
        # flash kernel keeps ~2e-7): f32 callers get flash's exactness
        return None
    b, h, sq, d = q_shape
    skv = kv_shape[-2]
    if sq % 1024 != 0:
        return None  # v5e-tuned 1024 q-blocks; other shapes use flash
    bkv = 2048 if skv % 2048 == 0 else 1024
    if skv % bkv != 0:
        return None
    try:
        return _build_splash_mha(h, sq, skv, causal, scale, 1024, bkv, False, False)
    except ImportError:
        _SPLASH_ATTENTION_UNAVAILABLE = True
        return None
    except Exception:
        return None


@functools.lru_cache(maxsize=64)
def _splash_attention_program(q_shape, kv_shape, causal: bool, scale: float, jdtype: str):
    """AOT-compiled executable of ``_splash_callable`` (same rationale as
    ``_pallas_attention_program``: per-shape Mosaic failures surface here,
    once, never at dispatch)."""
    run = _splash_callable(q_shape, kv_shape, causal, scale, jdtype)
    if run is None:
        return None
    try:
        jt = jnp.dtype(jdtype)
        return jax.jit(run).lower(
            jax.ShapeDtypeStruct(q_shape, jt),
            jax.ShapeDtypeStruct(kv_shape, jt),
            jax.ShapeDtypeStruct(kv_shape, jt),
        ).compile()
    except Exception:
        return None


@functools.lru_cache(maxsize=64)
def _pallas_attention_program(q_shape, kv_shape, causal: bool, scale: float, jdtype: str):
    """AOT-compiled Mosaic (Pallas) flash-attention executable for one
    signature, or None if the kernel cannot compile for it (VMEM overflow
    etc.) — the failure is cached so the signature is probed exactly once,
    and other signatures keep the kernel. Compiling here means a per-shape
    Mosaic error can never surface at dispatch time (dispatch only happens
    on concrete arrays; traced calls are gated to the blocked program)."""
    global _PALLAS_ATTENTION_UNAVAILABLE
    if _PALLAS_ATTENTION_UNAVAILABLE:
        return None
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention,
        )
    except Exception:
        _PALLAS_ATTENTION_UNAVAILABLE = True
        return None

    sq, skv = q_shape[-2], kv_shape[-2]
    # v5e-tuned tiles (interleaved sweep: ~1.4x over the blocked XLA
    # program at S=4096); clamp to divisors of the sequence length
    bq = 1024 if sq % 1024 == 0 else 512
    bkm = 2048 if skv % 2048 == 0 else (1024 if skv % 1024 == 0 else 512)
    bk = 1024 if skv % 2048 == 0 else 512
    bs = BlockSizes(
        block_q=bq, block_k_major=bkm, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkm, block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bkm, block_k_dq=bk, block_q_dq=bq,
    )

    def run(qa, ka, va):
        # x64 is off on TPU by platform policy (devices._apply_x64_policy),
        # so the kernel's int32 block-index maps trace cleanly; the
        # forced-x64 configuration is gated out in _pallas_attention
        return flash_attention(
            qa, ka, va, causal=causal, sm_scale=float(scale), block_sizes=bs
        )

    try:
        jt = jnp.dtype(jdtype)
        # the AOT Compiled executable is what gets called — compiling once
        # and dispatching through jit would compile the kernel a second
        # time (AOT lowering does not populate jit's dispatch cache)
        return jax.jit(run).lower(
            jax.ShapeDtypeStruct(q_shape, jt),
            jax.ShapeDtypeStruct(kv_shape, jt),
            jax.ShapeDtypeStruct(kv_shape, jt),
        ).compile()
    except Exception:
        return None


def _pallas_attention_fits(q_shape, k_shape, v_shape, dtype) -> bool:
    """Backend-independent tiling gate for the flash kernel: 4-D f32/bf16
    self-attention with 512-multiple sequence length and lane-aligned
    (64-multiple) head dim, q/k/v agreeing on batch/head/seq dims."""
    if len(q_shape) != 4 or jnp.dtype(dtype) not in (jnp.float32, jnp.bfloat16):
        return False
    b, h, sq, d = q_shape
    skv = k_shape[-2]
    return (
        tuple(k_shape) == (b, h, skv, d)
        and tuple(v_shape) == (b, h, skv, d)
        and sq == skv
        and sq % 512 == 0
        and d % 64 == 0
    )


def _pallas_attention(qa, ka, va, causal: bool, scale: float):
    """Mosaic (Pallas) fused flash-attention kernel for the single-device
    path — the native-kernel realization of the same online-softmax
    algorithm (one (Bq, Bk) tile in VMEM at a time). Returns None when the
    workload does not fit the kernel's tiling constraints; the blocked
    XLA program is the fallback and the numerical oracle."""
    if jax.default_backend() != "tpu":
        return None
    if jax.config.jax_enable_x64:
        # explicitly-forced x64 on TPU: the kernel's block-index maps mix
        # int32 iotas with Python ints and mis-trace in x64 mode — the
        # blocked XLA program serves this configuration
        return None
    if any(isinstance(t, jax.core.Tracer) for t in (qa, ka, va)):
        # inside a user jit/grad trace: only the blocked program is
        # guaranteed differentiable and compilable — the flash kernel's
        # dkv/dq backward kernels are never AOT-probed here
        return None
    if not _pallas_attention_fits(qa.shape, ka.shape, va.shape, qa.dtype):
        return None
    # the Compiled executable is lowered for default-device placement;
    # operands living elsewhere (explicit device_put, multi-chip sharding)
    # take the jitted blocked program, which places freely
    try:
        devs = {d for t in (qa, ka, va) for d in t.devices()}
    except Exception:
        return None
    if devs != {jax.devices()[0]}:
        return None
    # splash preferred (measured faster on v5e, see _splash_attention_program),
    # flash kernel as fallback, blocked XLA program as the oracle
    prog = _splash_attention_program(
        tuple(qa.shape), tuple(ka.shape), bool(causal), float(scale),
        np.dtype(qa.dtype).name,
    ) or _pallas_attention_program(
        tuple(qa.shape), tuple(ka.shape), bool(causal), float(scale),
        np.dtype(qa.dtype).name,
    )
    if prog is None:
        return None
    try:
        return prog(qa, ka, va)
    except Exception:
        # placement/runtime mismatch the gates missed — blocked fallback
        return None


def _single_device_attention(qa, ka, va, causal: bool, scale):
    """Shared single-device flash attention on raw jax arrays: non-inexact
    dtypes promote to float32, the default scale is 1/sqrt(d), and the
    blocked program runs — the ONE code path behind both ring_attention's
    single-device branch and functional.scaled_dot_product_attention's
    raw-array route (divergence here would mean same inputs, different
    numerics depending on the array wrapper)."""
    jt = qa.dtype if jnp.issubdtype(qa.dtype, jnp.inexact) else jnp.dtype(jnp.float32)
    qa, ka, va = (t.astype(jt) for t in (qa, ka, va))
    if scale is None:
        scale = 1.0 / float(np.sqrt(qa.shape[-1]))
    out = _pallas_attention(qa, ka, va, bool(causal), float(scale))
    if out is not None:
        return out
    prog = _blocked_attention_program(
        tuple(qa.shape), tuple(ka.shape), tuple(va.shape),
        bool(causal), float(scale), np.dtype(jt).name,
    )
    return prog(qa, ka, va)


def ring_attention(
    q: DNDarray,
    k: DNDarray,
    v: DNDarray,
    causal: bool = False,
    scale: Optional[float] = None,
) -> DNDarray:
    """Exact scaled-dot-product attention with the sequence axis sharded
    over the mesh (sequence parallelism for long contexts).

    ``q``/``k``/``v``: (..., S, D) DNDarrays split along the S axis
    (axis -2). Output matches q's shape and sharding. Unsplit inputs run
    the same program on a size-1 ring (plain flash-style attention).
    """
    for name, t in (("q", q), ("k", k), ("v", v)):
        if not isinstance(t, DNDarray):
            raise TypeError(f"{name} must be a DNDarray, got {type(t)}")
        if t.ndim < 2:
            raise ValueError(f"{name} needs at least (S, D) dims, got {t.ndim}")
    seq_axis = q.ndim - 2
    if q.split not in (None, seq_axis) or k.split not in (None, seq_axis) or v.split not in (None, seq_axis):
        raise ValueError(
            f"ring_attention shards the sequence axis ({seq_axis}); got splits "
            f"{q.split}/{k.split}/{v.split} — resplit the operands first"
        )
    if k.shape[:-1] != v.shape[:-1]:
        raise ValueError(
            f"k and v must agree on batch/sequence dims, got {k.shape} vs {v.shape}"
        )
    if q.shape[-1] != k.shape[-1]:
        raise ValueError(f"q and k head dims must agree, got {q.shape[-1]} vs {k.shape[-1]}")
    if q.gshape[:-2] != k.gshape[:-2]:
        raise ValueError(
            f"q and k batch dims must agree, got {q.gshape[:-2]} vs {k.gshape[:-2]}"
        )
    out_gshape = q.gshape[:-1] + (v.gshape[-1],)
    dtype = q.dtype if types.heat_type_is_inexact(q.dtype) else types.float32
    jt = dtype.jax_type()
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))

    comm = q.comm
    if comm.size == 1 or q.split is None:
        # single device / replicated q: blocked flash-style attention —
        # the dense formulation would materialize the (B, H, S, S) score
        # tensor (2 GB at S=4k), the blocked scan keeps it one tile
        # raw logical arrays: the helper owns promotion (same rule that
        # produced jt), so its policy is authoritative for BOTH routes
        out = _single_device_attention(q.larray, k.larray, v.larray, causal, scale)
        return DNDarray(
            comm.shard(out, q.split), out_gshape, dtype, q.split, q.device, comm
        )

    qp = q._phys.astype(jt) if q.split == seq_axis else comm.shard(q.larray.astype(jt), seq_axis)
    kp = k._phys.astype(jt) if k.split == seq_axis else comm.shard(k.larray.astype(jt), seq_axis)
    vp = v._phys.astype(jt) if v.split == seq_axis else comm.shard(v.larray.astype(jt), seq_axis)
    if _ring_kernel_eligible(qp, kp, vp, q.ndim, seq_axis, jt):
        kprog = _ring_attention_kernel_program(
            comm.mesh, comm.axis_name, q.shape[seq_axis], k.shape[seq_axis],
            q.shape[0], q.shape[1], q.shape[-1], bool(causal), float(scale),
            np.dtype(jt).name, _RING_KERNEL_INTERPRET,
        )
        if kprog is not None:
            try:
                out_phys = kprog(qp, kp, vp)
            except Exception:
                out_phys = None  # Mosaic runtime miss the gates can't see
            if out_phys is not None:
                return DNDarray(out_phys, out_gshape, dtype, seq_axis, q.device, comm)
    prog = _ring_attention_program(
        comm.mesh, comm.axis_name, q.ndim, seq_axis,
        q.shape[seq_axis], k.shape[seq_axis], bool(causal), float(scale),
        np.dtype(jt).name,
    )
    out_phys = prog(qp, kp, vp)
    return DNDarray(out_phys, out_gshape, dtype, seq_axis, q.device, comm)


def ring_self_attention(x: DNDarray, causal: bool = False, scale: Optional[float] = None) -> DNDarray:
    """Self-attention convenience: q = k = v = x."""
    return ring_attention(x, x, x, causal=causal, scale=scale)


# programs bake the mesh: clear on init_distributed world rebuilds
register_mesh_cache(_ring_attention_program_cached)
register_mesh_cache(_ring_attention_kernel_callable)
register_mesh_cache(_ring_attention_kernel_program)
