"""Data-parallel model wrapper.

Replaces /root/reference/heat/nn/data_parallel.py:21-310 (``DataParallel``):
the reference registers a backward hook on every parameter that issues a
(blocking or non-blocking) ``Allreduce`` of the gradient, plus
forward-pre-hooks that ``Wait`` on the previous iteration's handles — a
hand-built overlap scheme. On TPU none of that machinery exists: the model
parameters live REPLICATED on the mesh, the batch is sharded along axis 0,
and the gradient of a mean-over-global-batch loss is automatically
all-reduced by GSPMD inside the one jitted train step
(see ``heat_tpu.optim.DataParallelOptimizer``). XLA overlaps the emitted
collectives with compute on its own — the reference's wait-handle choreography
(data_parallel.py:239-295) has no analog because it is not needed.

``DataParallelMultiGPU`` (reference data_parallel.py:312: torch-DDP
node-local + DASO global) maps to the two-level mesh inside
``heat_tpu.optim.DASO``; the class here is a thin alias wiring the model to
a DASO optimizer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from typing import Optional

from ..core import types
from ..core.communication import sanitize_comm
from ..core.dndarray import DNDarray
from .modules import Module

__all__ = ["DataParallel", "DataParallelMultiGPU"]


class DataParallel:
    """Holds a module plus its parameters, replicated over the mesh.

    Parameters
    ----------
    module : Module
        The functional module (init/apply).
    comm : Communication, optional
        Device mesh; defaults to the global communicator.
    key : int or jax.Array, optional
        PRNG seed for parameter initialization.

    The reference signature ``DataParallel(module, comm, optimizer,
    blocking_parameter_updates)`` couples model and optimizer because the
    grad hooks must reach into the optimizer; here the optimizer wraps the
    model instead (``DataParallelOptimizer(opt, model)``) and no coupling
    argument exists.
    """

    def __init__(self, module: Module, comm=None, key=0):
        if not isinstance(module, Module):
            raise TypeError(f"module must be a heat_tpu.nn.Module, got {type(module)}")
        self.module = module
        self.comm = sanitize_comm(comm)
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        params = module.init(key)
        # replicate across the mesh: every device holds the full pytree
        repl = self.comm.sharding(0, None)
        self.params = jax.tree.map(lambda p: jax.device_put(p, repl), params)
        # optimizers owning divergent per-node replicas (DASO) install a
        # callable here so eval forwards always see current weights
        self._param_override = None

    def _current_params(self):
        return self._param_override() if self._param_override is not None else self.params

    def __call__(self, x, *, train: bool = False, key: Optional[jax.Array] = None):
        """Forward pass. DNDarray in → DNDarray out (batch split preserved);
        raw jax arrays pass through unchanged for use inside jitted steps."""
        params = self._current_params()
        if isinstance(x, DNDarray):
            out = self.module.apply(params, x.larray, train=train, key=key)
            split = x.split if x.split is not None and x.split < out.ndim else None
            gshape = tuple(int(s) for s in out.shape)
            phys = self.comm.shard(out, split)
            return DNDarray(
                phys, gshape, types.canonical_heat_type(out.dtype), split, x.device, self.comm
            )
        return self.module.apply(params, x, train=train, key=key)

    forward = __call__

    # ------------------------------------------------------------------ #
    # reference-API conveniences                                         #
    # ------------------------------------------------------------------ #
    def parameters(self):
        """Flat iterator over CURRENT parameter leaves (reference: torch
        ``module.parameters()``) — under DASO training these are the
        node-averaged weights, not the stale init."""
        return iter(jax.tree.leaves(self._current_params()))

    def state_dict(self):
        """Current weights for checkpointing (under DASO: node-averaged)."""
        return self._current_params()

    def load_state_dict(self, params):
        repl = self.comm.sharding(0, None)
        self.params = jax.tree.map(lambda p: jax.device_put(jnp.asarray(p), repl), params)
        # an owning optimizer (DASO) must adopt the loaded weights, else its
        # override would keep serving the pre-load replicas
        owner = getattr(self, "_owner", None)
        if owner is not None:
            owner.load_params(self.params)

    def train(self):
        return self

    def eval(self):
        return self


class DataParallelMultiGPU(DataParallel):
    """Reference data_parallel.py:312: node-local DDP + DASO global sync.
    On TPU the hierarchy lives in the DASO optimizer's two-level mesh;
    this subclass exists for API parity and simply tags the model so a
    ``heat_tpu.optim.DASO`` optimizer can adopt it."""

    def __init__(self, module: Module, comm=None, key=0):
        super().__init__(module, comm, key)
