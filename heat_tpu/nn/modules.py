"""Neural-network modules.

The reference's ``ht.nn`` is a thin pass-through to ``torch.nn``
(/root/reference/heat/nn/__init__.py:19-47): Heat supplies distribution
(DataParallel), torch supplies the layers. On TPU the layer zoo is supplied
by the JAX ecosystem instead; this module provides a minimal functional
module system (params as pytrees, ``init``/``apply``) covering what the
reference's examples exercise (examples/nn/mnist.py: Linear/Conv-free MLP
paths, activations, dropout, losses), plus a ``flax.linen`` fallback in the
package ``__getattr__`` mirroring the reference's delegation design.

All modules are stateless: ``init(key)`` returns a parameter pytree,
``apply(params, x, train=..., key=...)`` is a pure function — jit/grad/
shard_map compose for free, which is the whole point of the TPU-first
redesign (no backward hooks, no parameter mutation: reference
data_parallel.py:120-124 registers per-parameter grad hooks precisely
because torch mutates).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from typing import Any, Optional, Sequence, Tuple

__all__ = [
    "Module",
    "Linear",
    "ReLU",
    "GELU",
    "Tanh",
    "Sigmoid",
    "LogSoftmax",
    "Softmax",
    "Flatten",
    "Dropout",
    "Sequential",
    "MSELoss",
    "NLLLoss",
    "CrossEntropyLoss",
]


class Module:
    """Base class: stateless layer with ``init``/``apply``."""

    def init(self, key: jax.Array):
        """Return this module's parameter pytree ({} when parameter-free)."""
        return {}

    def apply(self, params, x, *, train: bool = False, key: Optional[jax.Array] = None):
        raise NotImplementedError

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)


class Linear(Module):
    """Affine layer y = x W + b.

    Parity with torch.nn.Linear (the reference MLP's building block) incl.
    its Kaiming-uniform init; the weight is stored (in_features,
    out_features) so the forward contraction feeds the MXU without a
    transpose.
    """

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 dtype=jnp.float32):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.bias = bool(bias)
        self.dtype = dtype

    def init(self, key: jax.Array):
        bound = 1.0 / math.sqrt(self.in_features)
        wkey, bkey = jax.random.split(key)
        params = {
            "weight": jax.random.uniform(
                wkey, (self.in_features, self.out_features), minval=-bound, maxval=bound,
                dtype=self.dtype,
            )
        }
        if self.bias:
            params["bias"] = jax.random.uniform(
                bkey, (self.out_features,), minval=-bound, maxval=bound, dtype=self.dtype
            )
        return params

    def apply(self, params, x, *, train: bool = False, key=None):
        y = x @ params["weight"]
        if self.bias:
            y = y + params["bias"]
        return y


class _Activation(Module):
    _fn = None

    def apply(self, params, x, *, train: bool = False, key=None):
        return type(self)._fn(x)


class ReLU(_Activation):
    _fn = staticmethod(jax.nn.relu)


class GELU(_Activation):
    _fn = staticmethod(jax.nn.gelu)


class Tanh(_Activation):
    _fn = staticmethod(jnp.tanh)


class Sigmoid(_Activation):
    _fn = staticmethod(jax.nn.sigmoid)


class LogSoftmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, *, train: bool = False, key=None):
        return jax.nn.log_softmax(x, axis=self.dim)


class Softmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, *, train: bool = False, key=None):
        return jax.nn.softmax(x, axis=self.dim)


class Flatten(Module):
    def __init__(self, start_dim: int = 1):
        self.start_dim = start_dim

    def apply(self, params, x, *, train: bool = False, key=None):
        lead = x.shape[: self.start_dim]
        return x.reshape(lead + (-1,))


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        if not 0.0 <= p < 1.0:
            raise ValueError(f"dropout probability must be in [0, 1), got {p}")
        self.p = float(p)

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train or self.p == 0.0:
            return x
        if key is None:
            raise ValueError("Dropout.apply(train=True) requires a PRNG key")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class Sequential(Module):
    """Chain of modules; params is a tuple of per-module pytrees."""

    def __init__(self, *modules: Module):
        self.modules = tuple(modules)

    def init(self, key: jax.Array):
        keys = jax.random.split(key, max(len(self.modules), 1))
        return tuple(m.init(k) for m, k in zip(self.modules, keys))

    def apply(self, params, x, *, train: bool = False, key=None):
        keys = (
            jax.random.split(key, max(len(self.modules), 1))
            if key is not None
            else (None,) * len(self.modules)
        )
        for m, p, k in zip(self.modules, params, keys):
            x = m.apply(p, x, train=train, key=k)
        return x


# --------------------------------------------------------------------- #
# losses                                                                #
# --------------------------------------------------------------------- #
def scalar_dndarray(val, comm, device):
    """Wrap a 0-d jax value as a replicated DNDarray (shared by losses and
    the optimizer step returns)."""
    from ..core.dndarray import DNDarray
    from ..core import types

    return DNDarray(
        jax.device_put(val, comm.sharding(0, None)),
        (),
        types.canonical_heat_type(val.dtype),
        None,
        device,
        comm,
    )


class _Loss:
    """Callable loss; ``raw`` operates on jax arrays (used inside jitted
    train steps), ``__call__`` accepts DNDarrays for API parity with the
    reference's ``criterion(output, target)`` pattern."""

    def raw(self, output, target, weight=None):
        per = self._per_sample(output, target)
        if weight is not None:
            return jnp.sum(per * weight) / jnp.maximum(jnp.sum(weight), 1.0)
        return jnp.mean(per)

    def _per_sample(self, output, target):
        raise NotImplementedError

    def __call__(self, output, target):
        from ..core.dndarray import DNDarray

        if isinstance(output, DNDarray):
            tgt_l = target.larray if isinstance(target, DNDarray) else target
            val = self.raw(output.larray, tgt_l)
            return scalar_dndarray(val, output.comm, output.device)
        return self.raw(output, target)


class MSELoss(_Loss):
    def _per_sample(self, output, target):
        d = (output - target.astype(output.dtype)) ** 2
        return d.reshape(d.shape[0], -1).mean(axis=1) if d.ndim > 1 else d


class NLLLoss(_Loss):
    """Negative log likelihood over log-probabilities."""

    def _per_sample(self, output, target):
        return -jnp.take_along_axis(output, target[:, None].astype(jnp.int32), axis=1)[:, 0]


class CrossEntropyLoss(_Loss):
    """Softmax cross entropy over raw logits (torch semantics)."""

    def _per_sample(self, output, target):
        logp = jax.nn.log_softmax(output, axis=-1)
        return -jnp.take_along_axis(logp, target[:, None].astype(jnp.int32), axis=1)[:, 0]
