"""Neural-network modules.

The reference's ``ht.nn`` is a thin pass-through to ``torch.nn``
(/root/reference/heat/nn/__init__.py:19-47): Heat supplies distribution
(DataParallel), torch supplies the layers. On TPU the layer zoo is supplied
by the JAX ecosystem instead; this module provides a minimal functional
module system (params as pytrees, ``init``/``apply``) covering what the
reference's examples exercise (examples/nn/mnist.py: Linear/Conv-free MLP
paths, activations, dropout, losses), plus a ``flax.linen`` fallback in the
package ``__getattr__`` mirroring the reference's delegation design.

All modules are stateless: ``init(key)`` returns a parameter pytree,
``apply(params, x, train=..., key=...)`` is a pure function — jit/grad/
shard_map compose for free, which is the whole point of the TPU-first
redesign (no backward hooks, no parameter mutation: reference
data_parallel.py:120-124 registers per-parameter grad hooks precisely
because torch mutates).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from typing import Any, Optional, Sequence, Tuple

from ..core.communication import place as _place

__all__ = [
    "Module",
    "Linear",
    "MultiheadAttention",
    "Conv2d",
    "MaxPool2d",
    "AvgPool2d",
    "LayerNorm",
    "Embedding",
    "ReLU",
    "GELU",
    "Tanh",
    "Sigmoid",
    "LogSoftmax",
    "Softmax",
    "Flatten",
    "Dropout",
    "Dropout2d",
    "Sequential",
    "MSELoss",
    "NLLLoss",
    "CrossEntropyLoss",
]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        a, b = v
        return int(a), int(b)
    return int(v), int(v)


class Module:
    """Base class: stateless layer with ``init``/``apply``."""

    def init(self, key: jax.Array):
        """Return this module's parameter pytree ({} when parameter-free)."""
        return {}

    def apply(self, params, x, *, train: bool = False, key: Optional[jax.Array] = None):
        raise NotImplementedError

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)


class Linear(Module):
    """Affine layer y = x W + b.

    Parity with torch.nn.Linear (the reference MLP's building block) incl.
    its Kaiming-uniform init; the weight is stored (in_features,
    out_features) so the forward contraction feeds the MXU without a
    transpose.
    """

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 dtype=jnp.float32):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.bias = bool(bias)
        self.dtype = dtype

    def init(self, key: jax.Array):
        bound = 1.0 / math.sqrt(self.in_features)
        wkey, bkey = jax.random.split(key)
        params = {
            "weight": jax.random.uniform(
                wkey, (self.in_features, self.out_features), minval=-bound, maxval=bound,
                dtype=self.dtype,
            )
        }
        if self.bias:
            params["bias"] = jax.random.uniform(
                bkey, (self.out_features,), minval=-bound, maxval=bound, dtype=self.dtype
            )
        return params

    def apply(self, params, x, *, train: bool = False, key=None):
        y = x @ params["weight"]
        if self.bias:
            y = y + params["bias"]
        return y


class Conv2d(Module):
    """2-D convolution over NCHW inputs — torch.nn.Conv2d parity (the
    reference's CNN example, examples/nn/mnist.py:26, uses ht.nn.Conv2d
    via the torch passthrough) including its Kaiming-uniform init. The
    contraction lowers to ``lax.conv_general_dilated``, which XLA tiles
    onto the MXU.
    """

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, bias: bool = True, dtype=jnp.float32):
        self.in_channels = int(in_channels)
        self.out_channels = int(out_channels)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        if isinstance(padding, str):
            pad = padding.lower()
            if pad == "valid":
                self.padding = ((0, 0), (0, 0))
            elif pad == "same":
                if self.stride != (1, 1):
                    # torch parity: conv.py raises the same way
                    raise ValueError(
                        "padding='same' is not supported for strided convolutions"
                    )
                # torch puts the odd element of an even kernel's padding on
                # the HIGH side of each dim; XLA's "SAME" string does not,
                # so spell the pads out
                kh, kw = self.kernel_size
                self.padding = (
                    ((kh - 1) // 2, kh - 1 - (kh - 1) // 2),
                    ((kw - 1) // 2, kw - 1 - (kw - 1) // 2),
                )
            else:
                raise ValueError(f"padding must be 'same', 'valid' or ints, got {padding!r}")
        else:
            ph, pw = _pair(padding)
            self.padding = ((ph, ph), (pw, pw))
        self.bias = bool(bias)
        self.dtype = dtype

    def init(self, key: jax.Array):
        kh, kw = self.kernel_size
        fan_in = self.in_channels * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        wkey, bkey = jax.random.split(key)
        params = {
            "weight": jax.random.uniform(
                wkey, (self.out_channels, self.in_channels, kh, kw),
                minval=-bound, maxval=bound, dtype=self.dtype,
            )
        }
        if self.bias:
            params["bias"] = jax.random.uniform(
                bkey, (self.out_channels,), minval=-bound, maxval=bound, dtype=self.dtype
            )
        return params

    def apply(self, params, x, *, train: bool = False, key=None):
        y = jax.lax.conv_general_dilated(
            x, params["weight"],
            window_strides=self.stride,
            padding=self.padding,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.bias:
            y = y + params["bias"][None, :, None, None]
        return y


class MultiheadAttention(Module):
    """Multi-head self-attention — ``torch.nn.MultiheadAttention`` parity
    (batch_first semantics, self-attention form) for building transformer
    blocks. The reference has NO attention stack (SURVEY §5); this module
    completes the model-building story around ``ring_attention``: packed
    q/k/v projection, per-head split, the SHARED single-device flash path
    (``attention._single_device_attention`` — splash/flash kernel when
    the workload fits, blocked program as oracle), merge, output
    projection. For a sequence-sharded model call
    ``nn.ring_attention``/``functional.scaled_dot_product_attention`` on
    DNDarrays directly; inside a jitted train step this module operates
    on the local (B, S, E) activations like every other layer.

    torch weight mapping (for checkpoint ports):
    ``in_proj_weight`` (3E, E) → ``params["in_proj"]`` transposed (E, 3E);
    ``out_proj.weight`` (E, E) → ``params["out_proj"]`` transposed.
    """

    def __init__(self, embed_dim: int, num_heads: int, bias: bool = True,
                 causal: bool = False, dtype=jnp.float32):
        if embed_dim % num_heads != 0:
            raise ValueError(
                f"embed_dim ({embed_dim}) must be divisible by num_heads ({num_heads})"
            )
        self.embed_dim = int(embed_dim)
        self.num_heads = int(num_heads)
        self.head_dim = self.embed_dim // self.num_heads
        self.bias = bool(bias)
        self.causal = bool(causal)
        self.dtype = dtype

    def init(self, key: jax.Array):
        e = self.embed_dim
        k_in, k_out = jax.random.split(key)
        # torch initializes in_proj with xavier_uniform over the (3E, E)
        # matrix; mirror the same fan computation on the transposed layout
        bound_in = math.sqrt(6.0 / (e + 3 * e))
        bound_out = 1.0 / math.sqrt(e)
        params = {
            "in_proj": jax.random.uniform(
                k_in, (e, 3 * e), minval=-bound_in, maxval=bound_in, dtype=self.dtype
            ),
            "out_proj": jax.random.uniform(
                k_out, (e, e), minval=-bound_out, maxval=bound_out, dtype=self.dtype
            ),
        }
        if self.bias:
            params["in_bias"] = jnp.zeros((3 * e,), dtype=self.dtype)
            params["out_bias"] = jnp.zeros((e,), dtype=self.dtype)
        return params

    def apply(self, params, x, *, train: bool = False, key=None):
        from .functional import scaled_dot_product_attention

        squeeze = x.ndim == 2  # (S, E) unbatched, like torch
        if squeeze:
            x = x[None]
        b, s, e = x.shape
        h, d = self.num_heads, self.head_dim
        qkv = x @ params["in_proj"]
        if self.bias:
            qkv = qkv + params["in_bias"]
        # (B, S, 3, H, D) → three (B, H, S, D)
        qkv = qkv.reshape(b, s, 3, h, d)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        out = scaled_dot_product_attention(q, k, v, is_causal=self.causal)
        out = jnp.moveaxis(out, 1, 2).reshape(b, s, e)
        out = out @ params["out_proj"]
        if self.bias:
            out = out + params["out_bias"]
        return out[0] if squeeze else out


class _Pool2d(Module):
    def __init__(self, kernel_size, stride=None):
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride) if stride is not None else self.kernel_size

    def _window(self, x):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        return (1, 1, kh, kw), (1, 1, sh, sw)


class MaxPool2d(_Pool2d):
    """torch.nn.MaxPool2d parity over NCHW (lax.reduce_window max)."""

    def apply(self, params, x, *, train: bool = False, key=None):
        dims, strides = self._window(x)
        # init must be a CONCRETE scalar of the operand dtype: a Python int
        # mismatches narrow int dtypes and a traced jnp constant breaks
        # reduce_window's reverse-mode rule
        import numpy as _np

        neg = (
            -jnp.inf if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.iinfo(x.dtype).min
        )
        return jax.lax.reduce_window(
            x, _np.dtype(x.dtype).type(neg), jax.lax.max, dims, strides, "VALID"
        )


class AvgPool2d(_Pool2d):
    """torch.nn.AvgPool2d parity over NCHW (lax.reduce_window mean)."""

    def apply(self, params, x, *, train: bool = False, key=None):
        dims, strides = self._window(x)
        kh, kw = self.kernel_size
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, "VALID")
        return summed / (kh * kw)


class LayerNorm(Module):
    """torch.nn.LayerNorm parity: normalize over the trailing
    ``normalized_shape`` dims with learnable scale/shift."""

    def __init__(self, normalized_shape, eps: float = 1e-5, elementwise_affine: bool = True,
                 dtype=jnp.float32):
        if isinstance(normalized_shape, (int,)):
            normalized_shape = (int(normalized_shape),)
        self.normalized_shape = tuple(int(s) for s in normalized_shape)
        self.eps = float(eps)
        self.elementwise_affine = bool(elementwise_affine)
        self.dtype = dtype

    def init(self, key: jax.Array):
        if not self.elementwise_affine:
            return {}
        return {
            "weight": jnp.ones(self.normalized_shape, self.dtype),
            "bias": jnp.zeros(self.normalized_shape, self.dtype),
        }

    def apply(self, params, x, *, train: bool = False, key=None):
        tail = tuple(x.shape[x.ndim - len(self.normalized_shape):])
        if tail != self.normalized_shape:
            # torch parity: mismatches raise instead of silently
            # normalizing/broadcasting over the wrong extent
            raise ValueError(
                f"expected input with trailing shape {self.normalized_shape}, got {tail}"
            )
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + self.eps)
        if self.elementwise_affine:
            y = y * params["weight"] + params["bias"]
        return y


class Embedding(Module):
    """torch.nn.Embedding parity: lookup table with N(0, 1) init."""

    def __init__(self, num_embeddings: int, embedding_dim: int, dtype=jnp.float32):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.dtype = dtype

    def init(self, key: jax.Array):
        return {
            "weight": jax.random.normal(
                key, (self.num_embeddings, self.embedding_dim), dtype=self.dtype
            )
        }

    def apply(self, params, x, *, train: bool = False, key=None):
        if not isinstance(x, jax.core.Tracer):
            # torch parity: out-of-range ids raise instead of JAX's silent
            # gather clamp (a -1 sentinel or vocab off-by-one would return
            # wrong rows and train on corrupt lookups); traced calls keep
            # clamp semantics — no host check is possible under jit
            xa = jnp.asarray(x)
            bad = (xa < 0) | (xa >= self.num_embeddings)
            if bool(jnp.any(bad)):
                raise IndexError(
                    f"index out of range in Embedding({self.num_embeddings}, "
                    f"{self.embedding_dim})"
                )
        return params["weight"][x]


class _Activation(Module):
    _fn = None

    def apply(self, params, x, *, train: bool = False, key=None):
        return type(self)._fn(x)


class ReLU(_Activation):
    _fn = staticmethod(jax.nn.relu)


class GELU(_Activation):
    _fn = staticmethod(jax.nn.gelu)


class Tanh(_Activation):
    _fn = staticmethod(jnp.tanh)


class Sigmoid(_Activation):
    _fn = staticmethod(jax.nn.sigmoid)


class LogSoftmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, *, train: bool = False, key=None):
        return jax.nn.log_softmax(x, axis=self.dim)


class Softmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, *, train: bool = False, key=None):
        return jax.nn.softmax(x, axis=self.dim)


class Flatten(Module):
    def __init__(self, start_dim: int = 1):
        self.start_dim = start_dim

    def apply(self, params, x, *, train: bool = False, key=None):
        lead = x.shape[: self.start_dim]
        return x.reshape(lead + (-1,))


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        if not 0.0 <= p <= 1.0:
            # torch parity: p=1.0 is legal (output all zeros)
            raise ValueError(f"dropout probability must be in [0, 1], got {p}")
        self.p = float(p)

    def _mask_shape(self, x):
        return x.shape

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train or self.p == 0.0:
            return x
        if self.p == 1.0:
            return jnp.zeros_like(x)
        if key is None:
            raise ValueError(f"{type(self).__name__}.apply(train=True) requires a PRNG key")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, self._mask_shape(x))
        return jnp.where(mask, x / keep, 0.0)


class Dropout2d(Dropout):
    """Channel-wise dropout over NCHW (torch.nn.Dropout2d parity): whole
    feature maps are zeroed together."""

    def _mask_shape(self, x):
        return x.shape[:2] + (1,) * (x.ndim - 2)


class Sequential(Module):
    """Chain of modules; params is a tuple of per-module pytrees."""

    def __init__(self, *modules: Module):
        self.modules = tuple(modules)

    def init(self, key: jax.Array):
        keys = jax.random.split(key, max(len(self.modules), 1))
        return tuple(m.init(k) for m, k in zip(self.modules, keys))

    def apply(self, params, x, *, train: bool = False, key=None):
        keys = (
            jax.random.split(key, max(len(self.modules), 1))
            if key is not None
            else (None,) * len(self.modules)
        )
        for m, p, k in zip(self.modules, params, keys):
            x = m.apply(p, x, train=train, key=k)
        return x


# --------------------------------------------------------------------- #
# losses                                                                #
# --------------------------------------------------------------------- #
def scalar_dndarray(val, comm, device):
    """Wrap a 0-d jax value as a replicated DNDarray (shared by losses and
    the optimizer step returns)."""
    from ..core.dndarray import DNDarray
    from ..core import types

    return DNDarray(
        _place(val, comm.sharding(0, None)),
        (),
        types.canonical_heat_type(val.dtype),
        None,
        device,
        comm,
    )


class _Loss:
    """Callable loss; ``raw`` operates on jax arrays (used inside jitted
    train steps), ``__call__`` accepts DNDarrays for API parity with the
    reference's ``criterion(output, target)`` pattern."""

    def raw(self, output, target, weight=None):
        per = self._per_sample(output, target)
        if weight is not None:
            return jnp.sum(per * weight) / jnp.maximum(jnp.sum(weight), 1.0)
        return jnp.mean(per)

    def _per_sample(self, output, target):
        raise NotImplementedError

    def __call__(self, output, target):
        from ..core.dndarray import DNDarray

        if isinstance(output, DNDarray):
            tgt_l = target.larray if isinstance(target, DNDarray) else target
            val = self.raw(output.larray, tgt_l)
            return scalar_dndarray(val, output.comm, output.device)
        return self.raw(output, target)


class MSELoss(_Loss):
    def _per_sample(self, output, target):
        d = (output - target.astype(output.dtype)) ** 2
        return d.reshape(d.shape[0], -1).mean(axis=1) if d.ndim > 1 else d


class NLLLoss(_Loss):
    """Negative log likelihood over log-probabilities."""

    def _per_sample(self, output, target):
        return -jnp.take_along_axis(output, target[:, None].astype(jnp.int32), axis=1)[:, 0]


class CrossEntropyLoss(_Loss):
    """Softmax cross entropy over raw logits (torch semantics)."""

    def _per_sample(self, output, target):
        logp = jax.nn.log_softmax(output, axis=-1)
        return -jnp.take_along_axis(logp, target[:, None].astype(jnp.int32), axis=1)[:, 0]
