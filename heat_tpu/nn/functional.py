"""Functional neural-network ops.

The reference's ``heat.nn.functional`` is a pass-through to
``torch.nn.functional`` (/root/reference/heat/nn/functional.py:9); here the
ecosystem equivalent is ``jax.nn``, re-exported with the common torch names
so reference-style code ports mechanically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

relu = jax.nn.relu
gelu = jax.nn.gelu
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax
softplus = jax.nn.softplus
leaky_relu = jax.nn.leaky_relu
elu = jax.nn.elu
one_hot = jax.nn.one_hot


def linear(x, weight, bias=None):
    """y = x W (+ b) with weight stored (in, out) — see nn.Linear."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def __getattr__(name):
    """Fall through to jax.nn for anything not aliased above (the analog of
    the reference's torch.nn.functional delegation)."""
    try:
        return getattr(jax.nn, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute '{name}'")
