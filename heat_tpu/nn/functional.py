"""Functional neural-network ops.

The reference's ``heat.nn.functional`` is a pass-through to
``torch.nn.functional`` (/root/reference/heat/nn/functional.py:9); here the
ecosystem equivalent is ``jax.nn``, re-exported with the common torch names
so reference-style code ports mechanically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

relu = jax.nn.relu
gelu = jax.nn.gelu
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax
softplus = jax.nn.softplus
leaky_relu = jax.nn.leaky_relu
elu = jax.nn.elu
one_hot = jax.nn.one_hot


def scaled_dot_product_attention(query, key, value, attn_mask=None, is_causal=False, scale=None):
    """torch-parity alias (torch.nn.functional.scaled_dot_product_attention)
    over the framework's attention: DNDarray operands route through
    ``nn.attention.ring_attention`` (sequence-parallel when the seq axis is
    split, blocked flash-style otherwise). ``attn_mask`` is not supported —
    use ``is_causal`` or mask scores explicitly."""
    from .attention import _single_device_attention, ring_attention
    from ..core.dndarray import DNDarray

    if attn_mask is not None:
        raise NotImplementedError("attn_mask is not supported; use is_causal")
    ops = (query, key, value)
    if any(isinstance(t, DNDarray) for t in ops):
        # mixed operands: lift raw arrays onto the DNDarray operand's comm
        # so the whole call takes ONE route with consistent diagnostics
        ref = next(t for t in ops if isinstance(t, DNDarray))
        from ..core import factories

        query, key, value = (
            t if isinstance(t, DNDarray)
            else factories.array(t, comm=ref.comm, device=ref.device)
            for t in ops
        )
        return ring_attention(query, key, value, causal=is_causal, scale=scale)
    # raw jax arrays: the same single-device kernel the DNDarray route
    # uses (shared helper: promotion, default scale, blocked program)
    return _single_device_attention(query, key, value, bool(is_causal), scale)


def max_pool2d(x, kernel_size, stride=None):
    """torch.nn.functional.max_pool2d parity over NCHW (the reference CNN
    example calls F.max_pool2d, examples/nn/mnist.py)."""
    from .modules import MaxPool2d

    return MaxPool2d(kernel_size, stride).apply({}, x)


def avg_pool2d(x, kernel_size, stride=None):
    """torch.nn.functional.avg_pool2d parity over NCHW."""
    from .modules import AvgPool2d

    return AvgPool2d(kernel_size, stride).apply({}, x)


def dropout(x, p=0.5, training=True, key=None):
    """torch.nn.functional.dropout parity (explicit PRNG key)."""
    from .modules import Dropout

    return Dropout(p).apply({}, x, train=training, key=key)


def linear(x, weight, bias=None):
    """y = x W (+ b) with weight stored (in, out) — see nn.Linear."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def __getattr__(name):
    """Fall through to jax.nn for anything not aliased above (the analog of
    the reference's torch.nn.functional delegation)."""
    try:
        return getattr(jax.nn, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute '{name}'")
