"""Functional neural-network ops.

The reference's ``heat.nn.functional`` is a pass-through to
``torch.nn.functional`` (/root/reference/heat/nn/functional.py:9); here the
ecosystem equivalent is ``jax.nn``, re-exported with the common torch names
so reference-style code ports mechanically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

relu = jax.nn.relu
gelu = jax.nn.gelu
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax
softplus = jax.nn.softplus
leaky_relu = jax.nn.leaky_relu
elu = jax.nn.elu
one_hot = jax.nn.one_hot


def scaled_dot_product_attention(query, key, value, attn_mask=None, is_causal=False, scale=None):
    """torch-parity alias (torch.nn.functional.scaled_dot_product_attention)
    over the framework's attention: DNDarray operands route through
    ``nn.attention.ring_attention`` (sequence-parallel when the seq axis is
    split, blocked flash-style otherwise). ``attn_mask`` is not supported —
    use ``is_causal`` or mask scores explicitly."""
    from .attention import ring_attention
    from ..core.dndarray import DNDarray

    if attn_mask is not None:
        raise NotImplementedError("attn_mask is not supported; use is_causal")
    if isinstance(query, DNDarray):
        return ring_attention(query, key, value, causal=is_causal, scale=scale)
    # raw jax arrays: the same blocked flash-style kernel the DNDarray
    # route uses on a single device (no (Sq, Sk) score materialization)
    import numpy as _np

    from .attention import _blocked_attention_program

    if scale is None:
        scale = 1.0 / float(_np.sqrt(query.shape[-1]))
    prog = _blocked_attention_program(
        tuple(query.shape), tuple(key.shape), tuple(value.shape),
        bool(is_causal), float(scale), _np.dtype(query.dtype).name,
    )
    return prog(query, key, value)


def linear(x, weight, bias=None):
    """y = x W (+ b) with weight stored (in, out) — see nn.Linear."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def __getattr__(name):
    """Fall through to jax.nn for anything not aliased above (the analog of
    the reference's torch.nn.functional delegation)."""
    try:
        return getattr(jax.nn, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute '{name}'")
