"""heat_tpu.kernels — hand-tiled single-chip kernels.

The repo's pattern (arXiv:2112.09017, applied to hSVD in
``core/linalg/_pallas_sketch.py``): a hand-tiled single-chip kernel
under an UNCHANGED collective schedule is where the throughput lives.
This package holds the kernels that are not tied to one algorithm
module — the local radix/columnsort sort engines feeding both
``ht.sort``'s single-chip path and the distributed sort networks'
local-sort steps (``core/parallel.py``), the lane-packing relayout
copies under the redistribution planner (``relayout``), and the
ppermute-ring collective-matmul primitives the TSQR merge and split
matmul overlap their compute with (``cmatmul``), and the
block-quantized wire codec the redistribution executor and the DP
optimizer ship collective payloads through (``quant``). Every kernel
here ships with capability gates, a numerical oracle as the fallback,
and an environment escape hatch.
"""

from . import cmatmul
from . import quant
from . import relayout
from . import sort
from . import spmm
from .cmatmul import (
    ring_all_gather,
    ring_matmul_reduce,
)
from .quant import (
    decode_blocks,
    encode_blocks,
    wire_ratio,
)
from .relayout import (
    lane_fill,
    pack_rows,
    unpack_rows,
)
from .sort import (
    block_sort,
    from_sortable,
    local_sort,
    sort_plan,
    to_sortable,
)

__all__ = [
    "cmatmul",
    "quant",
    "relayout",
    "sort",
    "spmm",
    "block_sort",
    "decode_blocks",
    "encode_blocks",
    "from_sortable",
    "lane_fill",
    "local_sort",
    "pack_rows",
    "ring_all_gather",
    "ring_matmul_reduce",
    "sort_plan",
    "to_sortable",
    "unpack_rows",
    "wire_ratio",
]
