"""TPU-native local radix sort: fused key+index sort feeding the
distributed sort networks.

``sort_1gb`` is the repo's weakest chip row (ROADMAP "sort": flat at
~208-216 Melem/s across verdicts): the single-chip local sort is
``lax.sort`` — an O(n log² n) comparison network whose stage count, not
HBM bandwidth, is the cost. The reference makes sort a first-class
distributed primitive (sample-sort + Alltoallv, HeAT paper §4); this
repo's distributed layer already replaced the Alltoallv with static
columnsort/odd-even schedules (core/parallel.py). This module is the
same move one level down: the per-chip LOCAL sort becomes an explicit
algorithm instead of one opaque ``lax.sort`` call, under capability
gates with ``lax.sort`` as the numerical oracle and fallback.

Three engines behind one dispatcher:

* **LSD radix** (``_radix_sort_xla`` + the Pallas block kernel): 8-bit
  digits, histogram + exclusive scan + stable rank + permutation-apply.
  The XLA formulation computes the histogram as a one-hot MATMUL
  (``ones @ onehot`` — MXU-friendly) and the stable scatter as a
  unique-index scatter; the Pallas TPU kernel runs the identical pass
  entirely in VMEM with the exclusive scan as a strict-upper-triangular
  matmul and the stable scatter as an EXACT one-hot permutation matmul
  (8-bit byte planes stage u32 words through f32 losslessly: every
  product is ``1.0 * v`` with ``v ≤ 255``, bf16-exact even if the MXU
  rounds its inputs). ``interpret=True`` runs
  the same kernel logic on CPU, so tier-1 exercises it without a TPU.
  Gated to VMEM-block sizes — the compiler generation in this container
  (no gather/scatter/dynamic-lane primitives in Mosaic) cannot express
  a bandwidth-rate global scatter, so the radix engine is the BASE CASE,
  not the 128M-element path (docs/PERF.md "Sort" has the arithmetic).

* **Blocked columnsort** (``_columnsort_local``): Leighton's network —
  the exact schedule ``parallel._columnsort_program`` runs over ICI —
  applied single-chip with the two all-to-alls as free HBM transposes:
  4 BATCHED row sorts (p rows of B = n/p elements) + 3 relayout passes
  replace one monolithic ``lax.sort``. Batched minor-dim sorts are the
  shape XLA's TPU sort emitter blocks into VMEM best; validity is the
  same Leighton bound the distributed program gates on (B ≥ 2(p-1)²,
  p | B), made unconditional here by sentinel padding to p·B.

* **``lax.sort``**: the oracle. Every kernel path produces the EXACT
  oracle argsort indices — the (key, index) pair is a distinct total
  order, so any correct sort agrees — and values equal under the
  comparator (−0.0 and NaN payload bits come back canonicalized, the
  transform's two collapsed tie classes). The tests pin both.

Dispatch: ``HEAT_TPU_SORT_KERNEL=0`` forces the oracle everywhere (the
escape hatch), ``=1`` forces the kernel family (tests/CI), and the
default ``auto`` keeps ``lax.sort`` off-TPU and AUTOTUNES on TPU for
large 1-D sorts — one timed probe per (n, dtype, form), cached, so a
path that loses on the real chip can never regress a workload.
"""

from __future__ import annotations

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import gates as _gates

try:  # pragma: no cover — present in all TPU-capable jax builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pl = None
    _VMEM = None

__all__ = [
    "to_sortable",
    "from_sortable",
    "local_sort",
    "block_sort",
    "sort_plan",
    "last_decisions",
]

# ---------------------------------------------------------------------- #
# capability gates                                                       #
# ---------------------------------------------------------------------- #
_RADIX_XLA_MAX = 1 << 12     # one-hot/rank matrices are O(n·256) and O(n²)
_PALLAS_BLOCK = 512          # elements per VMEM-resident kernel block
_VMEM_SORT_LOG2 = 20         # ~elements of a (key,idx) pair set resident in
                             # VMEM during a comparison sort (8 B/elem ≈ 8 MB)


def _mode() -> str:
    v = _gates.get("HEAT_TPU_SORT_KERNEL", "auto").strip().lower()
    if v in ("0", "off", "false"):
        return "0"
    if v in ("1", "on", "true", "force"):
        return "1"
    return "auto"


def _inc(name: str) -> None:
    from ..observability import telemetry

    telemetry.inc(name)


# ---------------------------------------------------------------------- #
# monotone bit transforms: dtype <-> radix-sortable unsigned             #
# ---------------------------------------------------------------------- #
_UINT_OF_BITS = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32, 64: jnp.uint64}


def _uint_dtype(itemsize: int):
    if itemsize == 8 and not jax.config.jax_enable_x64:
        return None  # no 64-bit lanes on this platform policy
    return _UINT_OF_BITS.get(itemsize * 8)


def transformable(dtype) -> bool:
    """True when ``to_sortable``/``from_sortable`` serve this dtype."""
    dt = jnp.dtype(dtype)
    if _uint_dtype(dt.itemsize) is None:
        return False
    return (
        jnp.issubdtype(dt, jnp.floating)
        or jnp.issubdtype(dt, jnp.signedinteger)
        or jnp.issubdtype(dt, jnp.unsignedinteger)
    )


def to_sortable(x: jax.Array) -> jax.Array:
    """Map ``x`` to an unsigned integer array of the same width whose
    UNSIGNED order equals ``lax.sort``'s comparator order on ``x``.

    floats: the sign-flip trick — non-negatives get the sign bit set,
    negatives are bitwise-complemented — with XLA's two tie classes
    COLLAPSED so the (key, index) order is exactly the oracle's stable
    order: every NaN (any sign/payload) maps to type-max (the value
    XLA's comparator treats all NaNs as, and the distributed sort's
    pad-sentinel contract: NaN pads sink to the global tail,
    ``manipulations._sort_sentinel_fill``), and −0.0 maps onto +0.0's
    key (XLA ties them). The map is a bijection everywhere else; ints
    are fully bijective (signed: flip the sign bit; unsigned: identity).

    One documented refinement: XLA's comparator runs on FTZ hardware
    and ties every SUBNORMAL with zero; the transform keeps the strict
    IEEE magnitude order for subnormals (values round-trip bit-exact).
    A transform-ordered array is therefore still sorted under XLA's
    comparator — only the argsort tie order among subnormals differs.
    """
    dt = jnp.dtype(x.dtype)
    udt = _uint_dtype(dt.itemsize)
    if udt is None:
        raise TypeError(f"no sortable transform for {dt} on this platform")
    bits = dt.itemsize * 8
    ut = np.dtype(udt).type
    sign = ut(ut(1) << ut(bits - 1))
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return x.astype(udt)
    if jnp.issubdtype(dt, jnp.signedinteger):
        return lax.bitcast_convert_type(x, udt) ^ sign
    if jnp.issubdtype(dt, jnp.floating):
        nmant = jnp.finfo(dt).nmant
        exp_all = ut(((1 << (bits - 1 - nmant)) - 1) << nmant)  # e.g. 0x7F800000
        s = lax.bitcast_convert_type(x, udt)
        isnan = (s & ~sign) > exp_all
        s = jnp.where(s == sign, ut(0), s)  # -0.0 -> +0.0 (XLA ties them)
        # mask = all-ones where negative (two's-complement 0 - 1), else sign
        mask = (ut(0) - (s >> ut(bits - 1))) | sign
        return jnp.where(isnan, ~ut(0), s ^ mask)
    raise TypeError(f"no sortable transform for {dt}")


def from_sortable(u: jax.Array, dtype) -> jax.Array:
    """Inverse of :func:`to_sortable`: exact bit round-trip everywhere
    except the two collapsed tie classes, which come back as their
    canonical representative (+0.0; the quiet positive NaN)."""
    dt = jnp.dtype(dtype)
    udt = _uint_dtype(dt.itemsize)
    bits = dt.itemsize * 8
    ut = np.dtype(udt).type
    sign = ut(ut(1) << ut(bits - 1))
    u = u.astype(udt)
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return u.astype(dt)
    if jnp.issubdtype(dt, jnp.signedinteger):
        return lax.bitcast_convert_type(u ^ sign, dt)
    # float: original was negative iff the transformed top bit is 0
    nmant = jnp.finfo(dt).nmant
    exp_all = ut(((1 << (bits - 1 - nmant)) - 1) << nmant)
    qnan = ut(exp_all | (ut(1) << ut(nmant - 1)))  # canonical quiet NaN
    neg = (u >> ut(bits - 1)) ^ ut(1)
    mask = (ut(0) - neg) | sign
    return lax.bitcast_convert_type(
        jnp.where(u == ~ut(0), qnan, u ^ mask), dt
    )


# ---------------------------------------------------------------------- #
# LSD radix — XLA formulation (one-hot matmul histogram; the kernel-    #
# logic reference and the CPU / forced-kernel small-n path)             #
# ---------------------------------------------------------------------- #
def _radix_pass_xla(digits: jax.Array, operands):
    """One stable counting-sort pass by ``digits`` ∈ [0, 256).

    histogram: ``ones(1, n) @ onehot(n, 256)`` — the one-hot matmul
    formulation (rides the MXU on TPU; XLA folds it to a reduce on CPU).
    Precision is pinned HIGHEST: the default TPU precision would feed
    the MXU bf16 inputs and counts ≥ 257 are not bf16-representable —
    a silently wrong destination permutation. rank: exclusive per-digit
    running count from the one-hot's exclusive column scan. scatter:
    destinations are a permutation (unique), so the apply is a
    unique-index scatter per operand.
    """
    n = digits.shape[0]
    oh = (digits[:, None] == jnp.arange(256, dtype=digits.dtype)[None, :])
    ohf = oh.astype(jnp.float32)
    hist = jnp.matmul(
        jnp.ones((1, n), jnp.float32), ohf, precision=lax.Precision.HIGHEST
    )[0]                                                             # (256,)
    excl = jnp.cumsum(hist) - hist                                   # exclusive
    within = jnp.sum((jnp.cumsum(ohf, axis=0) - ohf) * ohf, axis=1)  # (n,)
    base = jnp.take(excl, digits)          # excl[digit] — exact table lookup
    dest = (base + within).astype(jnp.int32)
    return tuple(jnp.zeros_like(t).at[dest].set(t, unique_indices=True) for t in operands)


def _radix_sort_xla(key_positions, operands, bytes_per_word):
    """Stable LSD radix sort of ``operands`` by the lexicographic key
    whose words sit at ``key_positions`` (most-significant FIRST; each
    an unsigned array whose unsigned order is the key order).
    ``bytes_per_word`` bounds the live bytes per word (e.g. 2 for an
    iota < 65536). LSD processes least-significant word first."""
    out = tuple(operands)
    for wi in range(len(key_positions) - 1, -1, -1):
        nbytes = bytes_per_word[wi]
        for b in range(nbytes):
            w = out[key_positions[wi]]
            digits = lax.shift_right_logical(
                w, np.dtype(w.dtype).type(8 * b)
            ).astype(jnp.int32) & 255
            out = _radix_pass_xla(digits, out)
    return out


# ---------------------------------------------------------------------- #
# LSD radix — the Pallas TPU kernel                                      #
# ---------------------------------------------------------------------- #
@functools.lru_cache(maxsize=16)
def _pallas_block_call(n_blocks: int, t: int, pay_bytes: int, key_bytes: int, interpret: bool):
    """Stable (key, payload)-lexicographic LSD radix of independent
    ``t``-element blocks, one block per sequential grid step, entirely
    in VMEM. Per 8-bit pass:

      histogram      one-hot (t, 256) colsum                     (VPU)
      exclusive scan ``hist @ strict_upper(256, 256)``           (MXU)
      stable rank    row-sum of (digit-equal & earlier) matrix   (VPU)
      stable scatter ``P @ data`` with P the destination one-hot (MXU)

    Every matmul is EXACT even if the MXU rounds its f32 INPUTS to
    bf16 (the TPU default-precision behavior): one operand of each dot
    is a 0/1 matrix, and the other never exceeds 255 — u32 words travel
    as FOUR 8-bit byte planes, and the count vectors (values up to t)
    enter the scan/base dots split into their own low/high byte planes,
    recombined by a ×256 f32 add on the exact accumulators. So every
    product is ``1.0 * v`` with v ≤ 255 (bf16-exact) and every sum
    stays an integer < 2^24 in the f32 accumulator. No gather, scatter,
    or dynamic indexing appears in the kernel; the only data-dependent
    movement is the permutation matmul, which is why this formulation
    compiles on Mosaic generations without dynamic-lane addressing."""

    def _byte_planes(w):
        # (t, 1) i32 word -> [(t, 1) f32] * 4, each plane ≤ 255
        return [
            (
                lax.shift_right_logical(w, jnp.full(w.shape, 8 * k, w.dtype)) & 255
            ).astype(jnp.float32)
            for k in range(4)
        ]

    def _recombine(planes):
        # [(t, 1) f32] * 4 -> (t, 1) i32
        word = planes[0].astype(jnp.int32)
        for k in range(1, 4):
            word = word | (planes[k].astype(jnp.int32) << (8 * k))
        return word

    def _split_dot(vec_f, mat):
        """``vec @ mat`` with ``mat`` 0/1 and ``vec`` integer-valued
        f32 ≤ 2^16: exact under bf16 input rounding via low/high byte
        planes of ``vec`` recombined in the f32 accumulator."""
        v_i = vec_f.astype(jnp.int32)
        lo = (v_i & 255).astype(jnp.float32)
        hi = lax.shift_right_logical(v_i, jnp.full(v_i.shape, 8, v_i.dtype)).astype(
            jnp.float32
        )
        return (
            jnp.dot(lo, mat, preferred_element_type=jnp.float32)
            + 256.0 * jnp.dot(hi, mat, preferred_element_type=jnp.float32)
        )

    def kernel(k_ref, p_ref, ko_ref, po_ref):
        key = k_ref[...].reshape(t, 1)
        pay = p_ref[...].reshape(t, 1)
        row = lax.broadcasted_iota(jnp.int32, (t, t), 0)
        col = lax.broadcasted_iota(jnp.int32, (t, t), 1)
        earlier = col < row
        bins = lax.broadcasted_iota(jnp.int32, (1, 256), 1)
        upper = (
            lax.broadcasted_iota(jnp.int32, (256, 256), 0)
            < lax.broadcasted_iota(jnp.int32, (256, 256), 1)
        ).astype(jnp.float32)
        frow = lax.broadcasted_iota(jnp.float32, (t, t), 0)

        passes = [("pay", b) for b in range(pay_bytes)] + [
            ("key", b) for b in range(key_bytes)
        ]
        for which, b in passes:
            w = pay if which == "pay" else key
            digit = (
                lax.shift_right_logical(w, jnp.full(w.shape, 8 * b, w.dtype)) & 255
            )
            eq = digit == digit.reshape(1, t)                       # (t, t)
            rank = jnp.sum(
                jnp.where(eq & earlier, 1.0, 0.0), axis=1, keepdims=True
            )                                                       # (t, 1) f32
            oh = (digit == bins).astype(jnp.float32)                # (t, 256)
            hist = jnp.sum(oh, axis=0, keepdims=True)               # (1, 256)
            excl = _split_dot(hist, upper)                          # (1, 256)
            # base = excl[digit], as onehot @ excl with excl byte-split
            e_i = excl.astype(jnp.int32)
            e_lo = (e_i & 255).astype(jnp.float32).reshape(256, 1)
            e_hi = lax.shift_right_logical(
                e_i, jnp.full(e_i.shape, 8, e_i.dtype)
            ).astype(jnp.float32).reshape(256, 1)
            base = jnp.dot(
                oh, e_lo, preferred_element_type=jnp.float32
            ) + 256.0 * jnp.dot(oh, e_hi, preferred_element_type=jnp.float32)
            dest = base + rank                                      # (t, 1), exact
            perm = (frow == dest.reshape(1, t)).astype(jnp.float32)  # (t, t)
            data = jnp.concatenate(
                _byte_planes(key) + _byte_planes(pay), axis=1
            )                                                        # (t, 8)
            moved = jnp.dot(perm, data, preferred_element_type=jnp.float32)
            key = _recombine([moved[:, k : k + 1] for k in range(4)])
            pay = _recombine([moved[:, 4 + k : 5 + k] for k in range(4)])

        ko_ref[...] = key.reshape(1, t)
        po_ref[...] = pay.reshape(1, t)

    spec = pl.BlockSpec((1, t), lambda i: (i, 0), memory_space=_VMEM)
    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((n_blocks, t), jnp.int32),
            jax.ShapeDtypeStruct((n_blocks, t), jnp.int32),
        ],
        interpret=interpret,
    )


def pallas_serviceable(n: int) -> bool:
    """Shape-level predicate: would the Pallas block kernel serve an
    ``n``-element fused key+index sort?"""
    return pl is not None and 0 < n <= _PALLAS_BLOCK


def _pallas_pair_sort(key_u32: jax.Array, pay_u32: jax.Array, pay_bytes: int = 4):
    """(key, payload)-lexicographic sort of one ≤ ``_PALLAS_BLOCK``
    block via the Pallas kernel (interpret mode off-TPU so the same
    kernel logic runs in tier-1 on CPU). Inputs/outputs are u32.

    Sentinel pads are (max, max) pairs: strictly after every real pair,
    because a real payload never reaches type-max (payloads are either
    an iota < block size or a transformed index whose extent fits the
    index dtype). ``pay_bytes`` may be lowered to 2 ONLY when the caller
    guarantees payloads < 2^16 (the iota-payload fast path)."""
    n = key_u32.shape[0]
    t = _PALLAS_BLOCK
    pad = t - n
    if pad:
        key_u32 = jnp.concatenate([key_u32, jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)])
        pay_u32 = jnp.concatenate(
            [pay_u32, jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)]
        )
    k2 = lax.bitcast_convert_type(key_u32, jnp.int32).reshape(1, t)
    p2 = lax.bitcast_convert_type(pay_u32, jnp.int32).reshape(1, t)
    interpret = jax.default_backend() != "tpu"
    ks, ps = _pallas_block_call(1, t, pay_bytes, 4, interpret)(k2, p2)
    ks = lax.bitcast_convert_type(ks.reshape(t), jnp.uint32)[:n]
    ps = lax.bitcast_convert_type(ps.reshape(t), jnp.uint32)[:n]
    return ks, ps


# ---------------------------------------------------------------------- #
# blocked columnsort — Leighton's network, single-chip                   #
# ---------------------------------------------------------------------- #
def _columnsort_p(n: int):
    """Largest power-of-2 p with rows B = ceil(n/p²)·p satisfying
    Leighton's bound B ≥ 2(p-1)² (and p | B by construction). Bigger p
    means shorter batched sort rows — the VMEM-friendly direction."""
    for p in (256, 128, 64, 32, 16, 8, 4):
        b = -(-n // (p * p)) * p
        if b >= 2 * (p - 1) ** 2:
            return p, b
    return None, None


def _columnsort_local(operands, num_keys: int, p: int, b: int, n: int):
    """Single-chip Leighton columnsort of 1-D ``operands`` (first
    ``num_keys`` are the lexicographic sort keys; operand 0 must be an
    unsigned transformed key so the pad sentinel type-max is a true
    maximum; a second key, when present, is an index operand that never
    reaches ITS type-max, so all-max pad tuples stay strictly last even
    against real type-max primary keys).

    The exact schedule of ``parallel._columnsort_program`` with the
    collectives replaced by their local data-movement equivalents:
    deal/undeal are the two all-to-alls as whole-array transposes, and
    the boundary cleanup is ONE batched (p-1, B) merge-sort instead of
    the two half-shard ppermute exchanges. 4 batched sorts + 3 relayout
    passes total; provably sorted for any input at B ≥ 2(p-1)², p | B.
    """
    pad = p * b - n
    padded = []
    for j, t in enumerate(operands):
        if pad:
            if j < num_keys:
                # sentinel pads are (max, ..., max) key tuples: strictly
                # after every real tuple, because a real SECONDARY key
                # (an index) never reaches its type-max even when the
                # primary key does (NaN sentinels / type-max data)
                fill = jnp.full((pad,), jnp.iinfo(t.dtype).max, t.dtype)
            else:
                fill = jnp.zeros((pad,), t.dtype)
            t = jnp.concatenate([t, fill])
        padded.append(t.reshape(p, b))

    def srt(ts):
        return list(lax.sort(tuple(ts), dimension=1, num_keys=num_keys, is_stable=True))

    def deal(t):
        # all_to_all(tiled) of the per-row round-robin deal, locally:
        # row c of the result is [t[r, q·p + c] for r, then q]
        return jnp.transpose(t.reshape(p, b // p, p), (2, 0, 1)).reshape(p, b)

    def undeal(t):
        # inverse deal: row d position q·p + r is t[r, d·(b//p) + q]
        return jnp.transpose(t.reshape(p, p, b // p), (1, 2, 0)).reshape(p, b)

    ts = srt(padded)                       # 1: sort columns
    ts = srt([deal(t) for t in ts])        # 2-3: deal + sort
    ts = srt([undeal(t) for t in ts])      # 4-5: undeal + sort
    # 6-8: boundary cleanup — every adjacent (bottom-half, top-half)
    # window jointly sorted in one batched pass (rows r and r+1 share
    # window r), then reassembled
    h = b // 2
    tops = [t[:, :h] for t in ts]
    bots = [t[:, h:] for t in ts]
    mid = srt(
        [jnp.concatenate([bt[:-1], tp[1:]], axis=1) for bt, tp in zip(bots, tops)]
    )                                      # (p-1, b)
    out = []
    for tp, bt, md in zip(tops, bots, mid):
        up = jnp.concatenate([tp[0:1], md[:, h:]], axis=0)   # (p, h)
        dn = jnp.concatenate([md[:, :h], bt[p - 1 : p]], axis=0)
        out.append(jnp.concatenate([up, dn], axis=1).reshape(p * b)[:n])
    return tuple(out)


# ---------------------------------------------------------------------- #
# dispatch                                                               #
# ---------------------------------------------------------------------- #
_DECISIONS: dict = {}


def last_decisions() -> dict:
    """Copy of the dispatcher's cached path decisions (and autotune
    timings where one ran): {(n, dtype, form): {"path": …, …}}."""
    return {k: dict(v) for k, v in _DECISIONS.items()}


def _kernel_path_for(n: int, itemsize: int = 4) -> str | None:
    """The kernel-family path serving an n-element 1-D fused sort, or
    None when no gate admits one. The Pallas pair kernel stages words
    through 16-bit f32 planes — 32-bit words only."""
    if itemsize == 4 and pallas_serviceable(n):
        return "pallas"
    if n <= _RADIX_XLA_MAX:
        return "radix_xla"
    if _columnsort_p(n)[0] is not None:
        return "columnsort"
    return None


def _sync_scalar(x) -> None:
    arr = x[0] if isinstance(x, tuple) else x
    np.asarray(jax.device_get(arr[(0,) * arr.ndim] if arr.ndim else arr))


def _autotune(n: int, dtype_name: str) -> str:
    """Time the eligible paths once on synthetic data of the real shape
    AND key width, and cache the winner. Runs only on TPU, eagerly
    (never under a trace), with a scalar read-back sync per rep
    (bench.py methodology: block_until_ready is a no-op over the remote
    tunnel)."""
    key = (n, dtype_name, "pairs")
    if key in _DECISIONS:
        return _DECISIONS[key]["path"]
    itemsize = jnp.dtype(dtype_name).itemsize
    cand = ["lax"]
    kp = _kernel_path_for(n, itemsize=itemsize)
    if kp == "columnsort":
        cand.append("columnsort")
    # well-mixed deterministic keys of the REAL width (Knuth
    # multiplicative hash of iota) — path costs scale with key bytes
    udt = _uint_dtype(itemsize) or jnp.uint32
    um = np.dtype(udt).type
    u = (jnp.arange(n, dtype=udt) * um(2654435761)) ^ um(0x9E3779B9)
    idx = jnp.arange(n, dtype=jnp.int32)
    timings = {}
    for path in cand:
        try:
            fn = jax.jit(functools.partial(_run_pair_path, path=path, n=n))
            _sync_scalar(fn(u, idx))  # compile + warm
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                _sync_scalar(fn(u, idx))
                best = min(best, time.perf_counter() - t0)
            timings[path] = best
        except Exception:  # pragma: no cover — lowering failed on this backend
            timings[path] = float("inf")
    path = min(timings, key=timings.get)
    _DECISIONS[key] = {"path": path, "timings": timings, "autotuned": True}
    return path


def _run_pair_path(u: jax.Array, idx: jax.Array, *, path: str, n: int):
    """(transformed key, index) pair sort by an explicit path — the
    autotune body and the kernel-route core of ``local_sort``."""
    if path == "lax":
        return lax.sort((u, idx), num_keys=2)
    if path == "pallas":
        su, si = _pallas_pair_sort(u, idx.astype(jnp.uint32), pay_bytes=2)
        return su, si.astype(idx.dtype)
    if path == "radix_xla":
        idx_bytes = 2 if n <= 0xFFFF else 4
        su, si = _radix_sort_xla((0, 1), (u, idx), (u.dtype.itemsize, idx_bytes))
        return su, si
    if path == "columnsort":
        p, b = _columnsort_p(n)
        return _columnsort_local((u, idx), 2, p, b, n)
    raise ValueError(f"unknown sort path {path!r}")


def _decide(n: int, dtype_name: str, concrete: bool, itemsize: int = 4) -> str:
    mode = _mode()
    if mode == "0":
        return "lax"
    if mode == "1":
        return _kernel_path_for(n, itemsize=itemsize) or "lax"
    # auto: lax off-TPU; autotuned on TPU for large 1-D sorts
    if jax.default_backend() != "tpu":
        return "lax"
    if n < (1 << 22):
        return "lax"
    key = (n, dtype_name, "pairs")
    # only AUTOTUNED entries may answer for auto mode — a decision cached
    # by a forced HEAT_TPU_SORT_KERNEL=1 call carries no timing evidence
    # and must not bypass the "never worse than lax.sort" floor
    if key in _DECISIONS and _DECISIONS[key].get("autotuned"):
        return _DECISIONS[key]["path"]
    if not concrete:
        return "lax"  # tracing: no autotune possible, stay on the oracle
    return _autotune(n, dtype_name)



def _index_dtype(n: int):
    """Argsort index dtype: int32 below 2^31 (the common case and the
    only kernel-eligible one); int64 above, where the x64 policy admits
    it (matches the pre-kernel ``manipulations.sort`` iota choice)."""
    if n < 2**31:
        return jnp.int32
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def local_sort(arr: jax.Array, axis: int = -1, descending: bool = False):
    """Fused values+argsort local sort along ``axis`` — the single-chip
    engine under ``ht.sort``'s non-split path.

    Returns ``(values, indices)`` with ``indices`` the STABLE argsort
    (``int32``). Semantics are exactly ``lax.sort``'s total order; the
    kernel paths operate on the monotone u32 transform and recover
    values by the inverse bijection — no gather pass. ``descending``
    sorts on the COMPLEMENTED transform in the same single pass (stable
    ties preserved), replacing the old argsort + take_along_axis route.
    """
    axis = axis % arr.ndim
    n = arr.shape[axis]
    # kernel paths carry the index through 32-bit machinery: huge axes
    # stay on the oracle with a wide-enough iota
    eligible = arr.ndim == 1 and n < 2**31 and transformable(arr.dtype)
    path = (
        _decide(
            n,
            jnp.dtype(arr.dtype).name,
            not isinstance(arr, jax.core.Tracer),
            itemsize=jnp.dtype(arr.dtype).itemsize,
        )
        if eligible
        else "lax"
    )
    if path == "lax":
        if eligible or arr.ndim == 1:
            _inc("sort.kernel.fallback")
        if descending and transformable(arr.dtype) and _mode() != "0":
            # one-pass stable descending: ascending sort of ~transform
            # (HEAT_TPU_SORT_KERNEL=0 keeps the pre-kernel two-pass route
            # below — the transform canonicalizes -0.0/NaN payload bits,
            # and the hatch's contract is byte-identical old behavior)
            u = ~to_sortable(arr)
            iota = lax.broadcasted_iota(_index_dtype(n), arr.shape, axis)
            su, si = lax.sort((u, iota), dimension=axis, num_keys=1, is_stable=True)
            return from_sortable(~su, arr.dtype), si
        if descending:
            indices = jnp.argsort(arr, axis=axis, descending=True, stable=True)
            return (
                jnp.take_along_axis(arr, indices, axis=axis),
                indices.astype(_index_dtype(n)),
            )
        iota = lax.broadcasted_iota(_index_dtype(n), arr.shape, axis)
        return lax.sort((arr, iota), dimension=axis, num_keys=1, is_stable=True)
    _inc("sort.kernel.hit")
    _DECISIONS.setdefault(
        (n, jnp.dtype(arr.dtype).name, "pairs"), {"path": path, "forced": True}
    )
    if isinstance(arr, jax.core.Tracer):
        return _pair_body(arr, path=path, n=n, descending=descending)
    return _pair_program(path, n, jnp.dtype(arr.dtype).name, descending)(arr)


def _pair_body(arr, *, path: str, n: int, descending: bool):
    """transform → pair sort → inverse, as one traceable body (jitted
    per (path, n, dtype, direction) by ``_pair_program`` so the eager
    public call pays ONE dispatch and XLA fuses the transforms into the
    sort's neighbors)."""
    u = to_sortable(arr)
    if descending:
        u = ~u
    idx = jnp.arange(n, dtype=jnp.int32)
    su, si = _run_pair_path(u, idx, path=path, n=n)
    if descending:
        su = ~su
    return from_sortable(su, arr.dtype), si


@functools.lru_cache(maxsize=64)
def _pair_program(path: str, n: int, dtype_name: str, descending: bool):
    return jax.jit(
        functools.partial(_pair_body, path=path, n=n, descending=descending)
    )


def block_sort(operands, dimension: int = 0, num_keys: int = 1, is_stable: bool = True, impl: str | None = None):
    """Drop-in ``lax.sort`` replacement for the LOCAL sort steps of the
    distributed programs (``parallel._columnsort_program`` /
    ``_oddeven_sort_program``) — traceable inside ``shard_map``.

    Default mode emits the identical ``lax.sort`` call (bit-identical
    HLO: the distributed collective census cannot move). With
    ``HEAT_TPU_SORT_KERNEL=1`` and a kernel-serviceable shape (1-D
    operands, ≤ 2 sort keys, transformable key dtypes), the sort runs
    through the radix/columnsort engines instead — still collective-free
    local compute, producing the exact oracle order (the (key, index)
    pair is a distinct total order); key VALUES come back canonicalized
    in the transform's two tie classes (−0.0 → +0.0, NaN payloads →
    quiet NaN), equal under the comparator."""
    operands = tuple(operands)
    if impl is None:
        impl = _mode()
    eligible = (
        impl == "1"
        and dimension == 0
        and all(t.ndim == 1 for t in operands)
        and num_keys <= 2
        and all(transformable(t.dtype) for t in operands[:num_keys])
    )
    if not eligible:
        if impl == "1":
            _inc("sort.kernel.fallback")
        return lax.sort(
            operands, dimension=dimension, num_keys=num_keys, is_stable=is_stable
        )
    n = operands[0].shape[0]
    keys_u = [to_sortable(t) for t in operands[:num_keys]]
    rest = operands[num_keys:]
    work = tuple(keys_u) + rest
    path = _kernel_path_for(n, itemsize=max(t.dtype.itemsize for t in keys_u))
    if path is None:
        _inc("sort.kernel.fallback")
        return lax.sort(
            operands, dimension=dimension, num_keys=num_keys, is_stable=is_stable
        )
    _inc("sort.kernel.hit")
    if path == "pallas" and num_keys == 1 and not rest:
        # values-only small block: ride a synthetic index (dropped)
        su, _ = _pallas_pair_sort(
            keys_u[0].astype(jnp.uint32), jnp.arange(n, dtype=jnp.uint32), pay_bytes=2
        )
        out = (su,)
    elif path == "pallas" and num_keys == 2 and not rest and n <= _PALLAS_BLOCK:
        su, si = _pallas_pair_sort(
            keys_u[0].astype(jnp.uint32), keys_u[1].astype(jnp.uint32)
        )
        out = (su, si)
    elif path in ("pallas", "radix_xla"):
        # general radix reference formulation (pallas shapes that don't
        # match the pair kernel fall through here too)
        bpw = tuple(t.dtype.itemsize for t in keys_u)
        out = _radix_sort_xla(tuple(range(num_keys)), work, bpw)
    else:  # columnsort
        p, b = _columnsort_p(n)
        out = _columnsort_local(work, num_keys, p, b, n)
    restored = tuple(
        from_sortable(out[j], operands[j].dtype) for j in range(num_keys)
    ) + tuple(out[num_keys:])
    return restored


# ---------------------------------------------------------------------- #
# pass-count model (bench sort_frac / PERF.md arithmetic)                #
# ---------------------------------------------------------------------- #
def sort_plan(n: int, dtype: str = "float32", with_indices: bool = True, path: str | None = None) -> dict:
    """Pass-count and HBM-byte model of an n-element local sort on the
    given path (default: the dispatcher's cached/predicted choice).

    ``lax.sort`` model: a comparison network of L(L+1)/2 merge stages
    (L = ⌈log₂ n⌉); all stages whose exchange span fits the
    VMEM-resident window (s = ``_VMEM_SORT_LOG2`` log₂-elements) fuse
    into ONE streaming pass, and each wider level k > s spills k − s
    passes — so passes = 1 + Σ_{k>s}(k − s). ``columnsort`` replaces
    one depth-L network with 4 batched depth-log₂(B) sorts (each fully
    VMEM-fusable when B ≤ 2^s) + 3 relayout passes. ``radix`` is
    ⌈bits/8⌉ histogram+scatter pass pairs. The bench row's
    ``sort_frac`` = model_bytes / t / HBM_peak — achieved fraction of
    stream peak AT the model's pass count (docs/PERF.md "Sort").
    """
    itemsize = jnp.dtype(dtype).itemsize
    ops_bytes = n * itemsize * (2 if with_indices else 1)
    per_pass = 2 * ops_bytes  # read + write every operand byte
    s = _VMEM_SORT_LOG2

    def _net_passes(m: int) -> int:
        # merge levels whose exchange span fits the VMEM window all fuse
        # into one streaming pass; level k > s spills (k - s) passes
        levels = max(int(np.ceil(np.log2(max(m, 2)))), 1)
        return int(1 + sum(k - s for k in range(s + 1, levels + 1)))

    if path is None:
        dec = _DECISIONS.get((n, jnp.dtype(dtype).name, "pairs"))
        path = dec["path"] if dec else (
            "lax" if _mode() != "1" else (_kernel_path_for(n, itemsize) or "lax")
        )
    if path == "columnsort":
        p, b = _columnsort_p(n)
        if p is None:
            path = "lax"
        else:
            passes = 4 * _net_passes(b) + 3
            return {
                "path": "columnsort",
                "p": p,
                "rows_b": b,
                "passes": passes,
                "hbm_bytes": passes * per_pass,
                "model": "4 batched depth-log2(B) sorts + 3 relayouts",
            }
    if path in ("radix_xla", "pallas"):
        key_bits = itemsize * 8
        idx_bits = max(int(np.ceil(np.log2(max(n, 2)))), 1)
        passes = -(-key_bits // 8) + (-(-idx_bits // 8) if with_indices else 0)
        return {
            "path": path,
            "passes": passes,
            "hbm_bytes": passes * per_pass,
            "model": "8-bit LSD: one histogram+scatter pair per digit",
        }
    passes = _net_passes(n)
    return {
        "path": "lax",
        "passes": passes,
        "hbm_bytes": passes * per_pass,
        "model": (
            "L(L+1)/2-stage comparison network, stages fused into HBM "
            f"passes at a 2^{s}-element VMEM window"
        ),
    }
